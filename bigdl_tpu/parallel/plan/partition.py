"""PartitionPlan: one declarative spec drives every parallelism
composition through the Optimizer façade.

SURVEY §7.5 names the target the reference never reached: parallelism
"expressed as sharding rules so DistriOptimizer-equivalent code stays
strategy-agnostic".  The reference's only strategy is flat data
parallelism over BlockManagers (parameters/AllReduceParameter.scala);
every other axis here is new capability, and before this module each
one had its own wiring ritual (``tensor_parallel_rules`` by hand,
``set_sequence_parallel``, ``MoE.set_mesh``, ``Pipeline.set_mesh``,
``configure_hybrid``).  A :class:`PartitionPlan` replaces the rituals:

* per-axis strategy assignment — ``PartitionPlan(dp=2, tp=2, pp=2)``
  maps strategies onto the canonical mesh axes
  (:data:`bigdl_tpu.parallel.mesh.AXES`),
* per-leaf PartitionSpec derivation via composable rule sets extending
  :class:`~bigdl_tpu.parallel.sharding.ShardingRules` (precedence:
  embedding-table rules > user rules > tensor-parallel rules > fsdp
  fallback > replicate),
* a :func:`resolve` planner that validates the composition against the
  model and the mesh, raising :class:`PlanError` with the offending
  axis/leaf named (the ``HybridPlanError`` pattern — which now IS a
  ``PlanError`` subclass), and
* the module-wiring closures (ring attention, expert dispatch, pipeline
  staging, table row-sharding) the Optimizer applies in
  ``set_partition_plan`` so ``_build_step``/``compile_step`` emit the
  same program shape for every composition.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.parallel.mesh import MeshConfig, mesh_axes
from bigdl_tpu.parallel.sharding import ShardingRules, tensor_parallel_rules

__all__ = ["STRATEGIES", "PlanError", "PartitionPlan", "ResolvedPlan",
           "resolve"]

# strategy name -> canonical mesh axis (parallel.mesh.AXES order)
STRATEGIES = {
    "dcn": "dcn",      # slice tier (slow network); batch-like
    "dp": "data",      # batch sharding
    "fsdp": "fsdp",    # batch sharding + parameter/optim-state sharding
    "tp": "model",     # megatron-style tensor parallelism
    "pp": "pipe",      # pipeline stages
    "sp": "seq",       # ring-attention sequence/context parallelism
    "ep": "expert",    # MoE expert parallelism
}

# default Megatron split for the in-repo transformer family: q/k/v and
# the FFN filter are column-parallel (output dim), the attention output
# and FFN output projections are row-parallel (input dim) — the same
# patterns analysis/hlo_budget.py budgets
_TRANSFORMER_TP_COLUMN = (r"q_layer", r"k_layer", r"v_layer",
                          r"filter_layer")
_TRANSFORMER_TP_ROW = (r"output_layer", r"out_layer")


class PlanError(ValueError):
    """A (plan, model, mesh) composition the planner cannot honor;
    the message names the offending axis or parameter leaf and says
    what to change."""


@dataclasses.dataclass
class PartitionPlan:
    """Per-axis strategy degrees plus strategy options.  Degrees are
    positive ints (1 = strategy off); exactly one may be ``-1`` to
    absorb the remaining devices.  ``resolve(plan, model)`` validates
    and returns the :class:`ResolvedPlan` the Optimizer consumes."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    dcn: int = 1

    # tp options: regex patterns over parameter paths (see
    # sharding.tensor_parallel_rules).  None -> transformer defaults
    # when the model has attention blocks, else a generic
    # column-parallel rule over every divisible weight.
    tp_column: Optional[Sequence[str]] = None
    tp_row: Optional[Sequence[str]] = None

    # pp options: microbatch count (default = pp degree) and schedule.
    # "gpipe" stages the forward (autodiff through the schedule);
    # "1f1b" runs fwd+loss+bwd inside the schedule (Pipeline models
    # only — the loss must live at the last stage).
    pp_microbatches: Optional[int] = None
    pp_schedule: str = "gpipe"

    # sp options: optional attention kernel and the head axis the ring
    # keeps sharded (defaults to "model" when composing with tp)
    sp_kernel: Optional[Callable] = None
    sp_head_axis: Optional[str] = None

    # ep options: capacity-based all_to_all dispatch when set, exact
    # psum fallback when None (see nn.moe.MoE.set_mesh)
    ep_capacity_factor: Optional[float] = None

    # sharded embedding tables row-shard over this (batch-like) axis
    embedding_axis: str = "data"

    # extra user rules, applied after table rules but before tp rules
    rules: Optional[ShardingRules] = None

    def degrees(self) -> Dict[str, int]:
        out = {k: getattr(self, k) for k in STRATEGIES}
        neg = [k for k, v in out.items() if v == -1]
        for k, v in out.items():
            if not isinstance(v, int) or v == 0 or v < -1:
                raise PlanError(
                    f"{k}={v!r}: strategy degrees are positive ints "
                    f"(1 = off), or -1 on at most one strategy to "
                    f"absorb the remaining devices")
        if len(neg) > 1:
            raise PlanError(
                f"only one strategy may be -1; got {sorted(neg)}")
        return out

    def mesh_axes(self) -> Dict[str, int]:
        """The MeshConfig axes this plan asks for (degree-1 strategies
        stay off the mesh)."""
        axes = {STRATEGIES[k]: v for k, v in self.degrees().items()
                if v != 1}
        return axes or {"data": 1}

    def describe(self) -> str:
        on = [f"{k}={v}" for k, v in self.degrees().items() if v != 1]
        return "PartitionPlan(" + (", ".join(on) or "single-device") + ")"


@dataclasses.dataclass
class ResolvedPlan:
    """A validated plan bound to a concrete mesh: the composed sharding
    rules, the module wirings to apply, and the resolved degrees.  The
    Optimizer stores this and routes ``_build_step``/``compile_step``
    decisions (e.g. the 1F1B schedule) through it."""

    plan: PartitionPlan
    mesh_config: MeshConfig
    mesh: Mesh
    rules: ShardingRules
    degrees: Dict[str, int]
    wirings: List[Tuple[str, Callable[[], Any]]]
    notes: List[str] = dataclasses.field(default_factory=list)
    applied: bool = False

    @property
    def pp_schedule(self) -> Optional[str]:
        return (self.plan.pp_schedule if self.degrees.get("pp", 1) > 1
                else None)

    @property
    def pp_axis(self) -> str:
        return STRATEGIES["pp"]

    def apply(self) -> "ResolvedPlan":
        """Run the module wirings (idempotent)."""
        if not self.applied:
            for _desc, fn in self.wirings:
                fn()
            self.applied = True
        return self

    def describe(self) -> str:
        comp = "×".join(f"{k}{v}" for k, v in self.degrees.items()
                        if v > 1) or "single-device"
        lines = [f"{comp} on mesh {dict(mesh_axes(self.mesh))}"]
        lines += [f"  wire: {d}" for d, _ in self.wirings]
        lines += [f"  note: {n}" for n in self.notes]
        return "\n".join(lines)


def _struct_homogeneous(blocks) -> bool:
    from bigdl_tpu.parallel.pipeline import Pipeline
    sigs = [Pipeline._struct_sig(b) for b in blocks]
    return all(s == sigs[0] for s in sigs[1:])


def _tp_rules_for(plan: PartitionPlan, model) -> ShardingRules:
    if plan.tp_column or plan.tp_row:
        return tensor_parallel_rules(column=plan.tp_column or (),
                                     row=plan.tp_row or ())
    has_attention = any(
        "q_layer" in getattr(m, "_modules", {})
        for _, m in model.named_modules())
    if has_attention:
        return tensor_parallel_rules(column=_TRANSFORMER_TP_COLUMN,
                                     row=_TRANSFORMER_TP_ROW)

    # generic fallback: column-shard every >=2-D weight whose output
    # dim divides.  Sharding annotations never change the math — GSPMD
    # inserts the collectives — so this gives non-transformer models a
    # meaningful tp without per-model rule sets.
    def col_spec(shape, mesh):
        axis = STRATEGIES["tp"]
        if axis not in mesh.axis_names:
            return P()
        if len(shape) >= 2 and shape[0] % mesh.shape[axis] == 0:
            return P(axis, *([None] * (len(shape) - 1)))
        return P()

    return ShardingRules([(r"weight", col_spec)])


def _check_tp(plan: PartitionPlan, model, mesh, tp: int,
              notes: List[str]) -> ShardingRules:
    """Validate that tensor parallelism actually shards something, and
    name the leaf that blocks it when nothing divides."""
    import jax
    from bigdl_tpu.core.module import param_paths, partition

    axis = STRATEGIES["tp"]
    tpr = _tp_rules_for(plan, model)
    params_tree, _ = partition(model)
    leaves = jax.tree_util.tree_leaves(params_tree)
    paths = param_paths(model)
    sharded, blocked = [], []
    for p, leaf in zip(paths, leaves):
        matched = any(pat.search(p) for pat, _fn in tpr.rules)
        if not matched:
            continue
        if tpr.spec_for(p, leaf.shape, mesh) != P():
            sharded.append(p)
        else:
            blocked.append((p, tuple(leaf.shape)))
    if not sharded:
        if blocked:
            p0, s0 = blocked[0]
            raise PlanError(
                f"tp={tp}: no parameter shards over axis {axis!r} — "
                f"leaf {p0!r} (shape {s0}) matches the tensor-parallel "
                f"rules but its split dim does not divide by {tp}; "
                f"lower the tp degree or pad the layer width")
        raise PlanError(
            f"tp={tp}: no parameter path matches the tensor-parallel "
            f"rules (column={list(plan.tp_column or ())!r}, "
            f"row={list(plan.tp_row or ())!r}) — pass tp_column/tp_row "
            f"patterns that name this model's layers")
    if blocked:
        notes.append(
            f"tp: {len(blocked)} matched leaf/leaves do not divide by "
            f"{tp} and stay replicated (e.g. {blocked[0][0]!r} "
            f"{blocked[0][1]})")
    return tpr


def resolve(plan: PartitionPlan, model, mesh: Optional[Mesh] = None, *,
            hierarchical: bool = False,
            compute_dtype=None) -> ResolvedPlan:
    """Validate ``plan`` against ``model`` (and ``mesh``, when given an
    explicit one) and return the :class:`ResolvedPlan`.

    Raises :class:`PlanError` naming the offending axis/leaf for every
    unhonorable composition: degrees that don't divide the device
    count, a planned axis missing from an explicit mesh, pp on a
    non-sequential model, tp that shards nothing, sp/ep on models
    without the corresponding structure, sharded embedding tables
    combined with non-batch axes, and hierarchical-sync or
    compute-dtype combinations the step builder would reject later.
    """
    degrees = plan.degrees()
    axes = plan.mesh_axes()
    if mesh is None:
        try:
            mesh_config = MeshConfig(**axes)
            mesh = mesh_config.build()
        except ValueError as e:
            raise PlanError(f"{plan.describe()}: {e}") from None
    else:
        shape = mesh_axes(mesh)
        for k, v in degrees.items():
            ax = STRATEGIES[k]
            if v == 1:
                continue
            if ax not in shape or shape[ax] <= 1:
                raise PlanError(
                    f"{k}={v}: axis {ax!r} is not on the mesh (axes: "
                    f"{dict(shape)}); build the mesh with "
                    f"MeshConfig({ax}={v}) or drop {k} from the plan")
            if v != -1 and shape[ax] != v:
                raise PlanError(
                    f"{k}={v}: mesh axis {ax!r} has size {shape[ax]}, "
                    f"not {v}; the plan and the mesh disagree")
        mesh_config = MeshConfig(**{a: int(s) for a, s in shape.items()})
    shape = mesh_axes(mesh)
    deg = {k: int(shape.get(STRATEGIES[k], 1)) for k in STRATEGIES}

    non_batch = [k for k in ("tp", "pp", "sp", "ep") if deg[k] > 1]
    if hierarchical and non_batch:
        raise PlanError(
            f"hierarchical gradient sync supports batch-parallel "
            f"meshes (dcn/data/fsdp axes); this plan also has "
            f"{non_batch} — use the flat sync when composing with "
            f"tensor/pipeline/sequence/expert parallelism")

    rule_list: List[Tuple[Any, Callable]] = []
    wirings: List[Tuple[str, Callable[[], Any]]] = []
    notes: List[str] = []

    # ---- sharded embedding tables (batch-parallel only) ----------------
    from bigdl_tpu.embedding.hybrid import sharded_tables
    tables = sharded_tables(model)
    if tables:
        from bigdl_tpu.embedding.hybrid import (
            embedding_rules, resolve_hybrid,
        )
        # resolve_hybrid raises HybridPlanError (a PlanError) naming
        # the failing axis/table
        resolve_hybrid(model, mesh, plan.embedding_axis,
                       hierarchical=hierarchical)
        rule_list.extend(embedding_rules(model, plan.embedding_axis).rules)
        _tables, _ax = tables, plan.embedding_axis

        def wire_tables(tables=_tables, axis=_ax, mesh=mesh):
            for t in tables.values():
                t.set_mesh(mesh, axis)

        wirings.append((
            f"embedding: row-shard {len(tables)} table(s) over "
            f"{plan.embedding_axis!r}", wire_tables))

    # ---- user rules ----------------------------------------------------
    if plan.rules is not None:
        rule_list.extend(plan.rules.rules)

    # ---- tensor parallelism --------------------------------------------
    if deg["tp"] > 1:
        rule_list.extend(_check_tp(plan, model, mesh, deg["tp"],
                                   notes).rules)

    # ---- pipeline parallelism ------------------------------------------
    if deg["pp"] > 1:
        _resolve_pp(plan, model, mesh, deg, compute_dtype, wirings,
                    notes)

    # ---- sequence parallelism ------------------------------------------
    if deg["sp"] > 1:
        if not hasattr(model, "set_sequence_parallel"):
            raise PlanError(
                f"sp={deg['sp']}: {type(model).__name__} has no "
                f"sequence-parallel path (set_sequence_parallel) — "
                f"ring attention over axis {STRATEGIES['sp']!r} "
                f"applies to attention models (models/transformer_lm)")
        head_axis = plan.sp_head_axis or (
            STRATEGIES["tp"] if deg["tp"] > 1 else None)

        def wire_sp(model=model, mesh=mesh, kernel=plan.sp_kernel,
                    head_axis=head_axis):
            model.set_sequence_parallel(mesh, STRATEGIES["sp"],
                                        kernel=kernel,
                                        head_axis=head_axis)

        wirings.append((
            f"sp: ring attention over {STRATEGIES['sp']!r}"
            + (f" (heads stay on {head_axis!r})" if head_axis else ""),
            wire_sp))

    # ---- expert parallelism --------------------------------------------
    if deg["ep"] > 1:
        from bigdl_tpu.nn.moe import MoE
        moes = [(p, m) for p, m in model.named_modules()
                if isinstance(m, MoE)]
        if not moes:
            raise PlanError(
                f"ep={deg['ep']}: the model has no MoE layer to "
                f"expert-shard over axis {STRATEGIES['ep']!r} — drop "
                f"ep from the plan or build the model on nn.moe.MoE")
        for p, m in moes:
            if m.num_experts % deg["ep"]:
                raise PlanError(
                    f"ep={deg['ep']}: MoE {p or m.name!r} has "
                    f"{m.num_experts} experts, not divisible over "
                    f"{deg['ep']} shards on axis {STRATEGIES['ep']!r}")

        def wire_ep(moes=moes, mesh=mesh, cf=plan.ep_capacity_factor):
            for _p, m in moes:
                m.set_mesh(mesh, STRATEGIES["ep"], capacity_factor=cf)

        wirings.append((
            f"ep: {len(moes)} MoE layer(s) over {STRATEGIES['ep']!r} "
            f"({'a2a cap ' + str(plan.ep_capacity_factor) if plan.ep_capacity_factor is not None else 'exact psum'})",
            wire_ep))

    if deg["fsdp"] > 1:
        notes.append(
            f"fsdp: unmatched parameter leaves shard their largest "
            f"divisible dim over {STRATEGIES['fsdp']!r} (ZeRO-3 style)")

    rules = ShardingRules(rule_list, fsdp=deg["fsdp"] > 1)
    return ResolvedPlan(plan=plan, mesh_config=mesh_config, mesh=mesh,
                        rules=rules, degrees=deg, wirings=wirings,
                        notes=notes)


def _resolve_pp(plan: PartitionPlan, model, mesh, deg: Dict[str, int],
                compute_dtype, wirings, notes) -> None:
    from bigdl_tpu.parallel.pipeline import Pipeline

    s = deg["pp"]
    axis = STRATEGIES["pp"]
    if plan.pp_schedule not in ("gpipe", "1f1b"):
        raise PlanError(
            f"pp_schedule={plan.pp_schedule!r}: known schedules are "
            f"'gpipe' and '1f1b'")
    if deg["sp"] > 1 or deg["ep"] > 1:
        both = [k for k in ("sp", "ep") if deg[k] > 1]
        raise PlanError(
            f"pp cannot compose with {both} in one program: the "
            f"ring-attention / expert all_to_all shard_map would nest "
            f"inside the pipeline shard_map — drop pp or {both[0]}")
    if plan.pp_schedule == "1f1b" and compute_dtype is not None:
        raise PlanError(
            "pp_schedule='1f1b' does not compose with "
            "set_compute_dtype: the in-schedule loss/backward runs at "
            "the stage dtype — use pp_schedule='gpipe' or drop the "
            "compute dtype")
    n_mb = plan.pp_microbatches or s
    if n_mb < 1:
        raise PlanError(f"pp_microbatches={n_mb}: must be >= 1")

    blocks = getattr(model, "blocks", None)
    if isinstance(model, Pipeline):
        n = len(model.blocks)
        if n % s:
            raise PlanError(
                f"pp={s}: model has {n} blocks, not divisible into "
                f"{s} stages on axis {axis!r}; regroup the blocks or "
                f"lower the pp degree")
        if plan.pp_schedule == "1f1b" \
                and not model._blocks_homogeneous():
            raise PlanError(
                "pp_schedule='1f1b' needs structurally homogeneous "
                "blocks (the stacked stage layout); this Pipeline's "
                "blocks differ — group them into structurally-equal "
                "stages or use pp_schedule='gpipe'")

        def wire_pipe(model=model, mesh=mesh, n_mb=n_mb, axis=axis):
            model.num_microbatches = n_mb
            model.set_mesh(mesh, axis)

        wirings.append((
            f"pp: {n} blocks → {s} stages over {axis!r} "
            f"({n_mb} microbatches, {plan.pp_schedule})", wire_pipe))
        return

    if hasattr(model, "set_pipeline_parallel"):
        if plan.pp_schedule == "1f1b":
            raise PlanError(
                f"pp_schedule='1f1b' runs the loss inside the pipeline "
                f"schedule, which requires the model to BE a "
                f"parallel.Pipeline (blocks only); "
                f"{type(model).__name__} has pre/post-block stages "
                f"(embedding/head) — use pp_schedule='gpipe'")
        if blocks is None or len(blocks) % s:
            n = 0 if blocks is None else len(blocks)
            raise PlanError(
                f"pp={s}: {type(model).__name__} has {n} blocks, not "
                f"divisible into {s} stages on axis {axis!r}")
        if not _struct_homogeneous(list(blocks)):
            raise PlanError(
                f"pp={s}: {type(model).__name__}'s blocks are not "
                f"structurally homogeneous; the stacked stage layout "
                f"needs structurally-equal blocks")

        def wire_model(model=model, mesh=mesh, n_mb=n_mb, axis=axis):
            model.set_pipeline_parallel(mesh, axis,
                                        num_microbatches=n_mb)

        wirings.append((
            f"pp: {len(blocks)} blocks → {s} stages over {axis!r} "
            f"({n_mb} microbatches, gpipe)", wire_model))
        return

    raise PlanError(
        f"pp={s}: {type(model).__name__} is not pipeline-stageable on "
        f"axis {axis!r}: it is neither a parallel.Pipeline nor exposes "
        f"set_pipeline_parallel(mesh, axis, num_microbatches) — wrap "
        f"its layers in parallel.Pipeline([...])")
