"""Pipeline parallelism over a mesh axis.

The reference has NO pipeline parallelism (SURVEY §2.6) — this is new,
TPU-first capability.  The design is the collective-permute pipeline
from the scaling playbook: the stages of a deep network are sharded over
the ``pipe`` mesh axis (each device holds ONE stage's parameters — a
stack of identical blocks, e.g. transformer layers, stacked on a leading
axis and sharded dim-0).  Microbatches stream through: at every tick
each device applies its stage to the activation it holds, then passes
the result to the next device with ``lax.ppermute`` (ICI
neighbor-to-neighbor).  A full batch of M microbatches over S stages
drains in M + S - 1 ticks (GPipe schedule; bubble fraction
(S-1)/(M+S-1)).

``gpipe`` is the functional entry; :class:`Pipeline` wraps a list of
identical Modules into the stacked representation.
"""

from __future__ import annotations

import functools
from typing import Callable, List

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.core.module import Module, ModuleList

__all__ = ["gpipe", "Pipeline"]


def _pipe_loop(stage_params, x_mb, stage_apply, axis_name: str):
    """Per-device pipeline loop (runs under shard_map).

    stage_params: this device's stage parameters (leading stage axis
    already sharded away → local block params).
    x_mb: [M, mb, ...] all microbatches (replicated on every device).
    Returns [M, mb, ...] outputs (replicated; only the last stage's
    contribution is nonzero before the psum).
    """
    s_total = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    # shard_map delivers the stage-sharded leaves with a size-1 leading
    # dim — strip it so stage_apply sees one stage's params as documented
    stage_params = jax.tree_util.tree_map(lambda l: l[0], stage_params)
    m_total = x_mb.shape[0]
    ticks = m_total + s_total - 1

    ys0 = jnp.zeros_like(x_mb)
    carry0 = jnp.zeros_like(x_mb[0])
    perm = [(i, i + 1) for i in range(s_total - 1)]

    def tick(t, state):
        carry, ys = state
        # stage 0 ingests microbatch t (while t < M); later stages use
        # the activation ppermuted from the previous stage
        feed_idx = jnp.clip(t, 0, m_total - 1)
        inp = jnp.where(me == 0, x_mb[feed_idx], carry)
        out = stage_apply(stage_params, inp)
        # last stage emits microbatch t - (S-1) when it's valid
        emit_idx = jnp.clip(t - (s_total - 1), 0, m_total - 1)
        valid = (t >= s_total - 1) & (me == s_total - 1)
        upd = jnp.where(valid, out, ys[emit_idx])
        ys = jax.lax.dynamic_update_index_in_dim(ys, upd, emit_idx, 0)
        carry = jax.lax.ppermute(out, axis_name, perm)
        return carry, ys

    _, ys = jax.lax.fori_loop(0, ticks, tick, (carry0, ys0))
    # replicate the last stage's outputs to every device
    keep = (me == s_total - 1).astype(ys.dtype)
    return jax.lax.psum(ys * keep, axis_name)


def gpipe(stage_apply: Callable, stacked_params, x, mesh: Mesh,
          axis: str = "pipe", num_microbatches: int = 1):
    """Run ``x`` through S pipeline stages sharded over ``axis``.

    stage_apply(stage_params, x_mb) -> y_mb applies ONE stage;
    stacked_params is a pytree whose leaves have a leading stage axis of
    size S = mesh.shape[axis]; x is the full batch [B, ...] with B
    divisible by num_microbatches.
    """
    s = mesh.shape[axis]
    b = x.shape[0]
    assert b % num_microbatches == 0, (b, num_microbatches)
    x_mb = x.reshape((num_microbatches, b // num_microbatches)
                     + x.shape[1:])

    fn = jax.shard_map(
        functools.partial(_pipe_loop, stage_apply=stage_apply,
                          axis_name=axis),
        mesh=mesh,
        in_specs=(_stage_specs(stacked_params, axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    y_mb = fn(stacked_params, x_mb)
    return y_mb.reshape((b,) + y_mb.shape[2:])


def _stage_specs(stacked_params, axis: str):
    return jax.tree_util.tree_map(lambda _: P(axis), stacked_params)


class Pipeline(Module):
    """Pipeline container over identical blocks (reference analogue:
    none — Sequential executes stages on one node, nn/Sequential.scala).

    ``Pipeline([block]*N, num_microbatches)`` stacks the blocks'
    parameters on a leading axis; ``forward(x)`` runs sequentially (for
    single-device correctness/testing), while :meth:`forward_on_mesh`
    runs the GPipe schedule over a mesh axis.  N must equal the mesh
    axis size × blocks-per-stage.
    """

    def __init__(self, blocks: List[Module], num_microbatches: int = 1):
        super().__init__()
        self.blocks = ModuleList(blocks)
        self.num_microbatches = num_microbatches
        self.pipe_mesh = None
        self.pipe_axis = "pipe"

    def set_mesh(self, mesh: Mesh, axis: str = "pipe") -> "Pipeline":
        """Route ``forward`` through the GPipe schedule on this mesh, so
        the container composes with the Optimizer (whose jitted step
        just calls ``model.forward``)."""
        self.pipe_mesh = mesh
        self.pipe_axis = axis
        return self

    def forward(self, x):
        if self.pipe_mesh is not None:
            return self.forward_on_mesh(x, self.pipe_mesh, self.pipe_axis)
        for blk in self.blocks:
            x = blk(x)
        return x

    def _stacked(self):
        """Stack per-block pytrees leaf-wise onto a leading stage axis."""
        trees = list(self.blocks)
        return jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *trees)

    def forward_on_mesh(self, x, mesh: Mesh, axis: str = "pipe"):
        s = mesh.shape[axis]
        n = len(self.blocks)
        assert n % s == 0, (n, s)
        per_stage = n // s

        def stage_apply(stage_tree, x_mb):
            # stage_tree leaves: [per_stage, ...] — apply blocks in order
            def one(i, acc):
                blk = jax.tree_util.tree_map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, i, 0, keepdims=False), stage_tree)
                return blk(acc)
            return jax.lax.fori_loop(0, per_stage, one, x_mb)

        # regroup the N stacked blocks as [S, per_stage, ...]
        stacked = jax.tree_util.tree_map(
            lambda l: l.reshape((s, per_stage) + l.shape[1:]),
            self._stacked())

        return gpipe(stage_apply, stacked, x, mesh, axis,
                     self.num_microbatches)
