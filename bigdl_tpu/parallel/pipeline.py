"""Pipeline parallelism over a mesh axis.

The reference has NO pipeline parallelism (SURVEY §2.6) — this is new,
TPU-first capability.  The design is the collective-permute pipeline
from the scaling playbook: the stages of a deep network are sharded over
the ``pipe`` mesh axis; microbatches stream through: at every tick each
device applies its stage to the activation it holds, then passes the
result to the next device with ``lax.ppermute`` (ICI
neighbor-to-neighbor).  A full batch of M microbatches over S stages
drains in M + S - 1 ticks (GPipe schedule; bubble fraction
(S-1)/(M+S-1)).

Memory (the r03 verdict's weak spot, fixed): the microbatch buffers are
SHARDED over the pipe axis — each device holds M/S input microbatches,
M/S output slots, and ONE working activation.  Each tick moves exactly
one microbatch: the feeding stage broadcasts the current input (a
masked psum of one [mb, ...] tensor), the last stage broadcasts its
emission, and every device keeps only the slots it is home to.
Per-device activation memory is O(B/S + mb), never the full batch.
When M is not divisible by S, the schedule pads with dummy microbatches
(compute waste, not memory).

Two parameter layouts:

* homogeneous stages (all blocks share a pytree structure): parameters
  stack on a leading stage axis and SHARD over the pipe axis — each
  device materializes only its own stage's weights.
* heterogeneous stages: parameters are passed replicated and the stage
  body is a ``lax.switch`` over per-stage functions (SPMD programs must
  agree, so heterogeneity costs parameter replication — documented
  trade-off; group your blocks into structurally-equal stages to get
  sharded parameters back).  The activation shape at every stage
  BOUNDARY must be uniform — the carry rides one ppermute buffer — so
  width changes must happen inside a stage, not across stages (an
  inherent constraint of SPMD collective-permute pipelines).

``gpipe`` is the functional entry; :class:`Pipeline` wraps a list of
Modules and picks the layout automatically.
"""

from __future__ import annotations

import functools
from typing import Callable, List

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.core.module import Module, ModuleList
from bigdl_tpu.telemetry import collectives as _coll
from bigdl_tpu.parallel.mesh import pin_replicated, shard_map_compat

__all__ = ["gpipe", "one_f_one_b", "Pipeline"]

# Per-device (inside-shard_map) buffer shapes of the most recent pipeline
# trace — a debug/test hook (module attrs would pollute the pytree).
LAST_PIPE_SHAPES = {}


def _pipe_loop(stage_params, x_loc, stage_apply, axis_name: str):
    """Per-device pipeline loop (runs under shard_map).

    stage_params: this device's stage parameters (sharded stacked
    leaves, or a replicated tuple of per-stage trees for heterogeneous
    stages — ``stage_apply`` knows which).
    x_loc: [M/S, mb, ...] THIS DEVICE'S shard of the microbatch ring.
    Returns [M/S, mb, ...]: the device's home shard of the outputs.
    """
    s_total = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    chunk = x_loc.shape[0]                     # M/S microbatches here
    m_total = chunk * s_total
    ticks = m_total + s_total - 1

    out_loc0 = jnp.zeros_like(x_loc)
    carry0 = jnp.zeros_like(x_loc[0])
    perm = [(i, i + 1) for i in range(s_total - 1)]
    LAST_PIPE_SHAPES.update(x_loc=x_loc.shape, carry=carry0.shape,
                            out_loc=out_loc0.shape)

    def tick(t, state):
        carry, out_loc = state
        # one microbatch enters the pipe per tick: its home device
        # broadcasts it (masked psum of a single [mb, ...] tensor)
        feed_idx = jnp.clip(t, 0, m_total - 1)
        mine = jax.lax.dynamic_index_in_dim(
            x_loc, feed_idx % chunk, 0, keepdims=False)
        feed = _coll.psum(
            jnp.where(me == feed_idx // chunk, mine, 0), axis_name)
        inp = jnp.where(me == 0, feed, carry)
        out = stage_apply(stage_params, inp, me)
        # the last stage emits microbatch t-(S-1); its output is
        # broadcast the same way and stored only by its home device
        emit_idx = jnp.clip(t - (s_total - 1), 0, m_total - 1)
        valid = t >= s_total - 1
        y = _coll.psum(
            jnp.where(valid & (me == s_total - 1), out, 0), axis_name)
        hslot = emit_idx % chunk
        old = jax.lax.dynamic_index_in_dim(out_loc, hslot, 0,
                                           keepdims=False)
        upd = jnp.where(valid & (me == emit_idx // chunk), y, old)
        out_loc = jax.lax.dynamic_update_index_in_dim(
            out_loc, upd, hslot, 0)
        carry = _coll.ppermute(out, axis_name, perm)
        return carry, out_loc

    _, out_loc = jax.lax.fori_loop(0, ticks, tick, (carry0, out_loc0))
    return out_loc


def _run_pipe(stage_apply, stacked_params, param_specs, x, mesh,
              axis: str, num_microbatches: int):
    """Shared driver: microbatch split + pad to a multiple of S, the
    sharded shard_map call, unpad."""
    s = mesh.shape[axis]
    b = x.shape[0]
    m = num_microbatches
    assert b % m == 0, (b, m)
    x_mb = x.reshape((m, b // m) + x.shape[1:])
    m_pad = -m % s
    if m_pad:
        # pad the schedule with dummy microbatches so the ring shards
        # evenly; costs bubble compute, not memory.  jnp.pad, NOT
        # concatenate-with-zeros: on multi-axis meshes GSPMD
        # mispartitions the concat feeding the shard_map (observed on
        # jax 0.4.37: jit result diverges from eager; tested in
        # test_parallel.py and the plan conformance matrix)
        x_mb = jnp.pad(x_mb, ((0, m_pad),) + ((0, 0),) * (x_mb.ndim - 1))

    fn = shard_map_compat(
        functools.partial(_pipe_loop, stage_apply=stage_apply,
                          axis_name=axis),
        mesh=mesh,
        in_specs=(param_specs, P(axis)),
        out_specs=P(axis),
    )
    stacked_params = pin_replicated(stacked_params, mesh)
    x_mb = pin_replicated(x_mb, mesh)
    y_mb = fn(stacked_params, x_mb)[:m]
    return y_mb.reshape((b,) + y_mb.shape[2:])


def gpipe(stage_apply: Callable, stacked_params, x, mesh: Mesh,
          axis: str = "pipe", num_microbatches: int = 1):
    """Run ``x`` through S pipeline stages sharded over ``axis``.

    stage_apply(stage_params, x_mb) -> y_mb applies ONE stage;
    stacked_params is a pytree whose leaves have a leading stage axis of
    size S = mesh.shape[axis]; x is the full batch [B, ...] with B
    divisible by num_microbatches.
    """
    def apply3(params, x_mb, _me):
        # shard_map delivers the stage-sharded leaves with a size-1
        # leading dim — strip it so stage_apply sees one stage's params
        params = jax.tree_util.tree_map(lambda l: l[0], params)
        return stage_apply(params, x_mb)

    specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    return _run_pipe(apply3, stacked_params, specs, x, mesh, axis,
                     num_microbatches)


# ---------------------------------------------------------------------------
# 1F1B: pipelined TRAINING STEP (fwd + loss + bwd in one schedule)
# ---------------------------------------------------------------------------

def _1f1b_loop(stage_params, x_loc, y_loc, stage_apply, loss_fn,
               axis_name: str, m_real: int, s_total: int):
    """Per-device lockstep 1F1B loop (runs under shard_map).

    Why a separate schedule: ``jax.grad`` THROUGH the gpipe fori_loop
    stores every tick's residuals — per device that is O(M) microbatch
    activations plus stage intermediates.  1F1B starts microbatch m's
    backward the same tick its forward clears the last stage (the loss
    lives INSIDE the schedule), so a stage needs at most 2(S-1)+1
    in-flight stage-inputs: a RING of static size R = 2S-1, independent
    of M.  The backward recomputes the stage forward from the saved
    input (jax.vjp at backward time — full-remat pipeline, the standard
    trade: O(S·mb) memory for one extra forward of compute).

    Timing (stage s, microbatch m, S stages): F at tick m+s; loss+its
    backward at the last stage the SAME tick its F completes
    (m+S-1); B at stage s at tick m + 2(S-1) - s.  Total ticks
    M + 2S - 2 — the same (S-1)/(M+S-1) bubble FRACTION as GPipe
    (each tick does 1F+1B instead of twice the ticks at half the
    work); the win is memory, not bubble.

    Returns (loss_sum, grads_local, dx_loc): the summed per-microbatch
    losses (psum'd), this device's stage-parameter cotangents, and the
    home shard of input cotangents.
    """
    me = jax.lax.axis_index(axis_name)
    chunk = x_loc.shape[0]
    m_total = chunk * s_total        # static: shapes depend on it
    ring_n = 2 * s_total - 1
    ticks = m_total + 2 * s_total - 2

    perm_down = [(i, i + 1) for i in range(s_total - 1)]
    perm_up = [(i + 1, i) for i in range(s_total - 1)]

    def strip(tree):
        return jax.tree_util.tree_map(lambda l: l[0], tree)

    params_me = strip(stage_params)
    carry_f0 = jnp.zeros_like(x_loc[0])
    ring0 = jnp.zeros((ring_n,) + x_loc.shape[1:], x_loc.dtype)
    # the bwd carry rides the STAGE-BOUNDARY shape (uniform, like fwd)
    carry_b0 = jnp.zeros_like(x_loc[0])
    grads0 = jax.tree_util.tree_map(jnp.zeros_like, params_me)
    dx_loc0 = jnp.zeros_like(x_loc)
    LAST_PIPE_SHAPES.update(ring=ring0.shape, ticks_1f1b=ticks)

    def fwd_of(p, xi):
        return stage_apply(p, xi)

    def tick(t, state):
        carry_f, carry_b, ring, grads, dx_loc, loss_sum = state

        # ---- forward lane: stage me runs F of microbatch mf = t - me.
        # The x feed is for STAGE 0's microbatch — a UNIFORM index
        # (every device must agree on whose microbatch rides the masked
        # psum; a per-device index would mix different requests)
        feed_idx = jnp.clip(t, 0, m_total - 1)
        mine = jax.lax.dynamic_index_in_dim(
            x_loc, feed_idx % chunk, 0, keepdims=False)
        feed = _coll.psum(
            jnp.where((me == feed_idx // chunk) & (t < m_total),
                      mine, 0), axis_name)
        inp = jnp.where(me == 0, feed, carry_f)
        # save the stage input (only when this device's F is real)
        mf = t - me
        f_valid = (mf >= 0) & (mf < m_total)
        slot = jnp.clip(mf, 0, m_total - 1) % ring_n
        old = jax.lax.dynamic_index_in_dim(ring, slot, 0, keepdims=False)
        ring = jax.lax.dynamic_update_index_in_dim(
            ring, jnp.where(f_valid, inp, old), slot, 0)
        out_f = fwd_of(params_me, inp)

        # ---- loss at the last stage, same tick as its F.  The target
        # feed is for STAGE S-1's microbatch — again a uniform index
        last_mb = t - (s_total - 1)
        last_idx = jnp.clip(last_mb, 0, m_total - 1)
        y_mine = jax.lax.dynamic_index_in_dim(
            y_loc, last_idx % chunk, 0, keepdims=False)
        y_feed = _coll.psum(
            jnp.where(me == last_idx // chunk, y_mine, 0), axis_name)
        # at stage S-1, B(m) shares the tick with F(m): differentiate
        # the loss of THIS tick's forward output
        loss_m, dy_local = jax.value_and_grad(loss_fn)(
            out_f.astype(jnp.float32), y_feed)
        loss_sum = loss_sum + jnp.where(
            (last_mb >= 0) & (last_mb < m_real) & (me == s_total - 1),
            loss_m, 0.0)

        # ---- backward lane: B of microbatch mb from the saved input
        mb = t - (2 * (s_total - 1) - me)
        b_valid = (mb >= 0) & (mb < m_real)
        mb_c = jnp.clip(mb, 0, m_total - 1)
        cot = jnp.where(me == s_total - 1,
                        dy_local.astype(carry_b.dtype), carry_b)
        cot = jnp.where(b_valid, cot, 0)
        saved = jax.lax.dynamic_index_in_dim(
            ring, mb_c % ring_n, 0, keepdims=False)
        _, pull = jax.vjp(fwd_of, params_me, saved)
        dp, dxi = pull(cot.astype(out_f.dtype))
        grads = jax.tree_util.tree_map(jnp.add, grads, dp)

        # stage 0's dxi is the pipeline-input cotangent: home it with
        # the uniform STAGE-0 backward index
        dx_mb = t - 2 * (s_total - 1)
        dx_idx = jnp.clip(dx_mb, 0, m_total - 1)
        dx_bcast = _coll.psum(
            jnp.where(me == 0, dxi, 0), axis_name)
        hslot = dx_idx % chunk
        old_dx = jax.lax.dynamic_index_in_dim(dx_loc, hslot, 0,
                                              keepdims=False)
        dx_loc = jax.lax.dynamic_update_index_in_dim(
            dx_loc, jnp.where((dx_mb >= 0) & (dx_mb < m_real)
                              & (me == dx_idx // chunk),
                              dx_bcast, old_dx), hslot, 0)

        carry_f = _coll.ppermute(out_f, axis_name, perm_down)
        carry_b = _coll.ppermute(dxi, axis_name, perm_up)
        return carry_f, carry_b, ring, grads, dx_loc, loss_sum

    _, _, _, grads, dx_loc, loss_sum = jax.lax.fori_loop(
        0, ticks, tick, (carry_f0, carry_b0, ring0, grads0, dx_loc0,
                         jnp.float32(0.0)))
    return _coll.psum(loss_sum, axis_name), grads, dx_loc


def one_f_one_b(stage_apply: Callable, loss_fn: Callable, stacked_params,
                x, targets, mesh: Mesh, axis: str = "pipe",
                num_microbatches: int = 1):
    """Pipelined training step with the 1F1B schedule.

    stage_apply(stage_params, x_mb) -> y_mb applies one stage;
    loss_fn(last_out_mb, target_mb) -> scalar per-microbatch loss;
    stacked_params has a leading stage axis S = mesh.shape[axis].

    Returns (loss, grads, dx): loss = mean over microbatches;
    grads = stacked [S, ...] parameter cotangents of the MEAN loss;
    dx [B, ...] input cotangents.  Unlike :func:`gpipe` + ``jax.grad``
    (which stashes O(M) tick residuals under autodiff), per-device
    activation memory is the 2S-1 slot ring — asserted in
    tests/test_parallel.py.
    """
    s = mesh.shape[axis]
    b = x.shape[0]
    m = num_microbatches
    assert b % m == 0, (b, m)
    x_mb = x.reshape((m, b // m) + x.shape[1:])
    t_mb = targets.reshape((m, b // m) + targets.shape[1:])
    m_pad = -m % s
    if m_pad:
        # jnp.pad, not concatenate-with-zeros — see _run_pipe
        x_mb = jnp.pad(x_mb, ((0, m_pad),) + ((0, 0),) * (x_mb.ndim - 1))
        t_mb = jnp.pad(t_mb, ((0, m_pad),) + ((0, 0),) * (t_mb.ndim - 1))

    specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    fn = shard_map_compat(
        functools.partial(_1f1b_loop, stage_apply=stage_apply,
                          loss_fn=loss_fn, axis_name=axis, m_real=m,
                          s_total=s),
        mesh=mesh,
        in_specs=(specs, P(axis), P(axis)),
        out_specs=(P(), specs, P(axis)),
    )
    stacked_params = pin_replicated(stacked_params, mesh)
    x_mb = pin_replicated(x_mb, mesh)
    t_mb = pin_replicated(t_mb, mesh)
    loss_sum, grads, dx_mb = fn(stacked_params, x_mb, t_mb)
    # mean over the real microbatches; grads follow the same scale.
    # shard_map concatenates the per-device (stripped) grad trees along
    # the leading axis — restore the [S, ...] stacked layout
    grads = jax.tree_util.tree_map(
        lambda g, p: (g / m).reshape(p.shape), grads, stacked_params)
    dx = dx_mb[:m].reshape((b,) + dx_mb.shape[2:]) / m
    return loss_sum / m, grads, dx


class Pipeline(Module):
    """Pipeline container over blocks (reference analogue: none —
    Sequential executes stages on one node, nn/Sequential.scala).

    ``Pipeline(blocks, num_microbatches)``; ``forward(x)`` runs
    sequentially (single-device correctness/testing), while
    :meth:`forward_on_mesh` runs the GPipe schedule over a mesh axis.
    len(blocks) must equal mesh-axis-size × blocks-per-stage.  When all
    blocks share a pytree structure the stage parameters are stacked and
    sharded over the axis; otherwise stages run via ``lax.switch`` with
    replicated parameters (see module docstring)."""

    def __init__(self, blocks: List[Module], num_microbatches: int = 1):
        super().__init__()
        self.blocks = ModuleList(blocks)
        self.num_microbatches = num_microbatches
        self.pipe_mesh = None
        self.pipe_axis = "pipe"

    def set_mesh(self, mesh: Mesh, axis: str = "pipe") -> "Pipeline":
        """Route ``forward`` through the GPipe schedule on this mesh, so
        the container composes with the Optimizer (whose jitted step
        just calls ``model.forward``)."""
        self.pipe_mesh = mesh
        self.pipe_axis = axis
        return self

    def forward(self, x):
        if self.pipe_mesh is not None:
            return self.forward_on_mesh(x, self.pipe_mesh, self.pipe_axis)
        for blk in self.blocks:
            x = blk(x)
        return x

    def _stacked(self):
        """Stack per-block pytrees leaf-wise onto a leading stage axis.
        Positional (leaf-list) stacking under block 0's treedef, so
        blocks differing only in display ``name`` still stack."""
        trees = list(self.blocks)
        flats = [jax.tree_util.tree_flatten(t)[0] for t in trees]
        treedef0 = jax.tree_util.tree_structure(trees[0])
        stacked = [jnp.stack(ls) for ls in zip(*flats)]
        return jax.tree_util.tree_unflatten(treedef0, stacked)

    @staticmethod
    def _struct_sig(obj):
        """Structural signature ignoring the display ``name`` (pure
        metadata) but keeping everything that affects compute: classes,
        param/buffer slots, static config, leaf shapes/dtypes.  Blocks
        renamed for logging must still take the sharded stacked path —
        falling back to the switch path replicates ALL stages' params
        on every device (an S-fold memory regression)."""
        from bigdl_tpu.core.module import Module, ModuleList

        def rec(o):
            if isinstance(o, Module):
                return (type(o), tuple(o._params.keys()),
                        tuple((n, tuple(b.shape), str(b.dtype))
                              for n, b in o._buffers.items()),
                        tuple((n, tuple(p.shape), str(p.dtype))
                              for n, p in o._params.items()),
                        tuple((n, rec(m)) for n, m in o._modules.items()),
                        tuple(sorted(o._static.items(),
                                     key=lambda kv: kv[0])),
                        o.training)
            if isinstance(o, ModuleList):
                return ("modlist", tuple(rec(m) for m in o._items))
            return ("leaf",)

        return rec(obj)

    def _blocks_homogeneous(self) -> bool:
        """True when EVERY block shares a compute-equivalent structure —
        the stacked path stacks per-block leaves, so per-stage
        similarity is not enough (e.g. [Linear, ReLU] × S must take the
        switch path even though the stages match each other)."""
        sigs = [self._struct_sig(b) for b in self.blocks]
        return all(s == sigs[0] for s in sigs[1:])

    def forward_on_mesh(self, x, mesh: Mesh, axis: str = "pipe"):
        s = mesh.shape[axis]
        n = len(self.blocks)
        assert n % s == 0, (n, s)
        per_stage = n // s

        if self._blocks_homogeneous():
            LAST_PIPE_SHAPES["layout"] = "stacked"
            return self._forward_stacked(x, mesh, axis, s, per_stage)
        LAST_PIPE_SHAPES["layout"] = "switch"
        groups = tuple(tuple(list(self.blocks)[i:i + per_stage])
                       for i in range(0, n, per_stage))
        return self._forward_hetero(x, groups, mesh, axis, s)

    def _forward_stacked(self, x, mesh, axis, s, per_stage):
        def stage_apply(stage_tree, x_mb):
            # stage_tree leaves: [per_stage, ...] — apply blocks in order
            def one(i, acc):
                blk = jax.tree_util.tree_map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, i, 0, keepdims=False), stage_tree)
                return blk(acc)
            return jax.lax.fori_loop(0, per_stage, one, x_mb)

        # regroup the N stacked blocks as [S, per_stage, ...]
        stacked = jax.tree_util.tree_map(
            lambda l: l.reshape((s, per_stage) + l.shape[1:]),
            self._stacked())

        return gpipe(stage_apply, stacked, x, mesh, axis,
                     self.num_microbatches)

    def train_step_on_mesh(self, x, targets, loss_fn, mesh: Mesh = None,
                           axis: str = None, ):
        """1F1B pipelined training step: ``(loss, grads, dx)`` where
        grads is the stacked [S, per_stage, ...] parameter-cotangent
        pytree of the mean-over-microbatches loss (see
        :func:`one_f_one_b`).  Requires the homogeneous stacked layout —
        the memory benefit is pointless with replicated parameters."""
        mesh = mesh if mesh is not None else self.pipe_mesh
        axis = axis if axis is not None else self.pipe_axis
        if not self._blocks_homogeneous():
            raise NotImplementedError(
                "1F1B needs the stacked (homogeneous) stage layout; "
                "group blocks into structurally-equal stages")
        s = mesh.shape[axis]
        n = len(self.blocks)
        assert n % s == 0, (n, s)
        per_stage = n // s

        def stage_apply(stage_tree, x_mb):
            def one(i, acc):
                blk = jax.tree_util.tree_map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, i, 0, keepdims=False), stage_tree)
                return blk(acc)
            return jax.lax.fori_loop(0, per_stage, one, x_mb)

        stacked = jax.tree_util.tree_map(
            lambda l: l.reshape((s, per_stage) + l.shape[1:]),
            self._stacked())
        return one_f_one_b(stage_apply, loss_fn, stacked, x, targets,
                           mesh, axis, self.num_microbatches)

    def _forward_hetero(self, x, groups, mesh, axis, s):
        """Structurally-different stages: one lax.switch over per-stage
        bodies; parameters ride along replicated (SPMD programs must
        agree across devices).  Every stage must map [mb, ...] to the
        SAME shape (see module docstring)."""
        params = groups  # pytree: tuple of tuples of Modules

        def stage_apply(groups_, x_mb, me):
            def branch(i):
                def run(x_mb):
                    y = x_mb
                    for blk in groups_[i]:
                        y = blk(y)
                    return y
                return run
            return jax.lax.switch(me, [branch(i) for i in range(s)], x_mb)

        specs = jax.tree_util.tree_map(lambda _: P(), params)
        return _run_pipe(stage_apply, params, specs, x, mesh, axis,
                         self.num_microbatches)
