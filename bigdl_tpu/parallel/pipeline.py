"""Pipeline parallelism over a mesh axis.

The reference has NO pipeline parallelism (SURVEY §2.6) — this is new,
TPU-first capability.  The design is the collective-permute pipeline
from the scaling playbook: the stages of a deep network are sharded over
the ``pipe`` mesh axis; microbatches stream through: at every tick each
device applies its stage to the activation it holds, then passes the
result to the next device with ``lax.ppermute`` (ICI
neighbor-to-neighbor).  A full batch of M microbatches over S stages
drains in M + S - 1 ticks (GPipe schedule; bubble fraction
(S-1)/(M+S-1)).

Memory (the r03 verdict's weak spot, fixed): the microbatch buffers are
SHARDED over the pipe axis — each device holds M/S input microbatches,
M/S output slots, and ONE working activation.  Each tick moves exactly
one microbatch: the feeding stage broadcasts the current input (a
masked psum of one [mb, ...] tensor), the last stage broadcasts its
emission, and every device keeps only the slots it is home to.
Per-device activation memory is O(B/S + mb), never the full batch.
When M is not divisible by S, the schedule pads with dummy microbatches
(compute waste, not memory).

Two parameter layouts:

* homogeneous stages (all blocks share a pytree structure): parameters
  stack on a leading stage axis and SHARD over the pipe axis — each
  device materializes only its own stage's weights.
* heterogeneous stages: parameters are passed replicated and the stage
  body is a ``lax.switch`` over per-stage functions (SPMD programs must
  agree, so heterogeneity costs parameter replication — documented
  trade-off; group your blocks into structurally-equal stages to get
  sharded parameters back).  The activation shape at every stage
  BOUNDARY must be uniform — the carry rides one ppermute buffer — so
  width changes must happen inside a stage, not across stages (an
  inherent constraint of SPMD collective-permute pipelines).

``gpipe`` is the functional entry; :class:`Pipeline` wraps a list of
Modules and picks the layout automatically.
"""

from __future__ import annotations

import functools
from typing import Callable, List

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.core.module import Module, ModuleList

__all__ = ["gpipe", "Pipeline"]

# Per-device (inside-shard_map) buffer shapes of the most recent pipeline
# trace — a debug/test hook (module attrs would pollute the pytree).
LAST_PIPE_SHAPES = {}


def _pipe_loop(stage_params, x_loc, stage_apply, axis_name: str):
    """Per-device pipeline loop (runs under shard_map).

    stage_params: this device's stage parameters (sharded stacked
    leaves, or a replicated tuple of per-stage trees for heterogeneous
    stages — ``stage_apply`` knows which).
    x_loc: [M/S, mb, ...] THIS DEVICE'S shard of the microbatch ring.
    Returns [M/S, mb, ...]: the device's home shard of the outputs.
    """
    s_total = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    chunk = x_loc.shape[0]                     # M/S microbatches here
    m_total = chunk * s_total
    ticks = m_total + s_total - 1

    out_loc0 = jnp.zeros_like(x_loc)
    carry0 = jnp.zeros_like(x_loc[0])
    perm = [(i, i + 1) for i in range(s_total - 1)]
    LAST_PIPE_SHAPES.update(x_loc=x_loc.shape, carry=carry0.shape,
                            out_loc=out_loc0.shape)

    def tick(t, state):
        carry, out_loc = state
        # one microbatch enters the pipe per tick: its home device
        # broadcasts it (masked psum of a single [mb, ...] tensor)
        feed_idx = jnp.clip(t, 0, m_total - 1)
        mine = jax.lax.dynamic_index_in_dim(
            x_loc, feed_idx % chunk, 0, keepdims=False)
        feed = jax.lax.psum(
            jnp.where(me == feed_idx // chunk, mine, 0), axis_name)
        inp = jnp.where(me == 0, feed, carry)
        out = stage_apply(stage_params, inp, me)
        # the last stage emits microbatch t-(S-1); its output is
        # broadcast the same way and stored only by its home device
        emit_idx = jnp.clip(t - (s_total - 1), 0, m_total - 1)
        valid = t >= s_total - 1
        y = jax.lax.psum(
            jnp.where(valid & (me == s_total - 1), out, 0), axis_name)
        hslot = emit_idx % chunk
        old = jax.lax.dynamic_index_in_dim(out_loc, hslot, 0,
                                           keepdims=False)
        upd = jnp.where(valid & (me == emit_idx // chunk), y, old)
        out_loc = jax.lax.dynamic_update_index_in_dim(
            out_loc, upd, hslot, 0)
        carry = jax.lax.ppermute(out, axis_name, perm)
        return carry, out_loc

    _, out_loc = jax.lax.fori_loop(0, ticks, tick, (carry0, out_loc0))
    return out_loc


def _run_pipe(stage_apply, stacked_params, param_specs, x, mesh,
              axis: str, num_microbatches: int):
    """Shared driver: microbatch split + pad to a multiple of S, the
    sharded shard_map call, unpad."""
    s = mesh.shape[axis]
    b = x.shape[0]
    m = num_microbatches
    assert b % m == 0, (b, m)
    x_mb = x.reshape((m, b // m) + x.shape[1:])
    m_pad = -m % s
    if m_pad:
        # pad the schedule with dummy microbatches so the ring shards
        # evenly; costs bubble compute, not memory
        x_mb = jnp.concatenate(
            [x_mb, jnp.zeros((m_pad,) + x_mb.shape[1:], x_mb.dtype)], 0)

    fn = jax.shard_map(
        functools.partial(_pipe_loop, stage_apply=stage_apply,
                          axis_name=axis),
        mesh=mesh,
        in_specs=(param_specs, P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )
    y_mb = fn(stacked_params, x_mb)[:m]
    return y_mb.reshape((b,) + y_mb.shape[2:])


def gpipe(stage_apply: Callable, stacked_params, x, mesh: Mesh,
          axis: str = "pipe", num_microbatches: int = 1):
    """Run ``x`` through S pipeline stages sharded over ``axis``.

    stage_apply(stage_params, x_mb) -> y_mb applies ONE stage;
    stacked_params is a pytree whose leaves have a leading stage axis of
    size S = mesh.shape[axis]; x is the full batch [B, ...] with B
    divisible by num_microbatches.
    """
    def apply3(params, x_mb, _me):
        # shard_map delivers the stage-sharded leaves with a size-1
        # leading dim — strip it so stage_apply sees one stage's params
        params = jax.tree_util.tree_map(lambda l: l[0], params)
        return stage_apply(params, x_mb)

    specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    return _run_pipe(apply3, stacked_params, specs, x, mesh, axis,
                     num_microbatches)


class Pipeline(Module):
    """Pipeline container over blocks (reference analogue: none —
    Sequential executes stages on one node, nn/Sequential.scala).

    ``Pipeline(blocks, num_microbatches)``; ``forward(x)`` runs
    sequentially (single-device correctness/testing), while
    :meth:`forward_on_mesh` runs the GPipe schedule over a mesh axis.
    len(blocks) must equal mesh-axis-size × blocks-per-stage.  When all
    blocks share a pytree structure the stage parameters are stacked and
    sharded over the axis; otherwise stages run via ``lax.switch`` with
    replicated parameters (see module docstring)."""

    def __init__(self, blocks: List[Module], num_microbatches: int = 1):
        super().__init__()
        self.blocks = ModuleList(blocks)
        self.num_microbatches = num_microbatches
        self.pipe_mesh = None
        self.pipe_axis = "pipe"

    def set_mesh(self, mesh: Mesh, axis: str = "pipe") -> "Pipeline":
        """Route ``forward`` through the GPipe schedule on this mesh, so
        the container composes with the Optimizer (whose jitted step
        just calls ``model.forward``)."""
        self.pipe_mesh = mesh
        self.pipe_axis = axis
        return self

    def forward(self, x):
        if self.pipe_mesh is not None:
            return self.forward_on_mesh(x, self.pipe_mesh, self.pipe_axis)
        for blk in self.blocks:
            x = blk(x)
        return x

    def _stacked(self):
        """Stack per-block pytrees leaf-wise onto a leading stage axis.
        Positional (leaf-list) stacking under block 0's treedef, so
        blocks differing only in display ``name`` still stack."""
        trees = list(self.blocks)
        flats = [jax.tree_util.tree_flatten(t)[0] for t in trees]
        treedef0 = jax.tree_util.tree_structure(trees[0])
        stacked = [jnp.stack(ls) for ls in zip(*flats)]
        return jax.tree_util.tree_unflatten(treedef0, stacked)

    @staticmethod
    def _struct_sig(obj):
        """Structural signature ignoring the display ``name`` (pure
        metadata) but keeping everything that affects compute: classes,
        param/buffer slots, static config, leaf shapes/dtypes.  Blocks
        renamed for logging must still take the sharded stacked path —
        falling back to the switch path replicates ALL stages' params
        on every device (an S-fold memory regression)."""
        from bigdl_tpu.core.module import Module, ModuleList

        def rec(o):
            if isinstance(o, Module):
                return (type(o), tuple(o._params.keys()),
                        tuple((n, tuple(b.shape), str(b.dtype))
                              for n, b in o._buffers.items()),
                        tuple((n, tuple(p.shape), str(p.dtype))
                              for n, p in o._params.items()),
                        tuple((n, rec(m)) for n, m in o._modules.items()),
                        tuple(sorted(o._static.items(),
                                     key=lambda kv: kv[0])),
                        o.training)
            if isinstance(o, ModuleList):
                return ("modlist", tuple(rec(m) for m in o._items))
            return ("leaf",)

        return rec(obj)

    def _blocks_homogeneous(self) -> bool:
        """True when EVERY block shares a compute-equivalent structure —
        the stacked path stacks per-block leaves, so per-stage
        similarity is not enough (e.g. [Linear, ReLU] × S must take the
        switch path even though the stages match each other)."""
        sigs = [self._struct_sig(b) for b in self.blocks]
        return all(s == sigs[0] for s in sigs[1:])

    def forward_on_mesh(self, x, mesh: Mesh, axis: str = "pipe"):
        s = mesh.shape[axis]
        n = len(self.blocks)
        assert n % s == 0, (n, s)
        per_stage = n // s

        if self._blocks_homogeneous():
            LAST_PIPE_SHAPES["layout"] = "stacked"
            return self._forward_stacked(x, mesh, axis, s, per_stage)
        LAST_PIPE_SHAPES["layout"] = "switch"
        groups = tuple(tuple(list(self.blocks)[i:i + per_stage])
                       for i in range(0, n, per_stage))
        return self._forward_hetero(x, groups, mesh, axis, s)

    def _forward_stacked(self, x, mesh, axis, s, per_stage):
        def stage_apply(stage_tree, x_mb):
            # stage_tree leaves: [per_stage, ...] — apply blocks in order
            def one(i, acc):
                blk = jax.tree_util.tree_map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, i, 0, keepdims=False), stage_tree)
                return blk(acc)
            return jax.lax.fori_loop(0, per_stage, one, x_mb)

        # regroup the N stacked blocks as [S, per_stage, ...]
        stacked = jax.tree_util.tree_map(
            lambda l: l.reshape((s, per_stage) + l.shape[1:]),
            self._stacked())

        return gpipe(stage_apply, stacked, x, mesh, axis,
                     self.num_microbatches)

    def _forward_hetero(self, x, groups, mesh, axis, s):
        """Structurally-different stages: one lax.switch over per-stage
        bodies; parameters ride along replicated (SPMD programs must
        agree across devices).  Every stage must map [mb, ...] to the
        SAME shape (see module docstring)."""
        params = groups  # pytree: tuple of tuples of Modules

        def stage_apply(groups_, x_mb, me):
            def branch(i):
                def run(x_mb):
                    y = x_mb
                    for blk in groups_[i]:
                        y = blk(y)
                    return y
                return run
            return jax.lax.switch(me, [branch(i) for i in range(s)], x_mb)

        specs = jax.tree_util.tree_map(lambda _: P(), params)
        return _run_pipe(stage_apply, params, specs, x, mesh, axis,
                         self.num_microbatches)
