"""Device mesh construction and topology discovery.

Reference equivalence: utils/Engine.scala:499-600 parses the Spark master
URL into (nodeNumber, coreNumber); here topology comes from
``jax.devices()`` and the mesh axes replace the reference's
executor×thread grid.  The reference's single parallelism axis (data)
generalizes to the full axis set {data, fsdp, model(tensor), pipe,
seq, expert} — absent in the reference (SURVEY §2.6) but first-class
here.

The canonical axis names used across the framework:

* ``data``  — batch sharding (≙ AllReduceParameter data parallelism)
* ``fsdp``  — parameter/optimizer-state sharding combined with data
* ``model`` — tensor parallelism (megatron-style)
* ``pipe``  — pipeline stages
* ``seq``   — sequence/context parallelism (ring attention)
* ``expert``— MoE expert parallelism
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_parallel_mesh", "MeshConfig", "P",
           "NamedSharding", "Mesh", "local_device_count", "batch_sharding"]

AXES = ("data", "fsdp", "model", "pipe", "seq", "expert")


def local_device_count() -> int:
    """Devices attached to THIS host (jax.local_device_count);
    use len(jax.devices()) for the global count."""
    return jax.local_device_count()


def _infer(shape: Dict[str, int], n: int) -> Dict[str, int]:
    """Resolve a single -1 entry so the product equals n."""
    known = 1
    unknown = None
    for k, v in shape.items():
        if v == -1:
            if unknown is not None:
                raise ValueError("only one mesh axis may be -1")
            unknown = k
        else:
            known *= v
    if unknown is not None:
        if n % known:
            raise ValueError(
                f"mesh axes {shape} don't divide device count {n}")
        shape = dict(shape)
        shape[unknown] = n // known
    else:
        prod = known
        if prod > n:
            raise ValueError(
                f"mesh axes {shape} (={prod}) exceed device count {n}")
        # prod < n: use the first prod devices (≙ running on a subset
        # of executors)
    return shape


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices=None) -> Mesh:
    """Build a Mesh over the given axes (dict axis→size; one may be -1).

    Axis order follows AXES so that the innermost (fastest-varying,
    best-ICI-locality) axis is the model/tensor axis — collectives for
    TP ride nearest-neighbour ICI links while DP gradients ride the
    outer dimensions, matching create_device_mesh's locality heuristics.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    axes = _infer(dict(axes), n)
    names = [a for a in AXES if a in axes]
    extra = [a for a in axes if a not in AXES]
    names += extra
    sizes = tuple(axes[a] for a in names)
    prod = int(np.prod(sizes))
    if prod < n:
        devices = devices[:prod]
    try:
        from jax.experimental import mesh_utils
        mesh_devices = mesh_utils.create_device_mesh(
            sizes, devices=devices)
    except Exception:
        mesh_devices = np.array(devices).reshape(sizes)
    return Mesh(mesh_devices, tuple(names))


def data_parallel_mesh(devices=None) -> Mesh:
    """All devices on one ``data`` axis — the reference's only strategy
    (AllReduceParameter over nodes; SURVEY §2.6)."""
    return make_mesh({"data": -1}, devices)


def batch_sharding(mesh: Mesh, *, extra_axes: Sequence[str] = ()) \
        -> NamedSharding:
    """Sharding for a batch-leading array: batch dim over every
    data-like axis present in the mesh."""
    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)
    spec = P(batch_axes if batch_axes else None, *extra_axes)
    return NamedSharding(mesh, spec)


class MeshConfig:
    """Declarative parallelism config used by the Optimizer (the
    TPU-native replacement for the reference's Engine node/core conf).

    Example::

        MeshConfig(data=-1)                      # pure DP (default)
        MeshConfig(data=2, model=4)              # DP×TP
        MeshConfig(data=2, pipe=2, model=2)      # 3D
    """

    def __init__(self, **axes: int):
        self.axes = axes or {"data": -1}

    def build(self, devices=None) -> Mesh:
        return make_mesh(self.axes, devices)
