"""Device mesh construction and topology discovery.

Reference equivalence: utils/Engine.scala:499-600 parses the Spark master
URL into (nodeNumber, coreNumber); here topology comes from
``jax.devices()`` and the mesh axes replace the reference's
executor×thread grid.  The reference's single parallelism axis (data)
generalizes to the full axis set {data, fsdp, model(tensor), pipe,
seq, expert} — absent in the reference (SURVEY §2.6) but first-class
here.

The canonical axis names used across the framework:

* ``dcn``   — the slow inter-slice network tier (data-center network
  between ICI slices); batch-like, but gradient sync across it should
  go through ``parallel.hierarchy`` (≙ the reference's inter-node
  links, whose slowness motivated FP16CompressedTensor)
* ``data``  — batch sharding (≙ AllReduceParameter data parallelism)
* ``fsdp``  — parameter/optimizer-state sharding combined with data
* ``model`` — tensor parallelism (megatron-style)
* ``pipe``  — pipeline stages
* ``seq``   — sequence/context parallelism (ring attention)
* ``expert``— MoE expert parallelism
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_parallel_mesh", "MeshConfig", "P",
           "NamedSharding", "Mesh", "local_device_count",
           "batch_sharding", "shard_map_compat", "axis_coord_maps",
           "mesh_axes", "pin_replicated"]


def pin_replicated(tree, mesh):
    """Pin every leaf to the fully-replicated layout before it enters a
    shard_map.  On multi-axis meshes GSPMD mispartitions IN-GRAPH
    producers of shard_map operands — a ``jnp.stack`` of per-stage /
    per-expert parameters or a pad of the microbatch ring compiled
    under jit silently yields values that DIVERGE from the eager result
    (observed on jax 0.4.37 CPU; exercised by the dp×pp / dp×ep
    training-equivalence tests and the partition-plan conformance
    matrix).  Forcing the operand replicated at the boundary removes
    the partitioner's freedom to misplace it; the shard_map's in_specs
    then carve the per-device shards themselves."""
    rep = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda l: jax.lax.with_sharding_constraint(l, rep), tree)


def shard_map_compat(f, mesh, in_specs, out_specs):
    """Version-compat shard_map — THE one spelling every module maps
    over a mesh with: ``jax.shard_map`` (with ``check_vma=False``)
    where the public name exists, else the ``jax.experimental``
    form (with the equivalent ``check_rep=False``).  Older jax
    releases only ship the experimental name, newer ones deprecate
    it; call sites that hardcode either spelling break on the other
    side of that line."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)

logger = logging.getLogger("bigdl_tpu.parallel")

# dcn is OUTERMOST (slowest-varying): devices of one slice stay
# contiguous in the flattened device order, so the fast axes ride
# nearest-neighbour ICI while only the dcn axis crosses slices
AXES = ("dcn", "data", "fsdp", "model", "pipe", "seq", "expert")

# the batch-like axes, in AXES order: a batch-leading array shards over
# every one of these present in the mesh
BATCH_AXES = ("dcn", "data", "fsdp")


def local_device_count() -> int:
    """Devices attached to THIS host (jax.local_device_count);
    use len(jax.devices()) for the global count."""
    return jax.local_device_count()


def _infer(shape: Dict[str, int], n: int) -> Dict[str, int]:
    """Resolve a single -1 entry so the product equals n."""
    known = 1
    unknown = None
    for k, v in shape.items():
        if v == -1:
            if unknown is not None:
                raise ValueError("only one mesh axis may be -1")
            unknown = k
        else:
            known *= v
    if unknown is not None:
        if n % known:
            raise ValueError(
                f"mesh axes {shape} don't divide device count {n}")
        shape = dict(shape)
        shape[unknown] = n // known
    else:
        prod = known
        if prod > n:
            raise ValueError(
                f"mesh axes {shape} (={prod}) exceed device count {n}")
        # prod < n: use the first prod devices (≙ running on a subset
        # of executors)
    return shape


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices=None) -> Mesh:
    """Build a Mesh over the given axes (dict axis→size; one may be -1).

    Axis order follows AXES so that the innermost (fastest-varying,
    best-ICI-locality) axis is the model/tensor axis — collectives for
    TP ride nearest-neighbour ICI links while DP gradients ride the
    outer dimensions, matching create_device_mesh's locality heuristics.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    axes = _infer(dict(axes), n)
    names = [a for a in AXES if a in axes]
    extra = [a for a in axes if a not in AXES]
    names += extra
    sizes = tuple(axes[a] for a in names)
    prod = int(np.prod(sizes))
    if prod < n:
        dropped = devices[prod:]
        logger.warning(
            "mesh axes %s cover only %d of %d devices; dropping device "
            "id(s) %s (pass -1 on one axis to use every device)",
            dict(zip(names, sizes)), prod, n,
            [getattr(d, "id", d) for d in dropped])
        devices = devices[:prod]
    mesh_devices = None
    if "dcn" in names and axes["dcn"] > 1:
        # the dcn axis must follow PHYSICAL slice boundaries or the
        # hierarchical sync inverts (full-width gradients over the
        # real DCN, compression on ICI): create_hybrid_device_mesh
        # places the dcn dim by slice_index and keeps ICI locality
        # within each slice.  Fake meshes (CPU devices carry no
        # slice_index) fall through to the flat path below, whose
        # dcn-outermost ordering IS the slice layout being simulated.
        try:
            from jax.experimental import mesh_utils
            ici_shape = tuple(1 if a == "dcn" else axes[a]
                              for a in names)
            dcn_shape = tuple(axes[a] if a == "dcn" else 1
                              for a in names)
            mesh_devices = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices)
        except Exception as e:
            if any(getattr(d, "slice_index", None) is not None
                   for d in devices):
                # a REAL multislice allocation where the hybrid layout
                # failed: the flat fallback may place the dcn axis
                # across physical slice boundaries — exactly the
                # inversion named above — so say so instead of
                # silently degrading
                logger.warning(
                    "create_hybrid_device_mesh failed on a multislice "
                    "allocation (%s); falling back to a flat device "
                    "mesh — the 'dcn' axis may not follow physical "
                    "slice boundaries, inverting the hierarchical "
                    "sync's fast/slow tiers", e)
            mesh_devices = None
    if mesh_devices is None:
        try:
            from jax.experimental import mesh_utils
            mesh_devices = mesh_utils.create_device_mesh(
                sizes, devices=devices)
        except Exception:
            mesh_devices = np.array(devices).reshape(sizes)
    return Mesh(mesh_devices, tuple(names))


def axis_coord_maps(mesh: Mesh) -> Dict[str, Dict[int, int]]:
    """``{axis: {logical_device_position: coordinate_along_axis}}`` for
    every mesh axis of size > 1 — the per-axis classifier inputs for
    :func:`bigdl_tpu.utils.xla_cost.per_axis_hlo_bytes`.

    HLO replica groups name devices by their position in the mesh's
    flattened device order (the same convention as
    ``parallel.hierarchy.dcn_slice_map``, which is this map's ``dcn``
    row).  Under the per-axis map a collective "crosses groups" exactly
    when one of its replica groups holds two devices with different
    coordinates along that axis — i.e. when its payload moves over that
    axis's links — so one compiled program classifies into a full
    {op, axis} byte matrix."""
    n = int(np.prod(mesh.devices.shape))
    out: Dict[str, Dict[int, int]] = {}
    for axis in mesh.axis_names:
        if mesh.shape[axis] <= 1:
            continue
        ai = mesh.axis_names.index(axis)
        coords = np.indices(mesh.devices.shape)[ai].reshape(-1)
        out[axis] = {i: int(coords[i]) for i in range(n)}
    return out


def mesh_axes(mesh: Mesh) -> Dict[str, int]:
    """``{axis: size}`` of a mesh — the canonical topology rendering
    the checkpoint manifest records (``utils/file.checkpoint_topology``)
    and the elastic N->M resume compares against the live mesh to
    decide whether a restore is resharding."""
    return {str(a): int(s) for a, s in
            zip(mesh.axis_names, mesh.devices.shape)}


def data_parallel_mesh(devices=None) -> Mesh:
    """All devices on one ``data`` axis — the reference's only strategy
    (AllReduceParameter over nodes; SURVEY §2.6)."""
    return make_mesh({"data": -1}, devices)


def batch_sharding(mesh: Mesh, *, extra_axes: Sequence[str] = ()) \
        -> NamedSharding:
    """Sharding for a batch-leading array: batch dim over every
    data-like axis present in the mesh (``dcn`` included — each slice
    consumes its own sub-batch, which is exactly what makes the
    hierarchical gradient sync's cross-slice hop small)."""
    batch_axes = tuple(a for a in BATCH_AXES if a in mesh.axis_names)
    spec = P(batch_axes if batch_axes else None, *extra_axes)
    return NamedSharding(mesh, spec)


class MeshConfig:
    """Declarative parallelism config used by the Optimizer (the
    TPU-native replacement for the reference's Engine node/core conf).

    Example::

        MeshConfig(data=-1)                      # pure DP (default)
        MeshConfig(data=2, model=4)              # DP×TP
        MeshConfig(data=2, pipe=2, model=2)      # 3D
        MeshConfig(dcn=2, data=-1)               # 2 slices × DP
    """

    def __init__(self, **axes: int):
        self.axes = axes or {"data": -1}

    def build(self, devices=None) -> Mesh:
        return make_mesh(self.axes, devices)
