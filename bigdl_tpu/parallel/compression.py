"""Gradient wire codecs for the slow (DCN) network tier.

Reference equivalence: ``parameters/FP16CompressedTensor.scala`` — the
reference halved gradient wire bytes because inter-node links were the
bottleneck at 256 nodes (whitepaper.md:150-196).  The TPU-native port
has the same two-tier problem one level up: ICI within a slice is
fast, the DCN hop between slices is slow, so
:func:`bigdl_tpu.parallel.hierarchy.hierarchical_grad_sync` compresses
ONLY the cross-slice payload with one of these codecs and accumulates
in fp32 on each side (compress → gather → decode → fp32 sum), exactly
the reference's compress-on-wire/decompress-to-accumulate discipline.

Two codecs, one contract (``encode`` → wire pytree, ``decode`` → fp32):

* :class:`Bf16Codec` — cast-to-bf16 (≙ ``FP16CompressedTensor``; bf16
  keeps fp32's exponent range so no overflow handling is needed).
  2 wire bytes/element, worst-case relative error ~2^-8.
* :class:`Int8Codec` — symmetric int8 with one fp32 scale per bucket
  (``max|x|/127`` over each ``bucket_size`` run of the flat vector) and
  optional stochastic rounding, which keeps the quantizer unbiased so
  errors average out across steps instead of accumulating as drift.
  ~1 wire byte/element (+ 4/bucket_size for scales); absolute error
  bounded by the bucket scale: ``|err| <= max|bucket|/127`` stochastic,
  half that deterministic.

Everything here is jit-traceable (shapes static, no host sync) so the
codecs compile straight into the train step around the DCN collective.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["Bf16Codec", "Int8Codec", "get_codec", "wire_itemsize",
           "wire_bytes"]

# floor for per-bucket scales: an all-zero bucket must decode to zeros,
# not NaN from 0/0
_SCALE_FLOOR = 1e-30


class Bf16Codec:
    """Cast-to-bf16 wire format (≙ FP16CompressedTensor)."""

    name = "bf16"
    wire_bytes_per_element = 2.0

    def encode(self, flat: jax.Array, key=None) -> Tuple[jax.Array]:
        return (flat.astype(jnp.bfloat16),)

    def decode(self, parts: Tuple[jax.Array], size: int) -> jax.Array:
        return parts[0].astype(jnp.float32)


class Int8Codec:
    """Symmetric int8 with per-bucket fp32 scales and stochastic
    rounding.

    ``encode`` pads the flat fp32 vector to a multiple of
    ``bucket_size``, scales each bucket by ``max|bucket|/127``, and
    rounds — stochastically when a PRNG ``key`` is given (unbiased:
    ``E[decode(encode(x))] == x``), round-to-nearest otherwise.
    ``decode`` multiplies back and strips the pad.  The quantization
    grid step IS the bucket scale, so the round-trip error of element
    ``e`` in bucket ``b`` is bounded by ``max|b|/127`` (stochastic) /
    half that (nearest) — the bound a unit test pins.
    """

    name = "int8"

    def __init__(self, bucket_size: int = 512, stochastic: bool = True):
        if bucket_size < 1:
            raise ValueError(f"bucket_size must be >= 1, got {bucket_size}")
        self.bucket_size = int(bucket_size)
        self.stochastic = bool(stochastic)

    @property
    def wire_bytes_per_element(self) -> float:
        # 1 int8 byte per element + one f32 scale per bucket
        return 1.0 + 4.0 / self.bucket_size

    def encode(self, flat: jax.Array, key=None) \
            -> Tuple[jax.Array, jax.Array]:
        n = flat.shape[0]
        # clamp the bucket to the vector: a gradient shard SMALLER than
        # bucket_size must not be zero-padded up to a full bucket, or
        # the "compressed" wire ends up larger than flat fp32 (decode
        # is shape-driven, so the clamp never has to be communicated)
        b = min(self.bucket_size, max(int(n), 1))
        pad = (-n) % b
        if pad:
            flat = jnp.pad(flat, (0, pad))
        buckets = flat.reshape(-1, b)
        scale = jnp.maximum(jnp.max(jnp.abs(buckets), axis=1) / 127.0,
                            _SCALE_FLOOR)
        v = buckets / scale[:, None]
        if self.stochastic and key is not None:
            # floor(v + u), u ~ U[0,1): E = v, so quantization noise is
            # zero-mean across steps instead of a deterministic bias
            v = jnp.floor(v + jax.random.uniform(key, v.shape))
        else:
            v = jnp.round(v)
        q = jnp.clip(v, -127, 127).astype(jnp.int8)
        return q, scale

    def decode(self, parts: Tuple[jax.Array, jax.Array],
               size: int) -> jax.Array:
        q, scale = parts
        out = q.astype(jnp.float32) * scale[:, None].astype(jnp.float32)
        return out.reshape(-1)[:size]


def get_codec(wire_dtype):
    """Resolve a user-facing ``wire_dtype`` to a codec instance.

    Accepts None (no compression), the strings ``"bf16"`` / ``"int8"``,
    the matching jnp dtypes, or an already-constructed codec (so a
    caller can tune ``Int8Codec(bucket_size=..., stochastic=...)``).
    """
    if wire_dtype is None:
        return None
    if isinstance(wire_dtype, (Bf16Codec, Int8Codec)):
        return wire_dtype
    name = None
    if isinstance(wire_dtype, str):
        name = wire_dtype.lower()
    else:
        try:
            name = jnp.dtype(wire_dtype).name
        except TypeError:
            pass
    if name in ("bf16", "bfloat16"):
        return Bf16Codec()
    if name in ("int8", "s8"):
        return Int8Codec()
    if name in ("fp32", "float32", "f32", "none"):
        return None
    raise ValueError(
        f"unknown gradient wire dtype {wire_dtype!r}: expected None, "
        f"'bf16', 'int8', a matching jnp dtype, or a codec instance")


def wire_itemsize(wire_dtype) -> float:
    """NOMINAL wire bytes per gradient element for a ``wire_dtype``
    (4.0 uncompressed) — the asymptotic factor for shards much larger
    than the int8 bucket.  The analytic comm floor uses
    :func:`wire_bytes`, which also accounts for ``encode()``'s bucket
    clamp on small shards."""
    codec = get_codec(wire_dtype)
    return 4.0 if codec is None else float(codec.wire_bytes_per_element)


def wire_bytes(wire_dtype, n_elements, n_chunks: int = 1) -> float:
    """Wire bytes ONE hop moves for an ``n_elements``-long fp32 payload
    split into ``n_chunks`` separately encoded chunks (``4.0 * n``
    uncompressed).  Unlike the nominal :func:`wire_itemsize` factor,
    this models ``Int8Codec.encode``'s bucket clamp: a chunk SMALLER
    than ``bucket_size`` still pays one full fp32 scale, so small
    shards carry proportionally more scale overhead — the factor the
    analytic comm floor (``parallel.sharding.grad_allreduce_bytes``)
    applies to the DCN hop, kept here so estimate and codec can't
    drift.  Sub-chunk zero padding is ignored (as elsewhere in the
    estimator)."""
    codec = get_codec(wire_dtype)
    n = max(int(n_elements), 0)
    if codec is None or n == 0:
        return 4.0 * n
    bucket = getattr(codec, "bucket_size", None)
    if bucket is None:
        return float(codec.wire_bytes_per_element) * n
    chunks = max(int(n_chunks), 1)
    k = -(-n // chunks)                    # ceil: elements per chunk
    b = min(int(bucket), max(k, 1))        # encode()'s clamp
    scales = chunks * (-(-k // b))
    return 1.0 * n + 4.0 * scales
