"""Parameter/activation sharding rules.

This module is the TPU-native replacement for the reference's entire
communication layer (parameters/AllReduceParameter.scala:81-331,
models/utils/ModelBroadcast.scala, utils/DistriParameterSynchronizer):
instead of sharding gradient *bytes* across BlockManagers and manually
re-publishing weights, we annotate every parameter leaf with a
``NamedSharding`` and let XLA insert the collectives (psum /
reduce-scatter / all-gather) into the compiled step — the "weight
broadcast" is the sharding itself, and straggler dropping disappears
under SPMD lockstep.

Rules map parameter paths (e.g. ``"fc1.weight"``) to PartitionSpecs:

* default             → fully replicated (pure DP ≙ the reference)
* ``fsdp_rules``      → shard the largest divisible dim over "fsdp"
  (ZeRO-3-style; ≙ nothing in the reference — new capability)
* ``tensor_parallel_rules`` → Megatron-style column/row splits over
  "model" driven by user-tagged layer names.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "ShardingRules", "replicated", "shard_model_params",
    "model_shardings", "fsdp_spec", "tensor_parallel_rules",
    "grad_allreduce_bytes",
]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def fsdp_spec(shape: Tuple[int, ...], mesh: Mesh,
              axis: str = "fsdp") -> P:
    """Shard the largest dim divisible by the fsdp axis size."""
    if axis not in mesh.axis_names:
        return P()
    size = mesh.shape[axis]
    dims = sorted(range(len(shape)), key=lambda i: -shape[i])
    for d in dims:
        if shape[d] % size == 0 and shape[d] >= size:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def tensor_parallel_rules(column: Sequence[str] = (),
                          row: Sequence[str] = (),
                          axis: str = "model",
                          fsdp: bool = False) -> "ShardingRules":
    """Megatron-style tensor parallelism as sharding rules.

    ``column`` / ``row`` are regex patterns over parameter paths (e.g.
    ``r"layers\\[0\\]"``).  Column-parallel splits the OUTPUT feature dim
    (weight dim 0 in this framework's Torch-style ``(out, in)`` layout,
    bias dim 0); row-parallel splits the INPUT dim (weight dim 1, bias
    replicated).  Under GSPMD the classic Megatron choreography — g/f
    identity-forward/all-reduce-backward conjugate operators around a
    column→row pair — is recovered automatically: annotating the weight
    shardings is enough and XLA's sharding propagation inserts the
    all-reduce after the row-parallel matmul.  (The reference has no TP
    at all — SURVEY §2.6 build-target row.)
    """
    def col_spec(shape, mesh):
        if axis not in mesh.axis_names:
            return P()
        if len(shape) >= 1 and shape[0] % mesh.shape[axis] == 0:
            return P(axis, *([None] * (len(shape) - 1)))
        return P()

    def row_spec(shape, mesh):
        if axis not in mesh.axis_names:
            return P()
        if len(shape) >= 2 and shape[1] % mesh.shape[axis] == 0:
            return P(None, axis, *([None] * (len(shape) - 2)))
        return P()  # 1-D leaves (row-layer bias) stay replicated

    rules = ([(pat, col_spec) for pat in column]
             + [(pat, row_spec) for pat in row])
    return ShardingRules(rules, fsdp=fsdp)


class ShardingRules:
    """Ordered (regex → spec_fn) rules resolved per parameter path.

    spec_fn: (shape, mesh) -> PartitionSpec.  First match wins; default
    is replicate (or fsdp when ``fsdp=True``).
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, Callable]]] = None,
                 fsdp: bool = False):
        self.rules = [(re.compile(pat), fn) for pat, fn in (rules or [])]
        self.fsdp = fsdp

    def spec_for(self, path: str, shape, mesh: Mesh) -> P:
        for pat, fn in self.rules:
            if pat.search(path):
                return fn(shape, mesh)
        if self.fsdp:
            return fsdp_spec(tuple(shape), mesh)
        return P()

    def sharding_for(self, path: str, shape, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.spec_for(path, shape, mesh))


def _walk_params(tree, prefix=""):
    """Yield (path, leaf) for a nested dict params tree."""
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _walk_params(v, f"{prefix}.{k}" if prefix else k)
    elif tree is not None:
        yield prefix, tree


def model_shardings(model, mesh: Mesh,
                    rules: Optional[ShardingRules] = None):
    """Shardings pytree matching the full module pytree: params get
    rule-resolved shardings (path-aware), buffers replicate."""
    rules = rules or ShardingRules()

    # Build a path-aware map over the module tree itself.
    from bigdl_tpu.core.module import Module, ModuleList

    def rec(obj, prefix):
        if isinstance(obj, Module):
            leaves = []
            for n in obj._params:
                path = f"{prefix}.{n}" if prefix else n
                leaves.append(rules.sharding_for(
                    path, obj._params[n].shape, mesh))
            for n in obj._buffers:
                leaves.append(replicated(mesh))
            for n in obj._modules:
                leaves.extend(rec(obj._modules[n],
                                  f"{prefix}.{n}" if prefix else n))
            return leaves
        if isinstance(obj, ModuleList):
            out = []
            for i, m in enumerate(obj._items):
                out.extend(rec(m, f"{prefix}[{i}]"))
            return out
        # generic leaf
        return [replicated(mesh)]

    leaves = rec(model, "")
    treedef = jax.tree_util.tree_structure(model)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def grad_allreduce_bytes(model, mesh: Mesh,
                         rules: Optional[ShardingRules] = None, *,
                         hierarchical: bool = False,
                         wire_dtype=None,
                         dcn_axis: str = "dcn") -> Dict:
    """Analytic per-step gradient-sync payload of this (model, mesh,
    rules) triple: the bytes the XLA-inserted data-parallel gradient
    all-reduce moves per device per step.

    The collectives behind ``NamedSharding`` never pass through the
    ``telemetry.collectives`` wrappers (sharding propagation inserts
    them during compilation), so this estimator gives call-site-free
    code a number to compare against the compiled ground truth
    (``utils/xla_cost.collective_hlo_bytes``).  Convention matches both:
    per-device OUTPUT payload — a parameter leaf sharded over ``s``
    devices contributes ``nbytes / s`` (its gradient reduces in the
    sharded layout); a fully replicated leaf contributes its whole
    ``nbytes``.  ≙ the byte count the reference's BlockManager
    all-reduce shipped per node (parameters/AllReduceParameter.scala),
    which its FP16 ``CompressedTensor`` existed to halve.

    ``hierarchical=True`` models the
    :func:`bigdl_tpu.parallel.hierarchy.hierarchical_grad_sync`
    schedule instead of the flat all-reduce, so the analytic floor
    matches the compressed wire: reduce-scatter over the fast batch
    axes (``flat/F``), the cross-slice hop at ``wire_dtype`` width
    (``S`` gathered shards of ``flat/F`` scaled by wire-bytes/element
    over 4), and the within-slice all-gather (``flat``).  Extra keys:
    ``dcn_bytes_per_step`` (the slow-tier payload — the number the
    ``dcn_bound`` roofline floor divides by DCN bandwidth),
    ``intra_bytes_per_step``, ``flat_fp32_bytes_per_step``,
    ``wire_dtype``, and ``compression_ratio`` (flat fp32 bytes /
    actual total wire bytes — what a round artifact records)."""
    from bigdl_tpu.core.module import Module, ModuleList
    rules = rules or ShardingRules()

    total = 0.0
    leaves = 0

    def shard_factor(spec) -> int:
        s = 1
        for entry in spec:
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                s *= mesh.shape[ax]
        return max(s, 1)

    def rec(obj, prefix):
        nonlocal total, leaves
        if isinstance(obj, Module):
            for n, p in obj._params.items():
                path = f"{prefix}.{n}" if prefix else n
                spec = rules.spec_for(path, p.shape, mesh)
                total += (int(np.prod(p.shape))
                          * np.dtype(p.dtype).itemsize
                          / shard_factor(spec))
                leaves += 1
            for n in obj._modules:
                rec(obj._modules[n], f"{prefix}.{n}" if prefix else n)
        elif isinstance(obj, ModuleList):
            for i, m in enumerate(obj._items):
                rec(m, f"{prefix}[{i}]")

    rec(model, "")
    out = {"bytes_per_step": total, "param_leaves": leaves,
           "mesh_axes": dict(mesh.shape)}
    if not hierarchical:
        # the flat all-reduce on a multi-slice mesh still crosses the
        # slow tier — with the FULL per-device payload, which is the
        # whole case for the hierarchical schedule; report it so the
        # flat baseline gets a dcn roofline floor too
        if dcn_axis in mesh.axis_names and mesh.shape[dcn_axis] > 1:
            out["dcn_bytes_per_step"] = total
        return out
    # hierarchical mode: model the rs-in-slice / compressed-dcn-hop /
    # ag-in-slice schedule over the FLAT fp32 gradient (the primitive
    # concatenates every leaf; leaf-level shard factors don't apply —
    # it requires replicated params, so a rules-reduced total would
    # silently model a configuration _grad_sync_plan rejects)
    if rules.rules or rules.fsdp:
        raise ValueError(
            "grad_allreduce_bytes(hierarchical=True) models the "
            "hierarchical sync, which requires fully replicated "
            "parameters — drop the sharding rules or estimate the "
            "flat sync (hierarchical=False)")
    from bigdl_tpu.parallel.compression import get_codec, wire_bytes
    from bigdl_tpu.parallel.hierarchy import fast_batch_axes_of
    flat_fp32 = total
    F = 1
    for a in fast_batch_axes_of(mesh):
        F *= mesh.shape[a]
    S = mesh.shape[dcn_axis] if dcn_axis in mesh.axis_names else 1
    # branch on the RESOLVED codec, not the raw wire_dtype: spellings
    # get_codec maps to no-compression ("fp32", "none", jnp.float32)
    # run the single-hop uncompressed psum at runtime and must not be
    # costed as the two-hop codec schedule
    codec = get_codec(wire_dtype)
    shard = flat_fp32 / max(F, 1)
    # per-device output payloads: reduce-scatter emits the 1/F shard,
    # the in-slice all-gather emits the full flat gradient
    intra = (shard + flat_fp32) if F > 1 else 0.0
    if S > 1:
        # compressed chunk-ownership all-reduce: two hops (all_to_all
        # the S encoded chunks, all-gather the reduced ones) of one
        # shard-size payload each — constant in S.  wire_bytes models
        # the codec's bucket clamp, so small shards cost their true
        # scale overhead (uncompressed psum: one shard at full width)
        dcn = (2.0 * wire_bytes(codec, shard / 4.0, n_chunks=S)
               if codec is not None else shard)
    else:
        dcn = 0.0
    wire_total = intra + dcn
    out.update({
        "bytes_per_step": wire_total,
        "flat_fp32_bytes_per_step": flat_fp32,
        "intra_bytes_per_step": intra,
        "dcn_bytes_per_step": dcn,
        "wire_dtype": (None if codec is None else str(wire_dtype)),
        "compression_ratio": (flat_fp32 / wire_total
                              if wire_total else 1.0),
    })
    return out


def shard_model_params(model, mesh: Mesh,
                       rules: Optional[ShardingRules] = None):
    """device_put every array leaf of the module per the rules —
    the TPU-native ModelBroadcast (ModelBroadcast.scala:51: broadcast
    once, attach shared storage per replica ⇒ here: one sharded copy)."""
    shardings = model_shardings(model, mesh, rules)
    leaves, treedef = jax.tree_util.tree_flatten(model)
    s_leaves = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    new_leaves = [jax.device_put(l, s) for l, s in zip(leaves, s_leaves)]
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
