"""Hierarchical gradient synchronization over a two-tier network.

Reference equivalence: the reference's whole scaling trick
(whitepaper.md:150-196) was shaping the parameter-manager all-reduce
around the network hierarchy and compressing the slow links
(``parameters/AllReduceParameter.scala`` + ``FP16CompressedTensor``).
The TPU-native analog is a mesh with a fast intra-slice tier (ICI —
the ``data``/``fsdp`` axes) and a slow inter-slice tier (DCN — the
``dcn`` axis, ``make_mesh({"dcn": 2, "data": -1})``).

A flat gradient all-reduce moves the FULL gradient across the slow
tier.  :func:`hierarchical_grad_sync` instead

1. **reduce-scatters** the flat gradient within each slice over the
   fast axes — every device ends up owning a ``1/F`` shard of the
   slice-local sum (``F`` = fast-axis extent);
2. moves ONLY that shard across the ``dcn`` axis — uncompressed as a
   plain psum, or compressed
   (:mod:`bigdl_tpu.parallel.compression`) via the reference's
   chunk-ownership all-reduce (``AllReduceParameter.scala``: the
   parameter is split into N chunks, node i owns and reduces chunk
   i): the shard is split into ``S`` chunks, each encoded and
   **all_to_all**'d so slice ``i`` receives every slice's encoding of
   chunk ``i``, decoded and **fp32-summed** there, then the reduced
   chunk is re-encoded and **all-gathered** back (compress-on-wire,
   accumulate-in-fp32, exactly the reference's ``CompressedTensor``
   discipline).  Two compressed hops of ``shard``-size each — the
   cross-slice wire is ``2·(shard·w)`` CONSTANT in ``S``, where a
   naive gather-everything schedule would grow as ``S·shard·w``;
3. **all-gathers** the synced shards back within the slice.

Cross-slice traffic drops by the slice size versus the flat
all-reduce, and the wire codec shrinks what remains (bf16 ~2x, int8
~4x on hardware with native small-dtype collectives).  Every
collective routes through :mod:`bigdl_tpu.telemetry.collectives`, so
the ``dcn`` hop shows up per-{op, axis} in ``collective_bytes_total``
and the compiled HLO's cross-slice payload can be read back with
:func:`bigdl_tpu.utils.xla_cost.cross_group_hlo_bytes` over
:func:`dcn_slice_map`.

The primitive is written for use INSIDE a ``shard_map`` over the
mesh's batch axes (each device passes its local gradient); the
Optimizer wires it in via ``opt.set_gradient_sync(hierarchical=True,
wire_dtype=...)``.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from bigdl_tpu.parallel.compression import get_codec
from bigdl_tpu.parallel.mesh import BATCH_AXES as _BATCH_AXES
from bigdl_tpu.parallel.mesh import shard_map_compat
from bigdl_tpu.telemetry import collectives as _coll

__all__ = [
    "DCN_AXIS", "FAST_BATCH_AXES", "hierarchical_grad_sync",
    "batch_axes_of", "fast_batch_axes_of", "dcn_slice_map", "shard_map",
]

DCN_AXIS = "dcn"

# batch-like axes that form the FAST (intra-slice, ICI) tier, in mesh
# order; the dcn axis is the slow tier above them.  Derived from the
# one canonical batch-axis list so a new batch-like axis added to
# mesh.BATCH_AXES is picked up here automatically.
FAST_BATCH_AXES = tuple(a for a in _BATCH_AXES if a != DCN_AXIS)


# the one version-compat shard_map spelling (parallel.mesh owns it),
# re-exported under the natural name for hierarchy call sites
shard_map = shard_map_compat


def _wire_pinned() -> bool:
    """The HLO-lint seam for the PR-8 widening bug.  Default True: the
    compressed dcn hop keeps its narrow dtype pinned on the wire with
    ``optimization_barrier``s.  ``BIGDL_TPU_UNPIN_DCN_WIRE=1`` (read at
    TRACE time) deliberately compiles the FAILURE-mode program instead
    — the decode hoisted above the exchange, so the cross-slice wire
    carries fp32 — which is what XLA itself produced before the
    barriers pinned it.  ``analysis/hlo_lint``'s narrow-wire pass must
    flag that program loudly (and would equally flag a future XLA
    version that learns to hoist past the barriers)."""
    return os.environ.get("BIGDL_TPU_UNPIN_DCN_WIRE") != "1"


def batch_axes_of(mesh, dcn_axis: str = DCN_AXIS) -> Tuple[str, ...]:
    """Every batch-like axis of ``mesh`` (slow tier first), the axes a
    batch-leading array shards over and a gradient sync reduces over."""
    return tuple(a for a in (dcn_axis,) + FAST_BATCH_AXES
                 if a in mesh.axis_names)


def fast_batch_axes_of(mesh) -> Tuple[str, ...]:
    return tuple(a for a in FAST_BATCH_AXES if a in mesh.axis_names)


def dcn_slice_map(mesh, dcn_axis: str = DCN_AXIS) -> Dict[int, int]:
    """``{logical_device_position: slice_index}`` for ``mesh`` — the
    classifier input for
    :func:`bigdl_tpu.utils.xla_cost.cross_group_hlo_bytes` (HLO
    replica groups name devices by their position in the mesh's
    flattened device order).  Without a ``dcn`` axis every device is
    slice 0."""
    n = int(np.prod(mesh.devices.shape))
    if dcn_axis not in mesh.axis_names:
        return {i: 0 for i in range(n)}
    axis = mesh.axis_names.index(dcn_axis)
    coords = np.indices(mesh.devices.shape)[axis].reshape(-1)
    return {i: int(coords[i]) for i in range(n)}


def _flatten_tree(grads):
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    shapes = [l.shape for l in leaves]
    flat = (jnp.concatenate([l.astype(jnp.float32).reshape(-1)
                             for l in leaves])
            if leaves else jnp.zeros((0,), jnp.float32))
    return flat, (treedef, shapes, [l.dtype for l in leaves])


def _unflatten_tree(flat, spec):
    treedef, shapes, dtypes = spec
    out, off = [], 0
    for shape, dtype in zip(shapes, dtypes):
        n = int(np.prod(shape)) if shape else 1
        out.append(flat[off:off + n].reshape(shape).astype(dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def hierarchical_grad_sync(grads, mesh, *, dcn_axis: str = DCN_AXIS,
                           fast_axes: Optional[Sequence[str]] = None,
                           wire_dtype=None, rng=None, mean: bool = True):
    """Hierarchically reduce a per-device local gradient pytree to the
    global mean (or sum) over the mesh's batch axes.

    MUST run inside a ``shard_map`` (or equivalent mapped context)
    whose axes include the mesh's batch axes; each device passes the
    gradient of its LOCAL batch shard.  See the module docstring for
    the three-stage schedule.  ``wire_dtype`` compresses only the
    cross-slice (``dcn``) hop — None / ``"bf16"`` / ``"int8"`` / a
    codec instance; ``rng`` seeds the int8 codec's stochastic rounding
    (pass a per-step key; None falls back to round-to-nearest).
    ``mean=False`` returns the sum instead.

    Degenerate meshes stay correct: with no ``dcn`` axis the schedule
    collapses to reduce-scatter + all-gather within the single slice
    (an explicit flat all-reduce); with no fast axes it is a pure
    compressed cross-slice exchange.
    """
    if fast_axes is None:
        fast_axes = fast_batch_axes_of(mesh)
    fast_axes = tuple(a for a in fast_axes if a in mesh.axis_names)
    has_dcn = dcn_axis in mesh.axis_names
    F = int(np.prod([mesh.shape[a] for a in fast_axes])) \
        if fast_axes else 1
    S = int(mesh.shape[dcn_axis]) if has_dcn else 1
    if F * S == 1:
        return grads
    codec = get_codec(wire_dtype)

    flat, spec = _flatten_tree(grads)
    n = flat.shape[0]
    pad = (-n) % F
    if pad:
        flat = jnp.pad(flat, (0, pad))

    # 1) fast tier: reduce-scatter the slice-local sum; each device
    #    owns 1/F of it
    if F > 1:
        axis = fast_axes[0] if len(fast_axes) == 1 else tuple(fast_axes)
        shard = _coll.psum_scatter(flat, axis, scatter_dimension=0,
                                   tiled=True)
    else:
        shard = flat

    # 2) slow tier: move only the shard across slices, compressed;
    #    decode each slice's payload and accumulate in fp32 (the
    #    CompressedTensor discipline — the wire is narrow, the master
    #    sum is not)
    if S > 1:
        if codec is None:
            shard = _coll.psum(shard, dcn_axis)
        else:
            # chunk-ownership all-reduce (≙ AllReduceParameter.scala):
            # slice i owns chunk i.  Hop 1: all_to_all the S encoded
            # chunks so the owner receives every slice's encoding of
            # its chunk; decode + fp32-sum there.  Hop 2: all-gather
            # the re-encoded reduced chunks back.  Each hop moves one
            # shard-size compressed payload, so the cross-slice wire
            # is constant in S (a gather-everything schedule grows
            # linearly and pessimizes compression beyond 2 slices).
            size = shard.shape[0]
            pad_s = (-size) % S
            if pad_s:
                shard = jnp.pad(shard, (0, pad_s))
            k = shard.shape[0] // S
            chunks = shard.reshape(S, k)
            if not _wire_pinned():
                # the deliberately-unpinned decode (lint seam, see
                # _wire_pinned): same chunk-ownership schedule, fp32 on
                # the wire — the program the widening bug produced
                recv = _coll.all_to_all(chunks, dcn_axis, split_axis=0,
                                        concat_axis=0)
                owned = jnp.sum(recv.reshape(S, k), axis=0)
                gathered = _coll.all_gather(owned, dcn_axis,
                                            tiled=False)
                shard = gathered.reshape(-1)[:size]
                if mean:
                    shard = shard / float(F * S)
                if F > 1:
                    axis = (fast_axes[0] if len(fast_axes) == 1
                            else tuple(fast_axes))
                    flat = _coll.all_gather(shard, axis, tiled=True)
                else:
                    flat = shard
                if pad:
                    flat = flat[:n]
                return _unflatten_tree(flat, spec)

            def _key(i):
                return None if rng is None else jax.random.fold_in(rng, i)

            enc = [codec.encode(chunks[j], key=_key(j)) for j in range(S)]
            parts = tuple(jnp.stack([e[p] for e in enc])
                          for p in range(len(enc[0])))
            # keep the narrow dtype ON the wire: without the barriers
            # XLA may hoist the decode convert above the collective,
            # silently widening the cross-slice payload back to fp32
            parts = jax.lax.optimization_barrier(parts)
            recv = tuple(_coll.all_to_all(p, dcn_axis, split_axis=0,
                                          concat_axis=0) for p in parts)
            recv = jax.lax.optimization_barrier(recv)
            owned = sum(codec.decode(tuple(r[i] for r in recv), k)
                        for i in range(S))
            parts2 = codec.encode(owned, key=_key(S))
            parts2 = jax.lax.optimization_barrier(parts2)
            gathered = tuple(_coll.all_gather(p, dcn_axis, tiled=False)
                             for p in parts2)
            gathered = jax.lax.optimization_barrier(gathered)
            shard = jnp.concatenate(
                [codec.decode(tuple(g[i] for g in gathered), k)
                 for i in range(S)])
            if pad_s:
                shard = shard[:size]

    if mean:
        shard = shard / float(F * S)

    # 3) fast tier: bring every device back to the full gradient
    if F > 1:
        axis = fast_axes[0] if len(fast_axes) == 1 else tuple(fast_axes)
        flat = _coll.all_gather(shard, axis, tiled=True)
    else:
        flat = shard
    if pad:
        flat = flat[:n]
    return _unflatten_tree(flat, spec)
