"""Ring attention: sequence/context parallelism over a mesh axis.

The reference has NO sequence parallelism of any kind (SURVEY §5.7 —
sequences are processed whole per replica, nn/Recurrent.scala:243,
nn/Attention.scala).  This module is new, TPU-first capability: contexts
longer than one chip's HBM are sharded over a mesh axis and attention is
computed with a ring schedule (Liu et al., "Ring Attention with
Blockwise Transformers").

Mechanics: under ``shard_map`` each device holds the local Q/K/V chunk
[B, H, T/n, D].  The ring runs n steps; at step s every device computes
blockwise attention between its Q chunk and the K/V chunk that
originated on device (me - s) mod n, merging partial results with the
online-softmax (m, l, acc) recurrence, then passes its current K/V
chunk to the next neighbor with ``lax.ppermute`` — the collective rides
a physical ICI ring, overlapping compute with transfer.  Causality is
handled per (my_chunk, src_chunk) pair: full block when src < mine,
diagonal mask when equal, skipped (fully masked) when src > mine.

``ring_attention`` is the per-shard function (call inside your own
shard_map); :func:`ring_self_attention` wraps a global [B, H, T, D]
array with the shard_map + NamedSharding plumbing.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.parallel.mesh import shard_map_compat
from bigdl_tpu.telemetry import collectives as _coll

__all__ = ["ring_attention", "ring_self_attention",
           "RingSelfAttention"]

_NEG_INF = -1e9


def _block_attend(q, k, v, bias_blk, scale, acc, m_prev, l_prev):
    """One blockwise-attention accumulation step (online softmax).

    q [B,H,Tq,D]; k,v [B,H,Tc,D]; bias_blk broadcastable [B,H,Tq,Tc] or
    None; carries acc [B,H,Tq,D], m/l [B,H,Tq] in fp32.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias_blk is not None:
        s = s + bias_blk.astype(jnp.float32)
    m_cur = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return acc, m_new, l_new


def _pick_block(tc: int) -> Optional[int]:
    """Largest lane-friendly block size dividing the chunk length, or
    None when no usable tiling exists."""
    for b in (128, 64, 32, 16, 8):
        if tc % b == 0:
            return b
    return None


def _use_flash_blocks(tc: int, d: int, kernel: Optional[str]) -> bool:
    """Route the per-step chunk attention through the Pallas flash-
    partial kernel?  Auto: on TPU when the chunk tiles cleanly (the
    XLA fallback materializes an O(Tc²) score block per ring step —
    fine for small chunks, ruinous at the long-context sizes SP exists
    for).  Override with kernel= or BIGDL_TPU_ATTENTION — but a forced
    "flash" still falls back when no block tiling exists (a crash
    would be strictly worse than the working XLA ring)."""
    import os
    from bigdl_tpu.ops.attention_kernels import _on_tpu

    tiles = _pick_block(tc) is not None and d % 8 == 0
    choice = kernel or os.environ.get("BIGDL_TPU_ATTENTION")
    if choice == "xla":
        return False
    if choice == "flash":
        return tiles
    return _on_tpu() and tc % 128 == 0 and tiles


def _ring_xla(q, k, v, axis_name: str, causal: bool, scale: float,
              bias):
    """XLA ring: one materialized [Tc, Tc] score block per step."""
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    b, h, tc, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc0 = jnp.zeros((b, h, tc, d), jnp.float32)
    m0 = jnp.full((b, h, tc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tc), jnp.float32)

    def body(s, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (me - s) % n  # chunk index the current K/V originated from
        blk_bias = None
        if bias is not None:
            blk_bias = jax.lax.dynamic_slice_in_dim(
                bias, src * tc, tc, axis=3)
        if causal:
            q_pos = me * tc + jax.lax.broadcasted_iota(
                jnp.int32, (tc, tc), 0)
            k_pos = src * tc + jax.lax.broadcasted_iota(
                jnp.int32, (tc, tc), 1)
            cb = jnp.where(q_pos >= k_pos, 0.0, _NEG_INF).astype(jnp.float32)
            blk_bias = cb if blk_bias is None else blk_bias + cb
        acc, m, l = _block_attend(q, k_cur, v_cur, blk_bias, scale,
                                  acc, m, l)
        k_nxt = _coll.ppermute(k_cur, axis_name, perm)
        v_nxt = _coll.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    acc, m, l, _, _ = jax.lax.fori_loop(
        0, n, body, (acc0, m0, l0, k, v))
    # rows that saw no unmasked key (can't happen for causal self-attn
    # since the diagonal block always contributes) — guard anyway
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe_l[..., None]).astype(q.dtype)


def _ring_flash_impl(q, k, v, cfg):
    """Flash-ring forward; returns (out, lse) — the final per-row
    logsumexp is the residual the blockwise backward needs."""
    axis_name, causal, scale, blk, interpret = cfg
    from bigdl_tpu.ops.attention_kernels import flash_attention_partial

    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    b, h, tc, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc0 = jnp.zeros((b, h, tc, d), jnp.float32)
    m0 = jnp.full((b, h, tc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tc), jnp.float32)

    def body(s, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (me - s) % n

        def attend(ops):
            acc_, m_, l_ = ops
            return flash_attention_partial(
                q, k_cur, v_cur, acc_, m_, l_,
                q_offset=me * tc, k_offset=src * tc, causal=causal,
                scale=scale, block_q=blk, block_k=blk,
                interpret=interpret)

        if causal:
            # chunks entirely above the diagonal contribute nothing
            # (and would poison m with exp(-inf - -inf) otherwise)
            acc, m, l = jax.lax.cond(
                src <= me, attend, lambda ops: ops, (acc, m, l))
        else:
            acc, m, l = attend((acc, m, l))
        k_nxt = _coll.ppermute(k_cur, axis_name, perm)
        v_nxt = _coll.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    acc, m, l, _, _ = jax.lax.fori_loop(
        0, n, body, (acc0, m0, l0, k, v))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / safe_l[..., None]).astype(q.dtype)
    return out, m + jnp.log(safe_l)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ring_flash(q, k, v, cfg):
    """Flash ring: each step merges the visiting chunk through the
    Pallas flash-partial kernel — O(block) score tiles, never O(Tc²).
    The backward is blockwise too (a second ring pass): dK/dV
    accumulators ROTATE WITH their K/V chunk, each device adding its
    contribution as the chunk visits, so after n steps every chunk —
    and its gradient — is back home."""
    out, _ = _ring_flash_impl(q, k, v, cfg)
    return out


def _ring_flash_fwd(q, k, v, cfg):
    out, lse = _ring_flash_impl(q, k, v, cfg)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(cfg, res, g):
    axis_name, causal, scale, blk, interpret = cfg
    from bigdl_tpu.ops.attention_kernels import (
        flash_attention_dq_partial, flash_attention_dkv_partial)

    q, k, v, out, lse = res
    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    b, h, tc, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]
    g32 = g.astype(jnp.float32)
    # Δ rows (Σ_j P_ij dP_ij) — whole-sequence, like lse
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1)

    z = jnp.zeros((b, h, tc, d), jnp.float32)

    def body(s, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (me - s) % n
        qoff, koff = me * tc, src * tc

        def dq_step(_):
            return flash_attention_dq_partial(
                q, k_cur, v_cur, g32, lse, delta, q_offset=qoff,
                k_offset=koff, causal=causal, scale=scale, block_q=blk,
                block_k=blk, interpret=interpret)

        def dkv_step(_):
            return flash_attention_dkv_partial(
                q, k_cur, v_cur, g32, lse, delta, q_offset=qoff,
                k_offset=koff, causal=causal, scale=scale, block_q=blk,
                block_k=blk, interpret=interpret)

        if causal:
            contrib = src <= me
            dq = dq + jax.lax.cond(contrib, dq_step,
                                   lambda _: z, None)
            dk_c, dv_c = jax.lax.cond(contrib, dkv_step,
                                      lambda _: (z, z), None)
        else:
            dq = dq + dq_step(None)
            dk_c, dv_c = dkv_step(None)
        dk_cur = dk_cur + dk_c
        dv_cur = dv_cur + dv_c
        # the chunk and its accumulated gradient rotate together; after
        # n steps both are back on the chunk's home device
        k_nxt = _coll.ppermute(k_cur, axis_name, perm)
        v_nxt = _coll.ppermute(v_cur, axis_name, perm)
        dk_nxt = _coll.ppermute(dk_cur, axis_name, perm)
        dv_nxt = _coll.ppermute(dv_cur, axis_name, perm)
        return dq, k_nxt, v_nxt, dk_nxt, dv_nxt

    dq, _, _, dk, dv = jax.lax.fori_loop(
        0, n, body, (z, k, v, z, z))
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   scale: Optional[float] = None, bias=None,
                   kernel: Optional[str] = None):
    """Per-shard ring attention (call under shard_map).

    q/k/v: the LOCAL sequence chunk [B, H, Tc, D]; axis_name: the mesh
    axis the sequence is sharded over.  bias, if given, is the LOCAL
    [B, H, Tc, T_global] slice of the additive attention bias (rows =
    my queries, columns = the full key axis in GLOBAL order) — the
    biased path always uses the XLA block step.  ``kernel`` ∈
    {"flash", "xla", None=auto (flash on TPU when the chunk tiles)}.
    Returns the local output chunk [B, H, Tc, D].
    """
    b, h, tc, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if bias is None and _use_flash_blocks(tc, d, kernel):
        from bigdl_tpu.ops.attention_kernels import _on_tpu
        # blk=None → the partial kernels auto-pick the largest VMEM-
        # fitting tiling (small blocks are grid-overhead-bound)
        cfg = (axis_name, bool(causal), float(scale), None,
               not _on_tpu())
        return _ring_flash(q, k, v, cfg)
    return _ring_xla(q, k, v, axis_name, causal, scale, bias)


def ring_self_attention(q, k, v, mesh: Mesh, axis: str = "seq", *,
                        causal: bool = False,
                        scale: Optional[float] = None, bias=None,
                        kernel: Optional[str] = None,
                        head_axis: Optional[str] = None):
    """Global entry: q/k/v [B, H, T, D] (T divisible by mesh axis size)
    are sequence-sharded over ``axis`` and attended with the ring
    schedule.  Equivalent to full attention, O(T/n) memory per chip.

    ``head_axis``: also shard the head dimension over this mesh axis —
    attention is per-head independent, so when the surrounding
    projections are tensor-parallel (Megatron column-split over heads)
    this keeps the TP sharding THROUGH the ring instead of forcing
    GSPMD to all-gather heads at the shard_map boundary (the
    "involuntary full rematerialization" SPMD warning)."""
    spec = P(None, head_axis, axis, None)
    if bias is None:
        fn = shard_map_compat(
            functools.partial(ring_attention, axis_name=axis,
                              causal=causal, scale=scale, kernel=kernel),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return fn(q, k, v)
    bias = jnp.broadcast_to(
        bias, (q.shape[0], q.shape[1], q.shape[2], k.shape[2]))
    fn = shard_map_compat(
        lambda q_, k_, v_, b_: ring_attention(
            q_, k_, v_, axis_name=axis, causal=causal, scale=scale,
            bias=b_),
        mesh=mesh, in_specs=(spec, spec, spec, spec), out_specs=spec)
    return fn(q, k, v, bias)


from bigdl_tpu.core.module import Module
from bigdl_tpu.nn.attention import Attention, causal_bias


class RingSelfAttention(Attention):
    """Drop-in for :class:`bigdl_tpu.nn.attention.Attention` that runs
    the training-time self-attention through the ring schedule (O(T/n)
    activation memory per chip).

    Routing: incremental decoding (``cache=...``) and cross-attention
    always use the dense path; a non-None additive ``bias`` also routes
    dense (broadcasting [B,1,1,T] to [B,H,T,T] would defeat the ring's
    memory point) with causality folded into the bias so semantics stay
    identical; training with ``attention_dropout > 0`` raises — the
    ring never materializes the softmax weights, so dropping them is
    impossible, and silently skipping dropout would change training.

    Build with :meth:`from_attention` to wrap an existing trained
    Attention — the four projection Linears are SHARED (same modules,
    same parameters, no RNG draws), so swapping in/out never touches
    weights.
    """

    def __init__(self, hidden_size, num_heads, mesh, axis="seq",
                 causal=True, attention_dropout=0.0, kernel=None,
                 head_axis=None):
        super().__init__(hidden_size, num_heads, attention_dropout)
        self.mesh = mesh
        self.seq_axis = axis
        self.causal = causal
        self.ring_kernel = kernel   # "flash" | "xla" | None=auto
        self.head_axis = head_axis  # TP mesh axis for the head dim

    def forward(self, x, y=None, bias=None, cache=None, cache_index=None,
                causal=False):
        # `causal` (kernel-side masking) is accepted for Attention API
        # compatibility; the ring applies its own causality from
        # self.causal, so a redundant True is absorbed — but a True on
        # a non-causal ring would be silently dropped, so refuse it
        if causal and not self.causal:
            raise ValueError(
                "RingSelfAttention was built with causal=False; "
                "kernel-side causal masking is not available on this "
                "ring — rebuild with causal=True")
        if cache is not None or (y is not None and y is not x):
            if causal:
                # kernel-side masking is start-of-cache-aligned and the
                # decode path masks via its own incremental bias;
                # silently forwarding would mis-mask mid-cache steps
                raise ValueError(
                    "causal=True is not supported on the cache/cross-"
                    "attention path; pass the decode-time incremental "
                    "bias instead")
            return Attention.forward(self, x, y, bias, cache, cache_index)
        if bias is not None:
            # dense fallback with equivalent masking: the ring would
            # have applied causality itself, so fold it into the bias.
            # (Attention.forward's materialized path also handles
            # training-time attention dropout, so no restriction here.)
            if self.causal:
                bias = bias + causal_bias(x.shape[1], dtype=bias.dtype)
            return Attention.forward(self, x, None, bias)
        if self.training and self.attention_dropout > 0.0:
            raise ValueError(
                "attention dropout is not supported on the ring path "
                "(the softmax weights are never materialized); train "
                "with the dense Attention or attention_dropout=0")
        n_shards = self.mesh.shape[self.seq_axis]
        if x.shape[1] % n_shards:
            raise ValueError(
                f"sequence length {x.shape[1]} is not divisible by the "
                f"{self.seq_axis!r} mesh axis size {n_shards}")
        head_axis = getattr(self, "head_axis", None)
        if head_axis is not None:
            n_head_shards = self.mesh.shape[head_axis]
            if self.num_heads % n_head_shards:
                raise ValueError(
                    f"num_heads {self.num_heads} is not divisible by "
                    f"the {head_axis!r} mesh axis size {n_head_shards}")
        q = self._split_heads(self.q_layer(x))
        k = self._split_heads(self.k_layer(x))
        v = self._split_heads(self.v_layer(x))
        ctxt = ring_self_attention(q, k, v, self.mesh, self.seq_axis,
                                   causal=self.causal,
                                   kernel=getattr(self, "ring_kernel",
                                                  None),
                                   head_axis=head_axis)
        return self.output_layer(self._combine_heads(ctxt))

    @classmethod
    def from_attention(cls, attn, mesh, axis="seq", causal=True,
                       kernel=None, head_axis=None):
        # rng-neutral construction: Attention.__init__ would draw four
        # throwaway Linear inits from the global RNG stream
        ring = object.__new__(cls)
        Module.__init__(ring)
        ring.training = attn.training  # Module.__init__ resets to True
        ring.hidden_size = attn.hidden_size
        ring.num_heads = attn.num_heads
        ring.attention_dropout = attn.attention_dropout
        ring.mesh = mesh
        ring.seq_axis = axis
        ring.causal = causal
        ring.ring_kernel = kernel
        ring.head_axis = head_axis
        # share the projection modules (and thus the parameters)
        ring.q_layer = attn.q_layer
        ring.k_layer = attn.k_layer
        ring.v_layer = attn.v_layer
        ring.output_layer = attn.output_layer
        return ring
