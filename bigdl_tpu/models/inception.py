"""Inception v1 (GoogLeNet) — reference models/inception/Inception_v1.scala.

NHWC; each inception module is four parallel towers concatenated on the
channel axis (reference's Concat(2) over NCHW ⇒ channel-last concat here).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core import init as init_methods
from bigdl_tpu.core.module import Module

__all__ = ["Inception_v1", "inception_module"]


def _conv(nin, nout, kw, kh, sw=1, sh=1, pw=0, ph=0, name=""):
    c = nn.SpatialConvolution(
        nin, nout, kw, kh, sw, sh, pw, ph,
        init_method=init_methods.Xavier)
    if name:
        c.set_name(name)
    return c


class InceptionModule(Module):
    """One inception block (reference Inception_v1.scala inception())."""

    def __init__(self, input_size, c1x1, c3x3r, c3x3, c5x5r, c5x5, pool_proj,
                 name="inception"):
        super().__init__()
        self.b1 = nn.Sequential(_conv(input_size, c1x1, 1, 1), nn.ReLU())
        self.b2 = nn.Sequential(
            _conv(input_size, c3x3r, 1, 1), nn.ReLU(),
            _conv(c3x3r, c3x3, 3, 3, 1, 1, 1, 1), nn.ReLU())
        self.b3 = nn.Sequential(
            _conv(input_size, c5x5r, 1, 1), nn.ReLU(),
            _conv(c5x5r, c5x5, 5, 5, 1, 1, 2, 2), nn.ReLU())
        self.b4 = nn.Sequential(
            nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1),
            _conv(input_size, pool_proj, 1, 1), nn.ReLU())
        self.set_name(name)

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=-1)


def inception_module(*args, **kw):
    return InceptionModule(*args, **kw)


class Inception_v1(Module):
    """GoogLeNet main tower (reference Inception_v1.scala apply; the two
    aux classifiers are train-time extras the reference enables via
    hasAuxOutputs — main path here, aux heads optional)."""

    def __init__(self, class_num: int = 1000, has_dropout: bool = True):
        super().__init__()
        self.stem = nn.Sequential(
            _conv(3, 64, 7, 7, 2, 2, 3, 3, "conv1/7x7_s2"), nn.ReLU(),
            nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
            nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
            _conv(64, 64, 1, 1, name="conv2/3x3_reduce"), nn.ReLU(),
            _conv(64, 192, 3, 3, 1, 1, 1, 1, "conv2/3x3"), nn.ReLU(),
            nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
            nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        self.i3a = InceptionModule(192, 64, 96, 128, 16, 32, 32, "3a")
        self.i3b = InceptionModule(256, 128, 128, 192, 32, 96, 64, "3b")
        self.pool3 = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
        self.i4a = InceptionModule(480, 192, 96, 208, 16, 48, 64, "4a")
        self.i4b = InceptionModule(512, 160, 112, 224, 24, 64, 64, "4b")
        self.i4c = InceptionModule(512, 128, 128, 256, 24, 64, 64, "4c")
        self.i4d = InceptionModule(512, 112, 144, 288, 32, 64, 64, "4d")
        self.i4e = InceptionModule(528, 256, 160, 320, 32, 128, 128, "4e")
        self.pool4 = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
        self.i5a = InceptionModule(832, 256, 160, 320, 32, 128, 128, "5a")
        self.i5b = InceptionModule(832, 384, 192, 384, 48, 128, 128, "5b")
        self.has_dropout = has_dropout
        if has_dropout:
            self.dropout = nn.Dropout(0.4)
        self.head = nn.Linear(1024, class_num)

    def forward(self, x):
        y = self.stem(x)
        y = self.pool3(self.i3b(self.i3a(y)))
        y = self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(y)))))
        y = self.pool4(y)
        y = self.i5b(self.i5a(y))
        y = jnp.mean(y, axis=(1, 2))
        if self.has_dropout and self.training:
            y = self.dropout(y)
        return jax.nn.log_softmax(self.head(y))
