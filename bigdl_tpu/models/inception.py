"""Inception v1 (GoogLeNet) and v2 (BN-Inception).

Reference: models/inception/Inception_v1.scala and Inception_v2.scala.
NHWC; each inception module is parallel towers concatenated on the
channel axis (reference's Concat(2) over NCHW ⇒ channel-last concat
here).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core import init as init_methods
from bigdl_tpu.core.module import Module

__all__ = ["Inception_v1", "Inception_v2", "inception_module"]


def _conv(nin, nout, kw, kh, sw=1, sh=1, pw=0, ph=0, name=""):
    c = nn.SpatialConvolution(
        nin, nout, kw, kh, sw, sh, pw, ph,
        init_method=init_methods.Xavier)
    if name:
        c.set_name(name)
    return c


class InceptionModule(Module):
    """One inception block (reference Inception_v1.scala inception())."""

    def __init__(self, input_size, c1x1, c3x3r, c3x3, c5x5r, c5x5, pool_proj,
                 name="inception"):
        super().__init__()
        self.b1 = nn.Sequential(_conv(input_size, c1x1, 1, 1), nn.ReLU())
        self.b2 = nn.Sequential(
            _conv(input_size, c3x3r, 1, 1), nn.ReLU(),
            _conv(c3x3r, c3x3, 3, 3, 1, 1, 1, 1), nn.ReLU())
        self.b3 = nn.Sequential(
            _conv(input_size, c5x5r, 1, 1), nn.ReLU(),
            _conv(c5x5r, c5x5, 5, 5, 1, 1, 2, 2), nn.ReLU())
        self.b4 = nn.Sequential(
            nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1),
            _conv(input_size, pool_proj, 1, 1), nn.ReLU())
        self.set_name(name)

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=-1)


def inception_module(*args, **kw):
    return InceptionModule(*args, **kw)


class Inception_v1(Module):
    """GoogLeNet main tower (reference Inception_v1.scala apply; the two
    aux classifiers are train-time extras the reference enables via
    hasAuxOutputs — main path here, aux heads optional)."""

    def __init__(self, class_num: int = 1000, has_dropout: bool = True):
        super().__init__()
        self.stem = nn.Sequential(
            _conv(3, 64, 7, 7, 2, 2, 3, 3, "conv1/7x7_s2"), nn.ReLU(),
            nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
            nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
            _conv(64, 64, 1, 1, name="conv2/3x3_reduce"), nn.ReLU(),
            _conv(64, 192, 3, 3, 1, 1, 1, 1, "conv2/3x3"), nn.ReLU(),
            nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
            nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        self.i3a = InceptionModule(192, 64, 96, 128, 16, 32, 32, "3a")
        self.i3b = InceptionModule(256, 128, 128, 192, 32, 96, 64, "3b")
        self.pool3 = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
        self.i4a = InceptionModule(480, 192, 96, 208, 16, 48, 64, "4a")
        self.i4b = InceptionModule(512, 160, 112, 224, 24, 64, 64, "4b")
        self.i4c = InceptionModule(512, 128, 128, 256, 24, 64, 64, "4c")
        self.i4d = InceptionModule(512, 112, 144, 288, 32, 64, 64, "4d")
        self.i4e = InceptionModule(528, 256, 160, 320, 32, 128, 128, "4e")
        self.pool4 = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
        self.i5a = InceptionModule(832, 256, 160, 320, 32, 128, 128, "5a")
        self.i5b = InceptionModule(832, 384, 192, 384, 48, 128, 128, "5b")
        self.has_dropout = has_dropout
        if has_dropout:
            self.dropout = nn.Dropout(0.4)
        self.head = nn.Linear(1024, class_num)

    def forward(self, x):
        y = self.stem(x)
        y = self.pool3(self.i3b(self.i3a(y)))
        y = self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(y)))))
        y = self.pool4(y)
        y = self.i5b(self.i5a(y))
        y = jnp.mean(y, axis=(1, 2))
        if self.has_dropout and self.training:
            y = self.dropout(y)
        return jax.nn.log_softmax(self.head(y))


def _cbr(nin, nout, k, stride=1, pad=0, name=""):
    """conv → BN(eps 1e-3) → ReLU, the v2 building unit (reference
    Inception_v2.scala adds SpatialBatchNormalization(·, 1e-3) after
    every convolution)."""
    return [_conv(nin, nout, k, k, stride, stride, pad, pad, name),
            nn.SpatialBatchNormalization(nout, eps=1e-3),
            nn.ReLU()]


class InceptionV2Module(Module):
    """One BN-inception block (reference Inception_Layer_v2, Inception_
    v2.scala:28).  config = (c1 | c3r,c3 | d3r,d3 | pool_type,proj):
    optional 1x1 tower, a 3x3 tower, a DOUBLE-3x3 tower, and a pool
    tower with optional projection.  ``pool_type=="max"`` with proj 0
    is the reference's grid-reduction block: both conv towers stride 2,
    the pool strides 2, and the input rides through the pool tower
    unprojected."""

    def __init__(self, input_size, c1, c3r, c3, d3r, d3,
                 pool_type="avg", pool_proj=0, name="inception"):
        super().__init__()
        downsample = pool_type == "max" and pool_proj == 0
        self.downsample = downsample
        stride = 2 if downsample else 1
        if c1:
            self.b1 = nn.Sequential(*_cbr(input_size, c1, 1))
        self.has_b1 = bool(c1)
        self.b2 = nn.Sequential(*_cbr(input_size, c3r, 1),
                                *_cbr(c3r, c3, 3, stride, 1))
        self.b3 = nn.Sequential(*_cbr(input_size, d3r, 1),
                                *_cbr(d3r, d3, 3, 1, 1),
                                *_cbr(d3, d3, 3, stride, 1))
        if downsample:
            pool = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
        elif pool_type == "max":
            pool = nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil()
        else:
            pool = nn.SpatialAveragePooling(3, 3, 1, 1, 1, 1).ceil()
        layers = [pool]
        if pool_proj:
            layers += _cbr(input_size, pool_proj, 1)
        self.b4 = nn.Sequential(*layers)
        self.set_name(name)

    def forward(self, x):
        if self.downsample and (x.shape[1] % 2 or x.shape[2] % 2):
            # stride-2 conv towers floor the output size while the
            # ceil()-ed pool tower rounds up — on an ODD grid they
            # disagree by one pixel and the concat dies with an opaque
            # XLA shape error (the reference has the same constraint;
            # its fixed 224px recipe never hits it)
            raise ValueError(
                f"Inception_v2 grid-reduction block {self.name!r} needs "
                f"an even feature map, got {x.shape[1]}x{x.shape[2]}; "
                f"use an input size divisible by 32 (e.g. 224)")
        towers = ([self.b1(x)] if self.has_b1 else []) \
            + [self.b2(x), self.b3(x), self.b4(x)]
        return jnp.concatenate(towers, axis=-1)


class Inception_v2(Module):
    """BN-Inception main tower (reference Inception_v2_NoAuxClassifier,
    Inception_v2.scala:185; the full Inception_v2 object adds two
    train-time aux classifier heads — same design stance as v1 here:
    main path, aux heads are train-time extras)."""

    def __init__(self, class_num: int = 1000):
        super().__init__()
        self.stem = nn.Sequential(
            *_cbr(3, 64, 7, 2, 3, "conv1/7x7_s2"),
            nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
            *_cbr(64, 64, 1, name="conv2/3x3_reduce"),
            *_cbr(64, 192, 3, 1, 1, "conv2/3x3"),
            nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
        cfg = [
            (192, 64, 64, 64, 64, 96, "avg", 32, "3a"),
            (256, 64, 64, 96, 64, 96, "avg", 64, "3b"),
            (320, 0, 128, 160, 64, 96, "max", 0, "3c"),
            (576, 224, 64, 96, 96, 128, "avg", 128, "4a"),
            (576, 192, 96, 128, 96, 128, "avg", 128, "4b"),
            (576, 160, 128, 160, 128, 160, "avg", 96, "4c"),
            (576, 96, 128, 192, 160, 192, "avg", 96, "4d"),
            (576, 0, 128, 192, 192, 256, "max", 0, "4e"),
            (1024, 352, 192, 320, 160, 224, "avg", 128, "5a"),
            (1024, 352, 192, 320, 192, 224, "max", 128, "5b"),
        ]
        self.blocks = nn.ModuleList(
            [InceptionV2Module(*c[:-1], name=c[-1]) for c in cfg])
        self.head = nn.Linear(1024, class_num)

    def forward(self, x):
        y = self.stem(x)
        for b in self.blocks:
            y = b(y)
        y = jnp.mean(y, axis=(1, 2))  # ≙ 7x7 global average pool
        return jax.nn.log_softmax(self.head(y))
