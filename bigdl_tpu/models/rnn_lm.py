"""Language models: PTB word-level LSTM LM + char-level SimpleRNN.

Reference: example/languagemodel/PTBModel.scala (embedding → stacked
LSTM → TimeDistributed Linear → logsoftmax) and models/rnn/SimpleRNN.scala
(char-LM with RnnCell).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module

__all__ = ["PTBModel", "SimpleRNN"]


class PTBModel(Module):
    """Word LM (reference PTBModel.scala): LookupTable → num_layers LSTM
    → TimeDistributed(Linear) → logsoftmax over vocab.

    Input: [batch, time] 1-based word ids; output [batch, time, vocab]
    log-probs.
    """

    def __init__(self, input_size: int, hidden_size: int = 200,
                 output_size: int = None, num_layers: int = 2,
                 key_dim: int = 0, dropout: float = 0.0):
        super().__init__()
        output_size = output_size or input_size
        self.embedding = nn.LookupTable(input_size, hidden_size)
        cells = [nn.LSTM(hidden_size, hidden_size)
                 for _ in range(num_layers)]
        self.recurrent = nn.Recurrent(
            nn.MultiRNNCell(cells) if num_layers > 1 else cells[0])
        self.dropout_p = dropout
        if dropout > 0:
            self.dropout = nn.Dropout(dropout)
        self.decoder = nn.TimeDistributed(
            nn.Linear(hidden_size, output_size))

    def forward(self, ids):
        x = self.embedding(ids)
        h = self.recurrent(x)
        if self.dropout_p > 0 and self.training:
            h = self.dropout(h)
        return jax.nn.log_softmax(self.decoder(h), axis=-1)


def SimpleRNN(input_size: int = 128, hidden_size: int = 128,
              output_size: int = 128):
    """Char-level RNN LM (reference models/rnn/SimpleRNN.scala):
    one-hot input → RnnCell(tanh) → TimeDistributed Linear → logsoftmax."""
    return nn.Sequential(
        nn.Recurrent(nn.RnnCell(input_size, hidden_size, nn.Tanh())),
        nn.TimeDistributed(nn.Linear(hidden_size, output_size)),
        nn.LogSoftMax(),
    )
