"""Neural Collaborative Filtering (NCF / NeuMF).

The reference ships the evaluation half of this recipe in core —
``HitRatio``/``NDCG`` with the 1-positive + negNum-negatives protocol
(optim/ValidationMethod.scala:883,950) — and the MovieLens reader in
Python (pyspark/bigdl/dataset/movielens.py); this model is the standard
consumer of both: a GMF branch (elementwise product of user/item
embeddings) and an MLP branch over concatenated embeddings, fused by a
final linear into one interaction probability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module

__all__ = ["NeuralCF"]


class NeuralCF(Module):
    """NeuMF: sigmoid(Linear([gmf_u * gmf_i ; MLP([mlp_u ; mlp_i])])).

    Input: int id pairs ``[..., 2]`` (user, item), 1-based like the raw
    MovieLens files and LookupTable.  Output: scores ``[...]`` in (0,1).
    The leading shape is free, so the same forward scores a training
    batch ``[B, 2]`` and a HitRatio evaluation batch ``[B, 1+neg, 2]``.
    """

    def __init__(self, user_count: int, item_count: int,
                 embed_dim: int = 16, mlp_dims=(32, 16, 8)):
        super().__init__()
        self.gmf_user = nn.LookupTable(user_count, embed_dim)
        self.gmf_item = nn.LookupTable(item_count, embed_dim)
        self.mlp_user = nn.LookupTable(user_count, embed_dim)
        self.mlp_item = nn.LookupTable(item_count, embed_dim)
        layers = []
        nin = 2 * embed_dim
        for nout in mlp_dims:
            layers += [nn.Linear(nin, nout), nn.ReLU()]
            nin = nout
        self.mlp = nn.Sequential(*layers)
        self.head = nn.Linear(self.mlp_dims_out(mlp_dims) + embed_dim, 1)

    @staticmethod
    def mlp_dims_out(mlp_dims) -> int:
        return mlp_dims[-1] if mlp_dims else 0

    def forward(self, pairs):
        users = pairs[..., 0]
        items = pairs[..., 1]
        gmf = self.gmf_user(users) * self.gmf_item(items)
        mlp = self.mlp(jnp.concatenate(
            [self.mlp_user(users), self.mlp_item(items)], axis=-1))
        score = self.head(jnp.concatenate([gmf, mlp], axis=-1))
        return jax.nn.sigmoid(score[..., 0])
