"""Mask R-CNN (reference models/maskrcnn/MaskRCNN.scala:57, params case
class at :35).

ResNet-50-FPN backbone → RegionProposal → BoxHead → MaskHead, assembled
from the TPU-native detection stack (bigdl_tpu/nn/detection.py): every
stage keeps static shapes (fixed proposal/detection slots + validity
masks), so the entire detector jits into one XLA program — unlike the
reference whose post-processing runs in data-dependent Scala loops.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module, ModuleList
from bigdl_tpu.core import init as init_methods
from bigdl_tpu.models.resnet import Bottleneck
from bigdl_tpu.nn.detection import FPN, BoxHead, MaskHead, RegionProposal

__all__ = ["MaskRCNN", "MaskRCNNParams", "ResNetFPNBackbone"]


@dataclass
class MaskRCNNParams:
    """Mirrors reference MaskRCNNParams (models/maskrcnn/MaskRCNN.scala:35)."""
    anchor_sizes: Tuple[float, ...] = (32, 64, 128, 256, 512)
    aspect_ratios: Tuple[float, ...] = (0.5, 1.0, 2.0)
    anchor_stride: Tuple[float, ...] = (4, 8, 16, 32, 64)
    pre_nms_topn_test: int = 1000
    post_nms_topn_test: int = 1000
    pre_nms_topn_train: int = 2000
    post_nms_topn_train: int = 2000
    rpn_nms_thresh: float = 0.7
    min_size: int = 0
    box_resolution: int = 7
    mask_resolution: int = 14
    scales: Tuple[float, ...] = (0.25, 0.125, 0.0625, 0.03125)
    sampling_ratio: int = 2
    box_score_thresh: float = 0.05
    box_nms_thresh: float = 0.5
    max_per_image: int = 100
    output_size: int = 1024
    layers: Tuple[int, ...] = (256, 256, 256, 256)
    dilation: int = 1
    use_gn: bool = False


class ResNetFPNBackbone(Module):
    """ResNet-50 C2–C5 + FPN (reference MaskRCNN.buildBackbone).  The
    stem/stage-1 freeze of the reference recipe corresponds to excluding
    those params from the optimizer mask."""

    def __init__(self, out_channels: int = 256):
        super().__init__()
        self.stem_conv = nn.SpatialConvolution(
            3, 64, 7, 7, 2, 2, 3, 3, with_bias=False,
            init_method=init_methods.MsraFiller(False))
        self.stem_bn = nn.SpatialBatchNormalization(64, eps=1e-3)
        self.stem_pool = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)
        stages = []
        nin = 64
        for width, blocks, stride in ((64, 3, 1), (128, 4, 2),
                                      (256, 6, 2), (512, 3, 2)):
            stage = []
            for i in range(blocks):
                stage.append(Bottleneck(nin, width, stride if i == 0 else 1))
                nin = width * Bottleneck.expansion
            stages.append(ModuleList(stage))
        self.stages = ModuleList(stages)
        self.fpn = FPN([256, 512, 1024, 2048], out_channels, top_blocks=1)

    def forward(self, x) -> List[jnp.ndarray]:
        y = jax.nn.relu(self.stem_bn(self.stem_conv(x)))
        y = self.stem_pool(y)
        cs = []
        for stage in self.stages:
            for block in stage:
                y = block(y)
            cs.append(y)
        return self.fpn(cs)


class MaskRCNN(Module):
    """``forward((images (1, H, W, 3), image_info (4,)))`` →
    ``(boxes (maxPerImage, 4), labels, scores, valid,
    masks (maxPerImage, 2*maskRes, 2*maskRes))``.

    ``image_info`` carries (height, width, orig_height, orig_width) as in
    the reference (MaskRCNN.scala:168 updateOutput); the first two drive
    box clipping.  Resizing masks back to the original image size is a
    host-side visualization step (reference postProcessorForMaskRCNN) —
    kept out of the jitted graph.
    """

    def __init__(self, in_channels: int = 256, out_channels: int = 256,
                 num_classes: int = 81,
                 config: MaskRCNNParams = None):
        super().__init__()
        cfg = config or MaskRCNNParams()
        self.config = cfg
        self.backbone = ResNetFPNBackbone(out_channels)
        self.rpn = RegionProposal(
            in_channels, cfg.anchor_sizes, cfg.aspect_ratios,
            cfg.anchor_stride, cfg.pre_nms_topn_test,
            cfg.post_nms_topn_test, cfg.pre_nms_topn_train,
            cfg.post_nms_topn_train, cfg.rpn_nms_thresh, cfg.min_size)
        self.box_head = BoxHead(
            in_channels, cfg.box_resolution, cfg.scales,
            cfg.sampling_ratio, cfg.box_score_thresh, cfg.box_nms_thresh,
            cfg.max_per_image, cfg.output_size, num_classes)
        self.mask_head = MaskHead(
            in_channels, cfg.mask_resolution, cfg.scales,
            cfg.sampling_ratio, cfg.layers, cfg.dilation, num_classes,
            use_gn=cfg.use_gn)

    def forward(self, inputs):
        images, image_info = inputs
        im_hw = image_info[:2]
        features = self.backbone(images)
        proposals, prop_scores = self.rpn((features, im_hw))
        boxes, labels, scores, valid = self.box_head(
            (features, proposals, im_hw, prop_scores > -jnp.inf))
        masks, _ = self.mask_head((features, boxes, labels))
        masks = jnp.where(valid[:, None, None], masks, 0.0)
        return boxes, labels, scores, valid, masks
