"""MNIST autoencoder (reference models/autoencoder/Autoencoder.scala:
Reshape(784) → Linear(784, classNum) → ReLU → Linear(classNum, 784) →
Sigmoid)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module

__all__ = ["Autoencoder", "autoencoder"]

ROW_N = COL_N = 28
FEATURE_SIZE = ROW_N * COL_N


class Autoencoder(Module):
    def __init__(self, class_num: int = 32):
        super().__init__()
        self.encoder = nn.Linear(FEATURE_SIZE, class_num)
        self.decoder = nn.Linear(class_num, FEATURE_SIZE)

    def forward(self, x):
        y = x.reshape(x.shape[0], -1)
        y = jax.nn.relu(self.encoder(y))
        return jax.nn.sigmoid(self.decoder(y))


def autoencoder(class_num: int = 32) -> Autoencoder:
    return Autoencoder(class_num)
