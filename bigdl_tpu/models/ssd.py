"""SSD-300 VGG16 single-shot detector (BASELINE config #5).

Reference composition: the reference builds SSD by Caffe import
(utils/caffe/CaffeLoader.scala:57) over its PriorBox
(nn/PriorBox.scala:1), NormalizeScale (nn/NormalizeScale.scala) and
DetectionOutputSSD (nn/DetectionOutputSSD.scala:1) layers; the int8
SSD/VGG16 benchmark is whitepaper fig10 (docs/docs/whitepaper.md:192).
Here the same architecture is assembled natively (NHWC, XLA-fused) with
Caffe-SSD layer names throughout so ``load_caffe_weights`` drops a
published VGG_coco/VOC caffemodel straight in.

Input: [B, 300, 300, 3] mean-subtracted BGR (Caffe convention).
Output: [B, keep_top_k, 6] rows [label, score, x1, y1, x2, y2] in
normalized [0, 1] coordinates.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module, ModuleList

__all__ = ["SSDVGG16", "ssd_vgg16_300"]

# (min_size, max_size, aspect_ratios, step, n_priors) per source map
_SSD300_PRIORS = [
    (30.0, 60.0, (2.0,), 8.0, 4),          # conv4_3_norm, 38x38
    (60.0, 111.0, (2.0, 3.0), 16.0, 6),    # fc7, 19x19
    (111.0, 162.0, (2.0, 3.0), 32.0, 6),   # conv6_2, 10x10
    (162.0, 213.0, (2.0, 3.0), 64.0, 6),   # conv7_2, 5x5
    (213.0, 264.0, (2.0,), 100.0, 4),      # conv8_2, 3x3
    (264.0, 315.0, (2.0,), 300.0, 4),      # conv9_2, 1x1
]


def _conv(nin, nout, k, stride=1, pad=0, dilation=1, name=""):
    if dilation != 1:
        m = nn.SpatialDilatedConvolution(nin, nout, k, k, stride, stride,
                                         pad, pad, dilation, dilation)
    else:
        m = nn.SpatialConvolution(nin, nout, k, k, stride, stride, pad, pad)
    return m.set_name(name)


class SSDVGG16(Module):
    """SSD-300 over the modified VGG16 base (fc6/fc7 as atrous convs,
    pool5 3x3/s1, L2-normalized conv4_3 source)."""

    def __init__(self, class_num: int = 21, nms_thresh: float = 0.45,
                 nms_topk: int = 400, keep_top_k: int = 200,
                 conf_thresh: float = 0.01):
        super().__init__()
        self.class_num = class_num

        # VGG16 base, Caffe-SSD layer names
        cfg = [(3, 64, "conv1_1"), (64, 64, "conv1_2"),
               (64, 128, "conv2_1"), (128, 128, "conv2_2"),
               (128, 256, "conv3_1"), (256, 256, "conv3_2"),
               (256, 256, "conv3_3"),
               (256, 512, "conv4_1"), (512, 512, "conv4_2"),
               (512, 512, "conv4_3"),
               (512, 512, "conv5_1"), (512, 512, "conv5_2"),
               (512, 512, "conv5_3")]
        self.base = ModuleList(
            [_conv(i, o, 3, pad=1, name=nm) for i, o, nm in cfg])
        self.pool = nn.SpatialMaxPooling(2, 2, 2, 2)
        self.pool_ceil = nn.SpatialMaxPooling(2, 2, 2, 2).ceil()
        self.pool5 = nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1)
        self.fc6 = _conv(512, 1024, 3, pad=6, dilation=6, name="fc6")
        self.fc7 = _conv(1024, 1024, 1, name="fc7")

        # extra feature layers
        self.conv6_1 = _conv(1024, 256, 1, name="conv6_1")
        self.conv6_2 = _conv(256, 512, 3, stride=2, pad=1, name="conv6_2")
        self.conv7_1 = _conv(512, 128, 1, name="conv7_1")
        self.conv7_2 = _conv(128, 256, 3, stride=2, pad=1, name="conv7_2")
        self.conv8_1 = _conv(256, 128, 1, name="conv8_1")
        self.conv8_2 = _conv(128, 256, 3, name="conv8_2")
        self.conv9_1 = _conv(256, 128, 1, name="conv9_1")
        self.conv9_2 = _conv(128, 256, 3, name="conv9_2")

        self.conv4_3_norm = nn.NormalizeScale(
            p=2.0, scale=20.0, size=(512,)).set_name("conv4_3_norm")

        src_channels = [512, 1024, 512, 256, 256, 256]
        src_names = ["conv4_3_norm", "fc7", "conv6_2", "conv7_2",
                     "conv8_2", "conv9_2"]
        locs, confs, priors = [], [], []
        for ch, name, (mn, mx, ars, step, np_) in zip(
                src_channels, src_names, _SSD300_PRIORS):
            locs.append(_conv(ch, np_ * 4, 3, pad=1,
                              name=f"{name}_mbox_loc"))
            confs.append(_conv(ch, np_ * class_num, 3, pad=1,
                               name=f"{name}_mbox_conf"))
            priors.append(nn.PriorBox(
                min_sizes=[mn], max_sizes=[mx], aspect_ratios=list(ars),
                is_flip=True, is_clip=False,
                variances=[0.1, 0.1, 0.2, 0.2], offset=0.5,
                img_size=300, step=step))
        self.loc_layers = ModuleList(locs)
        self.conf_layers = ModuleList(confs)
        self.prior_layers = ModuleList(priors)
        self.detection = nn.DetectionOutputSSD(
            n_classes=class_num, nms_thresh=nms_thresh, nms_topk=nms_topk,
            keep_top_k=keep_top_k, conf_thresh=conf_thresh)

    def feature_maps(self, x) -> List:
        """The six SSD source maps (conv4_3_norm … conv9_2)."""
        r = jax.nn.relu
        i = 0
        for upto, pool in ((2, self.pool), (4, self.pool),
                           (7, self.pool_ceil)):
            while i < upto:
                x = r(self.base[i](x))
                i += 1
            x = pool(x)
        while i < 10:
            x = r(self.base[i](x))
            i += 1
        s1 = self.conv4_3_norm(x)
        x = self.pool(x)
        while i < 13:
            x = r(self.base[i](x))
            i += 1
        x = self.pool5(x)
        x = r(self.fc6(x))
        s2 = r(self.fc7(x))
        x = r(self.conv6_1(s2))
        s3 = r(self.conv6_2(x))
        x = r(self.conv7_1(s3))
        s4 = r(self.conv7_2(x))
        x = r(self.conv8_1(s4))
        s5 = r(self.conv8_2(x))
        x = r(self.conv9_1(s5))
        s6 = r(self.conv9_2(x))
        return [s1, s2, s3, s4, s5, s6]

    def forward(self, x):
        sources = self.feature_maps(x)
        b = x.shape[0]
        locs, confs, priors = [], [], []
        for src, loc_l, conf_l, prior_l in zip(
                sources, self.loc_layers, self.conf_layers,
                self.prior_layers):
            locs.append(loc_l(src).reshape(b, -1))
            confs.append(conf_l(src).reshape(b, -1))
            priors.append(prior_l(src))
        loc = jnp.concatenate(locs, axis=1)
        conf = jnp.concatenate(confs, axis=1)
        prior = jnp.concatenate(priors, axis=1)
        conf = jax.nn.softmax(
            conf.reshape(b, -1, self.class_num), axis=-1).reshape(b, -1)
        return self.detection((loc, conf, prior))


def ssd_vgg16_300(class_num: int = 21, **kw) -> SSDVGG16:
    """SSD-300 VGG16 (the whitepaper fig10 int8 benchmark model)."""
    return SSDVGG16(class_num=class_num, **kw)
