from bigdl_tpu.models.lenet import LeNet5, lenet5_graph
from bigdl_tpu.models.resnet import (
    ResNet, resnet_cifar, resnet50, BasicBlock, Bottleneck,
)
from bigdl_tpu.models.inception import Inception_v1, Inception_v2
from bigdl_tpu.models.vgg import VggForCifar10, Vgg_16, Vgg_19
from bigdl_tpu.models.rnn_lm import PTBModel, SimpleRNN
from bigdl_tpu.models.autoencoder import Autoencoder, autoencoder
from bigdl_tpu.models.maskrcnn import (
    MaskRCNN, MaskRCNNParams, ResNetFPNBackbone,
)
from bigdl_tpu.models.ssd import SSDVGG16, ssd_vgg16_300
from bigdl_tpu.models.transformer_lm import TransformerLM, transformer_lm
from bigdl_tpu.models.ncf import NeuralCF
from bigdl_tpu.models.dlrm import WideAndDeep, wide_and_deep

# ---------------------------------------------------------------------------
# Zoo registry: name → builder, for CLI entry points (serving demo, tools)
# that take a model by name.  Only models constructible with no required
# arguments are listed; kwargs pass through to the builder.
# ---------------------------------------------------------------------------

def _transformer_lm_tiny(**kwargs):
    """Small decoder-only LM for the serving demos: big enough to show
    continuous batching winning, small enough to compile in seconds on
    the CPU backend."""
    cfg = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
               filter_size=128, max_len=128)
    cfg.update(kwargs)
    return transformer_lm(**cfg)


_ZOO = {
    "lenet5": LeNet5,
    "lenet5_graph": lenet5_graph,
    "autoencoder": autoencoder,
    "resnet_cifar": resnet_cifar,
    "vgg_cifar10": VggForCifar10,
    "transformer_lm_tiny": _transformer_lm_tiny,
    "wide_and_deep": wide_and_deep,
}

# per-sample (unbatched) input shape each zoo model expects, used by the
# serving CLI to parse stdin rows and warm up bucket shapes
_ZOO_SAMPLE_SHAPES = {
    "lenet5": (784,),
    "lenet5_graph": (784,),
    "autoencoder": (784,),
    "resnet_cifar": (32, 32, 3),
    "vgg_cifar10": (32, 32, 3),
    # (user, item) 1-based id pair — the scoring row RecommenderScorer
    # ships as the router "prompt"
    "wide_and_deep": (2,),
}


def zoo(name: str, **kwargs):
    """Build a zoo model by name (e.g. ``zoo('lenet5', class_num=10)``)."""
    try:
        builder = _ZOO[name]
    except KeyError:
        raise ValueError(
            f"unknown zoo model {name!r}; available: {sorted(_ZOO)}") \
            from None
    return builder(**kwargs)


def zoo_sample_shape(name: str):
    """Per-sample input shape for a zoo model (serving CLI contract)."""
    if name not in _ZOO_SAMPLE_SHAPES:
        raise ValueError(f"no registered sample shape for {name!r}; "
                         f"available: {sorted(_ZOO_SAMPLE_SHAPES)}")
    return _ZOO_SAMPLE_SHAPES[name]
