from bigdl_tpu.models.lenet import LeNet5, lenet5_graph
from bigdl_tpu.models.resnet import (
    ResNet, resnet_cifar, resnet50, BasicBlock, Bottleneck,
)
from bigdl_tpu.models.inception import Inception_v1, Inception_v2
from bigdl_tpu.models.vgg import VggForCifar10, Vgg_16, Vgg_19
from bigdl_tpu.models.rnn_lm import PTBModel, SimpleRNN
from bigdl_tpu.models.autoencoder import Autoencoder, autoencoder
from bigdl_tpu.models.maskrcnn import (
    MaskRCNN, MaskRCNNParams, ResNetFPNBackbone,
)
from bigdl_tpu.models.ssd import SSDVGG16, ssd_vgg16_300
from bigdl_tpu.models.transformer_lm import TransformerLM, transformer_lm
from bigdl_tpu.models.ncf import NeuralCF
