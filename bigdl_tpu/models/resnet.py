"""ResNet for CIFAR-10 (basic blocks) and ImageNet (bottleneck, ResNet-50).

Reference: models/resnet/ResNet.scala (shortcutType A/B, basicBlock,
bottleneck, iChannels plumbing) and TrainImageNet.scala.  NHWC layout,
MSRA init for convs, BN gamma-last-zero trick (optimnet in the reference
README recipe) supported via ``zero_init_residual``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core import init as init_methods
from bigdl_tpu.core.module import Module

__all__ = ["ResNet", "resnet_cifar", "resnet50", "BasicBlock", "Bottleneck"]


def _conv(nin, nout, k, stride=1, pad=0):
    return nn.SpatialConvolution(
        nin, nout, k, k, stride, stride, pad, pad, with_bias=False,
        init_method=init_methods.MsraFiller(False))


class BasicBlock(Module):
    """3x3+3x3 residual block (reference ResNet.scala basicBlock)."""

    expansion = 1

    def __init__(self, nin, nout, stride=1, zero_init_residual=True):
        super().__init__()
        self.conv1 = _conv(nin, nout, 3, stride, 1)
        self.bn1 = nn.SpatialBatchNormalization(nout)
        self.conv2 = _conv(nout, nout, 3, 1, 1)
        self.bn2 = nn.SpatialBatchNormalization(
            nout, init_weight=(jnp.zeros(nout) if zero_init_residual
                               else None))
        if stride != 1 or nin != nout:
            self.down_conv = _conv(nin, nout, 1, stride, 0)
            self.down_bn = nn.SpatialBatchNormalization(nout)
        self.has_down = stride != 1 or nin != nout

    def forward(self, x):
        import jax
        y = jax.nn.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        sc = self.down_bn(self.down_conv(x)) if self.has_down else x
        return jax.nn.relu(y + sc)


class Bottleneck(Module):
    """1x1/3x3/1x1 bottleneck (reference ResNet.scala bottleneck).

    ``fused=True`` (or env BIGDL_TPU_FUSED_CONVBN) routes the training
    forward through the fused conv+BN+ReLU Pallas kernels
    (ops/conv_bn_kernels.py): the 1x1 convs run as matmul kernels whose
    epilogue accumulates the following BN's batch statistics; the
    stride-1 3x3 conv2 runs as the 9-shift kernel with bn1's
    normalize+ReLU applied on the fly; conv3's kernel applies bn2's the
    same way — the normalized activations inside the block never touch
    HBM (strided conv2 keeps the XLA emitter).
    Numerics match the unfused path (same rounding points; test-locked).
    Eval mode, non-NHWC, and non-TPU backends fall back to the plain
    path (``fused="force"`` or env "force" overrides the backend check
    and runs the kernels in interpret mode — tests/debug only).
    """

    expansion = 4

    def __init__(self, nin, planes, stride=1, zero_init_residual=True,
                 fused=False):
        super().__init__()
        nout = planes * self.expansion
        self.conv1 = _conv(nin, planes, 1)
        self.bn1 = nn.SpatialBatchNormalization(planes)
        self.conv2 = _conv(planes, planes, 3, stride, 1)
        self.bn2 = nn.SpatialBatchNormalization(planes)
        self.conv3 = _conv(planes, nout, 1)
        self.bn3 = nn.SpatialBatchNormalization(
            nout, init_weight=(jnp.zeros(nout) if zero_init_residual
                               else None))
        if stride != 1 or nin != nout:
            self.down_conv = _conv(nin, nout, 1, stride, 0)
            self.down_bn = nn.SpatialBatchNormalization(nout)
        self.has_down = stride != 1 or nin != nout
        self.fused = fused

    _FUSABLE = frozenset({"conv1", "conv2", "conv3"})

    def _fused_selection(self):
        """Which convs to fuse.  env BIGDL_TPU_FUSED_CONVBN may be "0"
        (off everywhere), "1" (default set), "force" (fuse even off-TPU,
        via the interpret-mode kernels — tests/debug only), or a comma
        list drawn from {conv1, conv2, conv3} (optionally with
        "force").

        Off-TPU the kernels only run in Pallas interpret mode — orders
        of magnitude slower than XLA — so without an explicit "force"
        (env or ``fused="force"``) the plain path is used there."""
        import os
        from bigdl_tpu.ops.attention_kernels import _on_tpu
        env = os.environ.get("BIGDL_TPU_FUSED_CONVBN")
        if env == "0" or (not self.fused and not env):
            return None
        if not self.training or self.bn1.data_format != "NHWC":
            return None
        parts = {p.strip() for p in (env or "").split(",")
                 if p.strip() not in ("", "0", "1")}
        force = self.fused == "force" or "force" in parts
        parts -= {"force"}
        unknown = parts - self._FUSABLE
        if unknown:
            raise ValueError(
                f"BIGDL_TPU_FUSED_CONVBN: unknown selector(s) "
                f"{sorted(unknown)}; valid: {sorted(self._FUSABLE)}, "
                "force, 0, 1")
        if not force and not _on_tpu():
            return None
        return parts or set(self._FUSABLE)

    def forward(self, x):
        import jax
        sel = self._fused_selection()
        if sel is not None:
            return self._forward_fused(x, sel)
        y = jax.nn.relu(self.bn1(self.conv1(x)))
        y = jax.nn.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        sc = self.down_bn(self.down_conv(x)) if self.has_down else x
        return jax.nn.relu(y + sc)

    def _forward_fused(self, x, sel):
        import jax
        from bigdl_tpu.ops.attention_kernels import _on_tpu
        from bigdl_tpu.ops import conv_bn_kernels as ck

        interp = not _on_tpu()
        stop = jax.lax.stop_gradient

        def norm_vectors(bn, mean, var):
            """(mean, scale, beta) f32 vectors folding bn's batch stats
            to the kernel's subtract-first normalize form."""
            inv = jax.lax.rsqrt(var.astype(jnp.float32) + bn.eps)
            return (mean.astype(jnp.float32),
                    inv * bn.weight.astype(jnp.float32),
                    bn.bias.astype(jnp.float32))

        # conv1: plain 1x1 matmul + bn1-stats epilogue
        b, h, w, cin = x.shape
        w1 = self.conv1.weight[0, 0]
        m1, n1 = b * h * w, w1.shape[1]
        if "conv1" in sel and ck.fused_block_supported(
                m1, cin, n1, x.dtype.itemsize):
            y1, s1, s2 = ck.fused_matmul_bn(
                x.reshape(m1, cin), w1,
                kshift=stop(self.bn1.running_mean), interpret=interp)
            y1 = y1.reshape(b, h, w, n1)
            mean1, var1 = self.bn1.fold_stats(s1 / m1, s2 / m1, m1)
        else:
            y1 = self.conv1(x)
            d1, q1 = self.bn1.batch_stats(y1)
            mean1, var1 = self.bn1.fold_stats(d1, q1, m1)
        # conv2: stride-1 3x3 goes through the fused 9-shift Pallas
        # kernel with bn1's normalize+relu applied on the fly (z1 never
        # materialized in that case) and bn2's stats as the epilogue;
        # strided conv2 (first block of a stage) stays on the XLA conv
        # emitter with only its BN statistics computed here
        stride1 = self.conv2.stride == (1, 1)
        w2 = self.conv2.weight
        if ("conv2" in sel and stride1
                and ck.fused_conv3x3_supported(
                    y1.shape[1], y1.shape[2], y1.shape[3], w2.shape[-1],
                    y1.dtype.itemsize)):
            y2, u1, u2 = ck.fused_conv3x3_bn(
                y1, w2, norm=norm_vectors(self.bn1, mean1, var1),
                kshift=stop(self.bn2.running_mean), interpret=interp)
            m2n = self.bn2.stat_count(y2)
            mean2, var2 = self.bn2.fold_stats(u1 / m2n, u2 / m2n, m2n)
        else:
            z1 = jax.nn.relu(self.bn1.normalize(y1, mean1, var1))
            y2 = self.conv2(z1)
            d2, q2 = self.bn2.batch_stats(y2)
            mean2, var2 = self.bn2.fold_stats(d2, q2,
                                              self.bn2.stat_count(y2))

        bb, hh, ww, p = y2.shape
        w3 = self.conv3.weight[0, 0]
        m3, n3 = bb * hh * ww, w3.shape[1]
        if "conv3" in sel and ck.fused_block_supported(
                m3, p, n3, y2.dtype.itemsize):
            y3, t1, t2 = ck.fused_matmul_bn(
                y2.reshape(m3, p), w3,
                norm=norm_vectors(self.bn2, mean2, var2),
                kshift=stop(self.bn3.running_mean), interpret=interp)
            y3 = y3.reshape(bb, hh, ww, n3)
            mean3, var3 = self.bn3.fold_stats(t1 / m3, t2 / m3, m3)
        else:
            z2 = jax.nn.relu(self.bn2.normalize(y2, mean2, var2))
            y3 = self.conv3(z2)
            d3, q3 = self.bn3.batch_stats(y3)
            mean3, var3 = self.bn3.fold_stats(d3, q3, m3)

        z3 = self.bn3.normalize(y3, mean3, var3)
        sc = self.down_bn(self.down_conv(x)) if self.has_down else x
        return jax.nn.relu(z3 + sc)


class ResNet(Module):
    """Reference ResNet.scala apply(): ImageNet stem + 4 stages."""

    def __init__(self, block, layers, class_num=1000, cifar=False,
                 zero_init_residual=True, fused=False):
        super().__init__()
        self.cifar = cifar
        if cifar:
            self.stem_conv = _conv(3, 16, 3, 1, 1)
            self.stem_bn = nn.SpatialBatchNormalization(16)
            nin = 16
            widths = [16, 32, 64]
            strides = [1, 2, 2]
        else:
            self.stem_conv = nn.SpatialConvolution(
                3, 64, 7, 7, 2, 2, 3, 3, with_bias=False,
                init_method=init_methods.MsraFiller(False))
            self.stem_bn = nn.SpatialBatchNormalization(64)
            self.stem_pool = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)
            nin = 64
            widths = [64, 128, 256, 512]
            strides = [1, 2, 2, 2]
        blocks = []
        for w, s, n in zip(widths, strides, layers):
            for i in range(n):
                kw = {"fused": fused} if block is Bottleneck else {}
                blocks.append(block(nin, w, s if i == 0 else 1,
                                    zero_init_residual, **kw))
                nin = w * block.expansion
        self.blocks = nn.ModuleList(blocks)
        self.head = nn.Linear(nin, class_num,
                              init_method=init_methods.RandomNormal(0, 0.01))

    def forward(self, x):
        import jax
        y = jax.nn.relu(self.stem_bn(self.stem_conv(x)))
        if not self.cifar:
            y = self.stem_pool(y)
        for b in self.blocks:
            y = b(y)
        y = jnp.mean(y, axis=(1, 2))  # global average pool
        return self.head(y)


def resnet_cifar(depth: int = 20, class_num: int = 10) -> ResNet:
    """CIFAR ResNet (reference ResNet.scala CIFAR-10 path): depth=6n+2."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    return ResNet(BasicBlock, [n, n, n], class_num, cifar=True)


def resnet50(class_num: int = 1000, fused: bool = False) -> ResNet:
    """ImageNet ResNet-50 (reference TrainImageNet recipe).

    ``fused=True``: train-mode bottlenecks use the fused conv+BN+ReLU
    Pallas kernels (see Bottleneck docstring)."""
    return ResNet(Bottleneck, [3, 4, 6, 3], class_num, fused=fused)
