"""ResNet for CIFAR-10 (basic blocks) and ImageNet (bottleneck, ResNet-50).

Reference: models/resnet/ResNet.scala (shortcutType A/B, basicBlock,
bottleneck, iChannels plumbing) and TrainImageNet.scala.  NHWC layout,
MSRA init for convs, BN gamma-last-zero trick (optimnet in the reference
README recipe) supported via ``zero_init_residual``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core import init as init_methods
from bigdl_tpu.core.module import Module

__all__ = ["ResNet", "resnet_cifar", "resnet50", "BasicBlock", "Bottleneck"]


def _conv(nin, nout, k, stride=1, pad=0):
    return nn.SpatialConvolution(
        nin, nout, k, k, stride, stride, pad, pad, with_bias=False,
        init_method=init_methods.MsraFiller(False))


class BasicBlock(Module):
    """3x3+3x3 residual block (reference ResNet.scala basicBlock)."""

    expansion = 1

    def __init__(self, nin, nout, stride=1, zero_init_residual=True):
        super().__init__()
        self.conv1 = _conv(nin, nout, 3, stride, 1)
        self.bn1 = nn.SpatialBatchNormalization(nout)
        self.conv2 = _conv(nout, nout, 3, 1, 1)
        self.bn2 = nn.SpatialBatchNormalization(
            nout, init_weight=(jnp.zeros(nout) if zero_init_residual
                               else None))
        if stride != 1 or nin != nout:
            self.down_conv = _conv(nin, nout, 1, stride, 0)
            self.down_bn = nn.SpatialBatchNormalization(nout)
        self.has_down = stride != 1 or nin != nout

    def forward(self, x):
        import jax
        y = jax.nn.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        sc = self.down_bn(self.down_conv(x)) if self.has_down else x
        return jax.nn.relu(y + sc)


class Bottleneck(Module):
    """1x1/3x3/1x1 bottleneck (reference ResNet.scala bottleneck)."""

    expansion = 4

    def __init__(self, nin, planes, stride=1, zero_init_residual=True):
        super().__init__()
        nout = planes * self.expansion
        self.conv1 = _conv(nin, planes, 1)
        self.bn1 = nn.SpatialBatchNormalization(planes)
        self.conv2 = _conv(planes, planes, 3, stride, 1)
        self.bn2 = nn.SpatialBatchNormalization(planes)
        self.conv3 = _conv(planes, nout, 1)
        self.bn3 = nn.SpatialBatchNormalization(
            nout, init_weight=(jnp.zeros(nout) if zero_init_residual
                               else None))
        if stride != 1 or nin != nout:
            self.down_conv = _conv(nin, nout, 1, stride, 0)
            self.down_bn = nn.SpatialBatchNormalization(nout)
        self.has_down = stride != 1 or nin != nout

    def forward(self, x):
        import jax
        y = jax.nn.relu(self.bn1(self.conv1(x)))
        y = jax.nn.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        sc = self.down_bn(self.down_conv(x)) if self.has_down else x
        return jax.nn.relu(y + sc)


class ResNet(Module):
    """Reference ResNet.scala apply(): ImageNet stem + 4 stages."""

    def __init__(self, block, layers, class_num=1000, cifar=False,
                 zero_init_residual=True):
        super().__init__()
        self.cifar = cifar
        if cifar:
            self.stem_conv = _conv(3, 16, 3, 1, 1)
            self.stem_bn = nn.SpatialBatchNormalization(16)
            nin = 16
            widths = [16, 32, 64]
            strides = [1, 2, 2]
        else:
            self.stem_conv = nn.SpatialConvolution(
                3, 64, 7, 7, 2, 2, 3, 3, with_bias=False,
                init_method=init_methods.MsraFiller(False))
            self.stem_bn = nn.SpatialBatchNormalization(64)
            self.stem_pool = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)
            nin = 64
            widths = [64, 128, 256, 512]
            strides = [1, 2, 2, 2]
        blocks = []
        for w, s, n in zip(widths, strides, layers):
            for i in range(n):
                blocks.append(block(nin, w, s if i == 0 else 1,
                                    zero_init_residual))
                nin = w * block.expansion
        self.blocks = nn.ModuleList(blocks)
        self.head = nn.Linear(nin, class_num,
                              init_method=init_methods.RandomNormal(0, 0.01))

    def forward(self, x):
        import jax
        y = jax.nn.relu(self.stem_bn(self.stem_conv(x)))
        if not self.cifar:
            y = self.stem_pool(y)
        for b in self.blocks:
            y = b(y)
        y = jnp.mean(y, axis=(1, 2))  # global average pool
        return self.head(y)


def resnet_cifar(depth: int = 20, class_num: int = 10) -> ResNet:
    """CIFAR ResNet (reference ResNet.scala CIFAR-10 path): depth=6n+2."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    return ResNet(BasicBlock, [n, n, n], class_num, cifar=True)


def resnet50(class_num: int = 1000) -> ResNet:
    """ImageNet ResNet-50 (reference TrainImageNet recipe)."""
    return ResNet(Bottleneck, [3, 4, 6, 3], class_num)
