"""LeNet-5 (reference models/lenet/LeNet5.scala:26 apply, :42 graph)."""

from __future__ import annotations

import bigdl_tpu.nn as nn

__all__ = ["LeNet5", "lenet5_graph"]


def LeNet5(class_num: int = 10) -> nn.Sequential:
    """Sequential LeNet-5 (LeNet5.scala:26): conv5x5x6 → tanh → pool →
    conv5x5x12 → tanh → pool → fc100 → tanh → fc{classes} → logsoftmax.
    NHWC [batch, 28, 28, 1] input."""
    return nn.Sequential(
        nn.Reshape((28, 28, 1), batch_mode=True),
        nn.SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5"),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Flatten(),
        nn.Linear(12 * 4 * 4, 100).set_name("fc1"),
        nn.Tanh(),
        nn.Linear(100, class_num).set_name("fc2"),
        nn.LogSoftMax(),
    )


def lenet5_graph(class_num: int = 10) -> nn.Graph:
    """Graph-container variant (LeNet5.scala:42 graph())."""
    inp = nn.Input()
    x = nn.Reshape((28, 28, 1), batch_mode=True)(inp)
    x = nn.SpatialConvolution(1, 6, 5, 5)(x)
    x = nn.Tanh()(x)
    x = nn.SpatialMaxPooling(2, 2, 2, 2)(x)
    x = nn.SpatialConvolution(6, 12, 5, 5)(x)
    x = nn.Tanh()(x)
    x = nn.SpatialMaxPooling(2, 2, 2, 2)(x)
    x = nn.Flatten()(x)
    x = nn.Linear(12 * 4 * 4, 100)(x)
    x = nn.Tanh()(x)
    x = nn.Linear(100, class_num)(x)
    out = nn.LogSoftMax()(x)
    return nn.Graph(inp, out)
