"""VGG-16/19 + the CIFAR-10 variant.

Reference: models/vgg/VggForCifar10.scala and the Vgg_16/Vgg_19 builders
used by the perf tool (models/utils/DistriOptimizerPerf.scala).
"""

from __future__ import annotations

import bigdl_tpu.nn as nn

__all__ = ["VggForCifar10", "Vgg_16", "Vgg_19"]


def _block(seq, nin, nout, with_bn=True):
    seq.add(nn.SpatialConvolution(nin, nout, 3, 3, 1, 1, 1, 1))
    if with_bn:
        seq.add(nn.SpatialBatchNormalization(nout, 1e-3))
    seq.add(nn.ReLU())
    return nout


def VggForCifar10(class_num: int = 10, has_dropout: bool = True):
    """Conv-BN VGG for 32x32 inputs (reference VggForCifar10.scala)."""
    m = nn.Sequential()
    cfg = [(3, 64), (64, 64), "M", (64, 128), (128, 128), "M",
           (128, 256), (256, 256), (256, 256), "M",
           (256, 512), (512, 512), (512, 512), "M",
           (512, 512), (512, 512), (512, 512), "M"]
    for c in cfg:
        if c == "M":
            m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
        else:
            _block(m, c[0], c[1])
    m.add(nn.Flatten())
    m.add(nn.Linear(512, 512))
    m.add(nn.BatchNormalization(512))
    m.add(nn.ReLU())
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(512, class_num))
    m.add(nn.LogSoftMax())
    return m


def _vgg(cfg, class_num, has_dropout=True):
    m = nn.Sequential()
    nin = 3
    for c in cfg:
        if c == "M":
            m.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            nin = _block(m, nin, c, with_bn=False)
    m.add(nn.Flatten())
    m.add(nn.Linear(512 * 7 * 7, 4096))
    m.add(nn.ReLU())
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096))
    m.add(nn.ReLU())
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num))
    m.add(nn.LogSoftMax())
    return m


def Vgg_16(class_num: int = 1000, has_dropout: bool = True):
    return _vgg([64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                 512, 512, 512, "M", 512, 512, 512, "M"],
                class_num, has_dropout)


def Vgg_19(class_num: int = 1000, has_dropout: bool = True):
    return _vgg([64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
                 512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
                class_num, has_dropout)
