"""Wide-and-deep recommender over mesh-sharded embedding tables.

The DLRM/wide-and-deep shape (reference: the WideAndDeep zoo model the
BigDL examples ship; SURVEY §2.5) rebuilt as the first sparse-dense
HYBRID consumer: four :class:`~bigdl_tpu.embedding.ShardedEmbeddingTable`
leaves (deep user/item vectors plus dim-1 wide biases — the
memorization term of Cheng et al.'s wide component, reduced to its
id-cross essence) feeding a dp-replicated dense MLP tower.  Input is a
``[..., 2]`` (user, item) id-pair tensor, 1-based like
:class:`~bigdl_tpu.models.ncf.NeuralCF`; the leading shape is free so
the same forward scores training pairs ``[B, 2]`` and ranking slates
``[B, 1+neg, 2]``.

Trained through :func:`bigdl_tpu.embedding.configure_hybrid`: the
tables row-shard over the batch axis and update sparsely, the tower
all-reduces — one ``optimize()`` step, two gradient disciplines.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module
from bigdl_tpu.embedding.sharded_table import ShardedEmbeddingTable

__all__ = ["WideAndDeep", "wide_and_deep"]


class WideAndDeep(Module):
    """Wide (per-id biases) + deep (embedding MLP) scorer in [0, 1]."""

    def __init__(self, user_count: int, item_count: int,
                 embed_dim: int = 16,
                 mlp_dims: Sequence[int] = (32, 16)):
        super().__init__()
        self.user_count = int(user_count)
        self.item_count = int(item_count)
        # tables at top level: the hybrid per-table OptimMethods split
        # keys on these attribute names (embedding/hybrid.py)
        self.user_table = ShardedEmbeddingTable(user_count, embed_dim,
                                                name="user_table")
        self.item_table = ShardedEmbeddingTable(item_count, embed_dim,
                                                name="item_table")
        self.wide_user = ShardedEmbeddingTable(user_count, 1,
                                               name="wide_user")
        self.wide_item = ShardedEmbeddingTable(item_count, 1,
                                               name="wide_item")
        layers = []
        prev = 2 * embed_dim
        for d in mlp_dims:
            layers += [nn.Linear(prev, d), nn.ReLU()]
            prev = d
        layers.append(nn.Linear(prev, 1))
        self.tower = nn.Sequential(*layers)

    def set_mesh(self, mesh, axis: str = "data") -> "WideAndDeep":
        """Shard every table over ``axis`` (the tower stays
        replicated); ``configure_hybrid`` calls this via the table
        walk, this spelling is for standalone use."""
        for t in (self.user_table, self.item_table,
                  self.wide_user, self.wide_item):
            t.set_mesh(mesh, axis)
        return self

    def forward(self, pairs):
        pairs = jnp.asarray(pairs)
        users, items = pairs[..., 0], pairs[..., 1]
        deep = self.tower(jnp.concatenate(
            [self.user_table(users), self.item_table(items)], axis=-1))
        wide = self.wide_user(users) + self.wide_item(items)
        return jax.nn.sigmoid(deep + wide)


def wide_and_deep(user_count: int = 256, item_count: int = 128,
                  embed_dim: int = 16,
                  mlp_dims: Sequence[int] = (32, 16)) -> WideAndDeep:
    """Zoo builder: defaults divide evenly over the 8-device mesh so
    the serving demo and the budget probe shard without padding."""
    return WideAndDeep(user_count, item_count, embed_dim, mlp_dims)
