"""Decoder-only Transformer language model.

The reference's Transformer (nn/Transformer.scala:749, `TranslationModel`
/ `LanguageModel` modes) covers encoder-decoder and LM configurations;
this is the LM configuration as a standalone model family, built from
the same attention stack (nn/attention.py) plus:

* weight-tied embedding/output head (standard LM practice; the
  reference ties via `embeddingSharedWeights`),
* `jax.checkpoint` (rematerialization) per block when
  ``remat=True`` — trades recompute for activation memory so long
  sequences fit HBM,
* a causal+padding additive bias built once per batch.

TPU notes: the per-block compute is three dense matmuls + attention —
all MXU work; under a mesh, `parallel.tensor_parallel_rules
(column=[".*q_layer.*|.*k_layer.*|.*v_layer.*|.*filter_layer.*"],
row=[".*output_layer.*|.*out_layer.*"])` gives Megatron-style TP, and
`parallel.ring_attention` substitutes for in-block attention when the
sequence axis is sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, ModuleList, Parameter
from bigdl_tpu.nn.attention import (TransformerDecoderLayer, causal_bias,
                                    padding_bias, position_encoding)
from bigdl_tpu.nn.linear import LookupTable
from bigdl_tpu.nn.normalization import LayerNormalization

__all__ = ["TransformerLM", "transformer_lm"]


class TransformerLM(Module):
    """``forward(tokens [B,T] int, 1-based; 0 = padding) → logits
    [B, T, vocab+1]`` (index 0 of the logit axis is the padding id and
    is never a target)."""

    def __init__(self, vocab_size: int, hidden_size: int = 256,
                 num_layers: int = 4, num_heads: int = 4,
                 filter_size: int = 1024, max_len: int = 512,
                 dropout: float = 0.0, remat: bool = False):
        super().__init__()
        self.hidden_size = hidden_size
        self.max_len = max_len
        self.remat = remat
        self.embedding = LookupTable(vocab_size + 1, hidden_size)
        # N(0, 1/H) init (reference embeddingSharedWeights / T2T): with
        # the weight-tied head, unit-std embeddings would give init
        # logits of std sqrt(H) and a start loss far above ln(vocab)
        self.embedding.weight = Parameter(
            self.embedding.weight * hidden_size ** -0.5)
        self.blocks = ModuleList([
            TransformerDecoderLayer(hidden_size, num_heads, filter_size,
                                    attention_dropout=dropout,
                                    ffn_dropout=dropout,
                                    with_cross_attention=False)
            for _ in range(num_layers)])
        self.final_norm = LayerNormalization(hidden_size)

    def forward(self, tokens):
        B, T = tokens.shape
        if T > self.max_len:
            raise ValueError(
                f"sequence length {T} exceeds max_len={self.max_len}")
        # 0 is padding; clamp for the gather, bias masks it out of loss
        x = self.embedding.forward(jnp.maximum(tokens, 1))
        x = x * (self.hidden_size ** 0.5)
        x = x + position_encoding(T, self.hidden_size, dtype=x.dtype)
        bias = causal_bias(T, dtype=x.dtype) \
            + padding_bias(tokens).astype(x.dtype)

        for blk in self.blocks:
            if self.remat:
                # recompute the block in backward instead of storing its
                # activations (jax.checkpoint); module buffers are not
                # mutated in these blocks so the functional wrap is safe
                def run(blk_, x_, bias_):
                    return blk_.forward(x_, self_bias=bias_)
                x = jax.checkpoint(run)(blk, x, bias)
            else:
                x = blk.forward(x, self_bias=bias)
        x = self.final_norm(x)
        # weight-tied output head: logits against the embedding matrix
        emb = self.embedding.weight            # [vocab+1, H]
        return jnp.einsum("bth,vh->btv", x, emb)


def transformer_lm(vocab_size: int, hidden_size: int = 256,
                   num_layers: int = 4, num_heads: int = 4,
                   filter_size: int = 1024, max_len: int = 512,
                   dropout: float = 0.0, remat: bool = False) \
        -> TransformerLM:
    """Factory mirroring the models/* builder convention."""
    return TransformerLM(vocab_size, hidden_size, num_layers, num_heads,
                         filter_size, max_len, dropout, remat)
