"""Decoder-only Transformer language model.

The reference's Transformer (nn/Transformer.scala:749, `TranslationModel`
/ `LanguageModel` modes) covers encoder-decoder and LM configurations;
this is the LM configuration as a standalone model family, built from
the same attention stack (nn/attention.py) plus:

* weight-tied embedding/output head (standard LM practice; the
  reference ties via `embeddingSharedWeights`),
* `jax.checkpoint` (rematerialization) per block when
  ``remat=True`` — trades recompute for activation memory so long
  sequences fit HBM,
* a causal+padding additive bias built once per batch.

TPU notes: the per-block compute is three dense matmuls + attention —
all MXU work; under a mesh, `parallel.tensor_parallel_rules
(column=[".*q_layer.*|.*k_layer.*|.*v_layer.*|.*filter_layer.*"],
row=[".*output_layer.*|.*out_layer.*"])` gives Megatron-style TP, and
`parallel.ring_attention` substitutes for in-block attention when the
sequence axis is sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, ModuleList, Parameter
from bigdl_tpu.nn.attention import (SequenceBeamSearch,
                                    TransformerDecoderLayer, causal_bias,
                                    chunk_incremental_bias,
                                    incremental_bias, padding_bias,
                                    position_encoding)
from bigdl_tpu.nn.linear import LookupTable
from bigdl_tpu.nn.normalization import LayerNormalization

__all__ = ["TransformerLM", "transformer_lm"]


class TransformerLM(Module):
    """``forward(tokens [B,T] int, 1-based; 0 = padding) → logits
    [B, T, vocab+1]``.

    Logit-axis convention (locked by test_train_then_generate_token_
    convention): the framework's criteria are 1-based — target token t
    trains logit index t-1 — so logit index 0 is token 1's TRAINED slot
    and the LAST index (vocab_size) is the only never-trained row.
    Generation therefore emits ``argmax + 1`` and masks the last row."""

    def __init__(self, vocab_size: int, hidden_size: int = 256,
                 num_layers: int = 4, num_heads: int = 4,
                 filter_size: int = 1024, max_len: int = 512,
                 dropout: float = 0.0, remat: bool = False,
                 padded_inputs: bool = True):
        super().__init__()
        self.hidden_size = hidden_size
        self.max_len = max_len
        self.remat = remat
        self.seq_parallel = False
        # pipeline-parallel routing (set_pipeline_parallel): when armed,
        # the block stack runs through the GPipe schedule over pipe_mesh
        self.pipe_mesh = None
        self.pipe_axis = "pipe"
        self.pipe_microbatches = 1
        # padded_inputs=False: contiguous LM batching (no token-0
        # padding) — the causal mask moves INSIDE the attention kernel
        # (flash skips above-diagonal blocks; no [B,H,T,T] bias is
        # materialized or streamed).  Padding in that mode fails loudly
        # like the sequence-parallel path.
        self.padded_inputs = padded_inputs
        self.embedding = LookupTable(vocab_size + 1, hidden_size)
        # N(0, 1/H) init (reference embeddingSharedWeights / T2T): with
        # the weight-tied head, unit-std embeddings would give init
        # logits of std sqrt(H) and a start loss far above ln(vocab)
        self.embedding.weight = Parameter(
            self.embedding.weight * hidden_size ** -0.5)
        self.blocks = ModuleList([
            TransformerDecoderLayer(hidden_size, num_heads, filter_size,
                                    attention_dropout=dropout,
                                    ffn_dropout=dropout,
                                    with_cross_attention=False)
            for _ in range(num_layers)])
        self.final_norm = LayerNormalization(hidden_size)

    def set_sequence_parallel(self, mesh, axis: str = "seq",
                              kernel=None,
                              head_axis=None) -> "TransformerLM":
        """Run every block's self-attention through ring attention over
        ``mesh[axis]`` (sequence/context parallelism — contexts longer
        than one chip's HBM; see parallel/ring_attention.py).  The
        projection weights are SHARED with the existing Attention
        modules, so this toggles execution strategy, not parameters.
        The ring applies the causal mask itself; padded batches are not
        supported on this path (contiguous LM batching has none): a
        padded batch raises ValueError eagerly, and NaN-poisons the
        output under jit (tracers can't raise on data)."""
        from bigdl_tpu.parallel.ring_attention import RingSelfAttention
        for blk in self.blocks:
            if isinstance(blk.self_attn, RingSelfAttention):
                # reconfiguration: update in place, never keep a stale
                # mesh/axis from an earlier call
                blk.self_attn.mesh = mesh
                blk.self_attn.seq_axis = axis
                blk.self_attn.ring_kernel = kernel
                blk.self_attn.head_axis = head_axis
            else:
                blk.self_attn = RingSelfAttention.from_attention(
                    blk.self_attn, mesh, axis, causal=True,
                    kernel=kernel, head_axis=head_axis)
        self.seq_parallel = True
        return self

    def set_pipeline_parallel(self, mesh, axis: str = "pipe",
                              num_microbatches: int = None) \
            -> "TransformerLM":
        """Run the block stack through the GPipe schedule over
        ``mesh[axis]`` (embedding/posenc and final_norm/head stay
        replicated around it; the blocks are homogeneous
        TransformerDecoderLayers, so stage parameters stack and shard
        over the pipe axis).  Like the sequence-parallel path, the
        causal mask moves INSIDE the attention kernel (the per-batch
        padding bias cannot ride the microbatch ring), so padded
        batches are rejected the same way.  ``mesh=None`` disarms."""
        if mesh is not None:
            n = len(self.blocks)
            s = mesh.shape[axis]
            if n % s:
                raise ValueError(
                    f"set_pipeline_parallel: {n} blocks do not divide "
                    f"into {s} stages on axis {axis!r}")
        self.pipe_mesh = mesh
        self.pipe_axis = axis
        self.pipe_microbatches = (num_microbatches
                                  or (mesh.shape[axis] if mesh is not None
                                      else 1))
        return self

    def _blocks_gpipe(self, x):
        """Run the (homogeneous) blocks as pipeline stages: stack
        per-block leaves onto [S, per_stage, ...] and stream the batch
        through parallel.pipeline.gpipe.  Gradients flow through the
        schedule via autodiff (the Optimizer's outer value_and_grad)."""
        from bigdl_tpu.parallel.pipeline import gpipe
        mesh, axis = self.pipe_mesh, self.pipe_axis
        s = mesh.shape[axis]
        blocks = list(self.blocks)
        per_stage = len(blocks) // s
        flats = [jax.tree_util.tree_flatten(b)[0] for b in blocks]
        treedef0 = jax.tree_util.tree_structure(blocks[0])
        stacked_leaves = [
            jnp.stack(ls).reshape((s, per_stage) + ls[0].shape)
            for ls in zip(*flats)]
        stacked = jax.tree_util.tree_unflatten(treedef0, stacked_leaves)

        def stage_apply(stage_tree, x_mb):
            def one(i, acc):
                blk = jax.tree_util.tree_map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l, i, 0, keepdims=False), stage_tree)
                return blk.forward(acc, self_bias=None, self_causal=True)
            return jax.lax.fori_loop(0, per_stage, one, x_mb)

        return gpipe(stage_apply, stacked, x, mesh, axis,
                     self.pipe_microbatches)

    def forward(self, tokens):
        B, T = tokens.shape
        if T > self.max_len:
            raise ValueError(
                f"sequence length {T} exceeds max_len={self.max_len}")
        # 0 is padding; clamp for the gather, bias masks it out of loss
        x = self.embedding.forward(jnp.maximum(tokens, 1))
        x = x * (self.hidden_size ** 0.5)
        x = x + position_encoding(T, self.hidden_size, dtype=x.dtype)
        pipe = self.pipe_mesh is not None
        causal_in_kernel = False
        if self.seq_parallel or pipe or not self.padded_inputs:
            # Both modes handle causality INSIDE the attention kernel
            # (the ring applies it per block pair; the dense causal
            # flash path skips above-diagonal blocks) — an additive
            # bias would defeat their O-of-memory/traffic win.  Padded
            # batches are NOT supported on either — fail loudly instead
            # of silently diverging (contiguous LM batching has none):
            # eagerly that's a ValueError; under jit (tokens traced)
            # the activations are NaN-poisoned so the loss/logits are
            # unmistakably wrong, not subtly so
            mode = ("sequence-parallel" if self.seq_parallel
                    else "pipeline-parallel" if pipe
                    else "padded_inputs=False")
            if not isinstance(tokens, jax.core.Tracer):
                if bool(jnp.any(tokens == 0)):
                    raise ValueError(
                        f"{mode} TransformerLM does not support padded "
                        "batches (token 0): this path has no padding "
                        "mask; use contiguous LM batching")
            else:
                x = x + jnp.where(jnp.any(tokens == 0),
                                  jnp.asarray(jnp.nan, x.dtype),
                                  jnp.asarray(0, x.dtype))
            bias = None
            causal_in_kernel = not self.seq_parallel
        else:
            bias = causal_bias(T, dtype=x.dtype) \
                + padding_bias(tokens).astype(x.dtype)

        if pipe:
            x = self._blocks_gpipe(x)
        else:
            for blk in self.blocks:
                if self.remat:
                    # recompute the block in backward instead of storing
                    # its activations (jax.checkpoint); module buffers
                    # are not mutated in these blocks so the functional
                    # wrap is safe
                    def run(blk_, x_, bias_):
                        return blk_.forward(x_, self_bias=bias_,
                                            self_causal=causal_in_kernel)
                    x = jax.checkpoint(run)(blk, x, bias)
                else:
                    x = blk.forward(x, self_bias=bias,
                                    self_causal=causal_in_kernel)
        x = self.final_norm(x)
        # weight-tied output head: logits against the embedding matrix
        emb = self.embedding.weight            # [vocab+1, H]
        return jnp.einsum("bth,vh->btv", x, emb)


    # ---- incremental decoding (KV cache) -------------------------------

    def init_cache(self, batch: int, dtype=jnp.float32):
        """Per-block KV caches sized to ``max_len``, plus the per-slot
        padding flags the full forward expresses via padding_bias (one
        pytree, so everything flows through scan/while_loop and beam
        gathering together)."""
        return {
            "layers": [{"self": blk.self_attn.init_cache(
                batch, self.max_len, dtype)} for blk in self.blocks],
            "pad": jnp.zeros((batch, self.max_len), bool),
        }

    def decode_step(self, tokens, index, caches, with_logits=True):
        """One token step: ``tokens [B, 1]`` at position ``index`` →
        (logits [B, vocab+1], new caches).  Equivalent to column
        ``index`` of the full forward incl. its padding mask (tested),
        at O(T) cost instead of O(T^2).  ``with_logits=False`` skips
        the vocab projection (prefill)."""
        pad = jax.lax.dynamic_update_slice(
            caches["pad"], tokens == 0, (0, index))
        x = self.embedding.forward(jnp.maximum(tokens, 1))
        x = x * (self.hidden_size ** 0.5)
        pos = jax.lax.dynamic_slice_in_dim(
            position_encoding(self.max_len, self.hidden_size,
                              dtype=x.dtype), index, 1, axis=0)
        x = x + pos[None]
        bias = incremental_bias(self.max_len, index, pad, x.dtype)
        new_layers = []
        for blk, cache in zip(self.blocks, caches["layers"]):
            x, nc = blk.forward(x, self_bias=bias, cache=cache,
                                cache_index=index)
            new_layers.append(nc)
        new_caches = {"layers": new_layers, "pad": pad}
        if not with_logits:
            return None, new_caches
        x = self.final_norm(x)
        logits = jnp.einsum("bth,vh->btv", x, self.embedding.weight)
        return logits[:, 0], new_caches

    def prefill_kv(self, ptoks):
        """Per-layer K/V for every position of ``ptoks`` (a prompt minus
        its final token) as compact ``[B, heads, T, head_dim]`` arrays,
        plus the ``[B, T]`` bool padding flags — the parallel-prefill
        compute WITHOUT a max_len cache allocation.  ``_prefill``
        scatters these into the front of a fresh cache; the serving slot
        pool (serving/generation.py) scatters the same rows into
        individual pool slots instead, so both prefill paths share one
        implementation and cannot drift."""
        _B, T = ptoks.shape
        pad_cols = ptoks == 0
        x = self.embedding.forward(jnp.maximum(ptoks, 1))
        x = x * (self.hidden_size ** 0.5)
        x = x + position_encoding(T, self.hidden_size, dtype=x.dtype)
        bias = causal_bias(T, dtype=x.dtype) \
            + padding_bias(ptoks).astype(x.dtype)
        from bigdl_tpu.nn.attention import _residual_dropout
        from bigdl_tpu.ops import dot_product_attention
        layers = []
        for blk in self.blocks:
            # inline the block's attention so the K/V computed for the
            # cache are the ones used (blk.forward would recompute the
            # norm and all projections a second time)
            attn = blk.self_attn
            xn = blk.self_norm(x)
            k = attn._split_heads(attn.k_layer(xn))
            v = attn._split_heads(attn.v_layer(xn))
            layers.append({"k": k, "v": v})
            if blk.training and attn.attention_dropout > 0.0:
                # rare train-mode prefill: the materialized-dropout path
                # must run; recomputing k/v there is acceptable
                y = attn(xn, None, bias)
            else:
                q = attn._split_heads(attn.q_layer(xn))
                ctxt = dot_product_attention(q, k, v, bias)
                y = attn.output_layer(attn._combine_heads(ctxt))
            x = x + _residual_dropout(y, blk.ffn_dropout, blk.training)
            y = blk.ffn(blk.ffn_norm(x))
            x = x + _residual_dropout(y, blk.ffn_dropout, blk.training)
        return layers, pad_cols

    def prefill_chunk(self, toks, index, caches, slot=None):
        """KV-carry-in prefill: write K/V + padding flags for ``toks
        [B, W]`` at positions ``[index, index+W)`` of an incremental
        cache whose positions ``< index`` are already filled.  The chunk
        attends to the carried-in prefix AND itself (causally), so a
        long prompt can be prefilled in fixed-width chunks interleaved
        with decode steps instead of one monolithic forward — the
        static-shape cousin of Sarathi-style chunked prefill.  Same
        contract as :meth:`decode_step` (of which this is the W-token
        generalization, equivalent to columns ``[index, index+W)`` of
        the full forward); no logits are produced (prefill never needs
        the vocab projection).

        Two cache layouts:

        * ``slot=None`` — per-request rows: caches carry ``B`` rows
          aligned with ``toks``.
        * ``slot`` given (a traced scalar) — POOLED: caches hold S slot
          rows, ``toks`` is [1, W], and only ``slot``'s row is touched.
          The cache write covers exactly the chunk window (so a DONATED
          pool updates in place at O(chunk) write cost — writing a
          whole gathered row back was measured to cost the full row's
          traffic per chunk) and the attention keys are read by slicing
          the slot's row after the write.

        Attention is inlined like :meth:`prefill_kv` (the K/V written
        to the cache are the K/V attended), expecting eval mode — the
        serving slot pool always runs an eval clone."""
        from bigdl_tpu.nn.attention import _residual_dropout
        from bigdl_tpu.ops import dot_product_attention
        _B, W = toks.shape
        if slot is None:
            pad = jax.lax.dynamic_update_slice(caches["pad"], toks == 0,
                                               (0, index))
            pad_read = pad
        else:
            pad = jax.lax.dynamic_update_slice(caches["pad"], toks == 0,
                                               (slot, index))
            pad_read = jax.lax.dynamic_slice(pad, (slot, 0),
                                             (1, self.max_len))
        x = self.embedding.forward(jnp.maximum(toks, 1))
        x = x * (self.hidden_size ** 0.5)
        pos = jax.lax.dynamic_slice_in_dim(
            position_encoding(self.max_len, self.hidden_size,
                              dtype=x.dtype), index, W, axis=0)
        x = x + pos[None]
        bias = chunk_incremental_bias(self.max_len, index, W, pad_read,
                                      x.dtype)
        new_layers = []
        for blk, cache in zip(self.blocks, caches["layers"]):
            attn = blk.self_attn
            xn = blk.self_norm(x)
            k_new = attn._split_heads(attn.k_layer(xn))
            v_new = attn._split_heads(attn.v_layer(xn))
            old = cache["self"]
            if slot is None:
                k = jax.lax.dynamic_update_slice(
                    old["k"], k_new.astype(old["k"].dtype),
                    (0, 0, index, 0))
                v = jax.lax.dynamic_update_slice(
                    old["v"], v_new.astype(old["v"].dtype),
                    (0, 0, index, 0))
                k_read, v_read = k, v
            else:
                k = jax.lax.dynamic_update_slice(
                    old["k"], k_new.astype(old["k"].dtype),
                    (slot, 0, index, 0))
                v = jax.lax.dynamic_update_slice(
                    old["v"], v_new.astype(old["v"].dtype),
                    (slot, 0, index, 0))
                row = (1,) + old["k"].shape[1:]
                k_read = jax.lax.dynamic_slice(k, (slot, 0, 0, 0), row)
                v_read = jax.lax.dynamic_slice(v, (slot, 0, 0, 0), row)
            new_layers.append({"self": {"k": k, "v": v}})
            q = attn._split_heads(attn.q_layer(xn))
            ctxt = dot_product_attention(q, k_read, v_read, bias)
            y = attn.output_layer(attn._combine_heads(ctxt))
            x = x + _residual_dropout(y, blk.ffn_dropout, blk.training)
            y = blk.ffn(blk.ffn_norm(x))
            x = x + _residual_dropout(y, blk.ffn_dropout, blk.training)
        return {"layers": new_layers, "pad": pad}

    def _prefill(self, prompt, caches):
        """Write prompt[:, :-1]'s per-layer K/V into the caches with ONE
        dense forward over the whole prompt (parallel over T, MXU-
        friendly) rather than Tp sequential decode steps; the last
        prompt token is fed by the first decode step instead."""
        Tp = prompt.shape[1]
        if Tp == 1:
            return caches
        layers_kv, pad = self.prefill_kv(prompt[:, :-1])
        pad_cols = jax.lax.dynamic_update_slice(caches["pad"], pad, (0, 0))
        new_layers = []
        for kv, cache in zip(layers_kv, caches["layers"]):
            old = cache["self"]
            new_layers.append({"self": {
                "k": jax.lax.dynamic_update_slice(
                    old["k"], kv["k"].astype(old["k"].dtype),
                    (0, 0, 0, 0)),
                "v": jax.lax.dynamic_update_slice(
                    old["v"], kv["v"].astype(old["v"].dtype),
                    (0, 0, 0, 0)),
            }})
        return {"layers": new_layers, "pad": pad_cols}

    @staticmethod
    def _mask_untrained_logit(logits):
        """The framework's criteria are 1-based (ClassNLL/CrossEntropy:
        target token t trains logit index t-1), so logit index
        ``vocab_size`` (the last row of the tied head) is never a target
        and stays untrained noise — it must not win argmax/top_k.
        (Logit index 0 IS trained: it is token 1's slot.)"""
        neg = jnp.asarray(-1e9, logits.dtype)
        return logits.at[..., -1].set(neg)

    def generate(self, prompt, max_new_tokens: int, eos_id=None):
        """Greedy continuation: ``prompt [B, Tp]`` →
        ``[B, Tp + max_new_tokens]``; positions after ``eos_id`` (when
        given) are padded with 0."""
        B, Tp = prompt.shape
        if Tp + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {Tp} + {max_new_tokens} new tokens exceeds "
                f"max_len={self.max_len}")
        prompt = jnp.asarray(prompt, jnp.int32)
        caches = self._prefill(prompt, self.init_cache(B))

        def gen_step(carry, t):
            tok, caches, done = carry
            logits, caches = self.decode_step(tok, t, caches)
            # logit index i is token i+1's slot (1-based criteria), so
            # the emitted token id is argmax + 1
            nxt = jnp.argmax(self._mask_untrained_logit(logits),
                             axis=-1).astype(jnp.int32) + 1
            nxt = jnp.where(done, 0, nxt)
            if eos_id is not None:
                done = done | (nxt == eos_id)
            return (nxt[:, None], caches, done), nxt

        done0 = jnp.zeros((B,), bool)
        (_, _, _), toks = jax.lax.scan(
            gen_step, (prompt[:, -1:], caches, done0),
            Tp - 1 + jnp.arange(max_new_tokens))
        return jnp.concatenate([prompt, toks.T], axis=1)

    def generate_beam(self, prompt, beam_size: int = 4,
                      max_new_tokens: int = 20, eos_id: int = -1,
                      alpha: float = 0.6):
        """Length-normalized beam search continuation via
        nn.SequenceBeamSearch; returns (sequences [B, beam, T_new],
        scores [B, beam]).  ``eos_id=-1`` (no EOS) decodes to the full
        budget."""
        B, Tp = prompt.shape
        if Tp + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {Tp} + {max_new_tokens} new tokens exceeds "
                f"max_len={self.max_len}")
        prompt = jnp.asarray(prompt, jnp.int32)
        caches = self._prefill(prompt, self.init_cache(B))
        # the search feeds a zero "start" id at step 0; carry the true
        # last prompt token inside the cache pytree so it rides the
        # per-beam replication/gathering
        cache = dict(caches, tok0=prompt[:, -1:])
        vocab = self.embedding.weight.shape[0]
        # the search operates in LOGIT-INDEX space (ids start at 0 =
        # pad/start, reference SequenceBeamSearch.scala); our criteria
        # are 1-based, so EOS token id t lives at logit index t-1
        search = SequenceBeamSearch(
            vocab, beam_size, alpha, max_new_tokens,
            eos_id - 1 if eos_id >= 0 else eos_id)

        def logits_fn(ids, i, cache):
            # ids are the previous step's logit indices → token id + 1
            tok = jnp.where(i == 0, cache["tok0"],
                            ids.astype(jnp.int32) + 1)
            logits, sub = self.decode_step(
                tok, Tp - 1 + i,
                {"layers": cache["layers"], "pad": cache["pad"]})
            return self._mask_untrained_logit(logits), dict(
                sub, tok0=cache["tok0"])

        search.set_logit_fn(logits_fn)
        seqs, scores = search.search(B, cache)
        # back to token-id space; re-pad positions after the first EOS
        # (they were 0 in index space and must stay 0 in token space)
        toks = seqs + 1
        if eos_id >= 0:
            eos_before = jnp.cumsum(toks == eos_id, axis=-1) \
                - (toks == eos_id)
            toks = jnp.where(eos_before > 0, 0, toks)
        return toks, scores


def transformer_lm(vocab_size: int, hidden_size: int = 256,
                   num_layers: int = 4, num_heads: int = 4,
                   filter_size: int = 1024, max_len: int = 512,
                   dropout: float = 0.0, remat: bool = False,
                   padded_inputs: bool = True) -> TransformerLM:
    """Factory mirroring the models/* builder convention."""
    return TransformerLM(vocab_size, hidden_size, num_layers, num_heads,
                         filter_size, max_len, dropout, remat,
                         padded_inputs=padded_inputs)
