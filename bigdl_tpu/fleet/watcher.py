"""Continuous train-to-serve deployment: the checkpoint watcher.

The training tier commits CRC-manifested checkpoint generations
(:class:`~bigdl_tpu.utils.file.CheckpointManager`); the serving tier
swaps replicas with zero drops (:meth:`Router.deploy`).  The
:class:`CheckpointWatcher` is the conveyor between them: it polls
``latest_good()`` — which by construction only ever returns a
committed, CRC-verified generation, walking back past torn or
uncommitted ones — and on a NEW generation hot-loads it into the
serving pool one replica at a time: build a replacement from the
checkpoint through the pluggable factory, ``deploy()`` it over one
live member (drain, wait for ``admitted_outstanding() == 0``, remove),
then the next.  At no point does the pool lose more than the one
replica mid-swap, and greedy rows stay bit-identical across the swap
because the replacement serves the exact committed weights.

Freshness is published as ONE measured number,
``fleet_deploy_freshness_seconds``: the manifest's commit timestamp to
the moment the LAST replica in the pool came up serving the new
generation.  That is the number the whitepaper's "analytics + AI on
one pipeline" pitch turns into at production scale — how old are the
weights your users are talking to?
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, Optional

from bigdl_tpu import telemetry
from bigdl_tpu.fleet.controller import (next_replica_id,
                                        register_statusz,
                                        unregister_statusz)
from bigdl_tpu.telemetry import events as _events

__all__ = ["CheckpointWatcher"]

logger = logging.getLogger(__name__)


class CheckpointWatcher:
    """Poll a checkpoint directory; rolling hot-deploy every new
    latest-good generation into one model pool.

    ``factory(replica_id, model, checkpoint_path)`` must return a
    started replica serving the weights at ``checkpoint_path``.  With
    ``deploy_existing=False`` (default) the generation present at
    start is taken as the baseline the pool already serves; only
    generations committed AFTER that deploy.
    """

    def __init__(self, manager, router, factory: Callable[..., Any],
                 model: str = "default", poll_interval_s: float = 0.5,
                 deploy_timeout_s: float = 60.0,
                 deploy_existing: bool = False, start: bool = False):
        self.manager = manager
        self.router = router
        self.factory = factory
        self.model = str(model)
        self.poll_interval_s = float(poll_interval_s)
        self.deploy_timeout_s = float(deploy_timeout_s)
        self._deployed_gen: Optional[int] = None  # watcher-thread only
        self._baselined = bool(deploy_existing)
        self._lock = threading.Lock()
        self._status: Dict[str, Any] = {"running": False}
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="bigdl-fleet-ckpt-watcher",
            daemon=True)
        self._started = False
        if start:
            self.start()

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "CheckpointWatcher":
        if self._started:
            raise RuntimeError("watcher already started")
        self._started = True
        register_statusz("deploy", self.status)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop_evt.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
        unregister_statusz("deploy")

    def __enter__(self) -> "CheckpointWatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._status)

    # ---- the watch loop --------------------------------------------------

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.check_once()
            except Exception:  # pragma: no cover - one bad generation
                # must not end continuous deployment
                logger.exception("checkpoint watcher tick failed")
            self._stop_evt.wait(self.poll_interval_s)

    def check_once(self) -> Optional[Dict[str, Any]]:
        """One synchronous poll-and-maybe-deploy (tests and the smoke
        harness drive the watcher deterministically through this).
        Returns the deploy report when a deploy happened."""
        info = self.manager.latest_good_info()
        if info is None:
            return None
        gen = info.get("generation")
        if gen is None:
            return None  # legacy manifest-less payload: no generation
            # ordering to act on
        gen = int(gen)
        if not self._baselined:
            # the pool presumably already serves the weights that were
            # current when the watcher started; only NEWER generations
            # roll out
            self._baselined = True
            self._deployed_gen = gen
            self._publish_status(gen, None, 0)
            return None
        if self._deployed_gen is not None and gen <= self._deployed_gen:
            return None
        report = self._deploy(info, gen)
        self._deployed_gen = gen
        return report

    def _deploy(self, info: Dict, gen: int) -> Dict[str, Any]:
        """Rolling swap: every healthy pool member is replaced, one at
        a time, by a factory-built replica serving the new
        generation."""
        records = self.router.records()
        targets = []
        for rid in self.router.replica_ids():
            r = self.router.replica(rid)
            if r is None \
                    or getattr(r, "model", "default") != self.model:
                continue
            rec = records.get(rid)
            if rec is not None and not rec.get("healthy", True):
                continue  # the controller replaces the dead; deploying
                # over them would double-handle the slot
            targets.append(rid)
        swapped = []
        for old_id in targets:
            new_id = next_replica_id(self.router)
            replica = self.factory(new_id, self.model, info["path"])
            self.router.deploy(replica, replaces=old_id,
                               timeout=self.deploy_timeout_s)
            swapped.append((old_id, new_id))
            logger.info("hot-deploy gen %d: %d -> %d (%d/%d)", gen,
                        old_id, new_id, len(swapped), len(targets))
        committed = info.get("time")
        if committed is None:
            freshness = None
        else:
            # graftlint: disable=clock-discipline -- freshness spans
            # processes and restarts: the commit stamp in the manifest
            # is epoch time, so the serving-side end of the interval
            # must be read off the same shared clock (same exemption
            # as the registry's staleness checks)
            freshness = max(time.time() - float(committed), 0.0)
        # THE one hot_deploy emission site: one event per generation
        # rolled out, not one per replica swapped
        _events.record_event(
            "hot_deploy", model=self.model, generation=gen,
            payload=info.get("path"), replicas=len(swapped),
            freshness_s=(None if freshness is None
                         else round(freshness, 3)))
        if freshness is not None and telemetry.enabled():
            from bigdl_tpu.telemetry import families
            families.fleet_deploy_freshness_seconds().set(freshness)
        self._publish_status(gen, freshness, len(swapped))
        return {"generation": gen, "swapped": swapped,
                "freshness_s": freshness}

    def _publish_status(self, gen: int, freshness: Optional[float],
                        swapped: int) -> None:
        with self._lock:
            self._status = {
                "running": not self._stop_evt.is_set(),
                "model": self.model,
                "deployed_generation": gen,
                "last_freshness_s": freshness,
                "last_swapped": swapped,
            }
