"""Scaling policy: the pure decide() half of the fleet controller.

The controller splits Kubernetes-style into an OBSERVE/DECIDE half
(this module — no threads, no locks, no IO, fully unit-testable with
hand-built observations) and an ACTUATE half
(:mod:`bigdl_tpu.fleet.controller` — the reconcile thread that spawns,
drains, and removes replicas).  The split is what makes "the
controller did something — why?" answerable: every decision is a
:class:`Decision` with a human-readable reason string, and the same
reason lands verbatim in the flight-recorder event and the
``/statusz`` ``controller`` section.

Hysteresis semantics (the knobs an operator actually tunes):

* **Separate up/down thresholds** — scale-up triggers on
  ``queue_high`` / ``ttft_high_s`` / any shed; scale-down requires the
  queue at or below the LOWER ``queue_low`` watermark with no sheds
  and under one in-flight request per replica.  The gap between the
  watermarks is the dead band that stops the pool oscillating around
  a single threshold.
* **Consecutive-observation streaks** — a breach must hold for
  ``breach_consecutive`` reconcile ticks (and idleness for
  ``clear_consecutive``) before the policy acts; one noisy snapshot
  never moves the fleet.
* **Cooldown** — after any scaling action the policy answers ``hold``
  for ``cooldown_s``, long enough for the previous action's effect
  (a replica warming its compile cache, a drain finishing) to show up
  in the signals it decides on.  Without it the controller would read
  the still-breached queue and scale again every tick.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["PoolSpec", "Observation", "Decision", "ScalingPolicy"]


class PoolSpec:
    """Per-model pool configuration: the size envelope, the SLO class
    and admission budget pushed into the router, and the scaling
    thresholds the policy judges against.  ``ttft_high_s`` defaults to
    the pool's SLO target — breach the promise, grow the pool."""

    def __init__(self, model: str = "default", min_replicas: int = 1,
                 max_replicas: int = 4,
                 slo_ttft_p99_s: Optional[float] = None,
                 admission_budget: Optional[int] = None,
                 ttft_high_s: Optional[float] = None,
                 queue_high: int = 8, queue_low: int = 1,
                 breach_consecutive: int = 2,
                 clear_consecutive: int = 4,
                 cooldown_s: float = 5.0,
                 dead_after_polls: int = 2):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) < min_replicas "
                f"({min_replicas})")
        if queue_low >= queue_high:
            raise ValueError(
                f"queue_low ({queue_low}) must sit strictly below "
                f"queue_high ({queue_high}) — the gap is the "
                f"hysteresis dead band")
        self.model = str(model)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.slo_ttft_p99_s = (None if slo_ttft_p99_s is None
                               else float(slo_ttft_p99_s))
        self.admission_budget = (None if admission_budget is None
                                 else int(admission_budget))
        self.ttft_high_s = (float(ttft_high_s)
                            if ttft_high_s is not None
                            else self.slo_ttft_p99_s)
        self.queue_high = int(queue_high)
        self.queue_low = int(queue_low)
        self.breach_consecutive = int(breach_consecutive)
        self.clear_consecutive = int(clear_consecutive)
        self.cooldown_s = float(cooldown_s)
        self.dead_after_polls = int(dead_after_polls)

    def clamp(self, n: int) -> int:
        return max(self.min_replicas, min(self.max_replicas, int(n)))


class Observation:
    """One reconcile tick's view of a pool, already reduced to the
    signals the policy decides on."""

    __slots__ = ("live", "desired", "ttft_p99_s", "queue_depth",
                 "shed_delta", "inflight", "breakers_open")

    def __init__(self, live: int, desired: int, ttft_p99_s: float = 0.0,
                 queue_depth: int = 0, shed_delta: int = 0,
                 inflight: int = 0, breakers_open: int = 0):
        self.live = int(live)
        self.desired = int(desired)
        self.ttft_p99_s = float(ttft_p99_s)
        self.queue_depth = int(queue_depth)
        self.shed_delta = int(shed_delta)
        self.inflight = int(inflight)
        self.breakers_open = int(breakers_open)


class Decision:
    """What the policy wants this tick.  ``action`` is one of
    ``"up"`` / ``"down"`` / ``"hold"`` / ``None`` — ``hold`` means a
    breach-driven action WAS warranted but is suppressed (cooldown, or
    clamped at the pool envelope), the case an operator most wants
    explained; ``None`` means nothing to do at all.  ``key`` is a
    STABLE slug for the hold cause ("cooldown" / "at-max"): the reason
    string carries tick-varying numbers (streaks, seconds remaining),
    so the controller latches its one-event-per-episode flight-recorder
    emission on the key, not the prose."""

    __slots__ = ("action", "reason", "key")

    def __init__(self, action: Optional[str], reason: str = "",
                 key: Optional[str] = None):
        self.action = action
        self.reason = reason
        self.key = key

    def __repr__(self) -> str:
        return f"Decision({self.action!r}, {self.reason!r})"


class ScalingPolicy:
    """Streak + cooldown state for one pool.  Pure against injected
    time: every method takes ``now`` from the caller's
    ``time.perf_counter()`` so tests drive hysteresis without
    sleeping."""

    def __init__(self, spec: PoolSpec):
        self.spec = spec
        self._high_streak = 0
        self._low_streak = 0
        self._last_action_at: Optional[float] = None

    # -- observation -> decision -------------------------------------------

    def _breaches(self, obs: Observation) -> list:
        s = self.spec
        out = []
        if s.ttft_high_s is not None and obs.ttft_p99_s > s.ttft_high_s:
            out.append(f"ttft_p99 {obs.ttft_p99_s:.3f}s > "
                       f"{s.ttft_high_s:.3f}s")
        if obs.queue_depth >= s.queue_high:
            out.append(f"queue depth {obs.queue_depth} >= "
                       f"{s.queue_high}")
        if obs.shed_delta > 0:
            out.append(f"{obs.shed_delta} request(s) shed since last "
                       f"tick")
        return out

    def _idle(self, obs: Observation) -> bool:
        s = self.spec
        # an open breaker means part of the nominal capacity is
        # untrusted: never call that pool idle (a scale-down would
        # compound the degradation the breaker is riding out)
        return (obs.queue_depth <= s.queue_low
                and obs.shed_delta == 0
                and obs.breakers_open == 0
                and obs.inflight < max(obs.live, 1)
                and (s.ttft_high_s is None
                     or obs.ttft_p99_s <= s.ttft_high_s))

    def cooldown_remaining(self, now: float) -> float:
        if self._last_action_at is None:
            return 0.0
        return max(self.spec.cooldown_s - (now - self._last_action_at),
                   0.0)

    def decide(self, obs: Observation, now: float) -> Decision:
        s = self.spec
        breaches = self._breaches(obs)
        if breaches:
            self._high_streak += 1
            self._low_streak = 0
        elif self._idle(obs):
            self._low_streak += 1
            self._high_streak = 0
        else:
            self._high_streak = 0
            self._low_streak = 0
        if self._high_streak >= s.breach_consecutive:
            reason = "; ".join(breaches) \
                + f" (for {self._high_streak} ticks)"
            if obs.desired >= s.max_replicas:
                return Decision("hold", f"{reason} — already at "
                                        f"max_replicas={s.max_replicas}",
                                key="at-max")
            cd = self.cooldown_remaining(now)
            if cd > 0:
                return Decision("hold", f"{reason} — cooling down "
                                        f"{cd:.1f}s more",
                                key="cooldown")
            return Decision("up", reason)
        if self._low_streak >= s.clear_consecutive:
            reason = (f"idle for {self._low_streak} ticks (queue <= "
                      f"{s.queue_low}, no sheds, inflight "
                      f"{obs.inflight} < live {obs.live})")
            if obs.desired <= s.min_replicas:
                # sitting at the floor while idle is the steady state,
                # not a suppressed action worth paging about
                return Decision(None, "")
            if self.cooldown_remaining(now) > 0:
                return Decision(
                    "hold", f"{reason} — cooling down "
                            f"{self.cooldown_remaining(now):.1f}s more",
                    key="cooldown")
            return Decision("down", reason)
        return Decision(None, "")

    def actuated(self, now: float) -> None:
        """The controller carried out a scaling action: restart the
        streaks (the next action needs fresh evidence) and stamp the
        cooldown clock."""
        self._high_streak = 0
        self._low_streak = 0
        self._last_action_at = now
