"""The fleet acceptance harness: one scenario, three consumers.

``run_fleet_scenario`` drives the full self-driving loop against a
tiny deterministic transformer-LM fleet — sustained sessioned load, a
chaos replica kill, a load spike, and a new checkpoint generation —
with NO operator action between fault and recovery: the
:class:`~bigdl_tpu.fleet.controller.FleetController` replaces the dead
and scales the pool, the
:class:`~bigdl_tpu.fleet.watcher.CheckpointWatcher` rolling-hot-deploys
the new generation, and the report counts what the acceptance criteria
pin: zero dropped admitted requests (every future resolves ok or
TYPED), greedy rows bit-identical to solo ``generate()`` after the
swap, and the measured train-to-serve freshness.

The slow soak test, ``scripts/controller_smoke.sh``, and the bench
``FLEET_r<N>.json`` round all run THIS function — one encoding of the
scenario, three levels of budget.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from bigdl_tpu.telemetry import events as _events
from bigdl_tpu.utils import chaos

__all__ = ["build_tiny_lm", "checkpoint_factory", "run_fleet_scenario"]


def build_tiny_lm():
    """The deterministic tiny LM every consumer shares: same seed +
    config as the serving-fabric tests, so greedy rows are comparable
    across fresh builds, checkpoint round-trips, and solo oracles."""
    from bigdl_tpu.models import transformer_lm
    from bigdl_tpu.utils import set_seed
    set_seed(0)
    return transformer_lm(vocab_size=50, hidden_size=32, num_layers=2,
                          num_heads=4, filter_size=64,
                          max_len=64).eval_mode()


def solo_row(model, prompt, max_new: int):
    """The single-engine greedy oracle (no fabric in the path)."""
    import jax.numpy as jnp
    return np.asarray(model.generate(
        jnp.asarray(prompt, jnp.int32)[None], int(max_new)))[0]


def checkpoint_factory(snapshot_dir: str, checkpoint_dir: str,
                       slots: int = 2, publish_interval_s: float = 0.05):
    """A :class:`FleetController`/:class:`CheckpointWatcher` factory
    over the tiny LM: ``factory(rid, model, checkpoint_path)`` builds a
    started replica serving the weights at ``checkpoint_path`` — or,
    when None (scale-up / replacement), the newest committed generation
    (falling back to the deterministic seed weights before any commit).
    """
    from bigdl_tpu.serving import ModelServer, Replica
    from bigdl_tpu.utils.file import CheckpointManager, load_checkpoint

    def factory(replica_id: int, model: str,
                checkpoint_path: Optional[str]):
        lm = build_tiny_lm()
        path = checkpoint_path
        if path is None:
            path = CheckpointManager(checkpoint_dir).latest_good()
        if path is not None:
            model_state, _opt, _driver = load_checkpoint(path)
            lm.load_parameters(model_state["params"])
            if "buffers" in model_state:
                lm.load_buffers(model_state["buffers"])
        return Replica(replica_id, ModelServer(generator=lm,
                                               slots=slots),
                       snapshot_dir=snapshot_dir,
                       publish_interval_s=publish_interval_s,
                       model=model)

    return factory


def _wait(cond, timeout: float, msg: str) -> None:
    deadline = time.perf_counter() + timeout
    while not cond():
        if time.perf_counter() > deadline:
            raise TimeoutError(f"{msg} not reached in {timeout}s")
        time.sleep(0.02)


def _commit_generation(checkpoint_dir: str, lm, generation: int) -> str:
    """One committed checkpoint generation holding the LM's weights
    (the CRC manifest makes it ``latest_good()``-visible)."""
    from bigdl_tpu.utils.file import CheckpointManager

    def plain(tree):
        import jax
        return jax.tree_util.tree_map(np.asarray, tree)

    return CheckpointManager(checkpoint_dir).save(
        {"params": plain(lm.parameters()),
         "buffers": plain(lm.buffers())},
        [], {"epoch": 0, "neval": int(generation)},
        generation=int(generation))


def run_fleet_scenario(workdir: str, *, load_s: float = 3.0,
                       spike_requests: int = 18,
                       kill: bool = True, deploy: bool = True,
                       wait_scale_down: bool = True,
                       max_replicas: int = 3,
                       timeout_s: float = 120.0) -> Dict[str, Any]:
    """The closed-loop acceptance scenario.  Returns a report dict;
    raises TimeoutError if the loop never converges (that IS the
    failure the scenario exists to catch).

    Sequence: 1-replica fleet under sessioned load -> chaos kills the
    replica (stops publishing; registry reads it stale-unhealthy) ->
    controller replaces it -> a burst spike breaches the queue
    watermark -> controller scales up -> training commits a new
    checkpoint generation -> watcher rolling-hot-deploys it with the
    zero-drop ``deploy()`` path -> greedy rows after the swap are
    bit-identical to solo ``generate()`` -> idle fleet scales back
    down.  Every submitted future must resolve ok or typed-shed;
    anything else counts as ``dropped`` and the caller should fail.
    """
    from bigdl_tpu.serving import (NoReplicaAvailableError,
                                   RequestSheddedError, Router)
    from bigdl_tpu.fleet.controller import FleetController
    from bigdl_tpu.fleet.policy import PoolSpec
    from bigdl_tpu.fleet.watcher import CheckpointWatcher
    from bigdl_tpu.utils.file import CheckpointManager

    t_start = time.perf_counter()
    snap_dir = os.path.join(workdir, "snapshots")
    ckpt_dir = os.path.join(workdir, "checkpoints")
    os.makedirs(snap_dir, exist_ok=True)
    os.makedirs(ckpt_dir, exist_ok=True)

    lm = build_tiny_lm()
    _commit_generation(ckpt_dir, lm, 1)    # the baseline generation
    factory = checkpoint_factory(snap_dir, ckpt_dir)

    rng = np.random.default_rng(21)
    probe_prompts = [rng.integers(1, 50, 6).astype(np.int32)
                     for _ in range(3)]
    probe_max_new = 8
    oracles = [solo_row(lm, p, probe_max_new) for p in probe_prompts]

    victim = factory(0, "default", None)
    router = Router(replicas=[victim], snapshot_dir=snap_dir,
                    poll_interval_s=0.02, registry_max_age_s=0.6,
                    queue_capacity=256, shed_after_s=30.0)
    spec = PoolSpec(model="default", min_replicas=1,
                    max_replicas=int(max_replicas), queue_high=6,
                    queue_low=1, breach_consecutive=2,
                    clear_consecutive=4, cooldown_s=1.0,
                    dead_after_polls=2)
    controller = FleetController(router, factory, pools=[spec],
                                 interval_s=0.05, start=True)
    watcher = CheckpointWatcher(CheckpointManager(ckpt_dir), router,
                                factory, poll_interval_s=0.1,
                                deploy_timeout_s=timeout_s,
                                start=True) if deploy else None

    futures: List[Any] = []
    report: Dict[str, Any] = {"killed_replica": None,
                              "replaced_with": None}
    try:
        # warm the fleet before offering load: the first generate pays
        # the jit compile, and a multi-second compile under offered
        # load reads as a queue breach the scenario didn't script
        router.submit_generate(probe_prompts[0], probe_max_new,
                               timeout=timeout_s)

        # ---- phase A: sustained sessioned load ---------------------------
        t_end = time.perf_counter() + load_s
        i = 0
        while time.perf_counter() < t_end:
            futures.append(router.submit_generate_async(
                rng.integers(1, 50, int(rng.integers(3, 10))).astype(
                    np.int32),
                int(rng.integers(2, 8)), session=f"user-{i % 8}"))
            i += 1
            time.sleep(0.02)

            if kill and report["killed_replica"] is None \
                    and time.perf_counter() > t_end - load_s / 2:
                # ---- phase B: chaos kill, mid-load -----------------------
                chaos.install(kill_replica_after_s=0.0,
                              kill_replica_id=0)
                report["killed_replica"] = 0

        if kill:
            # the controller notices the stale snapshot and replaces
            # the dead replica with no operator step
            _wait(lambda: 0 not in router.replica_ids()
                  and len(router.replica_ids()) >= 1,
                  timeout_s, "dead replica replaced")
            report["replaced_with"] = sorted(router.replica_ids())

        # ---- phase C: load spike -> scale-up -----------------------------
        base_live = len(router.replica_ids())
        for _ in range(int(spike_requests)):
            futures.append(router.submit_generate_async(
                rng.integers(1, 50, 6).astype(np.int32), 32))
        _wait(lambda: len(router.replica_ids()) > base_live
              or len(router.replica_ids()) >= max_replicas,
              timeout_s, "scale-up past the spike")
        report["live_after_spike"] = len(router.replica_ids())

        # ---- drain the offered load (ok or TYPED, nothing dropped) -------
        ok = shed = dropped = 0
        for f in futures:
            try:
                f.result(timeout_s)
                ok += 1
            except (RequestSheddedError, NoReplicaAvailableError):
                shed += 1
            except Exception:
                dropped += 1
        report.update(submitted=len(futures), ok=ok, shed=shed,
                      dropped=dropped)

        # ---- phase D: new generation -> rolling hot-deploy ---------------
        if deploy:
            _commit_generation(ckpt_dir, lm, 2)
            _wait(lambda: watcher.status().get("deployed_generation")
                  == 2, timeout_s, "generation 2 hot-deployed")
            st = watcher.status()
            report["deployed_generation"] = st["deployed_generation"]
            report["freshness_s"] = st["last_freshness_s"]
            report["deploy_swapped"] = st["last_swapped"]

        # greedy rows across the (possibly swapped) fleet must equal
        # the solo oracle bit for bit
        rows = [router.submit_generate(p, probe_max_new,
                                       timeout=timeout_s)
                for p in probe_prompts]
        report["greedy_rows_equal"] = all(
            np.array_equal(r, o) for r, o in zip(rows, oracles))
        report["greedy_checked"] = len(rows)

        # ---- phase E: idle fleet scales back down ------------------------
        if wait_scale_down:
            _wait(lambda: len(router.replica_ids())
                  < report["live_after_spike"],
                  timeout_s, "scale-down after the spike drains")
        report["live_final"] = len(router.replica_ids())

        # the zero-drop invariant, measured the acceptance way
        report["admitted_outstanding"] = sum(
            router.replica(rid).admitted_outstanding()
            for rid in router.replica_ids()
            if router.replica(rid) is not None)
        report["controller_status"] = controller.status()
        kinds: Dict[str, int] = {}
        for e in _events.recent_events(500):
            kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
        report["events"] = {k: kinds.get(k, 0)
                            for k in ("scale_up", "scale_down",
                                      "hot_deploy", "controller_hold",
                                      "chaos_fault")}
        report["duration_s"] = round(time.perf_counter() - t_start, 2)
        return report
    finally:
        if watcher is not None:
            watcher.stop()
        controller.stop()
        chaos.reset()
        router.shutdown()
