"""The fleet controller: the closed loop between observation and
actuation.

PR-13 built the observations (per-replica health snapshots, router SLO
stats, typed shed counters) and the actuators (``add_replica``,
``drain``, zero-drop ``deploy``); PR-14 made any checkpoint restorable
at any width.  Until now an OPERATOR was the loop between them.  This
module closes it:

* :class:`FleetController` — a daemon reconcile thread in the
  Kubernetes mold: each tick it polls the
  :class:`~bigdl_tpu.serving.replica.ReplicaRegistry` and the router's
  stats, reduces them to one :class:`~bigdl_tpu.fleet.policy.Observation`
  per model pool, asks the pool's
  :class:`~bigdl_tpu.fleet.policy.ScalingPolicy` for a decision, and
  reconciles live state toward desired state: dead replicas (stale or
  corrupt snapshots) are replaced, breaches scale the pool up through
  the pluggable ``factory``, sustained idleness scales it down through
  the PR-13 zero-drop drain path — never below ``min_replicas``.
  Every action (and every suppressed one) lands in the flight
  recorder as ``scale_up`` / ``scale_down`` / ``controller_hold``
  with the policy's reason verbatim, so a pager week reconstructs
  from the event ring.
* :class:`TrainingSupervisor` — the training-side half of "no operator
  step": re-invokes a preempted ``optimize()`` from
  ``CheckpointManager.latest_good()`` at whatever width the mesh now
  grants (reshard faults already resume INSIDE ``optimize()`` via the
  PR-14 retry handler; preemption's clean return was the one edge that
  still needed a human).

The factory contract: ``factory(replica_id, model, checkpoint_path)``
returns a started :class:`~bigdl_tpu.serving.replica.Replica` serving
``model`` — from ``checkpoint_path`` when one is given (the
continuous-deploy path), from the factory's own latest weights when
``None`` (scale-up and replacement).

Lock discipline: the controller owns exactly one lock, guarding only
the published ``_status`` snapshot.  All reconcile state (``_pools``
and everything inside them) is touched by the reconcile thread alone,
and the lock is never held across a router call — the router has its
own lock and the controller must never entangle their order.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from bigdl_tpu import telemetry
from bigdl_tpu.fleet.policy import Observation, PoolSpec, ScalingPolicy
from bigdl_tpu.telemetry import events as _events

__all__ = ["FleetController", "TrainingSupervisor", "next_replica_id",
           "reserve_replica_ids",
           "register_statusz", "unregister_statusz",
           "controller_statusz"]

logger = logging.getLogger(__name__)


# ---- /statusz wiring ------------------------------------------------------
# Trainer and serve frontends embed a `controller` section when any
# controller-ish component is live in the process.  Providers register
# here by name; the statusz builders pull the merged dict lazily, so
# neither the optimizer nor examples/serve.py grows a hard dependency
# on this package.

_statusz_lock = threading.Lock()
_statusz_providers: Dict[str, Callable[[], Dict]] = {}


def register_statusz(name: str, fn: Callable[[], Dict]) -> None:
    with _statusz_lock:
        _statusz_providers[str(name)] = fn


def unregister_statusz(name: str) -> None:
    with _statusz_lock:
        _statusz_providers.pop(str(name), None)


def controller_statusz() -> Optional[Dict]:
    """The merged ``controller`` section for ``/statusz`` pages, or
    None when no controller component is live in this process."""
    with _statusz_lock:
        providers = dict(_statusz_providers)
    if not providers:
        return None
    out: Dict[str, Any] = {}
    for name, fn in providers.items():
        try:
            out[name] = fn()
        except Exception as e:  # a broken provider must not take the
            # whole debug page down with it
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


# ---- replica id allocation ------------------------------------------------
# The controller and the checkpoint watcher both mint replica ids from
# different threads; a shared monotonic allocator (seeded past whatever
# the router already holds) is what keeps them from colliding on
# ``add_replica``.

_id_lock = threading.Lock()
_next_rid = 0


def next_replica_id(router) -> int:
    global _next_rid
    existing = max(router.replica_ids(), default=-1)
    with _id_lock:
        _next_rid = max(_next_rid, existing + 1)
        rid = _next_rid
        _next_rid += 1
        return rid


def reserve_replica_ids(ids) -> None:
    """Advance the allocator past externally-created replica ids.

    The controller calls this with every id it OBSERVES (registry
    records included), not just the router's live members: a dead
    replica swept from the router still has a snapshot on disk for a
    while, and re-minting its id would pin the stale unhealthy record
    onto the fresh replacement."""
    global _next_rid
    top = max((int(i) for i in ids), default=-1)
    with _id_lock:
        _next_rid = max(_next_rid, top + 1)


class _PoolState:
    """Reconcile-thread-private state for one model pool."""

    def __init__(self, spec: PoolSpec):
        self.spec = spec
        self.policy = ScalingPolicy(spec)
        self.desired: Optional[int] = None      # set on the first tick
        self.unhealthy_streak: Dict[int, int] = {}
        self.dying: Dict[int, Any] = {}         # rid -> Replica, dead,
        #                                         awaiting outstanding==0
        self.draining_out: Dict[int, Any] = {}  # rid -> Replica,
        #                                         scale-down in flight
        self.last_shed = 0
        self.last_decision: Dict[str, Any] = {}
        self.hold_reason_emitted: Optional[str] = None


class FleetController:
    """Closed-loop autoscaler over one
    :class:`~bigdl_tpu.serving.router.Router`.

    >>> ctl = FleetController(
    ...     router, factory,
    ...     pools=[PoolSpec(model="default", min_replicas=2,
    ...                     max_replicas=4, slo_ttft_p99_s=0.5)])
    >>> ctl.start()
    ... # chaos kills a replica / load spikes: the controller replaces
    ... # and scales with no operator step
    >>> ctl.stop()
    """

    def __init__(self, router, factory: Callable[..., Any],
                 pools: Optional[List[PoolSpec]] = None,
                 interval_s: float = 0.25, start: bool = False):
        self.router = router
        self.factory = factory
        specs = list(pools) if pools else [PoolSpec()]
        models = [s.model for s in specs]
        if len(set(models)) != len(models):
            raise ValueError(f"duplicate pool models: {models}")
        self.interval_s = float(interval_s)
        self._pools: Dict[str, _PoolState] = {
            s.model: _PoolState(s) for s in specs}
        self._lock = threading.Lock()
        self._status: Dict[str, Any] = {"running": False, "pools": {}}
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="bigdl-fleet-controller", daemon=True)
        self._started = False
        if start:
            self.start()

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "FleetController":
        if self._started:
            raise RuntimeError("controller already started")
        self._started = True
        # push each pool's SLO class and admission budget into the
        # router before the first decision routes on them
        for pool in self._pools.values():
            s = pool.spec
            if s.slo_ttft_p99_s is not None:
                self.router.set_slo_class(s.model, s.slo_ttft_p99_s)
            if s.admission_budget is not None:
                self.router.set_admission_budget(s.model,
                                                 s.admission_budget)
        register_statusz("fleet", self.status)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Stop reconciling (daemon AND joined, the exporter pattern).
        Replicas the controller spawned stay with the router — the
        controller is the loop, not the fleet's owner."""
        self._stop_evt.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
        unregister_statusz("fleet")

    def __enter__(self) -> "FleetController":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def status(self) -> Dict[str, Any]:
        """The `/statusz` ``controller`` contribution: desired/live per
        pool, the last decision + reason, cooldown remaining."""
        with self._lock:
            return dict(self._status)

    # ---- the reconcile loop ----------------------------------------------

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self._tick()
            except Exception:  # pragma: no cover - the loop must
                # survive anything one tick throws (a wedged reconcile
                # loop is an outage multiplier)
                logger.exception("fleet controller tick failed")
            self._stop_evt.wait(self.interval_s)

    def reconcile_once(self) -> Dict[str, Any]:
        """One synchronous tick (tests and the smoke harness drive the
        loop deterministically through this)."""
        self._tick()
        return self.status()

    def _tick(self) -> None:
        try:
            records = self.router.registry.poll()
        except Exception:
            # a doctored/unreadable registry is an observation outage,
            # not a controller crash: hold everything this tick
            logger.exception("registry poll failed; holding")
            records = None
        try:
            stats = self.router.stats()
        except Exception:
            logger.exception("router stats failed; holding")
            records = None
            stats = {}
        if records is not None:
            reserve_replica_ids(list(records.keys())
                                + list(self.router.replica_ids()))
        now = time.perf_counter()
        status_pools: Dict[str, Any] = {}
        for model, pool in self._pools.items():
            if records is None:
                status_pools[model] = dict(
                    pool.last_decision,
                    error="registry unreadable; holding")
                continue
            try:
                status_pools[model] = self._reconcile_pool(
                    pool, records, stats, now)
            except Exception:
                logger.exception("reconcile failed for pool %r", model)
                status_pools[model] = dict(pool.last_decision,
                                           error="reconcile failed")
        with self._lock:
            self._status = {
                "running": not self._stop_evt.is_set(),
                "interval_s": self.interval_s,
                "pools": status_pools,
            }

    # ---- per-pool reconcile ----------------------------------------------

    def _members(self, pool: _PoolState) -> Dict[int, Any]:
        out = {}
        for rid in self.router.replica_ids():
            if rid in pool.dying or rid in pool.draining_out:
                continue
            r = self.router.replica(rid)
            if r is not None \
                    and getattr(r, "model", "default") == pool.spec.model:
                out[rid] = r
        return out

    def _reconcile_pool(self, pool: _PoolState, records: Dict,
                        stats: Dict, now: float) -> Dict[str, Any]:
        spec = pool.spec
        members = self._members(pool)

        # classify members on their registry records.  A member with
        # no record yet (just added, first snapshot racing the poll)
        # is presumed live — spawning another copy because the health
        # plane is half a tick behind would thrash the pool.
        live: Dict[int, Any] = {}
        dead: Dict[int, Any] = {}
        for rid, r in members.items():
            rec = records.get(rid)
            if rec is None:
                live[rid] = r
                pool.unhealthy_streak.pop(rid, None)
            elif rec.get("healthy"):
                live[rid] = r
                pool.unhealthy_streak.pop(rid, None)
            else:
                # stale/corrupt/healthz-failed: demand the verdict
                # hold for dead_after_polls consecutive ticks before
                # acting — one torn snapshot read must not kill a
                # healthy replica.  A suspect still counts as live
                # until confirmed: spawning its replacement early
                # would double the pool on a noisy read
                n = pool.unhealthy_streak.get(rid, 0) + 1
                pool.unhealthy_streak[rid] = n
                if n >= spec.dead_after_polls:
                    dead[rid] = r
                else:
                    live[rid] = r
        for rid, r in dead.items():
            reason = (records.get(rid) or {}).get("reason")
            logger.warning("pool %r: replica %d is dead (%s); "
                           "replacing", spec.model, rid, reason)
            pool.dying[rid] = r
            pool.unhealthy_streak.pop(rid, None)

        # finish in-flight removals the zero-drop way: a dying or
        # draining-out replica leaves only once its admitted work hits 0
        self._sweep_removals(pool)

        if pool.desired is None:
            pool.desired = spec.clamp(len(live) if live
                                      else spec.min_replicas)

        obs = self._observe(pool, live, records, stats)
        decision = pool.policy.decide(obs, now)
        if decision.action == "up":
            pool.desired = spec.clamp(pool.desired + 1)
            pool.policy.actuated(now)
        elif decision.action == "down":
            pool.desired = spec.clamp(pool.desired - 1)
            pool.policy.actuated(now)
        self._note_hold(pool, decision)

        # actuate toward desired
        spawned = self._spawn_missing(pool, live, dead, decision)
        self._drain_excess(pool, live)

        if decision.action or spawned or dead:
            pool.last_decision = {
                "action": decision.action,
                "reason": decision.reason or
                ("replaced dead replica(s) "
                 f"{sorted(dead)}" if dead else ""),
            }
        self._publish_gauges(spec.model, pool.desired, len(live))
        return {
            "desired": pool.desired,
            "live": len(live),
            "dying": sorted(pool.dying),
            "draining_out": sorted(pool.draining_out),
            "last_decision": dict(pool.last_decision),
            "cooldown_remaining_s": round(
                pool.policy.cooldown_remaining(now), 3),
            "observation": {
                "ttft_p99_s": obs.ttft_p99_s,
                "queue_depth": obs.queue_depth,
                "shed_delta": obs.shed_delta,
                "inflight": obs.inflight,
                "breakers_open": obs.breakers_open,
            },
        }

    def _observe(self, pool: _PoolState, live: Dict, records: Dict,
                 stats: Dict) -> Observation:
        model = pool.spec.model
        ttft = 0.0
        queue = 0
        for rid in live:
            rec = records.get(rid) or {}
            ttft = max(ttft, float(rec.get("ttft_p99_s", 0.0) or 0.0))
            queue += int(rec.get("queue_depth", 0) or 0)
        if len(self._pools) == 1:
            # single-pool fleet: the router's own queue + parked
            # requests all belong to this pool — they are the earliest
            # overload signal (work that could not even dispatch)
            queue += int(stats.get("queue_depth", 0) or 0)
            queue += int(stats.get("waiting", 0) or 0)
        shed_now = int(
            (stats.get("model_shed") or {}).get(model, 0) or 0)
        shed_delta = max(shed_now - pool.last_shed, 0)
        pool.last_shed = shed_now
        inflight = int(
            (stats.get("model_inflight") or {}).get(model, 0) or 0)
        breakers_open = int(stats.get("breakers_open", 0) or 0)
        return Observation(live=len(live), desired=pool.desired,
                           ttft_p99_s=ttft, queue_depth=queue,
                           shed_delta=shed_delta, inflight=inflight,
                           breakers_open=breakers_open)

    # ---- actuation -------------------------------------------------------

    def _spawn_missing(self, pool: _PoolState, live: Dict, dead: Dict,
                       decision) -> int:
        spec = pool.spec
        missing = pool.desired - len(live)
        spawned = 0
        while missing > 0:
            if dead:
                reason = (f"replacing dead replica(s) "
                          f"{sorted(dead)}")
            else:
                reason = decision.reason or "below desired count"
            try:
                rid = next_replica_id(self.router)
                replica = self.factory(rid, spec.model, None)
                self.router.add_replica(replica)
            except Exception:
                logger.exception("pool %r: replica spawn failed",
                                 spec.model)
                break
            # THE one scale_up emission site: load-driven growth and
            # dead-replica replacement share it, told apart by reason
            _events.record_event("scale_up", model=spec.model,
                                 replica=rid, desired=pool.desired,
                                 reason=reason)
            if telemetry.enabled():
                from bigdl_tpu.telemetry import families
                families.fleet_scale_events_total().labels("up").inc()
            live[rid] = replica
            spawned += 1
            missing -= 1
        return spawned

    def _drain_excess(self, pool: _PoolState, live: Dict) -> None:
        excess = len(live) - pool.desired
        while excess > 0 and len(live) > pool.spec.min_replicas:
            # evict the member with the least admitted work (cheapest
            # zero-drop drain), ties to the youngest id
            victim_id = min(
                live, key=lambda rid: (live[rid].admitted_outstanding(),
                                       -rid))
            victim = live.pop(victim_id)
            try:
                self.router.drain(victim_id)
            except Exception:
                logger.exception("pool %r: drain of %d failed",
                                 pool.spec.model, victim_id)
                break
            pool.draining_out[victim_id] = victim
            # THE one scale_down emission site
            _events.record_event(
                "scale_down", model=pool.spec.model, replica=victim_id,
                desired=pool.desired,
                outstanding=victim.admitted_outstanding())
            if telemetry.enabled():
                from bigdl_tpu.telemetry import families
                families.fleet_scale_events_total().labels("down").inc()
            excess -= 1

    def _sweep_removals(self, pool: _PoolState) -> None:
        for group in (pool.dying, pool.draining_out):
            for rid in list(group):
                replica = group[rid]
                try:
                    outstanding = replica.admitted_outstanding()
                except Exception:
                    outstanding = 0
                if outstanding > 0:
                    continue  # zero-drop: wait for admitted work
                try:
                    if rid in self.router.replica_ids():
                        self.router.remove_replica(rid, drain=True,
                                                   timeout=10.0)
                except Exception:
                    logger.exception("removal of replica %d failed",
                                     rid)
                group.pop(rid, None)

    def _note_hold(self, pool: _PoolState, decision) -> None:
        if decision.action != "hold":
            pool.hold_reason_emitted = None
            return
        # latch on the STABLE key (the reason prose carries tick-varying
        # streak counts and countdowns): one event per suppression
        # episode, not one per tick — the ring must outlive a long
        # cooldown
        latch = decision.key or decision.reason
        if pool.hold_reason_emitted == latch:
            return
        pool.hold_reason_emitted = latch
        # THE one controller_hold emission site
        _events.record_event("controller_hold", model=pool.spec.model,
                             desired=pool.desired,
                             reason=decision.reason)

    def _publish_gauges(self, model: str, desired: int,
                        live: int) -> None:
        if telemetry.enabled():
            from bigdl_tpu.telemetry import families
            families.fleet_replicas_desired().labels(model).set(desired)
            families.fleet_replicas_live().labels(model).set(live)


class TrainingSupervisor:
    """The training half of the self-driving fleet: run ``optimize()``
    and, when it returns preempted (the SIGTERM grace-checkpoint
    path), resume from ``latest_good()`` and keep going — the
    walkback-verified checkpoint plus its topology manifest mean the
    resume lands at whatever width the current mesh config grants,
    with no operator step.  Reshard faults never reach here: the
    PR-14 retry handler already rebuilds the mesh and resumes INSIDE
    ``optimize()``.

    >>> model = TrainingSupervisor(opt).run()
    """

    def __init__(self, optimizer, checkpoint_dir: Optional[str] = None,
                 max_resumes: int = 8):
        self.optimizer = optimizer
        self.checkpoint_dir = checkpoint_dir \
            or getattr(optimizer, "checkpoint_path", None)
        if self.checkpoint_dir is None:
            raise ValueError(
                "TrainingSupervisor needs a checkpoint directory "
                "(set_checkpoint on the optimizer, or pass "
                "checkpoint_dir) — resuming a preempted run without "
                "checkpoints is not a thing")
        self.max_resumes = int(max_resumes)
        self.resumes = 0
        self.last_resume_from: Optional[str] = None

    def _latest_good(self) -> Optional[str]:
        from bigdl_tpu.utils.file import CheckpointManager
        return CheckpointManager(self.checkpoint_dir).latest_good()

    def run(self):
        """``optimize()`` to completion, resuming past preemptions.
        Returns the trained model; raises RuntimeError when the run
        keeps getting preempted past ``max_resumes`` (at that point a
        human SHOULD look)."""
        register_statusz("training", self.statusz)
        try:
            while True:
                model = self.optimizer.optimize()
                if not getattr(self.optimizer, "preempted", False):
                    return model
                if self.resumes >= self.max_resumes:
                    raise RuntimeError(
                        f"run preempted {self.resumes + 1}x "
                        f"(max_resumes={self.max_resumes}); giving "
                        f"the pager a chance")
                good = self._latest_good()
                if good is None:
                    raise RuntimeError(
                        "preempted before any checkpoint committed; "
                        "nothing to resume from")
                self.resumes += 1
                self.last_resume_from = good
                logger.warning(
                    "preempted; auto-resuming from %s (resume %d/%d)",
                    good, self.resumes, self.max_resumes)
                self.optimizer.resume(good)
        finally:
            unregister_statusz("training")

    def statusz(self) -> Dict[str, Any]:
        return {
            "kind": "training_supervisor",
            "resumes": self.resumes,
            "max_resumes": self.max_resumes,
            "last_resume_from": self.last_resume_from,
            "preempted": bool(getattr(self.optimizer, "preempted",
                                      False)),
        }
