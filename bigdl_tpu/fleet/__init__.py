"""The self-driving fleet: closed-loop autoscaling + continuous
train-to-serve deployment (see docs/serving.md "Autoscaling &
continuous deployment").

* :class:`~bigdl_tpu.fleet.policy.PoolSpec` /
  :class:`~bigdl_tpu.fleet.policy.ScalingPolicy` — the pure
  observe/decide half (thresholds, hysteresis, cooldown).
* :class:`~bigdl_tpu.fleet.controller.FleetController` — the reconcile
  thread: replaces dead replicas, scales per-model pools on TTFT /
  queue / shed breaches, never below ``min_replicas``.
* :class:`~bigdl_tpu.fleet.controller.TrainingSupervisor` — auto-resume
  of preempted training runs from ``latest_good()``.
* :class:`~bigdl_tpu.fleet.watcher.CheckpointWatcher` — rolling
  zero-drop hot-deploy of every new CRC-verified checkpoint
  generation, freshness published as
  ``fleet_deploy_freshness_seconds``.
"""

from bigdl_tpu.fleet.controller import (FleetController,
                                        TrainingSupervisor,
                                        controller_statusz,
                                        next_replica_id,
                                        register_statusz,
                                        unregister_statusz)
from bigdl_tpu.fleet.policy import (Decision, Observation, PoolSpec,
                                    ScalingPolicy)
from bigdl_tpu.fleet.watcher import CheckpointWatcher

__all__ = ["FleetController", "TrainingSupervisor", "CheckpointWatcher",
           "PoolSpec", "ScalingPolicy", "Observation", "Decision",
           "controller_statusz", "register_statusz",
           "unregister_statusz", "next_replica_id"]
