"""DataFrame ML-pipeline integration (the dlframes analog).

Reference: dlframes/DLEstimator.scala:166 (Spark ML Estimator wrapping
an Optimizer; DLModel:368 Transformer wrapping a Predictor),
DLClassifier.scala:40, DLImageReader.scala, DLImageTransformer.scala.

The reference integrates with Spark ML pipelines; the TPU-native stack
integrates with the pandas/scikit-learn ecosystem instead: DLEstimator
follows the sklearn estimator protocol (``fit``/``transform``/
``get_params``) over pandas DataFrames whose cells hold features, so it
composes with sklearn ``Pipeline`` the way DLEstimator composed with
Spark ML pipelines.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from bigdl_tpu.core.module import Module

__all__ = ["DLEstimator", "DLClassifier", "DLModel", "DLClassifierModel",
           "DLImageReader", "DLImageTransformer"]


def _column_to_array(col, feature_size):
    arr = np.asarray([np.asarray(v, np.float32).reshape(feature_size)
                      for v in col])
    return arr


class DLEstimator:
    """Train a Module on DataFrame columns (reference
    dlframes/DLEstimator.scala:166).

    ``fit(df)`` trains on ``features_col``/``label_col`` and returns a
    :class:`DLModel`.  Cells may hold scalars, lists, or ndarrays;
    ``feature_size``/``label_size`` give the per-row shapes (reference
    featureSize/labelSize params).
    """

    def __init__(self, model: Module, criterion,
                 feature_size: Sequence[int],
                 label_size: Sequence[int],
                 features_col: str = "features",
                 label_col: str = "label",
                 prediction_col: str = "prediction",
                 batch_size: int = 32, max_epoch: int = 10,
                 learning_rate: float = 1e-3, optim_method=None):
        self.model = model
        self.criterion = criterion
        self.feature_size = tuple(feature_size)
        self.label_size = tuple(label_size)
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.batch_size = batch_size
        self.max_epoch = max_epoch
        self.learning_rate = learning_rate
        self.optim_method = optim_method

    # sklearn protocol -----------------------------------------------------
    def get_params(self, deep=True):
        return {k: getattr(self, k) for k in
                ("model", "criterion", "feature_size", "label_size",
                 "features_col", "label_col", "prediction_col",
                 "batch_size", "max_epoch", "learning_rate",
                 "optim_method")}

    def set_params(self, **params):
        for k, v in params.items():
            setattr(self, k, v)
        return self

    # builder-style setters mirroring the reference ------------------------
    def set_batch_size(self, v: int) -> "DLEstimator":
        self.batch_size = v
        return self

    def set_max_epoch(self, v: int) -> "DLEstimator":
        self.max_epoch = v
        return self

    def set_learning_rate(self, v: float) -> "DLEstimator":
        self.learning_rate = v
        return self

    def _label_array(self, df):
        return _column_to_array(df[self.label_col], self.label_size)

    def fit(self, df, y=None) -> "DLModel":
        from bigdl_tpu.dataset.dataset import LocalDataSet, Sample
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch
        from bigdl_tpu.optim import Optimizer, SGD, Trigger

        x = _column_to_array(df[self.features_col], self.feature_size)
        labels = self._label_array(df)
        samples = [Sample(f, l) for f, l in zip(x, labels)]
        ds = LocalDataSet(samples, shuffle=True).transform(
            SampleToMiniBatch(min(self.batch_size, len(samples))))
        method = self.optim_method or SGD(self.learning_rate)
        trained = (Optimizer(self.model, ds, self.criterion)
                   .set_optim_method(method)
                   .set_end_when(Trigger.max_epoch(self.max_epoch))
                   .optimize())
        return self._make_model(trained)

    def _make_model(self, trained) -> "DLModel":
        return DLModel(trained, self.feature_size,
                       features_col=self.features_col,
                       prediction_col=self.prediction_col,
                       batch_size=self.batch_size)


class DLModel:
    """Fitted transformer: appends ``prediction_col`` to a DataFrame
    (reference dlframes/DLEstimator.scala:368 DLModel.transform →
    internal Predictor)."""

    def __init__(self, model: Module, feature_size: Sequence[int],
                 features_col: str = "features",
                 prediction_col: str = "prediction",
                 batch_size: int = 32):
        self.model = model
        self.feature_size = tuple(feature_size)
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.batch_size = batch_size

    def _predict_array(self, x: np.ndarray) -> np.ndarray:
        from bigdl_tpu.optim import Predictor
        preds = Predictor(self.model, batch_size=self.batch_size) \
            .predict(list(x))
        return np.asarray(preds)

    def _format(self, preds: np.ndarray) -> List:
        return [np.asarray(p) for p in preds]

    def transform(self, df):
        x = _column_to_array(df[self.features_col], self.feature_size)
        out = df.copy()
        out[self.prediction_col] = self._format(self._predict_array(x))
        return out

    predict = transform


class DLClassifier(DLEstimator):
    """Classification sugar: ClassNLL over log-probs, argmax prediction
    (reference DLClassifier.scala:40 — label column holds 1-based class
    ids, prediction column gets the predicted id)."""

    def __init__(self, model: Module, criterion=None,
                 feature_size: Sequence[int] = (),
                 features_col: str = "features",
                 label_col: str = "label", **kw):
        import bigdl_tpu.nn as nn
        super().__init__(model, criterion or nn.ClassNLLCriterion(),
                         feature_size, (1,), features_col=features_col,
                         label_col=label_col, **kw)

    def _label_array(self, df):
        # class ids are per-row scalars: (B,) for ClassNLL
        return np.asarray(df[self.label_col], np.float32).reshape(-1)

    def _make_model(self, trained) -> "DLClassifierModel":
        return DLClassifierModel(trained, self.feature_size,
                                 features_col=self.features_col,
                                 prediction_col=self.prediction_col,
                                 batch_size=self.batch_size)


class DLClassifierModel(DLModel):
    def _format(self, preds: np.ndarray) -> List:
        return list(np.argmax(preds, axis=-1).astype(np.float64) + 1)


class DLImageReader:
    """Read an image directory into a DataFrame with an ``image`` column
    of HWC float arrays (reference DLImageReader.scala: reads to a
    DataFrame of image schema rows)."""

    @staticmethod
    def read_images(path: str, with_label_from_dirs: bool = False):
        import pandas as pd
        from bigdl_tpu.transform.vision import ImageFrame
        frame = ImageFrame.read(path, with_label_from_dirs)
        rows = {
            "image": [f.image for f in frame],
            "uri": [f.get(f.uri) for f in frame],
        }
        if with_label_from_dirs:
            rows["label"] = [f.get_label() for f in frame]
        return pd.DataFrame(rows)


class DLImageTransformer:
    """Apply a vision FeatureTransformer pipeline to an image column
    (reference DLImageTransformer.scala)."""

    def __init__(self, transformer, input_col: str = "image",
                 output_col: str = "features"):
        self.transformer = transformer
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, df):
        from bigdl_tpu.transform.vision import ImageFeature
        out = df.copy()
        feats = [ImageFeature(np.asarray(img))
                 for img in df[self.input_col]]
        # iterator form works for single transformers AND >>-chains
        results = [f.image for f in self.transformer(iter(feats))]
        out[self.output_col] = results
        return out
