"""Checkpointable input-pipeline state: sample-accurate resume.

The dataset layer's determinism contract (``dataset/dataset.py``:
epoch-``E`` order is a pure function of ``(seed, E)``) makes iterator
position expressible as three integers instead of an opaque RNG state.
:class:`PipelineState` captures that position — ``(seed, epoch,
batches-consumed offset)`` plus the mixing sampler's configuration when
the dataset is a :class:`~bigdl_tpu.data.mixing.MixedDataSet` — and the
``CheckpointManager`` persists it next to the model payload, CRC'd in
the same per-generation manifest.  On resume the Optimizer rebuilds the
epoch-``E`` iterator and skips exactly ``offset`` batches, so training
continues at the exact next batch: no sample is replayed, none is
skipped (the design tf.data's iterator checkpointing proved at fleet
scale — Murray et al., VLDB 2021 — rebuilt here on top of deterministic
reshuffling instead of serialized per-op buffers).

The restore cost is regenerating the skipped batches host-side (bounded
by one checkpoint interval of input-pipeline work); the payoff is that
a preemption-heavy fleet stops double-training every sample consumed
before each crash.
"""

from __future__ import annotations

import inspect
import logging
from typing import Any, Dict, Iterator, Optional

__all__ = ["PIPELINE_STATE_VERSION", "PipelineState", "epoch_iter",
           "skip_batches", "skip_samples", "supports_epoch",
           "dataset_seed"]

logger = logging.getLogger("bigdl_tpu.data")

PIPELINE_STATE_VERSION = 1


class PipelineState:
    """Snapshot of an input pipeline's position: everything needed to
    rebuild the exact iterator a crashed run was consuming.

    * ``seed``   — the permutation seed the epoch orders derive from;
    * ``epoch``  — the epoch whose order was being consumed;
    * ``offset`` — post-transform batches already consumed (stepped)
      within that epoch ON THE WRITING PROCESS — a per-host count,
      meaningful only at the writing topology;
    * ``global_offset`` / ``process_count`` / ``global_batch`` — the
      topology-portable position: SAMPLES consumed globally within the
      epoch, plus the writing process count and global batch size.
      Because every process consumes the same number of lockstep
      batches and ``DistributedDataSet`` shards ``order[pid::nproc]``
      of ONE global permutation, the consumed set is always a prefix
      of the global epoch order — so a resume on an M-process fleet
      converts ``global_offset`` into per-host sample skips instead of
      trusting the N-process batch count (which would silently skip
      the WRONG samples under a changed topology);
    * ``sampler`` — the mixing sampler's configuration
      (``MixedDataSet.sampler_state()``), present so restore can verify
      the mixture it is resuming into draws the same choice sequence.

    ``snapshot()``/``restore()`` round-trip through a plain JSON-able
    dict — the wire format the checkpoint manifest CRCs.  The global
    fields are additive (still version 1): a sidecar without them
    restores exactly as before at the SAME topology, and falls back to
    epoch-start replay at a different one.
    """

    __slots__ = ("seed", "epoch", "offset", "sampler", "global_offset",
                 "process_count", "global_batch")

    def __init__(self, seed: int, epoch: int = 1, offset: int = 0,
                 sampler: Optional[Dict] = None,
                 global_offset: Optional[int] = None,
                 process_count: Optional[int] = None,
                 global_batch: Optional[int] = None):
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.offset = int(offset)
        self.sampler = sampler
        self.global_offset = (None if global_offset is None
                              else int(global_offset))
        self.process_count = (None if process_count is None
                              else int(process_count))
        self.global_batch = (None if global_batch is None
                             else int(global_batch))

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"version": PIPELINE_STATE_VERSION,
                               "seed": self.seed, "epoch": self.epoch,
                               "offset": self.offset}
        if self.global_offset is not None:
            out["global_offset"] = self.global_offset
        if self.process_count is not None:
            out["process_count"] = self.process_count
        if self.global_batch is not None:
            out["global_batch"] = self.global_batch
        if self.sampler is not None:
            out["sampler"] = self.sampler
        return out

    @classmethod
    def restore(cls, snapshot: Dict[str, Any]) -> "PipelineState":
        v = snapshot.get("version")
        if v != PIPELINE_STATE_VERSION:
            raise ValueError(
                f"unsupported pipeline-state version {v!r} "
                f"(supported: {PIPELINE_STATE_VERSION})")
        return cls(seed=snapshot["seed"], epoch=snapshot["epoch"],
                   offset=snapshot.get("offset", 0),
                   sampler=snapshot.get("sampler"),
                   global_offset=snapshot.get("global_offset"),
                   process_count=snapshot.get("process_count"),
                   global_batch=snapshot.get("global_batch"))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"PipelineState(seed={self.seed}, epoch={self.epoch}, "
                f"offset={self.offset}, "
                f"global_offset={self.global_offset})")


def dataset_seed(dataset) -> int:
    """The permutation seed a dataset iterates under: its own ``seed()``
    when it exposes one, else the process seed."""
    seed = getattr(dataset, "seed", None)
    if callable(seed):
        try:
            return int(seed())
        except Exception:  # pragma: no cover - exotic wrapper
            pass
    from bigdl_tpu.utils.rng import get_seed
    return int(get_seed())


def epoch_iter(dataset, epoch: int, train: bool = True) -> Iterator:
    """One epoch's iterator, with the epoch key passed through when the
    dataset's ``data()`` accepts it (user wrappers that predate the
    keyword fall back to the epoch-less call — still deterministic
    per-object, but not replayable across a process restart, so resume
    degrades to epoch-start replay for them)."""
    if supports_epoch(dataset):
        return dataset.data(train=train, epoch=int(epoch))
    return dataset.data(train=train)


def supports_epoch(dataset) -> bool:
    """Does ``dataset.data`` accept the ``epoch`` keyword (i.e. is its
    order replayable across a process restart)?"""
    try:
        params = inspect.signature(dataset.data).parameters
    except (TypeError, ValueError):
        return False
    return "epoch" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD
        for p in params.values())


def skip_batches(it: Iterator, n: int) -> int:
    """Advance ``it`` past ``n`` batches (consume-and-discard — the
    restore cost of sample-accurate resume); returns how many were
    actually skipped (fewer means the epoch was shorter than the
    recorded offset, which the caller should treat as a fully-consumed
    epoch)."""
    skipped = 0
    for _ in range(int(n)):
        try:
            next(it)
        except StopIteration:
            break
        skipped += 1
    return skipped


def skip_samples(it: Iterator, n_samples: int) -> tuple:
    """Advance ``it`` until ``n_samples`` SAMPLES (summed ``b.size()``
    over pulled batches) have been consumed — the topology-portable
    form of :func:`skip_batches`, used when a checkpoint written on an
    N-process fleet resumes on M processes and the per-host sample
    count (not the per-host batch count) is what the global offset
    converts to.  Returns ``(batches_skipped, samples_skipped)``; the
    caller must verify ``samples_skipped == n_samples`` — an overshoot
    means the skip point lands MID-batch on the new batch size (the
    resume cannot split a batch and must fall back to epoch-start
    replay), an undershoot means the epoch was shorter than the
    recorded offset."""
    want = int(n_samples)
    batches = samples = 0
    while samples < want:
        try:
            b = next(it)
        except StopIteration:
            break
        batches += 1
        samples += int(b.size())
    return batches, samples
