"""Checkpointable input-pipeline state: sample-accurate resume.

The dataset layer's determinism contract (``dataset/dataset.py``:
epoch-``E`` order is a pure function of ``(seed, E)``) makes iterator
position expressible as three integers instead of an opaque RNG state.
:class:`PipelineState` captures that position — ``(seed, epoch,
batches-consumed offset)`` plus the mixing sampler's configuration when
the dataset is a :class:`~bigdl_tpu.data.mixing.MixedDataSet` — and the
``CheckpointManager`` persists it next to the model payload, CRC'd in
the same per-generation manifest.  On resume the Optimizer rebuilds the
epoch-``E`` iterator and skips exactly ``offset`` batches, so training
continues at the exact next batch: no sample is replayed, none is
skipped (the design tf.data's iterator checkpointing proved at fleet
scale — Murray et al., VLDB 2021 — rebuilt here on top of deterministic
reshuffling instead of serialized per-op buffers).

The restore cost is regenerating the skipped batches host-side (bounded
by one checkpoint interval of input-pipeline work); the payoff is that
a preemption-heavy fleet stops double-training every sample consumed
before each crash.
"""

from __future__ import annotations

import inspect
import logging
from typing import Any, Dict, Iterator, Optional

__all__ = ["PIPELINE_STATE_VERSION", "PipelineState", "epoch_iter",
           "skip_batches", "supports_epoch", "dataset_seed"]

logger = logging.getLogger("bigdl_tpu.data")

PIPELINE_STATE_VERSION = 1


class PipelineState:
    """Snapshot of an input pipeline's position: everything needed to
    rebuild the exact iterator a crashed run was consuming.

    * ``seed``   — the permutation seed the epoch orders derive from;
    * ``epoch``  — the epoch whose order was being consumed;
    * ``offset`` — post-transform batches already consumed (stepped)
      within that epoch;
    * ``sampler`` — the mixing sampler's configuration
      (``MixedDataSet.sampler_state()``), present so restore can verify
      the mixture it is resuming into draws the same choice sequence.

    ``snapshot()``/``restore()`` round-trip through a plain JSON-able
    dict — the wire format the checkpoint manifest CRCs.
    """

    __slots__ = ("seed", "epoch", "offset", "sampler")

    def __init__(self, seed: int, epoch: int = 1, offset: int = 0,
                 sampler: Optional[Dict] = None):
        self.seed = int(seed)
        self.epoch = int(epoch)
        self.offset = int(offset)
        self.sampler = sampler

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"version": PIPELINE_STATE_VERSION,
                               "seed": self.seed, "epoch": self.epoch,
                               "offset": self.offset}
        if self.sampler is not None:
            out["sampler"] = self.sampler
        return out

    @classmethod
    def restore(cls, snapshot: Dict[str, Any]) -> "PipelineState":
        v = snapshot.get("version")
        if v != PIPELINE_STATE_VERSION:
            raise ValueError(
                f"unsupported pipeline-state version {v!r} "
                f"(supported: {PIPELINE_STATE_VERSION})")
        return cls(seed=snapshot["seed"], epoch=snapshot["epoch"],
                   offset=snapshot.get("offset", 0),
                   sampler=snapshot.get("sampler"))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"PipelineState(seed={self.seed}, epoch={self.epoch}, "
                f"offset={self.offset})")


def dataset_seed(dataset) -> int:
    """The permutation seed a dataset iterates under: its own ``seed()``
    when it exposes one, else the process seed."""
    seed = getattr(dataset, "seed", None)
    if callable(seed):
        try:
            return int(seed())
        except Exception:  # pragma: no cover - exotic wrapper
            pass
    from bigdl_tpu.utils.rng import get_seed
    return int(get_seed())


def epoch_iter(dataset, epoch: int, train: bool = True) -> Iterator:
    """One epoch's iterator, with the epoch key passed through when the
    dataset's ``data()`` accepts it (user wrappers that predate the
    keyword fall back to the epoch-less call — still deterministic
    per-object, but not replayable across a process restart, so resume
    degrades to epoch-start replay for them)."""
    if supports_epoch(dataset):
        return dataset.data(train=train, epoch=int(epoch))
    return dataset.data(train=train)


def supports_epoch(dataset) -> bool:
    """Does ``dataset.data`` accept the ``epoch`` keyword (i.e. is its
    order replayable across a process restart)?"""
    try:
        params = inspect.signature(dataset.data).parameters
    except (TypeError, ValueError):
        return False
    return "epoch" in params or any(
        p.kind is inspect.Parameter.VAR_KEYWORD
        for p in params.values())


def skip_batches(it: Iterator, n: int) -> int:
    """Advance ``it`` past ``n`` batches (consume-and-discard — the
    restore cost of sample-accurate resume); returns how many were
    actually skipped (fewer means the epoch was shorter than the
    recorded offset, which the caller should treat as a fully-consumed
    epoch)."""
    skipped = 0
    for _ in range(int(n)):
        try:
            next(it)
        except StopIteration:
            break
        skipped += 1
    return skipped
