"""Async device prefetch: overlap H2D staging with device compute.

The optimizer's default data path stages each batch synchronously
(``jax.device_put`` into the mesh's data sharding) between dispatches —
on a high-latency host<->device link that transfer sits squarely in the
hot loop.  ``DevicePrefetch`` is a terminal pipeline stage that
double-buffers it away: a producer thread stages batch ``N+1`` into
device memory while step ``N`` runs, so by the time the loop asks for
the next batch its arrays are already device-resident and the
``_stage`` call in the optimizer passes them through untouched.

Off by default.  ``Optimizer.set_device_prefetch(n_ahead)`` wraps the
epoch iterator in one of these with the run's batch sharding; the stage
is also usable standalone at the end of a transform chain once a
sharding is set (``set_sharding``).  ``n_ahead=1`` is classic double
buffering; larger values additionally absorb jittery batch-assembly
times at the cost of ``n_ahead`` batches of HBM.
"""

from __future__ import annotations

import threading
import queue
from typing import Iterator, Optional

from bigdl_tpu import telemetry
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.telemetry import families as _tm

__all__ = ["DevicePrefetch"]

_STOP = object()


class _Failure:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def _stage_batch(batch, sharding):
    """Stage one item to device memory: MiniBatch inputs/targets (or a
    bare array pytree) through the optimizer's staging primitive, which
    handles the multi-process assemble-global-from-local case."""
    from bigdl_tpu.dataset.dataset import MiniBatch
    from bigdl_tpu.optim.optimizer import _stage
    if isinstance(batch, MiniBatch):
        return MiniBatch(_stage(batch.get_input(), sharding),
                         _stage(batch.get_target(), sharding))
    return _stage(batch, sharding)


class _DevicePrefetchIter:
    """The running prefetcher: a daemon producer staging upstream items
    to device over a bounded queue.  Exposes ``staged_total`` /
    ``occupancy()`` so tests (and the occupancy gauge) can observe that
    batch N+1 really is device-resident while the consumer still holds
    batch N."""

    def __init__(self, it: Iterator, sharding, n_ahead: int):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(n_ahead), 1))
        self._stop = threading.Event()
        self._done = False
        self.staged_total = 0
        self._m_occ = _tm.device_prefetch_buffer_occupancy()

        def produce():
            try:
                for item in it:
                    staged = _stage_batch(item, sharding)
                    self.staged_total += 1
                    if not self._put(staged):
                        return
                self._put(_STOP)
            except BaseException as e:  # noqa: BLE001 — relayed below
                self._put(_Failure(e))

        self._thread = threading.Thread(
            target=produce, daemon=True, name="bigdl-device-prefetch")
        self._thread.start()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def occupancy(self) -> int:
        """Device-resident batches buffered ahead of the consumer."""
        return self._q.qsize()

    def close(self) -> None:
        self._stop.set()

    def __iter__(self) -> "_DevicePrefetchIter":
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        if telemetry.enabled():
            # occupancy BEFORE the take: batches sitting device-ready
            # while the step ran; 0 here means the step waited on H2D
            self._m_occ.set(self._q.qsize())
        item = self._q.get()
        if item is _STOP:
            self._done = True
            self._stop.set()
            raise StopIteration
        if isinstance(item, _Failure):
            self._done = True
            self._stop.set()
            raise item.exc
        return item


class DevicePrefetch(Transformer):
    """Terminal transform stage staging batches to device ahead of
    consumption (see module docstring).  ``sharding=None`` stages onto
    the default device — set the mesh's batch sharding before iterating
    a sharded run (the Optimizer does this when wiring the stage)."""

    def __init__(self, n_ahead: int = 1, sharding=None):
        if n_ahead < 1:
            raise ValueError("DevicePrefetch needs n_ahead >= 1")
        self.n_ahead = int(n_ahead)
        self.sharding = sharding

    def set_sharding(self, sharding) -> "DevicePrefetch":
        self.sharding = sharding
        return self

    def apply(self, it: Iterator) -> _DevicePrefetchIter:
        return _DevicePrefetchIter(iter(it), self.sharding, self.n_ahead)
