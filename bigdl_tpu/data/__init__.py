"""``bigdl_tpu.data`` — the deterministic, checkpointable input-pipeline
service (docs/data_pipeline.md).

Three pieces on top of the dataset layer's ``(seed, epoch)`` determinism
contract:

* :class:`~bigdl_tpu.data.pipeline.PipelineState` — snapshot/restore of
  iterator position, persisted by the CheckpointManager alongside the
  model payload, so a crashed or preempted run resumes at the exact
  next batch (sample-accurate resume);
* :class:`~bigdl_tpu.data.mixing.MixedDataSet` — weighted multi-corpus
  interleaving with a checkpointable sampler;
* :class:`~bigdl_tpu.data.device_prefetch.DevicePrefetch` — async
  double-buffered ``jax.device_put`` so step N runs while batch N+1
  stages.
"""

from bigdl_tpu.data.pipeline import (
    PIPELINE_STATE_VERSION, PipelineState, dataset_seed, epoch_iter,
    skip_batches, supports_epoch,
)
from bigdl_tpu.data.mixing import MixedDataSet
from bigdl_tpu.data.device_prefetch import DevicePrefetch

__all__ = ["PIPELINE_STATE_VERSION", "PipelineState", "MixedDataSet",
           "DevicePrefetch", "dataset_seed", "epoch_iter",
           "skip_batches", "supports_epoch"]
