"""Weighted multi-corpus mixing with a checkpointable sampler.

``MixedDataSet`` interleaves several datasets by weight: each draw
picks a child with probability proportional to its weight and takes
that child's next item; exhausted children cycle onto their next
epoch-keyed pass (so a small corpus reshuffles every wrap instead of
repeating one frozen order).  The whole stream is a pure function of
``(seed, epoch, draw index)`` — the child-choice sequence comes from a
deterministic per-epoch RNG and each child's pass order from the
dataset layer's ``epoch_permutation`` contract — which is what makes
the sampler *checkpointable*: the PipelineState offset identifies the
draw position exactly, and ``sampler_state()`` records the mixture
configuration so restore can verify it is resuming into a mixture that
draws the same choice sequence (a silently changed weight vector would
otherwise desynchronize the replay).
"""

from __future__ import annotations

import copy as _copy
import logging
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.dataset import epoch_permutation

__all__ = ["MixedDataSet"]

logger = logging.getLogger("bigdl_tpu.data")


class MixedDataSet:
    """Interleave ``datasets`` by ``weights`` (default: proportional to
    their sizes).  One mixture epoch yields ``items_per_epoch`` items
    (default: the children's combined size), so downstream
    ``SampleToMiniBatch``/epoch bookkeeping see an ordinary
    finite-epoch dataset.

    Works transparently under multi-process training when every child
    is per-process-sharded: the child-choice sequence depends only on
    ``(seed, epoch)``, so all hosts draw the same children in the same
    order, each serving its own shard's rows — consistent global
    batches with zero coordination.
    """

    def __init__(self, datasets: Sequence, weights: Optional[Sequence[float]]
                 = None, seed: Optional[int] = None,
                 items_per_epoch: Optional[int] = None):
        if not datasets:
            raise ValueError("MixedDataSet needs at least one dataset")
        self._children = list(datasets)
        if weights is None:
            weights = [max(int(d.size()), 1) for d in self._children]
        if len(weights) != len(self._children):
            raise ValueError(
                f"MixedDataSet: {len(self._children)} datasets but "
                f"{len(weights)} weights")
        w = np.asarray(weights, dtype=np.float64)
        if (w < 0).any() or w.sum() <= 0:
            raise ValueError(
                "MixedDataSet weights must be non-negative with a "
                "positive sum")
        self._weights = w / w.sum()
        self._seed = seed
        self._items_per_epoch = items_per_epoch
        self._transformers: List = []
        self._auto_epoch = 0
        sharded = {bool(getattr(d, "per_process_sharded",
                                lambda: False)())
                   for d in self._children}
        if len(sharded) > 1:
            raise ValueError(
                "MixedDataSet children must be uniformly sharded: mixing "
                "a per-process-sharded dataset with a replicated one "
                "would feed some corpora process_count times per epoch")
        self._sharded = sharded.pop()
        if self._sharded:
            nproc = max(int(getattr(d, "process_count", 1))
                        for d in self._children)
            for i, d in enumerate(self._children):
                if int(d.size()) < nproc:
                    # knowable now; exploding later means a ValueError
                    # mid-epoch on the one host whose shard is empty
                    # while the others run into a collective and wedge
                    raise ValueError(
                        f"MixedDataSet child {i} has {d.size()} "
                        f"sample(s) for {nproc} processes: some hosts' "
                        f"shards would be empty and the first draw of "
                        f"that child would fail mid-training")

    # ---- DataSet protocol ------------------------------------------------

    def size(self) -> int:
        """GLOBAL items per mixture epoch (matching the
        DistributedDataSet contract: size() is global, data() yields
        this process's share)."""
        if self._items_per_epoch is not None:
            return int(self._items_per_epoch)
        return sum(int(d.size()) for d in self._children)

    def _local_items(self) -> int:
        """Items THIS process's data() yields per epoch: the global
        count split evenly across processes when the children are
        per-process-sharded (each host serves only its shard's rows,
        so serving the global count would consume every sample
        process_count times per epoch).  Floor division keeps every
        host's count identical — batch formation stays lockstep."""
        n = self.size()
        if not self._sharded:
            return n
        nproc = max((int(getattr(d, "process_count", 1))
                     for d in self._children), default=1)
        return max(n // max(nproc, 1), 1)

    def per_process_sharded(self) -> bool:
        return self._sharded

    def seed(self) -> int:
        if self._seed is not None:
            return int(self._seed)
        from bigdl_tpu.utils.rng import get_seed
        return int(get_seed())

    def transform(self, transformer) -> "MixedDataSet":
        out = _copy.copy(self)
        out._transformers = self._transformers + [transformer]
        return out

    def __rshift__(self, transformer):
        return self.transform(transformer)

    # ---- checkpointable sampler state ------------------------------------

    def sampler_state(self) -> Dict:
        """The mixing sampler's configuration — with deterministic
        epoch-keyed draws the sampler's full dynamic state IS the
        PipelineState's ``(epoch, offset)``, so what must survive a
        restart is the configuration the choice sequence derives from."""
        return {"kind": "weighted_mixing",
                "seed": self.seed(),
                "weights": [float(x) for x in self._weights],
                "children": len(self._children)}

    def restore_sampler(self, state: Optional[Dict]) -> None:
        """Verify a saved sampler configuration matches this mixture —
        resume replays the choice sequence from ``(seed, epoch)``, and
        a changed seed/weight vector would replay a DIFFERENT sequence
        while claiming sample accuracy.  Raises on mismatch."""
        if not state:
            return
        if state.get("kind") != "weighted_mixing":
            raise ValueError(
                f"pipeline sampler state of kind {state.get('kind')!r} "
                f"cannot restore into a weighted MixedDataSet")
        if int(state.get("children", -1)) != len(self._children):
            raise ValueError(
                f"MixedDataSet restore: checkpoint mixed "
                f"{state.get('children')} corpora, this dataset mixes "
                f"{len(self._children)}")
        saved = np.asarray(state.get("weights", []), dtype=np.float64)
        if saved.shape != self._weights.shape or \
                not np.allclose(saved, self._weights, atol=1e-9):
            raise ValueError(
                "MixedDataSet restore: checkpoint weights "
                f"{saved.tolist()} != current {self._weights.tolist()}; "
                "resuming would replay a different choice sequence")
        if int(state.get("seed", -1)) != self.seed():
            raise ValueError(
                f"MixedDataSet restore: checkpoint sampler seed "
                f"{state.get('seed')} != current {self.seed()}")

    # ---- iteration -------------------------------------------------------

    def _choice_rng(self, epoch: int) -> np.random.Generator:
        ss = np.random.SeedSequence(
            [self.seed() % (2 ** 63), int(epoch), 0x6D6978])  # 'mix'
        return np.random.default_rng(ss)

    def _child_stream(self, idx: int, epoch: int, train: bool):
        """Child ``idx``'s endless stream: consecutive epoch-keyed
        passes, the pass key advancing on every wrap so each cycle of a
        small corpus reshuffles (deterministically)."""
        from bigdl_tpu.data.pipeline import epoch_iter
        wrap = 0
        while True:
            key = (int(epoch) << 20) ^ wrap
            it = iter(epoch_iter(self._children[idx], epoch=key,
                                 train=train))
            got = False
            for item in it:
                got = True
                yield item
            if not got:
                raise ValueError(
                    f"MixedDataSet child {idx} produced no items")
            wrap += 1

    def data(self, train: bool = True, epoch: Optional[int] = None) \
            -> Iterator:
        if epoch is None:
            epoch = self._auto_epoch
            if train:
                self._auto_epoch += 1
        epoch = int(epoch)
        k = len(self._children)
        n_items = self._local_items()

        def mix():
            rng = self._choice_rng(epoch)
            streams = [None] * k  # built lazily: a 0-weight child
            remaining = n_items   # never constructs its stream
            while remaining > 0:
                # choices drawn in fixed-size blocks: ~100x less host
                # RNG overhead per item than scalar choice() calls
                # (which would bill real time to data wait on large
                # epochs), still a pure function of (seed, epoch,
                # draw) because block boundaries depend only on the
                # draw index
                block = rng.choice(k, size=min(remaining, 1024),
                                   p=self._weights)
                for i in block:
                    i = int(i)
                    if streams[i] is None:
                        streams[i] = self._child_stream(i, epoch, train)
                    yield next(streams[i])
                remaining -= len(block)

        it: Iterator = mix()
        for t in self._transformers:
            it = t(it)
        return it
