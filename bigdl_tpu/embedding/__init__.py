"""Mesh-sharded embedding tables and the sparse-dense hybrid workload.

The recommender tier (ROADMAP item 3): row-sharded tables with
all_to_all lookup (:mod:`sharded_table`), one-step sparse+dense hybrid
training (:mod:`hybrid`), streaming resumable HitRatio/NDCG evaluation
(:mod:`eval`), and embedding-shard serving affinity (:mod:`serving`).
See docs/recommender.md.
"""

from bigdl_tpu.embedding.eval import StreamingRecEval
from bigdl_tpu.embedding.hybrid import (
    HybridPlanError, configure_hybrid, embedding_rules,
    hybrid_optim_methods, resolve_hybrid, sharded_tables,
)
from bigdl_tpu.embedding.serving import RecommenderScorer, shard_affinity_key
from bigdl_tpu.embedding.sharded_table import ShardedEmbeddingTable

__all__ = [
    "ShardedEmbeddingTable", "StreamingRecEval", "HybridPlanError",
    "configure_hybrid", "embedding_rules", "hybrid_optim_methods",
    "resolve_hybrid", "sharded_tables", "RecommenderScorer",
    "shard_affinity_key",
]
