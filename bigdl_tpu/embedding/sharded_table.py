"""Mesh-sharded embedding tables.

The reference's recommendation stack (nn/LookupTable.scala,
nn/LookupTableSparse.scala) keeps every table on one node; the
production shape — a (rows x dim) table too big for a single device's
HBM — is new TPU-first capability.  :class:`ShardedEmbeddingTable`
row-shards the table across a mesh axis (the batch axes ``data`` /
``fsdp`` from :mod:`bigdl_tpu.parallel.mesh`; shard s owns the
contiguous row block ``[s*rows/n, (s+1)*rows/n)``) and lowers lookup
with the :mod:`bigdl_tpu.nn.moe` dispatch pattern:

1. bucket each device's local ids by owning shard (position-in-bucket
   via cumsum, exact — per-destination capacity is the local id count,
   so nothing is ever dropped);
2. ``all_to_all`` the id buckets to their owning shards;
3. local gather on the owner (``dedup_gather`` — duplicate ids combine
   into one scatter row on the backward);
4. ``all_to_all`` the vectors back and un-bucket.

Every collective goes through :mod:`bigdl_tpu.telemetry.collectives`,
so lookup traffic lands in ``collective_bytes_total{op="all_to_all",
axis}`` like every other exchange.  Per-device bytes per lookup step:
``n*S*4`` for the id exchange plus ``n*S*dim*itemsize`` for the vector
exchange (S = local flattened ids) — the formula docs/recommender.md
pins and scripts/parallel_budget.json red-gates.

The BACKWARD stays sparse: the table enters the ``shard_map`` with
``P(axis)`` over rows, so its cotangent is the per-shard scatter-add
of the combined unique-id updates that flowed back through the
transposed all_to_all — never a dense (rows x dim) all-reduce (pinned
by the compiled-HLO test and the budget entry).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.core.module import Module, Parameter
from bigdl_tpu.nn.sparse import dedup_gather
from bigdl_tpu.parallel.mesh import shard_map_compat
from bigdl_tpu.telemetry import collectives as _coll
from bigdl_tpu.utils.rng import next_key

__all__ = ["ShardedEmbeddingTable", "LAST_LOOKUP_SHAPES"]

# Per-device (inside-shard_map) buffer shapes of the most recent a2a
# lookup trace — a debug/test hook (module attrs would pollute the
# pytree), mirroring nn.moe.LAST_A2A_SHAPES.
LAST_LOOKUP_SHAPES = {}


def _account_lookup(table_name: str, n_ids: int, ids=None) -> None:
    """Best-effort telemetry: never raises into the lookup it
    describes.  ``embedding_lookup_ids_total`` is accounted at trace
    time per compiled program (the collective-counter convention);
    ``embedding_unique_id_fraction`` needs concrete values so it is
    set only on eager (non-traced) lookups."""
    try:
        from bigdl_tpu import telemetry
        from bigdl_tpu.telemetry import families as _fam
        if not telemetry.enabled():
            return
        _fam.embedding_lookup_ids_total().labels(table_name).inc(
            float(n_ids))
        if ids is not None and not isinstance(ids, jax.core.Tracer):
            vals = np.asarray(ids).reshape(-1)
            if vals.size:
                frac = float(np.unique(vals).size) / float(vals.size)
                _fam.embedding_unique_id_fraction().labels(
                    table_name).set(frac)
    except Exception:  # pragma: no cover - accounting is best-effort
        pass


class ShardedEmbeddingTable(Module):
    """Row-sharded embedding lookup, 1-based ids like
    :class:`bigdl_tpu.nn.linear.LookupTable`.

    Without :meth:`set_mesh` the forward is the plain dense gather
    (bit-identical to ``LookupTable`` with default options) — the
    single-device baseline the loss-equivalence test trains against.
    With a mesh set, ``forward`` routes through the all_to_all lookup
    so the layer composes with the Optimizer, whose jitted step just
    calls ``model.forward`` (the ``nn.moe`` integration shape).
    """

    def __init__(self, n_index: int, n_output: int,
                 name: Optional[str] = None):
        super().__init__()
        self.n_index = int(n_index)
        self.n_output = int(n_output)
        if name is not None:
            self.set_name(name)
        self.weight = Parameter(jax.random.normal(
            next_key(), (self.n_index, self.n_output)))
        self.mesh = None
        self.axis = "data"

    def __deepcopy__(self, memo):
        # Module.clone() deepcopies; the Mesh holds Device handles that
        # cannot be pickled, and after hybrid training the weights are
        # device-committed arrays whose NamedSharding references the
        # same handles.  Both are immutable — share them by reference
        # so a sharded-trained model clones for eval/serving.
        import copy as _copy
        new = self.__class__.__new__(self.__class__)
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            if k in ("_params", "_static"):
                # _static holds the Mesh, _params the (possibly
                # device-committed) weight — shallow-copy the dicts,
                # share the immutable values
                new.__dict__[k] = dict(v)
            else:
                new.__dict__[k] = _copy.deepcopy(v, memo)
        return new

    # -- placement ---------------------------------------------------------

    @property
    def n_shards(self) -> int:
        return 1 if self.mesh is None else int(self.mesh.shape[self.axis])

    @property
    def rows_per_shard(self) -> int:
        return self.n_index // self.n_shards

    def set_mesh(self, mesh: Mesh, axis: str = "data") \
            -> "ShardedEmbeddingTable":
        """Route lookups through the a2a path, row-sharding the table
        over ``axis``.  Rejects layouts the lookup cannot honor with
        actionable errors (the ``_grad_sync_plan`` discipline)."""
        if axis not in mesh.axis_names:
            raise ValueError(
                f"ShardedEmbeddingTable {self.name!r}: axis {axis!r} is "
                f"not on the mesh (axes: {tuple(mesh.axis_names)}); "
                f"build the mesh with MeshConfig({axis}=N) or pick one "
                f"of its batch axes")
        n = int(mesh.shape[axis])
        if self.n_index % n != 0:
            raise ValueError(
                f"ShardedEmbeddingTable {self.name!r}: {self.n_index} "
                f"rows do not divide over {n} shards on axis {axis!r}; "
                f"pad n_index to a multiple of {n} (unused high rows "
                f"are harmless) or shard over a smaller axis")
        self.mesh = mesh
        self.axis = axis
        try:
            from bigdl_tpu import telemetry
            from bigdl_tpu.telemetry import families as _fam
            if telemetry.enabled():
                g = _fam.embedding_shard_rows()
                for s in range(n):
                    g.labels(self.name, str(s)).set(self.n_index // n)
        except Exception:  # pragma: no cover - accounting is best-effort
            pass
        return self

    def owner_of(self, ids) -> jnp.ndarray:
        """Shard that owns each (1-based) id under the contiguous-block
        layout — also the serving affinity key's input (shard id as the
        consistent-hash key, docs/recommender.md)."""
        idx0 = jnp.clip(jnp.asarray(ids).astype(jnp.int32) - 1, 0,
                        self.n_index - 1)
        return idx0 // self.rows_per_shard

    # -- lookup ------------------------------------------------------------

    def forward(self, ids):
        ids = jnp.asarray(ids).astype(jnp.int32)
        _account_lookup(self.name, ids.size, ids)
        if self.mesh is None:
            return self._dense_lookup(ids)
        return self._forward_a2a(ids, self.mesh, self.axis)

    def _dense_lookup(self, ids):
        idx = jnp.clip(ids - 1, 0, self.n_index - 1)
        return dedup_gather(self.weight, idx)

    def _forward_a2a(self, ids, mesh: Mesh, axis: str):
        n = int(mesh.shape[axis])
        lead = ids.shape
        flat = ids.reshape(-1)
        if flat.shape[0] % n != 0:
            raise ValueError(
                f"ShardedEmbeddingTable {self.name!r}: {flat.shape[0]} "
                f"ids do not shard over the {n}-way {axis!r} axis; pad "
                f"the batch so batch*ids_per_sample is a multiple of "
                f"{n}")
        rows_shard = self.n_index // n
        n_index = self.n_index

        def shard_fn(w_local, ids_loc):
            # ids_loc [S] 1-based local ids; w_local [rows/n, dim]
            idx0 = jnp.clip(ids_loc - 1, 0, n_index - 1)
            owner = idx0 // rows_shard                        # [S]
            onehot = (owner[:, None]
                      == jnp.arange(n)[None, :]).astype(jnp.int32)
            pos = jnp.sum((jnp.cumsum(onehot, axis=0) - 1) * onehot,
                          axis=1)                             # [S]
            # per-destination capacity = S: exact (no drops), the
            # worst case being every local id owned by one shard
            send = jnp.zeros((n, ids_loc.shape[0]), jnp.int32)
            send = send.at[owner, pos].set(idx0 + 1)          # 0 = empty
            recv = _coll.all_to_all(send, axis, split_axis=0,
                                    concat_axis=0, tiled=True)
            me = jax.lax.axis_index(axis)
            local = recv - 1 - me * rows_shard
            valid = (recv > 0) & (local >= 0) & (local < rows_shard)
            vecs = dedup_gather(w_local,
                                jnp.clip(local, 0, rows_shard - 1))
            vecs = vecs * valid[..., None].astype(vecs.dtype)
            back = _coll.all_to_all(vecs, axis, split_axis=0,
                                    concat_axis=0, tiled=True)
            LAST_LOOKUP_SHAPES.update(send=send.shape, recv=recv.shape,
                                      vecs=vecs.shape, back=back.shape)
            return back[owner, pos]                           # [S, dim]

        fn = shard_map_compat(
            shard_fn, mesh=mesh,
            in_specs=(P(axis), P(axis)), out_specs=P(axis))
        out = fn(self.weight, flat)
        return out.reshape(lead + (self.n_output,))
