"""Sparse-dense hybrid training plan.

One ``optimize()`` step trains row-sharded embedding tables (sparse,
per-shard scatter-add updates) and dp-replicated dense towers (flat
all-reduce) together.  The mechanics are nothing but sharding
annotations: :func:`embedding_rules` pins every
:class:`~bigdl_tpu.embedding.sharded_table.ShardedEmbeddingTable`
weight to ``P(axis)`` over rows and leaves every dense leaf
replicated, so GSPMD all-reduces the dense gradients over the batch
axis while the table gradients — already per-shard after the lookup's
transposed all_to_all — sync nothing at all.

Like ``Optimizer._grad_sync_plan``, :func:`resolve_hybrid` REJECTS
compositions the plan cannot honor with actionable errors instead of
silently compiling something else: no sharded table in the model,
tensor/pipeline/sequence/expert axes on the mesh, hierarchical grad
sync (which requires fully replicated params), rows not divisible by
the shard count.

Per-table optimizer state rides the existing per-submodule
OptimMethods split (``Optimizer.set_optim_methods``): every table gets
its OWN method instance — sparse tables routinely want a different
learning rate than the dense towers, and per-table slots (momentum,
Adam moments) must never alias between tables.
"""

from __future__ import annotations

import copy
from typing import Dict, Optional

from bigdl_tpu.embedding.sharded_table import ShardedEmbeddingTable
from bigdl_tpu.parallel.plan import PlanError

__all__ = ["HybridPlanError", "sharded_tables", "embedding_rules",
           "resolve_hybrid", "hybrid_optim_methods", "configure_hybrid"]


class HybridPlanError(PlanError):
    """A mesh/model composition the hybrid embedding plan cannot
    honor; the message says what to change.  A ``PlanError``: the
    partition planner (``parallel.plan.resolve``) surfaces these
    unchanged when a plan touches a model with sharded tables."""


def sharded_tables(model) -> Dict[str, ShardedEmbeddingTable]:
    """``{param-path prefix: table}`` for every ShardedEmbeddingTable
    in the tree.  Prefixes align with ``core.module.param_paths``
    (root module = empty prefix)."""
    out: Dict[str, ShardedEmbeddingTable] = {}
    for prefix, mod in model.named_modules():
        if isinstance(mod, ShardedEmbeddingTable):
            out["" if mod is model else prefix] = mod
    return out


def embedding_rules(model, axis: str = "data"):
    """ShardingRules placing every sharded table's weight ``P(axis)``
    over rows; everything unmatched stays replicated (pure dp)."""
    import re

    from jax.sharding import PartitionSpec as P
    from bigdl_tpu.parallel.sharding import ShardingRules

    def row_spec(shape, mesh):
        if axis not in mesh.axis_names:
            return P()
        if shape and shape[0] % mesh.shape[axis] == 0:
            return P(axis, *([None] * (len(shape) - 1)))
        return P()

    rules = []
    for prefix in sharded_tables(model):
        path = f"{prefix}.weight" if prefix else "weight"
        rules.append((f"^{re.escape(path)}$", row_spec))
    return ShardingRules(rules)


def resolve_hybrid(model, mesh, axis: str = "data",
                   hierarchical: bool = False) -> Dict:
    """Validate the (model, mesh) composition and return the plan:
    ``{"tables", "axis", "n_shards", "bytes_per_lookup"}``.  Raises
    :class:`HybridPlanError` with an actionable message otherwise."""
    tables = sharded_tables(model)
    if not tables:
        raise HybridPlanError(
            "hybrid embedding plan: the model has no "
            "ShardedEmbeddingTable — use Optimizer.set_mesh directly, "
            "or build the towers on bigdl_tpu.embedding tables "
            "(models/dlrm.py is the template)")
    if axis not in mesh.axis_names:
        raise HybridPlanError(
            f"hybrid embedding plan: shard axis {axis!r} is not on the "
            f"mesh (axes: {tuple(mesh.axis_names)}); build it with "
            f"MeshConfig({axis}=N)")
    bad = [a for a in ("model", "pipe", "seq", "expert")
           if a in mesh.axis_names and mesh.shape[a] > 1]
    if bad:
        raise HybridPlanError(
            f"hybrid embedding plan supports batch-parallel meshes "
            f"(data/fsdp/dcn) only; mesh has {bad} axes > 1 — drop "
            f"them, or train the tables unsharded under those "
            f"compositions")
    if hierarchical:
        raise HybridPlanError(
            "hybrid embedding plan: hierarchical gradient sync "
            "requires fully replicated parameters, but sharded "
            "embedding tables are row-sharded — call "
            "set_gradient_sync(hierarchical=False) or train the "
            "tables unsharded")
    n = int(mesh.shape[axis])
    for prefix, t in tables.items():
        if t.n_index % n != 0:
            raise HybridPlanError(
                f"hybrid embedding plan: table "
                f"{prefix or t.name!r} has {t.n_index} rows, not "
                f"divisible over {n} shards on axis {axis!r}; pad "
                f"n_index to a multiple of {n} (unused high rows are "
                f"harmless)")
    # per-device bytes one lookup step moves for S local flattened ids
    # (the formula docs/recommender.md documents; itemsize 4 = fp32)
    bytes_per_lookup = {
        prefix: f"n*S*4 ids + n*S*{t.n_output}*4 vectors (n={n})"
        for prefix, t in tables.items()}
    return {"tables": tables, "axis": axis, "n_shards": n,
            "bytes_per_lookup": bytes_per_lookup}


def hybrid_optim_methods(model, table_method, dense_method) -> Dict:
    """Per-submodule OptimMethods: every sharded table gets its own
    deep copy of ``table_method`` (per-table state never aliases) and
    every other top-level child its own copy of ``dense_method``."""
    from bigdl_tpu.core.module import Module, ModuleList
    if isinstance(model, ShardedEmbeddingTable):
        raise HybridPlanError(
            "hybrid_optim_methods: the model IS a single table; use "
            "set_optim_method directly")
    if model._params:
        raise HybridPlanError(
            "hybrid_optim_methods: the model owns direct parameters "
            f"({sorted(model._params)}); per-submodule methods cannot "
            "cover them — move them into a child module or call "
            "set_optim_methods yourself")

    def subtree_has_table(obj) -> bool:
        if isinstance(obj, ShardedEmbeddingTable):
            return True
        if isinstance(obj, Module):
            return any(subtree_has_table(m) for m in obj._modules.values())
        if isinstance(obj, ModuleList):
            return any(subtree_has_table(m) for m in obj._items)
        return False

    methods: Dict = {}
    for name, child in model._modules.items():
        if isinstance(child, ShardedEmbeddingTable):
            methods[name] = copy.deepcopy(table_method)
        elif subtree_has_table(child):
            raise HybridPlanError(
                f"hybrid_optim_methods: child {name!r} mixes a nested "
                f"sharded table with dense parameters; hoist tables to "
                f"top-level attributes (models/dlrm.py layout) or call "
                f"set_optim_methods with explicit keys")
        else:
            methods[name] = copy.deepcopy(dense_method)
    return methods


def configure_hybrid(optimizer, axes: Optional[Dict[str, int]] = None,
                     axis: str = "data", table_method=None,
                     dense_method=None) -> Dict:
    """One-call hybrid setup on an :class:`~bigdl_tpu.optim.Optimizer`,
    lowered through the partition planner: the requested axes become a
    :class:`~bigdl_tpu.parallel.plan.PartitionPlan` (table row-sharding
    is one of its rules) and ``optimizer.set_partition_plan`` validates
    the composition, points every table's lookup at the mesh, and
    installs the sharding rules.  When both methods are given the
    per-table OptimMethods split is installed too.  Returns the
    resolved hybrid plan dict."""
    from bigdl_tpu.parallel.plan import STRATEGIES, PartitionPlan

    if (table_method is None) != (dense_method is None):
        raise HybridPlanError(
            "configure_hybrid: pass BOTH table_method and dense_method "
            "(or neither, keeping the optimizer's current method)")
    axis_to_strategy = {v: k for k, v in STRATEGIES.items()}
    degrees = {}
    for ax, size in (axes or {axis: -1}).items():
        strat = axis_to_strategy.get(ax)
        if strat is None:
            raise HybridPlanError(
                f"configure_hybrid: unknown mesh axis {ax!r} (known: "
                f"{sorted(axis_to_strategy)})")
        degrees[strat] = size
    pplan = PartitionPlan(embedding_axis=axis, **degrees)
    optimizer.set_partition_plan(pplan)
    model = optimizer.model
    mesh = optimizer.partition_plan.mesh
    plan = resolve_hybrid(
        model, mesh, axis,
        hierarchical=getattr(optimizer, "grad_sync_hierarchical", False))
    if table_method is not None:
        optimizer.set_optim_methods(
            hybrid_optim_methods(model, table_method, dense_method))
    plan["mesh"] = mesh
    return plan
