"""Streaming, resumable recommender evaluation.

HitRatio@K / NDCG@K over the 1-positive + N-negatives protocol
(``optim.validation``: scores [batch, 1+neg], positive at column 0),
consumed as a STREAM: the evaluator scores one minibatch at a time and
folds each method's ``(numerator, denominator)`` halves into running
partial sums, so a 100M-user eval sweep never materializes the score
matrix and can stop/resume at any batch boundary.

Resume rides the data-pipeline sidecar (:mod:`bigdl_tpu.data.pipeline`):
the snapshot carries a ``PipelineState`` (seed / epoch / batch offset,
plus the mixing sampler's configuration when the source is a PR-5
``MixedDataSet``) next to the partial sums.  Restoring replays the
exact iterator the interrupted sweep was consuming — same permutation
seed, same mixture draws — and verifies the sampler configuration
before trusting the offset, exactly like ``Optimizer``'s training
resume.  The pinned invariant: interrupted-and-resumed results equal
the one-shot sweep.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.data.pipeline import (
    PipelineState, dataset_seed, epoch_iter, skip_batches,
)
from bigdl_tpu.optim.validation import HitRatio, NDCG, ValidationMethod

__all__ = ["StreamingRecEval", "EVAL_STATE_VERSION"]

EVAL_STATE_VERSION = 1


class StreamingRecEval:
    """Streaming HitRatio/NDCG evaluator over minibatches of
    [1+neg, 2] id rows (user, item; positive first).

    >>> ev = StreamingRecEval(model)
    >>> _, state = ev.evaluate(ds, max_batches=2)   # interrupted
    >>> results, _ = StreamingRecEval(model).evaluate(ds, state=state)
    """

    def __init__(self, model,
                 methods: Optional[Sequence[ValidationMethod]] = None,
                 batch_size: int = 32):
        from bigdl_tpu.embedding.hybrid import sharded_tables
        from bigdl_tpu.optim.predictor import jit_forward
        self.methods = list(methods) if methods is not None \
            else [HitRatio(10), NDCG(10)]
        self.batch_size = int(batch_size)
        self._model, self._fn = jit_forward(model)
        # score on the dense lookup: eval batches (including the final
        # partial one) need not divide over the training mesh
        for t in sharded_tables(self._model).values():
            t.mesh = None

    # -- scoring -----------------------------------------------------------

    def _score(self, feats) -> jnp.ndarray:
        out = self._fn(self._model, jnp.asarray(feats))
        if out.ndim and out.shape[-1] == 1:
            out = out[..., 0]
        return out

    def _wrap(self, dataset):
        """Accept a DataSet/MixedDataSet of minibatches as-is; wrap a
        raw [U, 1+neg, 2] array into a deterministic batched one."""
        # require a CALLABLE .data: np.ndarray.data is a memoryview
        if callable(getattr(dataset, "data", None)):
            return dataset
        from bigdl_tpu.dataset import SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import DataSet, Sample
        rows = np.asarray(dataset)
        samples = [Sample(rows[i].astype(np.int32), 1)
                   for i in range(rows.shape[0])]
        return (DataSet.array(samples, shuffle=False)
                .transform(SampleToMiniBatch(self.batch_size)))

    # -- the stream --------------------------------------------------------

    def evaluate(self, dataset, state: Optional[Dict] = None,
                 max_batches: Optional[int] = None):
        """Consume (the rest of) one eval epoch.  Returns
        ``(results, snapshot)`` — ``results`` is None when
        ``max_batches`` interrupted the sweep mid-stream, in which case
        ``snapshot`` resumes it."""
        dataset = self._wrap(dataset)
        sampler = (dataset.sampler_state()
                   if hasattr(dataset, "sampler_state") else None)
        if state is not None:
            if state.get("version") != EVAL_STATE_VERSION:
                raise ValueError(
                    f"unsupported eval-state version "
                    f"{state.get('version')!r} "
                    f"(supported: {EVAL_STATE_VERSION})")
            fmts = [m.fmt for m in self.methods]
            if state.get("methods") != fmts:
                raise ValueError(
                    f"eval state was written for {state.get('methods')} "
                    f"but this evaluator computes {fmts}; resume with "
                    f"the same method list")
            ps = PipelineState.restore(state["pipeline"])
            if ps.sampler is not None and sampler is not None \
                    and ps.sampler != sampler:
                raise ValueError(
                    "eval state was written against a different mixing "
                    "configuration; resume over the same MixedDataSet "
                    "(weights/seed/children) it snapshotted")
            partials: List[Tuple[float, float]] = [
                (float(n), float(d)) for n, d in state["partials"]]
        else:
            ps = PipelineState(seed=dataset_seed(dataset), epoch=1,
                               offset=0, sampler=sampler)
            partials = [(0.0, 0.0) for _ in self.methods]

        it = epoch_iter(dataset, ps.epoch, train=False)
        if ps.offset:
            skipped = skip_batches(it, ps.offset)
            if skipped < ps.offset:
                raise ValueError(
                    f"eval state recorded {ps.offset} consumed batches "
                    f"but the epoch only has {skipped}; the dataset "
                    f"shrank since the snapshot — restart the sweep")
        consumed = 0
        for batch in it:
            scores = self._score(batch.get_input())
            partials = [
                (n + float(num), d + float(den))
                for (n, d), (num, den) in zip(
                    partials,
                    (m.batch_stats(scores) for m in self.methods))]
            ps.offset += 1
            consumed += 1
            if max_batches is not None and consumed >= max_batches:
                return None, self._snapshot(ps, partials)
        results = [m.to_result(n, d)
                   for m, (n, d) in zip(self.methods, partials)]
        return results, self._snapshot(ps, partials)

    def _snapshot(self, ps: PipelineState,
                  partials: List[Tuple[float, float]]) -> Dict:
        return {"version": EVAL_STATE_VERSION,
                "pipeline": ps.snapshot(),
                "partials": [[n, d] for n, d in partials],
                "methods": [m.fmt for m in self.methods]}
