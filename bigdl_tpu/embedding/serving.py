"""Embedding-aware serving: shard affinity + a scoring replica target.

Two pieces wire the recommender into the PR-13 serving fabric:

* :func:`shard_affinity_key` — a router ``session`` key derived from
  the EMBEDDING SHARD that owns a request's user id (contiguous-block
  layout, same math as ``ShardedEmbeddingTable.owner_of``).  The
  router's consistent-hash ring then pins every request touching one
  shard's rows to the same replica — the replica whose lookup cache /
  pinned host rows stay warm for exactly those users — without the
  router learning anything about embeddings: the shard id is just a
  session key.

* :class:`RecommenderScorer` — adapts a one-shot scoring
  ``ModelServer`` (dynamic batcher, admission, SLO metrics — the
  existing machinery, untouched) to the ``submit_generate_async``
  protocol :class:`~bigdl_tpu.serving.replica.Replica` speaks, so a
  wide-and-deep/NeuralCF model serves scored requests through the
  Router end-to-end.  ``prompt`` carries the [2] (user, item) id row
  (or a [1+neg, 2] ranking slate); ``max_new_tokens`` is ignored — a
  score is one forward, not a decode loop.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["shard_affinity_key", "RecommenderScorer"]


def shard_affinity_key(user_id: int, n_rows: int, n_shards: int,
                       model: str = "default",
                       table: str = "user") -> str:
    """Router session key for the shard owning ``user_id`` (1-based)
    under the contiguous row-block layout ``ShardedEmbeddingTable``
    uses.  All sessions touching one shard hash to one home replica."""
    n_shards = max(1, int(n_shards))
    rows_per_shard = max(1, int(n_rows) // n_shards)
    idx0 = min(max(int(user_id) - 1, 0), int(n_rows) - 1)
    shard = min(idx0 // rows_per_shard, n_shards - 1)
    return f"emb-{model}-{table}-s{shard}"


class RecommenderScorer:
    """Replica-target adapter over a one-shot scoring ModelServer.

    >>> rep = Replica(0, RecommenderScorer(model), snapshot_dir=d)
    >>> fut = router.submit_generate_async(
    ...     np.asarray([user, item], np.int32), 1,
    ...     session=shard_affinity_key(user, rows, shards))
    >>> score = fut.result()
    """

    def __init__(self, model, max_batch: int = 32, **server_kwargs):
        from bigdl_tpu.embedding.hybrid import sharded_tables
        from bigdl_tpu.serving.server import ModelServer
        # score on the DENSE lookup: a replica holds the full tables
        # and a 1-row request cannot ride the 8-way training a2a; the
        # shard-affinity key routes for cache warmth, not for sharding
        model = model.clone()
        for t in sharded_tables(model).values():
            t.mesh = None
        self._server = ModelServer(backend=model, max_batch=max_batch,
                                   **server_kwargs)

    def warmup(self, example_sample) -> "RecommenderScorer":
        self._server.warmup(example_sample)
        return self

    # ---- the Replica target protocol -----------------------------------

    def submit_generate_async(self, prompt, max_new_tokens: int = 0,
                              eos_id=None, on_token=None,
                              timeout: Optional[float] = None):
        # a scored request is one forward: the "prompt" is the id row,
        # the "generation" is its score
        return self._server.submit_async(
            np.asarray(prompt, np.int32), timeout=timeout)

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        self._server.shutdown(drain=drain, timeout=timeout)

    # ---- health/stats delegation (router drain + load accounting) ------

    def admitted_outstanding(self) -> int:
        return self._server.admitted_outstanding()

    def queue_depth(self) -> int:
        return self._server.queue_depth()

    def stats(self):
        return {"slots": self._server.max_batch,
                "queue_depth": self._server.queue_depth()}
