"""Core module protocol for the bigdl-tpu framework.

This replaces the reference's Torch-style ``AbstractModule`` hierarchy
(reference: spark/dl/src/main/scala/com/intel/analytics/bigdl/nn/abstractnn/AbstractModule.scala:59)
with a TPU/JAX-native design:

* A :class:`Module` is a *mutable* Python object for ergonomic, Torch-style
  model construction (``self.weight = Parameter(...)``, ``m.forward(x)``),
  but every Module class is registered as a JAX **pytree**.  A jitted step
  function receives the model as an argument, freely mutates the traced
  copy (e.g. BatchNorm running stats), and returns the updated model —
  imperative inside the trace, purely functional at jit boundaries.

* ``forward``/``__call__`` compute the output (reference ``updateOutput``,
  AbstractModule.scala:329).  There is no hand-written backward: gradients
  come from ``jax.grad`` over the params partition.  A convenience
  :meth:`Module.backward` mirroring AbstractModule.scala:305 is provided
  via ``jax.vjp`` for API parity and testing.

* Leaves are classified as *parameters* (trainable, created with
  :class:`Parameter`) or *buffers* (non-trainable state, e.g. BN running
  mean; any bare array assignment).  ``partition()/combine()`` split a
  module into a params-only tree and a remainder so optimizers can
  differentiate w.r.t. parameters only (reference ``parameters()``,
  AbstractModule.scala:370).

* ``get_parameters()`` returns the flattened compact (weights, unravel)
  view mirroring ``getParameters()`` (AbstractModule.scala:390).
"""

from __future__ import annotations

import copy as _copy
import threading
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Parameter",
    "Module",
    "ModuleList",
    "partition",
    "combine",
    "tree_map_params",
    "forward_context",
    "next_rng_key",
    "has_rng",
    "current_context",
]


class Parameter:
    """Marker wrapper: ``self.weight = Parameter(array)`` registers a
    trainable leaf.  The wrapper is unwrapped on assignment; modules store
    raw ``jax.Array``s."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = jnp.asarray(value)


def _is_array(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray, jnp.ndarray))


# --------------------------------------------------------------------------
# Forward context: carries RNG + mode through Torch-style forward() calls
# without changing their signatures.  Runs at trace time, so the key is a
# (possibly traced) JAX PRNG key split functionally with a Python counter.
# --------------------------------------------------------------------------

class _ForwardContext(threading.local):
    def __init__(self):
        self.key = None
        self._count = 0


_ctx = _ForwardContext()


@contextmanager
def forward_context(rng=None):
    """Provide an RNG key for stochastic layers (Dropout, RReLU, sampling)
    during the enclosed ``forward`` calls."""
    prev_key, prev_count = _ctx.key, _ctx._count
    _ctx.key = rng
    _ctx._count = 0
    try:
        yield
    finally:
        _ctx.key, _ctx._count = prev_key, prev_count


def has_rng() -> bool:
    return _ctx.key is not None


def _in_active_trace() -> bool:
    try:
        from jax._src import core as _core
        return not _core.trace_state_clean()
    except Exception:
        return False


def next_rng_key():
    """Split a fresh key off the ambient forward context.

    The forward_context MUST be opened *inside* the jitted function (with
    the key passed as a traced argument); a context opened outside jit
    would bake the key into the compiled program as a constant.
    """
    if _ctx.key is None:
        raise RuntimeError(
            "No RNG in scope: wrap the forward call in "
            "`with forward_context(rng=key):` (training mode stochastic "
            "layers need randomness)."
        )
    if _in_active_trace() and not isinstance(_ctx.key, jax.core.Tracer):
        raise RuntimeError(
            "forward_context was opened OUTSIDE the jitted function: the "
            "RNG key would be baked into the compiled trace as a constant "
            "and every call would reuse the same randomness. Pass the key "
            "into the jitted function and open forward_context inside it."
        )
    _ctx._count += 1
    return jax.random.fold_in(_ctx.key, _ctx._count)


def current_context():
    return _ctx


# --------------------------------------------------------------------------
# Module
# --------------------------------------------------------------------------

class _Sentinel:
    """Placeholder stored in __dict__ for attrs living in the classified
    dicts.  Deepcopy/pickle-stable singleton so `is _SENTINEL` survives
    Module.clone() and serialization."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __copy__(self):
        return self

    def __deepcopy__(self, memo):
        return self

    def __reduce__(self):
        return (_Sentinel, ())


_SENTINEL = _Sentinel()


class Module:
    """Base class of every layer/container (reference AbstractModule.scala:59).

    Subclasses are automatically registered as pytrees.  Dynamic leaves are
    (in order): parameters, buffers, submodules.  Everything else set on the
    instance is static aux data and must be hashable-equatable (ints,
    floats, strings, tuples, callables).
    """

    # -- construction ------------------------------------------------------

    def __init__(self):
        # use object.__setattr__ to avoid classification of bookkeeping
        object.__setattr__(self, "_params", {})     # name -> array
        object.__setattr__(self, "_buffers", {})    # name -> array
        object.__setattr__(self, "_modules", {})    # name -> Module|ModuleList
        object.__setattr__(self, "_static", {})     # name -> hashable
        object.__setattr__(self, "training", True)
        object.__setattr__(self, "name", self.__class__.__name__)

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        jax.tree_util.register_pytree_with_keys(
            cls, cls._tree_flatten_with_keys, cls._tree_unflatten,
            flatten_func=cls._tree_flatten)

    # -- attribute classification -----------------------------------------

    def __setattr__(self, name, value):
        if name in ("training", "name"):
            object.__setattr__(self, name, value)
            return
        # Remove from previous slot ONLY if re-assigned with a different
        # kind.  Same-kind re-assignment updates in place: dict order is
        # pytree STRUCTURE, so a pop-and-reinsert would make the tree
        # definition depend on which forward path assigned a buffer
        # last (e.g. MoE.aux_loss/drop_rate) — a jit cache-miss-or-error
        # class of bug.
        if isinstance(value, Parameter):
            target, stored = self._params, value.value
        elif _is_array(value):
            target, stored = self._buffers, jnp.asarray(value)
        elif isinstance(value, (Module, ModuleList)):
            target, stored = self._modules, value
        elif isinstance(value, (list, tuple)) and value and \
                all(isinstance(v, Module) for v in value):
            target, stored = self._modules, ModuleList(list(value))
        else:
            if isinstance(value, list):
                # static aux must be hashable for jit caching
                value = tuple(value)
            target, stored = self._static, value
        for d in (self._params, self._buffers, self._modules, self._static):
            if d is not target:
                d.pop(name, None)
        target[name] = stored
        object.__setattr__(self, name, _SENTINEL)

    def __getattribute__(self, name):
        v = object.__getattribute__(self, name)
        if v is _SENTINEL:
            for dn in ("_params", "_buffers", "_modules", "_static"):
                d = object.__getattribute__(self, dn)
                if name in d:
                    return d[name]
            raise AttributeError(name)
        return v

    # -- pytree protocol ---------------------------------------------------

    def _tree_flatten(self):
        children, _ = self._tree_flatten_with_keys()
        return [c for _, c in children], self._aux()

    def _tree_flatten_with_keys(self):
        children = []
        for n in self._params:
            children.append((jax.tree_util.GetAttrKey(n), self._params[n]))
        for n in self._buffers:
            children.append((jax.tree_util.GetAttrKey(n), self._buffers[n]))
        for n in self._modules:
            children.append((jax.tree_util.GetAttrKey(n), self._modules[n]))
        return children, self._aux()

    def _aux(self):
        return (
            tuple(self._params.keys()),
            tuple(self._buffers.keys()),
            tuple(self._modules.keys()),
            tuple(sorted(self._static.items(), key=lambda kv: kv[0])),
            self.training,
            self.name,
        )

    @classmethod
    def _tree_unflatten(cls, aux, children):
        pnames, bnames, mnames, static_items, training, name = aux
        obj = object.__new__(cls)
        object.__setattr__(obj, "_params", {})
        object.__setattr__(obj, "_buffers", {})
        object.__setattr__(obj, "_modules", {})
        object.__setattr__(obj, "_static", dict(static_items))
        object.__setattr__(obj, "training", training)
        object.__setattr__(obj, "name", name)
        it = iter(children)
        for n in pnames:
            obj._params[n] = next(it)
        for n in bnames:
            obj._buffers[n] = next(it)
        for n in mnames:
            obj._modules[n] = next(it)
        for n in (list(obj._params) + list(obj._buffers)
                  + list(obj._modules) + list(obj._static)):
            object.__setattr__(obj, n, _SENTINEL)
        return obj

    # -- forward / backward ------------------------------------------------

    def forward(self, *inputs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        # Graph-building DSL (reference nn/Graph.scala `inputs()`):
        # calling a module on Node objects creates a new graph Node
        # instead of executing forward.
        if inputs and not kwargs:
            from bigdl_tpu.nn.containers import Node, node_of
            if all(isinstance(i, Node) for i in inputs):
                return node_of(self, *inputs)
        return self.forward(*inputs, **kwargs)

    def backward(self, input, grad_output):
        """API-parity helper (reference AbstractModule.scala:305): returns
        grad_input via jax.vjp.  Training uses jax.grad over params instead.

        Runs the vjp on a functional copy of the module so buffer mutations
        inside forward can't leak tracers into this live instance."""
        leaves, treedef = jax.tree_util.tree_flatten(self)

        def pure_forward(x, leaves):
            m = jax.tree_util.tree_unflatten(treedef, leaves)
            return m.forward(x)

        y, vjp = jax.vjp(pure_forward, input, leaves)
        gi, _ = vjp(grad_output)
        return gi

    # -- mode --------------------------------------------------------------

    def train_mode(self, flag: bool = True) -> "Module":
        """Set training mode recursively (reference ``training()``)."""
        self.training = flag
        for m in self.modules():
            m.train_mode(flag)
        return self

    def eval_mode(self) -> "Module":
        """Set evaluation mode recursively (reference ``evaluate()``)."""
        return self.train_mode(False)

    def is_training(self) -> bool:
        return self.training

    # -- traversal ---------------------------------------------------------

    def _named_children(self) -> List[Tuple[str, "Module"]]:
        """(key, submodule) pairs with nested ModuleLists flattened to
        ``name[i]``/``name[i][j]`` keys."""
        out = []

        def expand(key, v):
            if isinstance(v, ModuleList):
                for i, item in enumerate(v._items):
                    expand(f"{key}[{i}]", item)
            else:
                out.append((key, v))

        for n, v in self._modules.items():
            expand(n, v)
        return out

    def modules(self) -> List["Module"]:
        return [m for _, m in self._named_children()]

    def named_modules(self, prefix: str = "") -> List[Tuple[str, "Module"]]:
        res = [(prefix or self.name, self)]
        for n, v in self._named_children():
            res.extend(v.named_modules(f"{prefix}.{n}" if prefix else n))
        return res

    def apply_to_modules(self, fn: Callable[["Module"], None]) -> "Module":
        fn(self)
        for m in self.modules():
            m.apply_to_modules(fn)
        return self

    def set_name(self, name: str) -> "Module":
        self.name = name
        return self

    def get_name(self) -> str:
        return self.name

    # -- parameters --------------------------------------------------------

    def parameters(self) -> Dict[str, Any]:
        """Nested dict of trainable parameters (reference parameters():370)."""
        out = dict(self._params)
        for n, v in self._named_children():
            sub = v.parameters()
            if sub:
                out[n] = sub
        return out

    def buffers(self) -> Dict[str, Any]:
        out = dict(self._buffers)
        for n, v in self._named_children():
            sub = v.buffers()
            if sub:
                out[n] = sub
        return out

    def get_parameters(self):
        """Compact flat view: (flat_weights, unravel_fn).  Mirrors
        ``getParameters()`` (AbstractModule.scala:390) which flattens all
        trainable weights into one contiguous tensor."""
        from jax.flatten_util import ravel_pytree
        flat, unravel = ravel_pytree(self.parameters())
        return flat, unravel

    def n_parameters(self) -> int:
        return sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(self.parameters()))

    def load_parameters(self, params) -> "Module":
        """Set trainable parameters from a nested dict of the same
        structure as :meth:`parameters` (in place)."""
        for n in self._params:
            if n in params:
                self._params[n] = jnp.asarray(params[n])
        for n, v in self._named_children():
            if n in params:
                v.load_parameters(params[n])
        return self

    def load_buffers(self, buffers) -> "Module":
        """Set buffers (e.g. BN running stats) from a nested dict of the
        same structure as :meth:`buffers` (in place)."""
        for n in self._buffers:
            if n in buffers:
                self._buffers[n] = jnp.asarray(buffers[n])
        for n, v in self._named_children():
            if n in buffers:
                v.load_buffers(buffers[n])
        return self

    # -- freezing / lr scale (reference freeze/unfreeze, scaleW/scaleB) ----

    def freeze(self, *names: str) -> "Module":
        """Mark this module (or named descendants) as non-trainable:
        their params are excluded from the grad partition
        (reference AbstractModule.freeze)."""
        if names:
            wanted = set(names)
            for nm, m in self.named_modules():
                if m.name in wanted or nm in wanted:
                    m.apply_to_modules(
                        lambda mm: mm._static.__setitem__("_frozen", True))
        else:
            self.apply_to_modules(lambda m: m._static.__setitem__("_frozen", True))
        return self

    def unfreeze(self) -> "Module":
        self.apply_to_modules(lambda m: m._static.__setitem__("_frozen", False))
        return self

    def is_frozen(self) -> bool:
        return bool(self._static.get("_frozen", False))

    # -- per-layer regularizers + gradient lr-scaling ----------------------
    # (≙ layer wRegularizer/bRegularizer ctor args, nn/Linear.scala:48 +
    # AbstractModule.setScaleW/setScaleB; applied by the Optimizer's step
    # as pure per-leaf transforms — see optim/regularizer.py)

    _KEEP_REGULARIZER = ("__keep__",)

    def set_regularizers(self, w_regularizer=_KEEP_REGULARIZER,
                         b_regularizer=_KEEP_REGULARIZER) -> "Module":
        """Attach L1/L2/L1L2 regularizers to THIS module's own params:
        ``w_regularizer`` covers params whose name does not contain
        "bias", ``b_regularizer`` the rest.  Writes the SAME static
        slots as the layer constructor args (e.g. nn.Linear(...,
        w_regularizer=...)), so either spelling reaches the optimizer.
        Only the arguments you pass are changed — setting one slot
        never wipes the other; pass ``None`` explicitly to clear."""
        if w_regularizer is not Module._KEEP_REGULARIZER:
            self.w_regularizer = w_regularizer
        if b_regularizer is not Module._KEEP_REGULARIZER:
            self.b_regularizer = b_regularizer
        return self

    def set_scale_w(self, scale: float) -> "Module":
        """Gradient scale for weight-like params, propagated to all
        submodules (≙ AbstractModule.setScaleW; Container propagates)."""
        self.apply_to_modules(
            lambda m: m._static.__setitem__("_scale_w", float(scale)))
        return self

    def set_scale_b(self, scale: float) -> "Module":
        """Gradient scale for bias params, propagated to all submodules
        (≙ AbstractModule.setScaleB)."""
        self.apply_to_modules(
            lambda m: m._static.__setitem__("_scale_b", float(scale)))
        return self

    # -- misc --------------------------------------------------------------

    def __deepcopy__(self, memo):
        # _static values are contractually hashable-immutable, and some
        # hold a Mesh (set_pipeline_parallel / ring attention) whose
        # Device handles cannot be pickled — share them by reference and
        # copy everything else, so a mesh-armed model still clone()s.
        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            if k == "_static":
                new.__dict__[k] = dict(v)
            else:
                new.__dict__[k] = _copy.deepcopy(v, memo)
        return new

    def clone(self) -> "Module":
        return _copy.deepcopy(self)

    # -- inference entry points (≙ AbstractModule.predict:660 /
    #    evaluate:890; delegate to the optim runtime) ---------------------

    def predict(self, data, batch_size: int = 32):
        from bigdl_tpu.optim.predictor import Predictor
        return Predictor(self, batch_size).predict(data)

    def predict_class(self, data, batch_size: int = 32):
        from bigdl_tpu.optim.predictor import Predictor
        return Predictor(self, batch_size).predict_class(data)

    def evaluate(self, data, methods, batch_size: int = 32):
        from bigdl_tpu.optim.predictor import Evaluator
        return Evaluator(self, batch_size).evaluate(data, methods)

    # -- persistence (≙ AbstractModule.saveModule / Module.loadModule) ----

    def save(self, path: str) -> "Module":
        from bigdl_tpu.utils.serializer import save_module
        save_module(self, path)
        return self

    @staticmethod
    def load(path: str) -> "Module":
        from bigdl_tpu.utils.serializer import load_module
        return load_module(path)

    def save_weights(self, path: str) -> "Module":
        from bigdl_tpu.utils.serializer import save_weights
        save_weights(self, path)
        return self

    def load_weights(self, path: str, strict: bool = True) -> "Module":
        from bigdl_tpu.utils.serializer import load_weights
        return load_weights(self, path, strict=strict)

    def quantize(self) -> "Module":
        """Int8 inference copy (≙ AbstractModule.quantize:954)."""
        from bigdl_tpu.nn.quantized import Quantizer
        return Quantizer.quantize(self)

    def __repr__(self):
        parts = []
        for n, p in self._params.items():
            # p can be None on a partition()'d half — repr must never
            # throw (error messages embed it)
            parts.append(
                f"{n}:{tuple(p.shape) if hasattr(p, 'shape') else p!r}")
        inner = ", ".join(parts)
        subs = "".join(
            "\n  " + repr(m).replace("\n", "\n  ") for m in self.modules())
        return f"{self.__class__.__name__}({inner}){subs}"


class ModuleList:
    """Container for a homogeneous list of submodules (registered pytree)."""

    def __init__(self, items: Sequence[Module] = ()):
        self._items: List[Module] = list(items)

    def append(self, m: Module) -> "ModuleList":
        self._items.append(m)
        return self

    def __iter__(self):
        return iter(self._items)

    def __len__(self):
        return len(self._items)

    def __getitem__(self, i):
        return self._items[i]


jax.tree_util.register_pytree_with_keys(
    ModuleList,
    lambda ml: ([(jax.tree_util.SequenceKey(i), m)
                 for i, m in enumerate(ml._items)], len(ml._items)),
    lambda n, children: ModuleList(list(children)),
    flatten_func=lambda ml: (list(ml._items), len(ml._items)),
)


# --------------------------------------------------------------------------
# partition / combine — equinox-style filtering so optimizers can grad
# w.r.t. trainable parameters only.
# --------------------------------------------------------------------------

def partition(mod: Module):
    """Split a module into ``(params, remainder)`` — two same-structure
    pytrees with ``None`` at complementary leaves; frozen modules' params
    stay in the remainder.  ``combine(params, remainder)`` restores."""
    leaves_p, leaves_r = [], []
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(mod)
    # Determine param-ness per leaf by re-walking the module structure.
    flags = _param_flags(mod)
    assert len(flags) == len(paths_leaves)
    for (path, leaf), is_p in zip(paths_leaves, flags):
        if is_p:
            leaves_p.append(leaf)
            leaves_r.append(None)
        else:
            leaves_p.append(None)
            leaves_r.append(leaf)
    return (jax.tree_util.tree_unflatten(treedef, leaves_p),
            jax.tree_util.tree_unflatten(treedef, leaves_r))


def _param_flags(obj) -> List[bool]:
    """Per-flattened-leaf flags: True if the leaf is a trainable param
    of a non-frozen module."""
    flags: List[bool] = []
    if isinstance(obj, Module):
        frozen = obj.is_frozen()
        for n in obj._params:
            flags.append(not frozen)
        for n in obj._buffers:
            flags.append(False)
        for n in obj._modules:
            flags.extend(_param_flags(obj._modules[n]))
    elif isinstance(obj, ModuleList):
        for m in obj._items:
            flags.extend(_param_flags(m))
    else:
        # generic pytree (tuple/list/dict of the above or raw leaves)
        children = jax.tree_util.tree_leaves(
            obj, is_leaf=lambda x: isinstance(x, (Module, ModuleList))
            and x is not obj)
        for c in children:
            if isinstance(c, (Module, ModuleList)):
                flags.extend(_param_flags(c))
            else:
                flags.append(False)
    return flags


def param_paths(mod: Module) -> List[str]:
    """Dotted paths of trainable params, aligned with the flattened leaf
    order of ``partition(mod)[0]`` (frozen modules excluded)."""
    paths: List[str] = []

    def rec(obj, prefix):
        if isinstance(obj, Module):
            if not obj.is_frozen():
                for n in obj._params:
                    paths.append(f"{prefix}.{n}" if prefix else n)
            for n in obj._modules:
                rec(obj._modules[n], f"{prefix}.{n}" if prefix else n)
        elif isinstance(obj, ModuleList):
            for i, m in enumerate(obj._items):
                rec(m, f"{prefix}[{i}]")

    rec(mod, "")
    return paths


def combine(a, b):
    """Merge two same-structure trees, taking the non-None leaf."""
    return jax.tree_util.tree_map(
        lambda x, y: x if x is not None else y, a, b,
        is_leaf=lambda x: x is None)


def cast_floating(tree, dtype):
    """Cast every floating-point array leaf to dtype (mixed-precision
    helper: bf16 compute ≙ the reference's FP16 wire compression,
    parameters/FP16CompressedTensor.scala — but end-to-end)."""
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, tree)


def tree_map_params(fn: Callable, mod: Module) -> Module:
    """Apply fn to every trainable param leaf, returning a new module."""
    params, rest = partition(mod)
    params = jax.tree_util.tree_map(fn, params)
    return combine(params, rest)
