"""Weight initialization methods.

Reference: spark/dl/.../nn/InitializationMethod.scala (Zeros, Ones, Const,
RandomUniform, RandomNormal, Xavier, MsraFiller, BilinearFiller) and the
Initializable protocol (nn/abstractnn/Initializable.scala:48).

Each method is a callable ``(key, shape, dtype, fan_in=None, fan_out=None)
-> jax.Array``.  Fans default to the Torch/BigDL convention: for a 2-D
weight (out, in) fan_in = shape[1]; for conv kernels (out_c, in_c, kh, kw)
fan_in = in_c*kh*kw.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "Zeros", "Ones", "ConstInitMethod", "RandomUniform", "RandomNormal",
    "Xavier", "MsraFiller", "BilinearFiller", "Bilinear", "calc_fans",
]


def calc_fans(shape: Tuple[int, ...]) -> Tuple[int, int]:
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[1], shape[0]
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    return shape[1] * receptive, shape[0] * receptive


class InitMethod:
    def __call__(self, key, shape, dtype=jnp.float32,
                 fan_in: Optional[int] = None, fan_out: Optional[int] = None):
        raise NotImplementedError


class _Zeros(InitMethod):
    def __call__(self, key, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
        return jnp.zeros(shape, dtype)


class _Ones(InitMethod):
    def __call__(self, key, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
        return jnp.ones(shape, dtype)


class ConstInitMethod(InitMethod):
    def __init__(self, value: float):
        self.value = value

    def __call__(self, key, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
        return jnp.full(shape, self.value, dtype)


class RandomUniform(InitMethod):
    """U(lower, upper); with no bounds, U(-1/sqrt(fan_in), 1/sqrt(fan_in))
    (the Torch default used throughout the reference layer zoo)."""

    def __init__(self, lower: Optional[float] = None,
                 upper: Optional[float] = None):
        if (lower is None) != (upper is None):
            raise ValueError(
                "RandomUniform needs both bounds or neither "
                f"(got lower={lower}, upper={upper})")
        self.lower, self.upper = lower, upper

    def __call__(self, key, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
        if self.lower is None:
            fi, _ = calc_fans(shape) if fan_in is None else (fan_in, None)
            bound = 1.0 / math.sqrt(max(fi, 1))
            lo, hi = -bound, bound
        else:
            lo, hi = self.lower, self.upper
        return jax.random.uniform(key, shape, dtype, minval=lo, maxval=hi)


class RandomNormal(InitMethod):
    def __init__(self, mean: float = 0.0, stdv: float = 1.0):
        self.mean, self.stdv = mean, stdv

    def __call__(self, key, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
        return self.mean + self.stdv * jax.random.normal(key, shape, dtype)


class _Xavier(InitMethod):
    """Glorot uniform: U(-sqrt(6/(fan_in+fan_out)), +...)."""

    def __call__(self, key, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
        fi, fo = calc_fans(shape)
        fi = fan_in if fan_in is not None else fi
        fo = fan_out if fan_out is not None else fo
        bound = math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


class MsraFiller(InitMethod):
    """Kaiming/He normal: N(0, sqrt(2/fan)) (reference MsraFiller)."""

    def __init__(self, variance_norm_average: bool = True):
        self.average = variance_norm_average

    def __call__(self, key, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
        fi, fo = calc_fans(shape)
        fi = fan_in if fan_in is not None else fi
        fo = fan_out if fan_out is not None else fo
        # non-average mode uses fan_out, matching the reference MsraFiller
        # (InitializationMethod.scala:322-326)
        n = (fi + fo) / 2.0 if self.average else fo
        std = math.sqrt(2.0 / max(n, 1))
        return std * jax.random.normal(key, shape, dtype)


class BilinearFiller(InitMethod):
    """Bilinear upsampling kernel init for transposed conv
    (reference BilinearFiller; used by segmentation decoders)."""

    def __call__(self, key, shape, dtype=jnp.float32, fan_in=None, fan_out=None):
        assert len(shape) == 4, "BilinearFiller expects (out, in, kh, kw)"
        kh, kw = shape[2], shape[3]
        f_h, f_w = math.ceil(kh / 2.0), math.ceil(kw / 2.0)
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        ys = jnp.arange(kh)
        xs = jnp.arange(kw)
        wy = 1.0 - jnp.abs(ys / f_h - c_h)
        wx = 1.0 - jnp.abs(xs / f_w - c_w)
        kernel = jnp.outer(wy, wx).astype(dtype)
        return jnp.broadcast_to(kernel, shape).astype(dtype)


Zeros = _Zeros()
Ones = _Ones()
Xavier = _Xavier()
Bilinear = BilinearFiller()
