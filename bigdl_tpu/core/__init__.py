from bigdl_tpu.core.module import (
    Module, ModuleList, Parameter, partition, combine, tree_map_params,
    forward_context, next_rng_key, has_rng,
)
from bigdl_tpu.core import init
