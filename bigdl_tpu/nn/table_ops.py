"""Table (tuple-activity) arithmetic and combination layers.

Reference: nn/CAddTable.scala, nn/CSubTable.scala, nn/CMulTable.scala,
nn/CDivTable.scala, nn/CMaxTable.scala, nn/CMinTable.scala,
nn/CAveTable.scala, nn/JoinTable.scala, nn/SplitTable.scala,
nn/SelectTable.scala, nn/NarrowTable.scala, nn/FlattenTable.scala,
nn/MixtureTable.scala, nn/DotProduct.scala, nn/CosineDistance.scala,
nn/PairwiseDistance.scala, nn/MM.scala, nn/MV.scala,
nn/BifurcateSplitTable.scala, nn/CrossProduct.scala,
nn/TableOperation.scala.

A reference "Table" is a Python tuple/list here (any pytree works).
"""

from __future__ import annotations

from functools import reduce
from typing import Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module

__all__ = [
    "CAddTable", "CSubTable", "CMulTable", "CDivTable", "CMaxTable",
    "CMinTable", "CAveTable", "JoinTable", "SplitTable", "SelectTable",
    "NarrowTable", "FlattenTable", "MixtureTable", "DotProduct",
    "CosineDistance", "PairwiseDistance", "MM", "MV",
    "BifurcateSplitTable", "CrossProduct", "TableOperation",
]


class CAddTable(Module):
    """Elementwise sum of the input table (reference nn/CAddTable.scala)."""

    def __init__(self, inplace: bool = False):
        super().__init__()

    def forward(self, xs):
        return reduce(jnp.add, xs)


class CSubTable(Module):
    def forward(self, xs):
        return xs[0] - xs[1]


class CMulTable(Module):
    def forward(self, xs):
        return reduce(jnp.multiply, xs)


class CDivTable(Module):
    def forward(self, xs):
        return xs[0] / xs[1]


class CMaxTable(Module):
    def forward(self, xs):
        return reduce(jnp.maximum, xs)


class CMinTable(Module):
    def forward(self, xs):
        return reduce(jnp.minimum, xs)


class CAveTable(Module):
    def __init__(self, inplace: bool = False):
        super().__init__()

    def forward(self, xs):
        return reduce(jnp.add, xs) / len(xs)


class JoinTable(Module):
    """Concatenate table elements along dim (reference nn/JoinTable.scala;
    1-based; n_input_dims offsets for batched input)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def forward(self, xs):
        d = self.dimension - 1
        if self.n_input_dims > 0 and xs[0].ndim > self.n_input_dims:
            d += xs[0].ndim - self.n_input_dims
        return jnp.concatenate(list(xs), axis=d)


class SplitTable(Module):
    """Split a tensor along dim into a table of slices
    (reference nn/SplitTable.scala)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def forward(self, x):
        d = self.dimension - 1
        if self.n_input_dims > 0 and x.ndim > self.n_input_dims:
            d += x.ndim - self.n_input_dims
        if d < 0:
            d += x.ndim
        return tuple(jax.lax.index_in_dim(x, i, axis=d, keepdims=False)
                     for i in range(x.shape[d]))


class SelectTable(Module):
    """Pick the index-th element of the table (reference
    nn/SelectTable.scala; 1-based, negative from end)."""

    def __init__(self, index: int):
        super().__init__()
        self.index = index

    def forward(self, xs):
        i = self.index - 1 if self.index > 0 else len(xs) + self.index
        return xs[i]


class NarrowTable(Module):
    """Sub-table [offset, offset+length) (reference nn/NarrowTable.scala)."""

    def __init__(self, offset: int, length: int = 1):
        super().__init__()
        self.offset, self.length = offset, length

    def forward(self, xs):
        length = self.length if self.length >= 0 \
            else len(xs) - self.offset + 2 + self.length
        return tuple(xs[self.offset - 1:self.offset - 1 + length])


class FlattenTable(Module):
    """Flatten nested tables into a flat table (reference
    nn/FlattenTable.scala)."""

    def forward(self, xs):
        out = []

        def rec(t):
            if isinstance(t, (tuple, list)):
                for e in t:
                    rec(e)
            else:
                out.append(t)

        rec(xs)
        return tuple(out)


class MixtureTable(Module):
    """Mixture-of-experts blend: (gater [b,n], experts table/tensor) →
    sum_i gater_i * expert_i (reference nn/MixtureTable.scala)."""

    def __init__(self, dim: int = 2147483647):
        super().__init__()

    def forward(self, inputs):
        gater, experts = inputs
        if isinstance(experts, (tuple, list)):
            stacked = jnp.stack(list(experts), axis=1)  # [b, n, ...]
        else:
            stacked = experts
        g = gater.reshape(gater.shape + (1,) * (stacked.ndim - gater.ndim))
        return jnp.sum(g * stacked, axis=1)


class DotProduct(Module):
    """Row-wise dot product of two inputs (reference nn/DotProduct.scala)."""

    def forward(self, inputs):
        a, b = inputs
        return jnp.sum(a * b, axis=-1)


class CosineDistance(Module):
    """Row-wise cosine similarity (reference nn/CosineDistance.scala)."""

    def forward(self, inputs):
        a, b = inputs
        an = a / (jnp.linalg.norm(a, axis=-1, keepdims=True) + 1e-12)
        bn = b / (jnp.linalg.norm(b, axis=-1, keepdims=True) + 1e-12)
        return jnp.sum(an * bn, axis=-1)


class PairwiseDistance(Module):
    """Row-wise Lp distance (reference nn/PairwiseDistance.scala)."""

    def __init__(self, norm: int = 2):
        super().__init__()
        self.norm = norm

    def forward(self, inputs):
        a, b = inputs
        return jnp.linalg.norm(a - b, ord=self.norm, axis=-1)


class MM(Module):
    """Batch (or plain) matrix-matrix product with optional transposes
    (reference nn/MM.scala)."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__()
        self.trans_a, self.trans_b = trans_a, trans_b

    def forward(self, inputs):
        a, b = inputs
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b


class MV(Module):
    """Batch matrix-vector product (reference nn/MV.scala)."""

    def __init__(self, trans: bool = False):
        super().__init__()
        self.trans = trans

    def forward(self, inputs):
        m, v = inputs
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


class BifurcateSplitTable(Module):
    """Split a tensor into two halves along dim
    (reference nn/BifurcateSplitTable.scala)."""

    def __init__(self, dimension: int):
        super().__init__()
        self.dimension = dimension

    def forward(self, x):
        d = self.dimension - 1
        half = x.shape[d] // 2
        return (jax.lax.slice_in_dim(x, 0, half, axis=d),
                jax.lax.slice_in_dim(x, half, x.shape[d], axis=d))


class CrossProduct(Module):
    """Pairwise dot products between all table entries
    (reference nn/CrossProduct.scala)."""

    def __init__(self, num_tensor: int = 0, embedding_size: int = 0):
        super().__init__()

    def forward(self, xs):
        outs = []
        for i in range(len(xs)):
            for j in range(i + 1, len(xs)):
                outs.append(jnp.sum(xs[i] * xs[j], axis=-1, keepdims=True))
        return jnp.concatenate(outs, axis=-1)


class TableOperation(Module):
    """Apply a two-input table layer (CMulTable, CSubTable, …) after
    expanding the smaller tensor to the larger one's shape (reference
    nn/TableOperation.scala — used by wide-and-deep to combine a scalar
    gate with a feature map)."""

    def __init__(self, operation_layer: Module):
        super().__init__()
        self.operation_layer = operation_layer

    def forward(self, inputs):
        a, b = inputs
        if a.size > b.size:
            b = jnp.broadcast_to(b, a.shape)
        elif b.size > a.size:
            a = jnp.broadcast_to(a, b.shape)
        return self.operation_layer.forward((a, b))
