"""Sparse-tensor layers.

Reference: tensor/SparseTensor.scala (COO sparse tensor),
nn/DenseToSparse.scala, nn/SparseJoinTable.scala, nn/SparseLinear.scala,
nn/LookupTableSparse.scala — the stack used by wide-and-deep style
recommendation models.

TPU-first design: XLA has no dynamic-nnz sparse formats, so
:class:`SparseTensor` is a *fixed-capacity* COO pytree
``(indices (nnz, ndim) int32, values (nnz,), shape)``.  Padding entries
simply carry ``value == 0`` — exact for every linear consumer here
(SpMM, embedding sums), so no validity mask is needed.  Sparse matmul
and embedding lookups lower to gather + ``segment_sum``, which XLA
turns into efficient one-hot/scatter programs on TPU.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.module import Module, Parameter
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.utils.rng import next_key

__all__ = [
    "SparseTensor", "DenseToSparse", "SparseJoinTable", "SparseLinear",
    "LookupTableSparse", "dedup_gather", "dedup_scatter_updates",
]


def dedup_scatter_updates(idx, grads):
    """Combine duplicate-row updates before a scatter-add.

    ``idx`` (N,) int row ids with repeats, ``grads`` (N, ...) their
    per-occurrence updates.  Returns ``(rows, contrib)`` of the same
    static shapes where every row id's total update is carried by its
    FIRST occurrence in sorted order and every other occurrence
    carries exact zeros — ``zeros.at[rows].add(contrib)`` lands one
    non-zero update per unique row instead of one per occurrence.
    The combine is a sort + ``segment_sum``, not a per-duplicate
    scatter chain, which is what keeps a duplicate-heavy batch from
    serializing the table update on TPU.
    """
    idx = idx.reshape(-1)
    order = jnp.argsort(idx)
    sidx = idx[order]
    sg = grads[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sidx[1:] != sidx[:-1]])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    summed = jax.ops.segment_sum(sg, seg, num_segments=idx.shape[0])
    keep = first.reshape((-1,) + (1,) * (grads.ndim - 1))
    contrib = summed[seg] * keep.astype(grads.dtype)
    return sidx, contrib


@jax.custom_vjp
def dedup_gather(w, idx):
    """``w[idx]`` whose backward scatter-adds ONE combined update per
    unique id (via :func:`dedup_scatter_updates`) instead of one row
    per occurrence — the duplicate-heavy recommender batch fix."""
    return w[idx]


def _dedup_gather_fwd(w, idx):
    # residual leaves must be jax types: a zero-size token carries the
    # table's row count and dtype instead of raw shape/dtype objects
    return w[idx], (idx, jnp.zeros((w.shape[0], 0), w.dtype))


def _dedup_gather_bwd(res, g):
    idx, token = res
    tail = g.shape[idx.ndim:]
    flat = g.reshape((-1,) + tail)
    rows, contrib = dedup_scatter_updates(idx.reshape(-1), flat)
    dw = jnp.zeros((token.shape[0],) + tail, token.dtype)
    dw = dw.at[rows].add(contrib.astype(token.dtype))
    return dw, None


dedup_gather.defvjp(_dedup_gather_fwd, _dedup_gather_bwd)


class SparseTensor:
    """Fixed-capacity 2-D-or-n-D COO tensor (≙ tensor/SparseTensor.scala).

    ``indices``: (nnz, ndim) int32; ``values``: (nnz,); ``shape``: the
    dense shape — registered as *static* pytree aux data so it stays a
    Python tuple under jit.  Zero-valued entries are padding.
    """

    __slots__ = ("indices", "values", "shape")

    def __init__(self, indices, values, shape: Tuple[int, ...]):
        self.indices = indices
        self.values = values
        self.shape = tuple(int(s) for s in shape)

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, "
                f"capacity={self.values.shape[0]})")

    def to_dense(self) -> jnp.ndarray:
        flat_idx = jnp.ravel_multi_index(
            tuple(self.indices[:, d] for d in range(len(self.shape))),
            self.shape, mode="clip")
        out = jnp.zeros(int(np.prod(self.shape)), self.values.dtype)
        out = out.at[flat_idx].add(self.values)
        return out.reshape(self.shape)

    @staticmethod
    def from_dense(x) -> "SparseTensor":
        """Capacity = full size; zero entries become padding."""
        shape = tuple(int(s) for s in x.shape)
        grid = jnp.stack(jnp.meshgrid(
            *[jnp.arange(s) for s in shape], indexing="ij"),
            axis=-1).reshape(-1, len(shape)).astype(jnp.int32)
        return SparseTensor(grid, x.reshape(-1), shape)


jax.tree_util.register_pytree_node(
    SparseTensor,
    lambda t: ((t.indices, t.values), t.shape),
    lambda shape, children: SparseTensor(children[0], children[1], shape),
)


class DenseToSparse(Module):
    """Dense → COO (reference nn/DenseToSparse.scala).  Keeps full
    capacity so the op stays shape-static under jit."""

    def forward(self, x):
        return SparseTensor.from_dense(x)


class SparseJoinTable(Module):
    """Concatenate sparse tensors along ``dimension`` (1-based, like the
    reference nn/SparseJoinTable.scala)."""

    def __init__(self, dimension: int = 2):
        super().__init__()
        self.dimension = dimension  # 1-based

    def forward(self, tensors: Sequence[SparseTensor]) -> SparseTensor:
        d = self.dimension - 1
        offset = 0
        all_idx, all_val = [], []
        for t in tensors:
            idx = t.indices.at[:, d].add(offset)
            all_idx.append(idx)
            all_val.append(t.values)
            offset += t.shape[d]
        shape = list(tensors[0].shape)
        shape[d] = offset
        return SparseTensor(jnp.concatenate(all_idx, 0),
                            jnp.concatenate(all_val, 0), tuple(shape))


class SparseLinear(Module):
    """Linear layer over a sparse (batch, in) input
    (reference nn/SparseLinear.scala).  Lowered to gather + segment_sum:
    each nnz contributes ``value * W[:, col]`` to its row."""

    def __init__(self, input_size: int, output_size: int,
                 with_bias: bool = True, backward_start: int = -1,
                 backward_length: int = -1,
                 w_regularizer=None, b_regularizer=None,
                 init_weight=None, init_bias=None):
        super().__init__()
        self.inner = Linear(input_size, output_size, with_bias,
                            w_regularizer, b_regularizer,
                            init_weight, init_bias)
        self.output_size = output_size

    @property
    def weight(self):
        return self.inner.weight

    @property
    def bias(self):
        return self.inner.bias

    def forward(self, x):
        if isinstance(x, (tuple, list)):
            raise ValueError("SparseLinear expects a single SparseTensor; "
                             "use SparseJoinTable to merge inputs first")
        rows = x.indices[:, 0]
        cols = x.indices[:, 1]
        w = self.inner.weight  # (out, in)
        contrib = x.values[:, None] * w.T[cols]          # (nnz, out)
        y = jax.ops.segment_sum(contrib, rows, num_segments=x.shape[0])
        if self.inner.with_bias:
            y = y + self.inner.bias
        return y


class LookupTableSparse(Module):
    """Embedding lookup over sparse id tensors with sum/mean/sqrtn
    combiners (reference nn/LookupTableSparse.scala; the TF
    embedding_lookup_sparse semantics).

    ``forward(ids)`` or ``forward((ids, weights))`` where ``ids`` is a
    SparseTensor of shape (batch, maxlen) whose *values* are 1-based
    embedding ids (0 ids are padding), and ``weights`` (optional) is a
    SparseTensor with the same layout carrying per-id weights.
    Output: (batch, embedding_dim).
    """

    def __init__(self, n_index: int, n_output: int,
                 combiner: str = "sum", max_norm: float = -1.0,
                 w_regularizer=None):
        super().__init__()
        assert combiner in ("sum", "mean", "sqrtn")
        self.n_index = n_index
        self.n_output = n_output
        self.combiner = combiner
        self.max_norm = max_norm
        self.weight = Parameter(jax.random.normal(
            next_key(), (n_index, n_output)) * 0.05)

    def forward(self, x):
        if isinstance(x, SparseTensor):
            ids, weights = x, None
        else:
            ids, weights = x
        rows = ids.indices[:, 0]
        id_vals = ids.values.astype(jnp.int32)
        present = (id_vals > 0).astype(self.weight.dtype)
        # dedup_gather: duplicate ids in one batch (the common
        # recommender shape) backward into ONE combined scatter row per
        # unique id, not one per occurrence
        emb = dedup_gather(self.weight,
                           jnp.clip(id_vals - 1, 0, self.n_index - 1))
        if self.max_norm > 0:
            # clip only the gathered (nnz, dim) rows, not the whole table
            norms = jnp.linalg.norm(emb, axis=1, keepdims=True)
            emb = emb * jnp.minimum(1.0, self.max_norm
                                    / jnp.maximum(norms, 1e-7))
        w = weights.values if weights is not None else present
        w = w * present
        batch = ids.shape[0]
        summed = jax.ops.segment_sum(emb * w[:, None], rows,
                                     num_segments=batch)
        if self.combiner == "sum":
            return summed
        wsum = jax.ops.segment_sum(w, rows, num_segments=batch)
        if self.combiner == "mean":
            return summed / jnp.maximum(wsum, 1e-7)[:, None]
        wsq = jax.ops.segment_sum(w * w, rows, num_segments=batch)
        return summed / jnp.maximum(jnp.sqrt(wsq), 1e-7)[:, None]
