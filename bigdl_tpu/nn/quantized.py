"""Int8 quantized inference.

Reference: nn/quantized/ (Quantizer.scala walks a trained model and
swaps Linear/SpatialConvolution for int8 versions backed by BigQuant
native GEMM; per-channel min/max quantization windows; algorithm in
docs/docs/whitepaper.md:179-196).

TPU-native design: BigQuant's hand-written int8 CPU GEMM becomes an
int8×int8→int32 ``dot_general``/``conv_general_dilated`` with
``preferred_element_type=int32`` — XLA lowers this straight onto the
MXU's int8 path.  Quantization windows:

* weights: symmetric per-output-channel max-abs scaling, computed once
  at quantize time (≙ BigQuant ConvKernelLoadFromModel per-channel
  min/max);
* activations: symmetric per-row (per-sample) max-abs scaling computed
  dynamically per batch (≙ BigQuant ConvDataInit min/max windows).

Quantized weights live as int8 *buffers* — not parameters — so the
quantized model is inference-only (matching the reference, where
quantized layers error on backward).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, ModuleList
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.conv import SpatialConvolution, SpatialDilatedConvolution

__all__ = ["QuantizedLinear", "QuantizedSpatialConvolution", "Quantizer",
           "quantize"]


def _quantize_per_channel(w: jnp.ndarray, channel_axis: int):
    """Symmetric max-abs int8 quantization with a per-output-channel
    scale (≙ BigQuant per-channel kernel descriptors)."""
    reduce_axes = tuple(i for i in range(w.ndim) if i != channel_axis)
    amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _quantize_rows(x: jnp.ndarray):
    """Dynamic symmetric per-row activation quantization: each sample
    row gets its own max-abs window."""
    reduce_axes = tuple(range(1, x.ndim))
    amax = jnp.max(jnp.abs(x), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


class QuantizedLinear(Module):
    """(≙ nn/quantized/Linear.scala over BigQuant FC kernels)"""

    def __init__(self, linear: Linear):
        super().__init__()
        w = linear._params["weight"]                   # [out, in]
        qw, sw = _quantize_per_channel(w, channel_axis=0)
        self.qweight = qw                               # int8 buffer
        self.wscale = sw.reshape(-1)                    # [out]
        self.bias = (jnp.asarray(linear._params["bias"])
                     if "bias" in linear._params else None)
        self.input_size = linear.input_size
        self.output_size = linear.output_size

    def forward(self, x):
        squeeze = x.ndim == 1
        if squeeze:
            x = x[None]
        qx, sx = _quantize_rows(x)                      # [b,in], [b,1]
        acc = jax.lax.dot_general(
            qx, self.qweight,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)           # [b, out]
        out = acc.astype(jnp.float32) * sx * self.wscale[None, :]
        if self.bias is not None:
            out = out + self.bias
        out = out.astype(x.dtype)
        return out[0] if squeeze else out


class QuantizedSpatialConvolution(Module):
    """(≙ nn/quantized/SpatialConvolution.scala over BigQuant conv
    kernels).  NHWC; weight stored HWIO-int8."""

    def __init__(self, conv: SpatialConvolution):
        super().__init__()
        if getattr(conv, "n_group", 1) != 1:
            raise NotImplementedError(
                "grouped conv quantization not supported")
        w = conv._params["weight"]                       # HWIO
        qw, sw = _quantize_per_channel(w, channel_axis=3)
        self.qweight = qw
        self.wscale = sw.reshape(-1)                     # [out]
        self.bias = (jnp.asarray(conv._params["bias"])
                     if "bias" in conv._params else None)
        self.stride = conv.stride
        self.pad = conv.pad
        self.dilation = getattr(conv, "dilation", (1, 1))
        self.data_format = conv.data_format

    def forward(self, x):
        if self.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        qx, sx = _quantize_rows(x)                       # [b,h,w,c],[b,1,1,1]
        pad = self.pad
        padding = "SAME" if pad[0] == -1 else \
            ((pad[0], pad[0]), (pad[1], pad[1]))
        acc = jax.lax.conv_general_dilated(
            qx, self.qweight,
            window_strides=self.stride,
            padding=padding,
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * sx * self.wscale
        if self.bias is not None:
            out = out + self.bias
        out = out.astype(x.dtype)
        if self.data_format == "NCHW":
            out = jnp.transpose(out, (0, 3, 1, 2))
        return out


class Quantizer:
    """Walk a trained model and swap quantizable layers for int8
    versions (≙ nn/quantized/Quantizer.scala)."""

    SWAPS = {
        Linear: QuantizedLinear,
        SpatialConvolution: QuantizedSpatialConvolution,
        SpatialDilatedConvolution: QuantizedSpatialConvolution,
    }

    @classmethod
    def quantize(cls, model: Module) -> Module:
        model = model.clone().eval_mode()
        swapped = cls._maybe_swap(model)
        if swapped is model:
            cls._walk(model)
        return swapped

    @classmethod
    def _maybe_swap(cls, mod: Module) -> Module:
        for src, dst in cls.SWAPS.items():
            if type(mod) is src:
                try:
                    return dst(mod)
                except NotImplementedError:
                    return mod
        return mod

    @classmethod
    def _walk(cls, mod: Module):
        for name, child in list(mod._modules.items()):
            if isinstance(child, ModuleList):
                for i, item in enumerate(child._items):
                    swapped = cls._maybe_swap(item)
                    if swapped is not item:
                        child._items[i] = swapped
                    else:
                        cls._walk(item)
            else:
                swapped = cls._maybe_swap(child)
                if swapped is not child:
                    mod._modules[name] = swapped
                else:
                    cls._walk(child)


def quantize(model: Module) -> Module:
    """``quantize(model)`` (≙ AbstractModule.quantize:954)."""
    return Quantizer.quantize(model)
