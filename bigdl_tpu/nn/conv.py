"""Convolution layers.

Reference: nn/SpatialConvolution.scala, nn/SpatialDilatedConvolution.scala,
nn/SpatialFullConvolution.scala, nn/SpatialSeparableConvolution.scala,
nn/SpatialShareConvolution.scala, nn/TemporalConvolution.scala,
nn/VolumetricConvolution.scala, nn/VolumetricFullConvolution.scala,
nn/LocallyConnected1D.scala, nn/LocallyConnected2D.scala.

TPU-first design: all convs lower to ``lax.conv_general_dilated`` so XLA
tiles them onto the MXU; layout is NHWC activations / HWIO kernels (the
TPU-native layout) with optional NCHW acceptance for parity with the
reference's default format.  The reference's im2col+gemm strategy
(SpatialConvolution.scala updateOutput) is the compiler's job here.

Constructor argument order mirrors the reference Scala signatures
(nInputPlane, nOutputPlane, kernelW, kernelH, strideW, strideH, padW,
padH, nGroup).  pad = -1 means SAME padding (reference convention used
by Inception, models/inception/Inception_v1.scala).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from bigdl_tpu.core.module import Module, Parameter
from bigdl_tpu.core import init as init_methods
from bigdl_tpu.utils.rng import next_key

__all__ = [
    "SpatialConvolution", "SpatialDilatedConvolution",
    "SpatialFullConvolution", "SpatialSeparableConvolution",
    "SpatialShareConvolution", "TemporalConvolution",
    "VolumetricConvolution", "VolumetricFullConvolution",
    "LocallyConnected2D", "LocallyConnected1D", "SpatialConvolutionMap",
]


def _to_nhwc(x, fmt):
    return jnp.transpose(x, (0, 2, 3, 1)) if fmt == "NCHW" else x


def _from_nhwc(x, fmt):
    return jnp.transpose(x, (0, 3, 1, 2)) if fmt == "NCHW" else x


def _pad_spec(pad_h, pad_w):
    if pad_h == -1 or pad_w == -1:
        return "SAME"
    return ((pad_h, pad_h), (pad_w, pad_w))


class SpatialConvolution(Module):
    """2-D convolution (reference nn/SpatialConvolution.scala)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 n_group: int = 1, propagate_back: bool = True,
                 w_regularizer=None, b_regularizer=None,
                 init_weight=None, init_bias=None,
                 with_bias: bool = True, data_format: str = "NHWC",
                 init_method=None):
        super().__init__()
        assert n_input_plane % n_group == 0
        assert n_output_plane % n_group == 0
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.n_group = n_group
        self.with_bias = with_bias
        self.data_format = data_format
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        fan_in = n_input_plane // n_group * kernel_h * kernel_w
        fan_out = n_output_plane // n_group * kernel_h * kernel_w
        if init_weight is not None:
            self.weight = Parameter(init_weight)
        else:
            im = init_method or init_methods.RandomUniform()
            # HWIO: (kh, kw, in/groups, out)
            self.weight = Parameter(im(
                next_key(),
                (kernel_h, kernel_w, n_input_plane // n_group, n_output_plane),
                fan_in=fan_in, fan_out=fan_out))
        if with_bias:
            if init_bias is not None:
                self.bias = Parameter(init_bias)
            else:
                bound = 1.0 / math.sqrt(fan_in)
                self.bias = Parameter(jax.random.uniform(
                    next_key(), (n_output_plane,), minval=-bound, maxval=bound))

    def forward(self, x):
        unbatched = x.ndim == 3
        if unbatched:
            x = x[None]
        x = _to_nhwc(x, self.data_format)
        y = jax.lax.conv_general_dilated(
            x, self.weight,
            window_strides=self.stride,
            padding=_pad_spec(*self.pad),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_group)
        if self.with_bias:
            y = y + self.bias
        # Remat anchor: under jax.checkpoint with a
        # save_only_these_names policy, conv outputs are the natural
        # residual set for conv->BN->ReLU chains — the elementwise tail
        # is recomputed in the backward from the conv output instead of
        # being round-tripped through HBM.  A no-op outside such a
        # policy.
        y = checkpoint_name(y, "conv_out")
        y = _from_nhwc(y, self.data_format)
        return y[0] if unbatched else y


class SpatialShareConvolution(SpatialConvolution):
    """Memory-sharing variant in the reference
    (nn/SpatialShareConvolution.scala); identical math — XLA handles
    buffer reuse, so this is an alias."""


class SpatialDilatedConvolution(Module):
    """Atrous convolution (reference nn/SpatialDilatedConvolution.scala)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 dilation_w: int = 1, dilation_h: int = 1,
                 w_regularizer=None, b_regularizer=None,
                 data_format: str = "NHWC", with_bias: bool = True):
        super().__init__()
        self.stride = (dh, dw)
        self.pad = (pad_h, pad_w)
        self.dilation = (dilation_h, dilation_w)
        self.data_format = data_format
        self.with_bias = with_bias
        fan_in = n_input_plane * kh * kw
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = Parameter(jax.random.uniform(
            next_key(), (kh, kw, n_input_plane, n_output_plane),
            minval=-bound, maxval=bound))
        if with_bias:
            self.bias = Parameter(jax.random.uniform(
                next_key(), (n_output_plane,), minval=-bound, maxval=bound))

    def forward(self, x):
        x = _to_nhwc(x, self.data_format)
        y = jax.lax.conv_general_dilated(
            x, self.weight,
            window_strides=self.stride,
            padding=_pad_spec(*self.pad),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.with_bias:
            y = y + self.bias
        return _from_nhwc(y, self.data_format)


class SpatialFullConvolution(Module):
    """Transposed convolution (reference nn/SpatialFullConvolution.scala):
    output size = (in-1)*stride - 2*pad + kernel + adj."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 adj_w: int = 0, adj_h: int = 0,
                 n_group: int = 1, no_bias: bool = False,
                 w_regularizer=None, b_regularizer=None,
                 data_format: str = "NHWC"):
        super().__init__()
        self.kernel = (kh, kw)
        self.stride = (dh, dw)
        self.pad = (pad_h, pad_w)
        self.adj = (adj_h, adj_w)
        self.n_group = n_group
        self.with_bias = not no_bias
        self.data_format = data_format
        fan_in = n_input_plane * kh * kw
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = Parameter(jax.random.uniform(
            next_key(), (kh, kw, n_input_plane // n_group, n_output_plane),
            minval=-bound, maxval=bound))
        if self.with_bias:
            self.bias = Parameter(jax.random.uniform(
                next_key(), (n_output_plane,), minval=-bound, maxval=bound))

    def forward(self, x):
        x = _to_nhwc(x, self.data_format)
        kh, kw = self.kernel
        ph, pw = self.pad
        ah, aw = self.adj
        # Transposed conv = lhs-dilated conv with flipped spatial padding:
        # pad_lo = k - 1 - p, pad_hi = k - 1 - p + adj.
        y = jax.lax.conv_general_dilated(
            x, jnp.flip(self.weight, axis=(0, 1)),
            window_strides=(1, 1),
            padding=((kh - 1 - ph, kh - 1 - ph + ah),
                     (kw - 1 - pw, kw - 1 - pw + aw)),
            lhs_dilation=self.stride,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_group)
        if self.with_bias:
            y = y + self.bias
        return _from_nhwc(y, self.data_format)


class SpatialSeparableConvolution(Module):
    """Depthwise + pointwise conv
    (reference nn/SpatialSeparableConvolution.scala)."""

    def __init__(self, n_input_channel: int, n_output_channel: int,
                 depth_multiplier: int, kw: int, kh: int,
                 sw: int = 1, sh: int = 1, pw: int = 0, ph: int = 0,
                 has_bias: bool = True, data_format: str = "NHWC",
                 w_regularizer=None, b_regularizer=None, p_regularizer=None):
        super().__init__()
        self.stride = (sh, sw)
        self.pad = (ph, pw)
        self.n_input_channel = n_input_channel
        self.depth_multiplier = depth_multiplier
        self.with_bias = has_bias
        self.data_format = data_format
        fan_in = kh * kw
        bound = 1.0 / math.sqrt(fan_in)
        # depthwise kernel: HWIO with feature_group_count = in_channels
        self.depth_weight = Parameter(jax.random.uniform(
            next_key(), (kh, kw, 1, n_input_channel * depth_multiplier),
            minval=-bound, maxval=bound))
        pbound = 1.0 / math.sqrt(n_input_channel * depth_multiplier)
        self.point_weight = Parameter(jax.random.uniform(
            next_key(), (1, 1, n_input_channel * depth_multiplier,
                         n_output_channel),
            minval=-pbound, maxval=pbound))
        if has_bias:
            self.bias = Parameter(jnp.zeros(n_output_channel))

    def forward(self, x):
        x = _to_nhwc(x, self.data_format)
        y = jax.lax.conv_general_dilated(
            x, self.depth_weight,
            window_strides=self.stride,
            padding=_pad_spec(*self.pad),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=self.n_input_channel)
        y = jax.lax.conv_general_dilated(
            y, self.point_weight,
            window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.with_bias:
            y = y + self.bias
        return _from_nhwc(y, self.data_format)


class TemporalConvolution(Module):
    """1-D convolution over [batch, time, inputFrameSize]
    (reference nn/TemporalConvolution.scala)."""

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1,
                 propagate_back: bool = True,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.stride_w = stride_w
        fan_in = input_frame_size * kernel_w
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = Parameter(jax.random.uniform(
            next_key(), (kernel_w, input_frame_size, output_frame_size),
            minval=-bound, maxval=bound))
        self.bias = Parameter(jax.random.uniform(
            next_key(), (output_frame_size,), minval=-bound, maxval=bound))

    def forward(self, x):
        unbatched = x.ndim == 2
        if unbatched:
            x = x[None]
        y = jax.lax.conv_general_dilated(
            x, self.weight,
            window_strides=(self.stride_w,),
            padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        y = y + self.bias
        return y[0] if unbatched else y


class VolumetricConvolution(Module):
    """3-D convolution over NDHWC (reference nn/VolumetricConvolution.scala,
    whose default is NCDHW — converted on entry if data_format=NCDHW)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 with_bias: bool = True,
                 w_regularizer=None, b_regularizer=None,
                 data_format: str = "NDHWC"):
        super().__init__()
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.with_bias = with_bias
        self.data_format = data_format
        fan_in = n_input_plane * k_t * k_h * k_w
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = Parameter(jax.random.uniform(
            next_key(), (k_t, k_h, k_w, n_input_plane, n_output_plane),
            minval=-bound, maxval=bound))
        if with_bias:
            self.bias = Parameter(jax.random.uniform(
                next_key(), (n_output_plane,), minval=-bound, maxval=bound))

    def forward(self, x):
        if self.data_format == "NCDHW":
            x = jnp.transpose(x, (0, 2, 3, 4, 1))
        pt, ph, pw = self.pad
        pad = "SAME" if pt == -1 else ((pt, pt), (ph, ph), (pw, pw))
        y = jax.lax.conv_general_dilated(
            x, self.weight,
            window_strides=self.stride,
            padding=pad,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.with_bias:
            y = y + self.bias
        if self.data_format == "NCDHW":
            y = jnp.transpose(y, (0, 4, 1, 2, 3))
        return y


class VolumetricFullConvolution(Module):
    """3-D transposed convolution
    (reference nn/VolumetricFullConvolution.scala)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 adj_t: int = 0, adj_w: int = 0, adj_h: int = 0,
                 n_group: int = 1, no_bias: bool = False,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.adj = (adj_t, adj_h, adj_w)
        self.with_bias = not no_bias
        fan_in = n_input_plane * k_t * k_h * k_w
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = Parameter(jax.random.uniform(
            next_key(), (k_t, k_h, k_w, n_input_plane, n_output_plane),
            minval=-bound, maxval=bound))
        if self.with_bias:
            self.bias = Parameter(jnp.zeros(n_output_plane))

    def forward(self, x):
        kt, kh, kw = self.kernel
        pt, ph, pw = self.pad
        at, ah, aw = self.adj
        y = jax.lax.conv_general_dilated(
            x, jnp.flip(self.weight, axis=(0, 1, 2)),
            window_strides=(1, 1, 1),
            padding=((kt - 1 - pt, kt - 1 - pt + at),
                     (kh - 1 - ph, kh - 1 - ph + ah),
                     (kw - 1 - pw, kw - 1 - pw + aw)),
            lhs_dilation=self.stride,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.with_bias:
            y = y + self.bias
        return y


class LocallyConnected2D(Module):
    """Unshared-weight convolution (reference nn/LocallyConnected2D.scala).
    Implemented as patch extraction + per-position einsum — maps to one
    big batched matmul on the MXU."""

    def __init__(self, n_input_plane: int, input_width: int,
                 input_height: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 propagate_back: bool = True,
                 w_regularizer=None, b_regularizer=None,
                 init_weight=None, init_bias=None,
                 with_bias: bool = True, data_format: str = "NHWC"):
        super().__init__()
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.with_bias = with_bias
        self.data_format = data_format
        out_h = (input_height + 2 * pad_h - kernel_h) // stride_h + 1
        out_w = (input_width + 2 * pad_w - kernel_w) // stride_w + 1
        self.out_size = (out_h, out_w)
        fan_in = n_input_plane * kernel_h * kernel_w
        bound = 1.0 / math.sqrt(fan_in)
        if init_weight is not None:
            self.weight = Parameter(init_weight)
        else:
            self.weight = Parameter(jax.random.uniform(
                next_key(),
                (out_h, out_w, kernel_h * kernel_w * n_input_plane,
                 n_output_plane),
                minval=-bound, maxval=bound))
        if with_bias:
            self.bias = Parameter(
                init_bias if init_bias is not None
                else jnp.zeros((out_h, out_w, n_output_plane)))

    def forward(self, x):
        x = _to_nhwc(x, self.data_format)
        ph, pw = self.pad
        if ph or pw:
            x = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
        kh, kw = self.kernel
        sh, sw = self.stride
        out_h, out_w = self.out_size
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # patches: [n, out_h, out_w, kh*kw*c]
        y = jnp.einsum("nhwk,hwko->nhwo", patches, self.weight)
        if self.with_bias:
            y = y + self.bias
        return _from_nhwc(y, self.data_format)


class LocallyConnected1D(Module):
    """Temporal conv with unshared weights per output frame
    (reference nn/LocallyConnected1D.scala).  Lowered to one batched
    einsum over unfolded windows so the MXU sees a single contraction."""

    def __init__(self, n_input_frame: int, input_frame_size: int,
                 output_frame_size: int, kernel_w: int, stride_w: int = 1,
                 propagate_back: bool = True,
                 w_regularizer=None, b_regularizer=None,
                 init_weight=None, init_bias=None, with_bias: bool = True):
        super().__init__()
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.with_bias = with_bias
        n_out_frame = (n_input_frame - kernel_w) // stride_w + 1
        self.n_output_frame = n_out_frame
        fan_in = kernel_w * input_frame_size
        bound = 1.0 / math.sqrt(fan_in)
        if init_weight is not None:
            self.weight = Parameter(init_weight)
        else:
            self.weight = Parameter(jax.random.uniform(
                next_key(),
                (n_out_frame, output_frame_size, kernel_w,
                 input_frame_size), minval=-bound, maxval=bound))
        if with_bias:
            self.bias = Parameter(
                init_bias if init_bias is not None
                else jax.random.uniform(next_key(),
                                        (n_out_frame, output_frame_size),
                                        minval=-bound, maxval=bound))

    def forward(self, x):
        # x: (B, T, in) → windows (B, n_out, kw, in)
        idx = (jnp.arange(self.n_output_frame)[:, None] * self.stride_w
               + jnp.arange(self.kernel_w)[None, :])
        win = x[:, idx]                      # (B, n_out, kw, in)
        y = jnp.einsum("bokc,olkc->bol", win, self.weight)
        return y + self.bias if self.with_bias else y


class SpatialConvolutionMap(Module):
    """Convolution with an explicit input→output connection table
    (reference nn/SpatialConvolutionMap.scala).  Implemented as a dense
    conv with a constant connectivity mask on the kernel — MXU-friendly,
    gradients flow only through connected pairs.

    ``conn_table``: (n_links, 2) 1-based [in_plane, out_plane] pairs
    (Torch convention; build with :meth:`full`, :meth:`one_to_one`,
    or :meth:`random`).
    """

    def __init__(self, conn_table, kw: int, kh: int,
                 dw: int = 1, dh: int = 1, pad_w: int = 0, pad_h: int = 0,
                 n_input_plane: int = 0, n_output_plane: int = 0,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        conn = np.asarray(conn_table, np.int32)
        # table max only sees *connected* planes — pass the counts
        # explicitly when the table may omit the last plane (random())
        n_in = n_input_plane or int(conn[:, 0].max())
        n_out = n_output_plane or int(conn[:, 1].max())
        assert conn[:, 0].max() <= n_in and conn[:, 1].max() <= n_out, \
            "connection table references planes beyond the declared counts"
        mask = np.zeros((kh, kw, n_in, n_out), np.float32)
        for i, o in conn:
            mask[:, :, i - 1, o - 1] = 1.0
        self.mask = jnp.asarray(mask)
        self.stride = (dh, dw)
        self.pad = (pad_h, pad_w)
        fan_in = int(conn.shape[0] / n_out * kh * kw)
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = Parameter(jax.random.uniform(
            next_key(), (kh, kw, n_in, n_out), minval=-bound, maxval=bound))
        self.bias = Parameter(jax.random.uniform(
            next_key(), (n_out,), minval=-bound, maxval=bound))

    @staticmethod
    def full(n_in: int, n_out: int):
        return [[i + 1, o + 1] for o in range(n_out) for i in range(n_in)]

    @staticmethod
    def one_to_one(n_features: int):
        return [[i + 1, i + 1] for i in range(n_features)]

    @staticmethod
    def random(n_in: int, n_out: int, n_from: int, seed: int = 0):
        """Random table à la Torch; pass n_input_plane/n_output_plane to
        the constructor since the sample may omit the highest planes."""
        rng = np.random.RandomState(seed)
        table = []
        for o in range(n_out):
            for i in rng.choice(n_in, size=n_from, replace=False):
                table.append([int(i) + 1, o + 1])
        return table

    def forward(self, x):
        w = self.weight * self.mask
        ph, pw = self.pad
        y = jax.lax.conv_general_dilated(
            x, w, self.stride,
            ((ph, ph), (pw, pw)) if (ph, pw) != (-1, -1) else "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + self.bias
