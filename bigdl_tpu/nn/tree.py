"""Tree-structured LSTMs.

Reference: nn/TreeLSTM.scala (abstract protocol over parse trees),
nn/BinaryTreeLSTM.scala (constituency binary-tree composer used by
treeLSTMSentiment example).

TPU-first design: the reference walks the tree with recursive Scala
calls per node.  Here a batch of trees is encoded as *node arrays in
topological (children-first) order* and processed with one
``lax.fori_loop`` over node slots — gathers fetch child states, a
``dynamic_update_index`` writes the composed state, and the whole thing
jits with static shapes.  Batching is a vmap over trees.

Tree encoding (per tree, ``n_nodes`` slots, padded with -1):
  * ``children (n_nodes, 2)`` int32: indices of left/right children in
    the node array, or -1 for none (leaf).
  * ``leaf_ids (n_nodes,)`` int32: index into the input sequence for
    leaves, -1 for internal nodes.
Nodes must be ordered so every child index < its parent index (standard
post-order satisfies this).  Padding slots (children AND leaf_id all -1,
placed after the real nodes) copy the previous slot's state forward, so
``output[:, -1]`` is the root state for every tree in a ragged batch.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, Parameter
from bigdl_tpu.utils.rng import next_key

__all__ = ["TreeLSTM", "BinaryTreeLSTM"]


class TreeLSTM(Module):
    """Abstract tree-LSTM protocol (reference nn/TreeLSTM.scala):
    subclasses implement ``compose(child_h, child_c, leaf_x, is_leaf)``."""

    def __init__(self, input_size: int, hidden_size: int):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size

    def compose(self, child_h, child_c, leaf_x, is_leaf):
        raise NotImplementedError

    def forward(self, inputs):
        """``inputs = (x (B, T, in), children (B, N, 2),
        leaf_ids (B, N))`` → hidden states (B, N, hidden)."""
        x, children, leaf_ids = inputs
        return jax.vmap(self._one_tree)(x, children, leaf_ids)

    def _one_tree(self, x, children, leaf_ids):
        n_nodes = children.shape[0]
        H = self.hidden_size
        h0 = jnp.zeros((n_nodes + 1, H), x.dtype)  # slot n_nodes = "none"
        c0 = jnp.zeros((n_nodes + 1, H), x.dtype)

        def body(i, hc):
            h, c = hc
            kid = children[i]
            # -1 (none) → the zero slot at index n_nodes
            kid_idx = jnp.where(kid < 0, n_nodes, kid)
            child_h = h[kid_idx]          # (2, H)
            child_c = c[kid_idx]
            lid = leaf_ids[i]
            leaf_x = x[jnp.clip(lid, 0, x.shape[0] - 1)]
            is_leaf = (lid >= 0)
            nh, nc = self.compose(child_h, child_c, leaf_x, is_leaf)
            # padding slots (no children, no leaf) carry the previous
            # slot's state forward, so slot -1 always holds the root of
            # every tree in a ragged batch
            is_pad = (lid < 0) & (kid[0] < 0) & (kid[1] < 0)
            prev = jnp.maximum(i - 1, 0)
            nh = jnp.where(is_pad, h[prev], nh)
            nc = jnp.where(is_pad, c[prev], nc)
            return (h.at[i].set(nh), c.at[i].set(nc))

        h, c = jax.lax.fori_loop(0, n_nodes, body, (h0, c0))
        return h[:n_nodes]


class BinaryTreeLSTM(TreeLSTM):
    """Constituency binary tree-LSTM (reference nn/BinaryTreeLSTM.scala):
    leaves run an input transform; internal nodes compose (hl, hr)
    with separate left/right gate weights."""

    def __init__(self, input_size: int, hidden_size: int,
                 gate_output: bool = True, with_graph: bool = True):
        super().__init__(input_size, hidden_size)
        self.gate_output = gate_output
        H, D = hidden_size, input_size
        s = 1.0 / math.sqrt(H)

        def rnd(*shape):
            return Parameter(jax.random.uniform(
                next_key(), shape, minval=-s, maxval=s))

        # leaf transform: x → (c, o)
        self.leaf_w = rnd(2 * H, D)
        self.leaf_b = rnd(2 * H)
        # composer: [hl, hr] → gates i, lf, rf, update, o
        self.comp_w = rnd(5 * H, 2 * H)
        self.comp_b = rnd(5 * H)

    def compose(self, child_h, child_c, leaf_x, is_leaf):
        H = self.hidden_size
        # leaf path
        proj = self.leaf_w @ leaf_x + self.leaf_b
        c_leaf = proj[:H]
        o_leaf = jax.nn.sigmoid(proj[H:])
        h_leaf = o_leaf * jnp.tanh(c_leaf) if self.gate_output \
            else jnp.tanh(c_leaf)
        # internal path
        hl, hr = child_h[0], child_h[1]
        cl, cr = child_c[0], child_c[1]
        g = self.comp_w @ jnp.concatenate([hl, hr]) + self.comp_b
        i = jax.nn.sigmoid(g[:H])
        lf = jax.nn.sigmoid(g[H:2 * H])
        rf = jax.nn.sigmoid(g[2 * H:3 * H])
        u = jnp.tanh(g[3 * H:4 * H])
        o = jax.nn.sigmoid(g[4 * H:])
        c_int = i * u + lf * cl + rf * cr
        h_int = o * jnp.tanh(c_int) if self.gate_output \
            else jnp.tanh(c_int)
        h = jnp.where(is_leaf, h_leaf, h_int)
        c = jnp.where(is_leaf, c_leaf, c_int)
        return h, c
