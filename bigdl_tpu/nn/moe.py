"""Mixture-of-Experts with expert parallelism.

The reference's closest layer is MixtureTable (nn/MixtureTable.scala —
a gater weighting expert outputs on ONE node, no parallelism); real
expert parallelism is new TPU-first capability (SURVEY §2.6: EP absent
from the reference).

Design: top-k token routing with load-balancing auxiliary loss (the
standard Shazeer/Switch recipe).  Three execution paths:

* dense (single device / no expert axis): every expert runs over all
  tokens via ``vmap`` over stacked expert parameters; outputs combine
  with the routing weights.  O(E·T) compute — exact, used for tests and
  small E.
* expert-parallel all_to_all (``set_mesh(..., capacity_factor=f)``) —
  THE scalable path: tokens are sharded over the expert axis alongside
  the experts; each device builds a capacity-bounded dispatch for its
  local S = B·T/n tokens (position-in-expert via cumsum, overflow
  DROPPED per the Switch policy), ships [E, C, H] expert buffers with
  ``lax.all_to_all``, runs its E/n local experts over the n·C received
  slots, and reverses the exchange to combine.  Per-device activation
  memory is O(f·k·B·T·H/n) — tokens/device, NOT the full batch.
* expert-parallel psum fallback (``capacity_factor=None``): each device
  computes its local experts' contribution over fully-replicated
  activations and psums.  Exact (no capacity drops) but O(B·T·H)
  replicated memory — right for small E / small batches only.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.core.module import Module, ModuleList, Parameter
from bigdl_tpu.telemetry import collectives as _coll
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.utils.rng import next_key
from bigdl_tpu.parallel.mesh import pin_replicated, shard_map_compat

__all__ = ["MoE"]

# Per-device (inside-shard_map) buffer shapes of the most recent a2a
# trace — a debug/test hook (module attrs would pollute the pytree).
LAST_A2A_SHAPES = {}


class MoE(Module):
    """Top-k routed mixture of experts over position-wise expert modules.

    experts: list of identical Modules mapping [..., H] -> [..., H]
    (e.g. FeedForwardNetwork).  ``forward(x)`` takes [B, T, H].
    After a forward, ``self.aux_loss`` holds the load-balancing loss
    (mean over tokens of E · Σ_e f_e · p_e) to be added to the training
    objective by the caller.
    """

    def __init__(self, hidden_size: int, experts: List[Module],
                 top_k: int = 2):
        super().__init__()
        self.hidden_size = hidden_size
        self.top_k = top_k
        self.num_experts = len(experts)
        self.experts = ModuleList(experts)
        self.gate = Linear(hidden_size, self.num_experts, with_bias=False)
        self.aux_loss = jnp.zeros(())
        # overflow-drop fraction of the last a2a forward (0 on the
        # dense/psum paths, which never drop)
        self.drop_rate = jnp.zeros(())
        self.expert_mesh = None
        self.expert_axis = "expert"
        self.capacity_factor = None

    def set_mesh(self, mesh: Mesh, axis: str = "expert",
                 capacity_factor: Optional[float] = None) -> "MoE":
        """Route ``forward`` through the expert-parallel path on this
        mesh, so the layer composes with the Optimizer (whose jitted
        step just calls ``model.forward``).

        ``capacity_factor``: when set, use capacity-based all_to_all
        token dispatch (per-expert, per-source-device capacity
        C = max(1, round(f·k·S/E)) with S = B·T/n local tokens; tokens
        beyond capacity are dropped, Switch-style).  ``None`` keeps the
        exact psum fallback (replicated activations — small E only)."""
        self.expert_mesh = mesh
        self.expert_axis = axis
        self.capacity_factor = capacity_factor
        return self

    # -- routing -----------------------------------------------------------

    def _gate_probs(self, x):
        """Softmax routing probabilities [B, T, E] (fp32)."""
        logits = self.gate(x)  # [B, T, E]
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    def _set_aux_loss(self, probs, mask):
        """Switch-style load-balancing loss:
        E · Σ_e (fraction routed to e)·(mean prob of e)."""
        frac = jnp.mean(mask.astype(jnp.float32), axis=(0, 1))
        mean_p = jnp.mean(probs, axis=(0, 1))
        self.aux_loss = self.num_experts * jnp.sum(frac * mean_p)

    def _topk_mask(self, probs):
        top_vals, _ = jax.lax.top_k(probs, self.top_k)
        return probs >= top_vals[..., -1:]

    def _route(self, x, probs=None):
        """Returns combine weights [B, T, E] (zero for non-top-k) and
        stores the load-balancing aux loss.  ``probs`` lets a caller
        that already ran the gate avoid running it twice."""
        if probs is None:
            probs = self._gate_probs(x)
        mask = self._topk_mask(probs)
        weights = jnp.where(mask, probs, 0.0)
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
        self._set_aux_loss(probs, mask)
        return weights.astype(x.dtype)

    def _stacked_experts(self):
        return jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *list(self.experts))

    @staticmethod
    def _apply_stacked(stacked, x):
        """vmap one expert-apply over the stacked leading axis; x is
        shared across experts.  Returns [E, B, T, H]."""
        def one(tree):
            return tree(x)
        return jax.vmap(one, in_axes=(0,))(stacked)

    # -- dense path --------------------------------------------------------

    def forward(self, x):
        # reset so the telemetry never carries a stale a2a value onto a
        # path that cannot drop (comment contract at __init__)
        self.drop_rate = jnp.zeros(())
        if self.expert_mesh is not None:
            return self.forward_on_mesh(x, self.expert_mesh,
                                        self.expert_axis)
        weights = self._route(x)  # [B, T, E]
        outs = self._apply_stacked(self._stacked_experts(), x)  # [E,B,T,H]
        return jnp.einsum("ebth,bte->bth", outs, weights)

    # -- expert-parallel paths --------------------------------------------

    def _dispatch_combine(self, probs, capacity: int):
        """Capacity-bounded dispatch/combine tensors for S local tokens.

        probs [S, E] fp32 → (dispatch [S, E, C] 0/1, combine [S, E, C]).
        Slot-by-slot greedy assignment (top-1 choices claim positions
        before top-2, the Switch/GShard priority); position-in-expert by
        cumsum over the device-local token order; tokens whose position
        exceeds the capacity are dropped (their combine weight is 0 —
        the residual stream carries them unchanged)."""
        S, E = probs.shape
        top_vals, top_idx = jax.lax.top_k(probs, self.top_k)
        denom = jnp.sum(top_vals, axis=-1, keepdims=True)  # renormalize
        dispatch = jnp.zeros((S, E, capacity), jnp.float32)
        combine = jnp.zeros((S, E, capacity), jnp.float32)
        counts = jnp.zeros((E,), jnp.int32)
        kept = jnp.zeros((), jnp.float32)
        for slot in range(self.top_k):
            mask = jax.nn.one_hot(top_idx[:, slot], E,
                                  dtype=jnp.int32)       # [S, E]
            pos_e = jnp.cumsum(mask, axis=0) - mask + counts[None, :]
            pos = jnp.sum(pos_e * mask, axis=1)          # [S]
            counts = counts + jnp.sum(mask, axis=0)
            keep = (pos < capacity).astype(jnp.float32)  # overflow drop
            kept = kept + jnp.sum(keep)
            slot_hot = (mask.astype(jnp.float32)[:, :, None]
                        * jax.nn.one_hot(pos, capacity)[:, None, :]
                        * keep[:, None, None])           # [S, E, C]
            dispatch = dispatch + slot_hot
            w = (top_vals[:, slot] / denom[:, 0])
            combine = combine + slot_hot * w[:, None, None]
        # fraction of routed (token, slot) assignments that overflowed
        # this shard's per-expert capacity — the telemetry the reference
        # never needed (its MoE is single-node); exposed via
        # ``self.drop_rate`` so training loops can watch whether the
        # aux loss is balancing load well enough
        drop_rate = 1.0 - kept / (S * self.top_k)
        return dispatch, combine, drop_rate

    def forward_on_mesh(self, x, mesh: Mesh, axis: str = "expert"):
        self.drop_rate = jnp.zeros(())  # psum path cannot drop
        if self.capacity_factor is not None:
            return self._forward_a2a(x, mesh, axis, self.capacity_factor)
        return self._forward_psum(x, mesh, axis)

    def _forward_a2a(self, x, mesh: Mesh, axis: str,
                     capacity_factor: float):
        """Scalable EP: tokens sharded over the expert axis; per-device
        capacity-bounded dispatch; two all_to_all exchanges bracket the
        local expert compute.  Per-device shapes (recorded in
        the module-level ``LAST_A2A_SHAPES`` while tracing, for the memory
        test): dispatch [S, E, C], expert buffers [E, C, H] and
        [E/n, n·C, H] — all O(B·T/n), never the full batch."""
        B, T, H = x.shape
        E, k = self.num_experts, self.top_k
        n = mesh.shape[axis]
        s_total = B * T
        assert E % n == 0, (E, n)
        assert s_total % n == 0, (s_total, n)
        S = s_total // n
        capacity = max(1, int(round(capacity_factor * k * S / E)))

        # routing probs computed once, full-batch (the gate is tiny);
        # aux loss uses the pre-capacity mask exactly like the dense
        # path (per-shard top_k for dispatch happens in _dispatch_combine)
        probs = self._gate_probs(x)                   # [B, T, E]
        self._set_aux_loss(probs, self._topk_mask(probs))
        xf = x.reshape(s_total, H)
        pf = probs.reshape(s_total, E)
        stacked = self._stacked_experts()

        moe = self

        def shard_fn(stacked_local, x_loc, p_loc):
            # x_loc [S, H]; p_loc [S, E]; stacked_local leaves [E/n, ...]
            dispatch, combine, drop = moe._dispatch_combine(p_loc,
                                                            capacity)
            expert_in = jnp.einsum("sec,sh->ech", dispatch,
                                   x_loc.astype(jnp.float32))  # [E, C, H]
            expert_in = expert_in.astype(x_loc.dtype)
            # ship each device its local experts' slots from everyone
            recv = _coll.all_to_all(expert_in, axis, split_axis=0,
                                      concat_axis=1, tiled=True)
            # recv [E/n, n*C, H]
            LAST_A2A_SHAPES.update(
                dispatch=dispatch.shape, expert_in=expert_in.shape,
                recv=recv.shape)
            outs = jax.vmap(lambda tree, xe: tree(xe),
                            in_axes=(0, 0))(stacked_local, recv)
            back = _coll.all_to_all(outs, axis, split_axis=1,
                                      concat_axis=0, tiled=True)
            # back [E, C, H]
            y = jnp.einsum("sec,ech->sh", combine,
                           back.astype(jnp.float32))
            return (y.astype(x_loc.dtype),
                    _coll.pmean(drop, axis))

        fn = shard_map_compat(
            shard_fn, mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked),
                      P(axis), P(axis)),
            out_specs=(P(axis), P()))
        # pin operands replicated — see parallel.mesh.pin_replicated
        stacked = pin_replicated(stacked, mesh)
        xf = pin_replicated(xf, mesh)
        pf = pin_replicated(pf, mesh)
        y, drop = fn(stacked, xf, pf)
        self.drop_rate = jax.lax.stop_gradient(drop)
        return y.reshape(B, T, H)

    def _forward_psum(self, x, mesh: Mesh, axis: str = "expert"):
        n = mesh.shape[axis]
        assert self.num_experts % n == 0, (self.num_experts, n)
        weights = self._route(x)
        stacked = self._stacked_experts()

        def shard_fn(stacked_local, x_rep, w_rep):
            # stacked_local leaves: [E/n, ...]; w_rep [B, T, E]
            me = jax.lax.axis_index(axis)
            e_local = jax.tree_util.tree_leaves(stacked_local)[0].shape[0]
            outs = MoE._apply_stacked(stacked_local, x_rep)  # [E/n,B,T,H]
            w_local = jax.lax.dynamic_slice_in_dim(
                w_rep, me * e_local, e_local, axis=2)
            part = jnp.einsum("ebth,bte->bth", outs, w_local)
            return _coll.psum(part, axis)

        fn = shard_map_compat(
            shard_fn, mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked),
                      P(), P()),
            out_specs=P())
        stacked = pin_replicated(stacked, mesh)
        x = pin_replicated(x, mesh)
        weights = pin_replicated(weights, mesh)
        return fn(stacked, x, weights)
