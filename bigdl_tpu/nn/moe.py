"""Mixture-of-Experts with expert parallelism.

The reference's closest layer is MixtureTable (nn/MixtureTable.scala —
a gater weighting expert outputs on ONE node, no parallelism); real
expert parallelism is new TPU-first capability (SURVEY §2.6: EP absent
from the reference).

Design: top-k token routing with load-balancing auxiliary loss (the
standard Shazeer/Switch recipe).  Two execution paths:

* dense (single device / no expert axis): every expert runs over all
  tokens via ``vmap`` over stacked expert parameters; outputs combine
  with the routing weights.  O(E·T) compute — exact, used for tests and
  small E.
* expert-parallel (``forward_on_mesh``): experts are sharded over the
  ``expert`` mesh axis under shard_map; each device computes ONLY its
  local experts' contribution for all tokens and the weighted partial
  outputs are ``psum``'d over the axis.  Routing weights zero out
  non-selected experts so the psum reconstructs the exact dense result.
  (Capacity-based all_to_all dispatch is a further optimization; the
  psum formulation is exact and keeps the MXU busy at E/n experts per
  chip.)
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.core.module import Module, ModuleList, Parameter
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.utils.rng import next_key

__all__ = ["MoE"]


class MoE(Module):
    """Top-k routed mixture of experts over position-wise expert modules.

    experts: list of identical Modules mapping [..., H] -> [..., H]
    (e.g. FeedForwardNetwork).  ``forward(x)`` takes [B, T, H].
    After a forward, ``self.aux_loss`` holds the load-balancing loss
    (mean over tokens of E · Σ_e f_e · p_e) to be added to the training
    objective by the caller.
    """

    def __init__(self, hidden_size: int, experts: List[Module],
                 top_k: int = 2):
        super().__init__()
        self.hidden_size = hidden_size
        self.top_k = top_k
        self.num_experts = len(experts)
        self.experts = ModuleList(experts)
        self.gate = Linear(hidden_size, self.num_experts, with_bias=False)
        self.aux_loss = jnp.zeros(())
        self.expert_mesh = None
        self.expert_axis = "expert"

    def set_mesh(self, mesh: Mesh, axis: str = "expert") -> "MoE":
        """Route ``forward`` through the expert-parallel path on this
        mesh, so the layer composes with the Optimizer (whose jitted
        step just calls ``model.forward``)."""
        self.expert_mesh = mesh
        self.expert_axis = axis
        return self

    # -- routing -----------------------------------------------------------

    def _route(self, x):
        """Returns combine weights [B, T, E] (zero for non-top-k) and
        stores the load-balancing aux loss."""
        logits = self.gate(x)  # [B, T, E]
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        top_vals, _ = jax.lax.top_k(probs, self.top_k)
        thresh = top_vals[..., -1:]
        mask = probs >= thresh
        weights = jnp.where(mask, probs, 0.0)
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
        # Switch-style aux loss: E * Σ_e (fraction routed to e)·(mean prob e)
        frac = jnp.mean(mask.astype(jnp.float32), axis=(0, 1))
        mean_p = jnp.mean(probs, axis=(0, 1))
        self.aux_loss = self.num_experts * jnp.sum(frac * mean_p)
        return weights.astype(x.dtype)

    def _stacked_experts(self):
        return jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *list(self.experts))

    @staticmethod
    def _apply_stacked(stacked, x):
        """vmap one expert-apply over the stacked leading axis; x is
        shared across experts.  Returns [E, B, T, H]."""
        def one(tree):
            return tree(x)
        return jax.vmap(one, in_axes=(0,))(stacked)

    # -- dense path --------------------------------------------------------

    def forward(self, x):
        if self.expert_mesh is not None:
            return self.forward_on_mesh(x, self.expert_mesh,
                                        self.expert_axis)
        weights = self._route(x)  # [B, T, E]
        outs = self._apply_stacked(self._stacked_experts(), x)  # [E,B,T,H]
        return jnp.einsum("ebth,bte->bth", outs, weights)

    # -- expert-parallel path ---------------------------------------------

    def forward_on_mesh(self, x, mesh: Mesh, axis: str = "expert"):
        n = mesh.shape[axis]
        assert self.num_experts % n == 0, (self.num_experts, n)
        weights = self._route(x)
        stacked = self._stacked_experts()

        def shard_fn(stacked_local, x_rep, w_rep):
            # stacked_local leaves: [E/n, ...]; w_rep [B, T, E]
            me = jax.lax.axis_index(axis)
            e_local = jax.tree_util.tree_leaves(stacked_local)[0].shape[0]
            outs = MoE._apply_stacked(stacked_local, x_rep)  # [E/n,B,T,H]
            w_local = jax.lax.dynamic_slice_in_dim(
                w_rep, me * e_local, e_local, axis=2)
            part = jnp.einsum("ebth,bte->bth", outs, w_local)
            return jax.lax.psum(part, axis)

        fn = jax.shard_map(
            shard_fn, mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(axis), stacked),
                      P(), P()),
            out_specs=P(), check_vma=False)
        return fn(stacked, x, weights)
