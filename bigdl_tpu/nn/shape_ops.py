"""Shape / indexing / reduction layers.

Reference: nn/Reshape.scala, nn/View.scala, nn/Squeeze.scala,
nn/Unsqueeze.scala, nn/Transpose.scala, nn/Select.scala, nn/Narrow.scala,
nn/Replicate.scala, nn/Padding.scala, nn/SpatialZeroPadding.scala,
nn/Cropping2D.scala, nn/Cropping3D.scala, nn/Tile.scala,
nn/ExpandSize.scala, nn/InferReshape.scala, nn/Contiguous.scala,
nn/Index.scala, nn/MaskedSelect.scala, nn/Max.scala, nn/Min.scala,
nn/Mean.scala, nn/Sum.scala, nn/Masking.scala, nn/Pack.scala,
nn/Reverse.scala.

Dim arguments follow the reference's Torch convention: 1-based and, for
layers documented as batch-excluding, offset by the batch axis.
Negative-size (-1) inference is supported where the reference supports it.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module

__all__ = [
    "Reshape", "View", "Squeeze", "Unsqueeze", "Transpose", "Select",
    "Narrow", "Replicate", "Padding", "SpatialZeroPadding", "Cropping2D",
    "Cropping3D", "Tile", "ExpandSize", "InferReshape", "Contiguous",
    "Index", "MaskedSelect", "Max", "Min", "Mean", "Sum", "Masking",
    "Pack", "Reverse", "Flatten",
]


class Reshape(Module):
    """Reshape non-batch dims to `size`; batch dim preserved when the
    input has one more dim than `size` implies (reference nn/Reshape.scala
    batchMode semantics)."""

    def __init__(self, size: Sequence[int], batch_mode: Optional[bool] = None):
        super().__init__()
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def forward(self, x):
        n_elem = 1
        for s in self.size:
            n_elem *= s
        total = 1
        for s in x.shape:
            total *= s
        if self.batch_mode is True or (
                self.batch_mode is None and total != n_elem):
            return x.reshape((x.shape[0],) + self.size)
        return x.reshape(self.size)


class Flatten(Module):
    """Collapse all non-batch dims (keras-style convenience)."""

    def forward(self, x):
        return x.reshape((x.shape[0], -1))


class View(Module):
    """Reshape with -1 inference, batch preserved (reference nn/View.scala)."""

    def __init__(self, *sizes: int):
        super().__init__()
        if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
            sizes = tuple(sizes[0])
        self.sizes = tuple(sizes)

    def forward(self, x):
        return x.reshape((x.shape[0],) + self.sizes)


class Squeeze(Module):
    """Drop singleton dim(s) (reference nn/Squeeze.scala; 1-based dim,
    counting from the first non-batch axis when batch_mode)."""

    def __init__(self, dim: Optional[int] = None, num_input_dims: int = -1):
        super().__init__()
        self.dim = dim
        self.num_input_dims = num_input_dims

    def forward(self, x):
        if self.dim is None:
            return jnp.squeeze(x)
        d = self.dim - 1
        if self.num_input_dims > 0 and x.ndim > self.num_input_dims:
            d += x.ndim - self.num_input_dims  # batch offset
        return jnp.squeeze(x, axis=d)


class Unsqueeze(Module):
    """Insert singleton dim at pos (1-based, batch excluded per reference
    nn/Unsqueeze.scala when used inside batched models)."""

    def __init__(self, pos: int, num_input_dims: int = -1):
        super().__init__()
        self.pos = pos
        self.num_input_dims = num_input_dims

    def forward(self, x):
        d = self.pos - 1
        if self.num_input_dims > 0 and x.ndim > self.num_input_dims:
            d += x.ndim - self.num_input_dims  # batch offset
        return jnp.expand_dims(x, axis=d)


class Transpose(Module):
    """Swap listed dim pairs (1-based, reference nn/Transpose.scala)."""

    def __init__(self, permutations: Sequence[Tuple[int, int]]):
        super().__init__()
        self.permutations = tuple(tuple(p) for p in permutations)

    def forward(self, x):
        for d1, d2 in self.permutations:
            x = jnp.swapaxes(x, d1 - 1, d2 - 1)
        return x


class Select(Module):
    """Select index along dim, dropping it (reference nn/Select.scala;
    1-based dim and index; negative values count from the end)."""

    def __init__(self, dim: int, index: int):
        super().__init__()
        self.dim, self.index = dim, index

    def forward(self, x):
        dim = self.dim - 1 if self.dim > 0 else x.ndim + self.dim
        idx = self.index - 1 if self.index > 0 else x.shape[dim] + self.index
        return jax.lax.index_in_dim(x, idx, axis=dim, keepdims=False)


class Narrow(Module):
    """Slice `length` elements from `offset` along dim
    (reference nn/Narrow.scala; 1-based; negative length = until end+1+length)."""

    def __init__(self, dimension: int, offset: int, length: int = 1):
        super().__init__()
        self.dimension, self.offset, self.length = dimension, offset, length

    def forward(self, x):
        dim = self.dimension - 1 if self.dimension > 0 else x.ndim + self.dimension
        start = self.offset - 1
        length = self.length if self.length >= 0 \
            else x.shape[dim] - start + self.length + 1
        return jax.lax.slice_in_dim(x, start, start + length, axis=dim)


class Replicate(Module):
    """Insert new dim of size n_features at dim (reference nn/Replicate.scala)."""

    def __init__(self, n_features: int, dim: int = 1, n_dim: int = 2147483647):
        super().__init__()
        self.n_features, self.dim = n_features, dim

    def forward(self, x):
        y = jnp.expand_dims(x, axis=self.dim - 1)
        reps = [1] * y.ndim
        reps[self.dim - 1] = self.n_features
        return jnp.tile(y, reps)


class Padding(Module):
    """Pad `pad` entries (before if negative, after if positive) along dim
    with value (reference nn/Padding.scala)."""

    def __init__(self, dim: int, pad: int, n_input_dim: int,
                 value: float = 0.0, n_index: int = 1):
        super().__init__()
        self.dim, self.pad, self.value = dim, pad, value
        self.n_input_dim = n_input_dim

    def forward(self, x):
        dim = self.dim - 1
        if x.ndim > self.n_input_dim:
            dim += x.ndim - self.n_input_dim  # batch present
        widths = [(0, 0)] * x.ndim
        widths[dim] = (abs(self.pad), 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, widths, constant_values=self.value)


class SpatialZeroPadding(Module):
    """Zero-pad H/W of NHWC (or NCHW) images
    (reference nn/SpatialZeroPadding.scala)."""

    def __init__(self, pad_left: int, pad_right: int, pad_top: int,
                 pad_bottom: int, data_format: str = "NHWC"):
        super().__init__()
        self.pads = (pad_left, pad_right, pad_top, pad_bottom)
        self.data_format = data_format

    def forward(self, x):
        l, r, t, b = self.pads
        if self.data_format == "NHWC":
            widths = [(0, 0), (t, b), (l, r), (0, 0)]
        else:
            widths = [(0, 0), (0, 0), (t, b), (l, r)]
        if x.ndim == 3:  # unbatched
            widths = widths[1:]
        return jnp.pad(x, widths)


class Cropping2D(Module):
    """Crop H/W (reference nn/Cropping2D.scala)."""

    def __init__(self, height_crop: Tuple[int, int] = (0, 0),
                 width_crop: Tuple[int, int] = (0, 0),
                 data_format: str = "NHWC"):
        super().__init__()
        self.height_crop = tuple(height_crop)
        self.width_crop = tuple(width_crop)
        self.data_format = data_format

    def forward(self, x):
        (t, b), (l, r) = self.height_crop, self.width_crop
        if self.data_format == "NHWC":
            return x[:, t:x.shape[1] - b, l:x.shape[2] - r, :]
        return x[:, :, t:x.shape[2] - b, l:x.shape[3] - r]


class Cropping3D(Module):
    """Crop D/H/W of NDHWC volumes (reference nn/Cropping3D.scala)."""

    def __init__(self, dim1_crop=(0, 0), dim2_crop=(0, 0), dim3_crop=(0, 0),
                 data_format: str = "NDHWC"):
        super().__init__()
        self.crops = (tuple(dim1_crop), tuple(dim2_crop), tuple(dim3_crop))
        self.data_format = data_format

    def forward(self, x):
        (d1a, d1b), (d2a, d2b), (d3a, d3b) = self.crops
        if self.data_format == "NDHWC":
            return x[:, d1a:x.shape[1] - d1b, d2a:x.shape[2] - d2b,
                     d3a:x.shape[3] - d3b, :]
        return x[:, :, d1a:x.shape[2] - d1b, d2a:x.shape[3] - d2b,
                 d3a:x.shape[4] - d3b]


class Tile(Module):
    """Repeat along dim `copies` times (reference nn/Tile.scala)."""

    def __init__(self, dim: int = 1, copies: int = 2):
        super().__init__()
        self.dim, self.copies = dim, copies

    def forward(self, x):
        reps = [1] * x.ndim
        reps[self.dim - 1] = self.copies
        return jnp.tile(x, reps)


class ExpandSize(Module):
    """Broadcast singleton dims to target sizes (-1 keeps size;
    reference nn/ExpandSize.scala)."""

    def __init__(self, sizes: Sequence[int]):
        super().__init__()
        self.sizes = tuple(sizes)

    def forward(self, x):
        target = tuple(x.shape[i] if s == -1 else s
                       for i, s in enumerate(self.sizes))
        return jnp.broadcast_to(x, target)


class InferReshape(Module):
    """Reshape where -1 infers a dim and 0 copies the input dim
    (reference nn/InferReshape.scala)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False):
        super().__init__()
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def forward(self, x):
        in_shape = x.shape[1:] if self.batch_mode else x.shape
        out = []
        for i, s in enumerate(self.size):
            if s == 0:
                out.append(in_shape[i])
            else:
                out.append(s)
        if self.batch_mode:
            return x.reshape((x.shape[0],) + tuple(out))
        return x.reshape(tuple(out))


class Contiguous(Module):
    """No-op on TPU: XLA arrays have no stride aliasing
    (reference nn/Contiguous.scala)."""

    def forward(self, x):
        return x


class Index(Module):
    """Table input (tensor, indices): index along dim
    (reference nn/Index.scala; 1-based)."""

    def __init__(self, dimension: int):
        super().__init__()
        self.dimension = dimension

    def forward(self, inputs):
        x, idx = inputs
        return jnp.take(x, jnp.asarray(idx).astype(jnp.int32) - 1,
                        axis=self.dimension - 1)


class MaskedSelect(Module):
    """Table input (tensor, mask): select masked entries.  The reference
    (nn/MaskedSelect.scala) returns a dynamic-length vector; for XLA
    static shapes we return (values_where_mask_else_0, mask) when jitted
    callers need fixed shapes, or the compacted vector in eager mode."""

    def forward(self, inputs):
        x, mask = inputs
        mask = mask.astype(bool)
        try:
            return x[mask]  # eager path: dynamic shape ok
        except jax.errors.ConcretizationTypeError:
            return jnp.where(mask, x, 0)


class Max(Module):
    """Max along dim, optionally returning values only
    (reference nn/Max.scala; 1-based, num_input_dims for batch offset)."""

    def __init__(self, dim: int = 1, num_input_dims: int = -1):
        super().__init__()
        self.dim = dim
        self.num_input_dims = num_input_dims

    def _axis(self, x):
        d = self.dim - 1
        if self.num_input_dims > 0 and x.ndim > self.num_input_dims:
            d += x.ndim - self.num_input_dims
        return d

    def forward(self, x):
        return jnp.max(x, axis=self._axis(x))


class Min(Max):
    def forward(self, x):
        return jnp.min(x, axis=self._axis(x))


class Mean(Module):
    """Mean along dim (reference nn/Mean.scala; 1-based, squeeze option)."""

    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 squeeze: bool = True):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.squeeze = squeeze

    def forward(self, x):
        d = self.dimension - 1
        if self.n_input_dims > 0 and x.ndim > self.n_input_dims:
            d += x.ndim - self.n_input_dims
        return jnp.mean(x, axis=d, keepdims=not self.squeeze)


class Sum(Module):
    """Sum along dim (reference nn/Sum.scala)."""

    def __init__(self, dimension: int = 1, n_input_dims: int = -1,
                 size_average: bool = False, squeeze: bool = True):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims
        self.size_average = size_average
        self.squeeze = squeeze

    def forward(self, x):
        d = self.dimension - 1
        if self.n_input_dims > 0 and x.ndim > self.n_input_dims:
            d += x.ndim - self.n_input_dims
        if self.size_average:
            return jnp.mean(x, axis=d, keepdims=not self.squeeze)
        return jnp.sum(x, axis=d, keepdims=not self.squeeze)


class Masking(Module):
    """Zero out timesteps equal to mask_value in all features
    (reference nn/Masking.scala)."""

    def __init__(self, mask_value: float = 0.0):
        super().__init__()
        self.mask_value = float(mask_value)

    def forward(self, x):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return x * keep.astype(x.dtype)


class Pack(Module):
    """Stack a table of tensors along a new dim (reference nn/Pack.scala)."""

    def __init__(self, dimension: int = 1):
        super().__init__()
        self.dimension = dimension

    def forward(self, xs):
        return jnp.stack(list(xs), axis=self.dimension - 1)


class Reverse(Module):
    """Reverse along dim (reference nn/Reverse.scala)."""

    def __init__(self, dimension: int = 1, is_inplace: bool = False):
        super().__init__()
        self.dimension = dimension

    def forward(self, x):
        return jnp.flip(x, axis=self.dimension - 1)
