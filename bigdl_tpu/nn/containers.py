"""Structural containers.

Reference: nn/Container.scala, nn/Sequential.scala, nn/Concat.scala,
nn/ConcatTable.scala, nn/ParallelTable.scala, nn/MapTable.scala,
nn/Bottle.scala, nn/Graph.scala (+ StaticGraph topo-sorted execution,
nn/StaticGraph.scala:44) and utils/DirectedGraph.scala.

A "Table" activity in the reference maps to a Python tuple/list (any JAX
pytree is a valid activity here).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, ModuleList

__all__ = [
    "Container", "Sequential", "Concat", "ConcatTable", "ParallelTable",
    "MapTable", "Bottle", "Node", "Input", "Graph", "Module", "ModuleList",
]


class Container(Module):
    """Base composite module (reference nn/Container.scala)."""

    def __init__(self, *modules: Module):
        super().__init__()
        self.layers = ModuleList(list(modules))

    def add(self, module: Module) -> "Container":
        self.layers.append(module)
        return self

    def __len__(self):
        return len(self.layers)

    def __getitem__(self, i) -> Module:
        return self.layers[i]


class Sequential(Container):
    """Chain modules (reference nn/Sequential.scala)."""

    def forward(self, x):
        for m in self.layers:
            x = m(x)
        return x


class Concat(Container):
    """Apply each branch to the same input and concatenate the outputs
    along `dimension` (reference nn/Concat.scala; dimension is 1-based
    counting the batch dim, Torch convention)."""

    def __init__(self, dimension: int, *modules: Module):
        super().__init__(*modules)
        self.dimension = dimension

    def forward(self, x):
        outs = [m(x) for m in self.layers]
        return jnp.concatenate(outs, axis=self.dimension - 1)


class ConcatTable(Container):
    """Apply each branch to the same input, return the tuple of outputs
    (reference nn/ConcatTable.scala)."""

    def forward(self, x):
        return tuple(m(x) for m in self.layers)


class ParallelTable(Container):
    """Apply i-th module to i-th element of the input table
    (reference nn/ParallelTable.scala)."""

    def forward(self, xs):
        return tuple(m(x) for m, x in zip(self.layers, xs))


class MapTable(Container):
    """Apply one shared module to every element of the input table
    (reference nn/MapTable.scala)."""

    def __init__(self, module: Module):
        super().__init__(module)

    def forward(self, xs):
        m = self.layers[0]
        return tuple(m(x) for x in xs)


class Bottle(Container):
    """Collapse leading dims, apply module, restore
    (reference nn/Bottle.scala)."""

    def __init__(self, module: Module, n_input_dim: int = 2,
                 n_output_dim: int = 2):
        super().__init__(module)
        self.n_input_dim = n_input_dim
        self.n_output_dim = n_output_dim

    def forward(self, x):
        lead = x.shape[:x.ndim - self.n_input_dim + 1]
        flat = x.reshape((-1,) + x.shape[x.ndim - self.n_input_dim + 1:])
        y = self.layers[0](flat)
        return y.reshape(lead + y.shape[1:])


# --------------------------------------------------------------------------
# Graph (functional DAG, reference nn/Graph.scala + StaticGraph)
# --------------------------------------------------------------------------

class Node:
    """Graph node wrapping a module; calling a module on nodes builds
    edges (reference utils/Node + the `inputs` DSL of nn/Graph.scala)."""

    _counter = [0]

    def __init__(self, module: Optional[Module]):
        self.module = module
        self.prev: List["Node"] = []
        Node._counter[0] += 1
        self.id = Node._counter[0]

    def __repr__(self):
        m = self.module.name if self.module else "Input"
        return f"Node[{self.id}]({m})"


def Input() -> Node:
    """Placeholder input node (reference nn/Input.scala)."""
    return Node(None)


def node_of(module: Module, *inputs: Node) -> Node:
    n = Node(module)
    n.prev = list(inputs)
    return n


class Graph(Module):
    """DAG container executed in topological order (reference
    nn/Graph.scala:403 topologySort; StaticGraph.scala:44 pre-computed
    execution order).  Under jit, execution order is baked into the
    trace, so this is exactly the reference StaticGraph semantics."""

    # Node objects are build-time scaffolding; execution state lives in
    # the id tuples + graph_modules, so persistence skips them
    serialize_skip_static = ("input_nodes", "output_nodes")

    def __init__(self, inputs: Union[Node, Sequence[Node]],
                 outputs: Union[Node, Sequence[Node]]):
        super().__init__()
        self.input_nodes = [inputs] if isinstance(inputs, Node) else list(inputs)
        self.output_nodes = ([outputs] if isinstance(outputs, Node)
                             else list(outputs))
        order = self._topo_sort()
        self.exec_order = tuple(n.id for n in order)
        self.node_prevs = tuple(tuple(p.id for p in n.prev) for n in order)
        self.input_ids = tuple(n.id for n in self.input_nodes)
        self.output_ids = tuple(n.id for n in self.output_nodes)
        self.graph_modules = ModuleList(
            [n.module for n in order if n.module is not None])
        self.module_node_ids = tuple(
            n.id for n in order if n.module is not None)

    def _topo_sort(self) -> List[Node]:
        visited: Dict[int, Node] = {}
        order: List[Node] = []
        temp = set()

        def visit(n: Node):
            if n.id in visited:
                return
            if n.id in temp:
                raise ValueError("Graph has a cycle")
            temp.add(n.id)
            for p in n.prev:
                visit(p)
            temp.discard(n.id)
            visited[n.id] = n
            order.append(n)

        for out in self.output_nodes:
            visit(out)
        for inp in self.input_nodes:
            if inp.id not in visited:
                raise ValueError(
                    f"Input node {inp} is not connected to any output")
        return order

    def forward(self, *xs):
        if len(xs) == 1 and isinstance(xs[0], (tuple, list)) \
                and len(self.input_ids) > 1:
            xs = tuple(xs[0])
        if len(xs) != len(self.input_ids):
            raise ValueError(
                f"Graph expects {len(self.input_ids)} input(s), "
                f"got {len(xs)}")
        values: Dict[int, object] = {}
        for nid, x in zip(self.input_ids, xs):
            values[nid] = x
        mod_for_node = dict(zip(self.module_node_ids, self.graph_modules))
        for nid, prevs in zip(self.exec_order, self.node_prevs):
            if nid in values and not prevs:
                continue  # input node
            args = [values[p] for p in prevs]
            m = mod_for_node[nid]
            # multi-input nodes receive a Table (tuple), reference
            # nn/Graph.scala input gathering
            values[nid] = m.forward(args[0]) if len(args) == 1 \
                else m.forward(tuple(args))
        outs = tuple(values[o] for o in self.output_ids)
        return outs[0] if len(outs) == 1 else outs


# -- structural aliases ------------------------------------------------------
# The reference's execution-machinery split collapses under XLA:
# * BaseModule (nn/BaseModule.scala) is "a module defined by an internal
#   built graph" — any Module here can hold a Graph attribute;
# * DynamicContainer (nn/DynamicContainer.scala) is the add()-accepting
#   container base — Container already is one;
# * DynamicGraph (nn/DynamicGraph.scala) executes graphs with a
#   Scheduler/FrameManager for control-flow ops — control flow compiles
#   to lax.cond/while_loop inside a static Graph (see ops/control.py and
#   the TF while-frame importer), so the static executor serves both.
BaseModule = Module
DynamicContainer = Container
DynamicGraph = Graph

__all__ += ["BaseModule", "DynamicContainer", "DynamicGraph"]
