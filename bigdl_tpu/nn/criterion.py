"""Loss criterions.

Reference: nn/abstractnn/AbstractCriterion.scala plus the criterion zoo
(ClassNLLCriterion.scala, CrossEntropyCriterion.scala, MSECriterion.scala,
BCECriterion.scala, …).  ``forward(input, target)`` returns a scalar;
gradients come from jax.grad (no hand-written updateGradInput needed).

Class targets follow the reference's Torch convention: 1-based class
indices.  Criterions accept ``size_average`` where the reference does.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, ModuleList

__all__ = [
    "Criterion", "ClassNLLCriterion", "CrossEntropyCriterion",
    "CategoricalCrossEntropy", "BCECriterion", "MSECriterion",
    "AbsCriterion", "SmoothL1Criterion", "DistKLDivCriterion",
    "KLDCriterion", "GaussianCriterion", "CosineEmbeddingCriterion",
    "HingeEmbeddingCriterion", "MarginCriterion", "MarginRankingCriterion",
    "MultiCriterion", "ParallelCriterion", "MultiLabelMarginCriterion",
    "MultiLabelSoftMarginCriterion", "MultiMarginCriterion",
    "SoftMarginCriterion", "L1HingeEmbeddingCriterion",
    "CosineDistanceCriterion", "CosineProximityCriterion",
    "DotProductCriterion", "PoissonCriterion", "MeanAbsolutePercentageCriterion",
    "MeanSquaredLogarithmicCriterion", "KullbackLeiblerDivergenceCriterion",
    "ClassSimplexCriterion", "L1Cost", "DiceCoefficientCriterion",
    "PGCriterion", "TimeDistributedCriterion", "TransformerCriterion",
    "TimeDistributedMaskCriterion",
]


class Criterion(Module):
    """Base criterion (reference nn/abstractnn/AbstractCriterion.scala).
    forward(input, target) -> scalar loss."""

    def forward(self, input, target):
        raise NotImplementedError

    def __call__(self, input, target=None):
        return self.forward(input, target)

    def backward(self, input, target):
        """grad of loss w.r.t. input (reference updateGradInput)."""
        return jax.grad(lambda x: self.forward(x, target))(input)


def _reduce(x, size_average: bool):
    return jnp.mean(x) if size_average else jnp.sum(x)


def _one_based(target):
    return jnp.asarray(target).astype(jnp.int32) - 1


class ClassNLLCriterion(Criterion):
    """NLL over log-probabilities with 1-based class targets and
    optional class weights; paddingValue rows contribute zero
    (reference nn/ClassNLLCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True,
                 logProbAsInput: bool = True, paddingValue: int = -1):
        super().__init__()
        self.size_average = size_average
        self.log_prob_as_input = logProbAsInput
        self.padding_value = paddingValue
        if weights is not None:
            self.class_weights = jnp.asarray(weights)

    def forward(self, input, target):
        logp = input if self.log_prob_as_input else jnp.log(input + 1e-8)
        t = jnp.asarray(target).astype(jnp.int32)
        idx = jnp.clip(t - 1, 0, logp.shape[-1] - 1)
        picked = jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]
        valid = (t != self.padding_value).astype(logp.dtype)
        if "class_weights" in self._buffers:
            w = self.class_weights[idx] * valid
        else:
            w = valid
        total = -jnp.sum(picked * w)
        if self.size_average:
            return total / jnp.maximum(jnp.sum(w), 1e-8)
        return total


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (reference nn/CrossEntropyCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.inner = ClassNLLCriterion(weights, size_average)

    def forward(self, input, target):
        return self.inner(jax.nn.log_softmax(input, axis=-1), target)


class CategoricalCrossEntropy(Criterion):
    """Cross entropy with one-hot targets over probabilities
    (reference nn/CategoricalCrossEntropy.scala)."""

    def forward(self, input, target):
        logp = jnp.log(jnp.clip(input, 1e-8, 1.0))
        return -jnp.mean(jnp.sum(target * logp, axis=-1))


class BCECriterion(Criterion):
    """Binary cross entropy on probabilities, optional per-element weights
    (reference nn/BCECriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.size_average = size_average
        if weights is not None:
            self.elem_weights = jnp.asarray(weights)

    def forward(self, input, target):
        eps = 1e-12
        p = jnp.clip(input, eps, 1 - eps)
        ll = target * jnp.log(p) + (1 - target) * jnp.log1p(-p)
        if "elem_weights" in self._buffers:
            ll = ll * self.elem_weights
        return _reduce(-ll, self.size_average)


class MSECriterion(Criterion):
    """(reference nn/MSECriterion.scala)"""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def forward(self, input, target):
        return _reduce((input - target) ** 2, self.size_average)


class AbsCriterion(Criterion):
    """(reference nn/AbsCriterion.scala)"""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def forward(self, input, target):
        return _reduce(jnp.abs(input - target), self.size_average)


class SmoothL1Criterion(Criterion):
    """Huber with delta=1 (reference nn/SmoothL1Criterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def forward(self, input, target):
        d = jnp.abs(input - target)
        loss = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return _reduce(loss, self.size_average)


class DistKLDivCriterion(Criterion):
    """KL(target || input) with input = log-probs
    (reference nn/DistKLDivCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def forward(self, input, target):
        pointwise = target * (jnp.log(jnp.clip(target, 1e-12, None)) - input)
        pointwise = jnp.where(target > 0, pointwise, 0.0)
        # reference divides by nElement() (DistKLDivCriterion.scala:51),
        # not by batch size
        return jnp.mean(pointwise) if self.size_average \
            else jnp.sum(pointwise)


class KLDCriterion(Criterion):
    """KL(N(mu, sigma) || N(0,1)) from (mean, log_var) table — VAE loss
    (reference nn/KLDCriterion.scala)."""

    def forward(self, input, target=None):
        mean, log_var = input
        return 0.5 * jnp.sum(mean ** 2 + jnp.exp(log_var) - log_var - 1.0)


class GaussianCriterion(Criterion):
    """Negative log-likelihood of target under N(mean, exp(log_var))
    (reference nn/GaussianCriterion.scala)."""

    def forward(self, input, target):
        mean, log_var = input
        return 0.5 * jnp.sum(
            log_var + (target - mean) ** 2 / jnp.exp(log_var)
            + jnp.log(2 * jnp.pi))


class CosineEmbeddingCriterion(Criterion):
    """(reference nn/CosineEmbeddingCriterion.scala): y=1 → 1-cos,
    y=-1 → max(0, cos - margin)."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__()
        self.margin = float(margin)
        self.size_average = size_average

    def forward(self, input, target):
        x1, x2 = input
        y = target.reshape(-1) if hasattr(target, "reshape") else target
        cos = jnp.sum(x1 * x2, -1) / (
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1)
            + 1e-12)
        loss = jnp.where(y > 0, 1.0 - cos,
                         jnp.maximum(0.0, cos - self.margin))
        return _reduce(loss, self.size_average)


class HingeEmbeddingCriterion(Criterion):
    """(reference nn/HingeEmbeddingCriterion.scala)"""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = float(margin)
        self.size_average = size_average

    def forward(self, input, target):
        loss = jnp.where(target > 0, input,
                         jnp.maximum(0.0, self.margin - input))
        return _reduce(loss, self.size_average)


class L1HingeEmbeddingCriterion(Criterion):
    """Hinge on L1 distance of a pair (reference
    nn/L1HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0):
        super().__init__()
        self.margin = float(margin)

    def forward(self, input, target):
        x1, x2 = input
        d = jnp.sum(jnp.abs(x1 - x2), axis=-1)
        loss = jnp.where(target > 0, d, jnp.maximum(0.0, self.margin - d))
        return jnp.sum(loss)


class MarginCriterion(Criterion):
    """Hinge loss max(0, margin - y*x); squared variant for L2-SVM
    (reference nn/MarginCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True,
                 squared: bool = False):
        super().__init__()
        self.margin = float(margin)
        self.size_average = size_average
        self.squared = squared

    def forward(self, input, target):
        h = jnp.maximum(0.0, self.margin - input * target)
        if self.squared:
            h = h * h
        return _reduce(h, self.size_average)


class MarginRankingCriterion(Criterion):
    """max(0, -y*(x1-x2) + margin) (reference nn/MarginRankingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin = float(margin)
        self.size_average = size_average

    def forward(self, input, target):
        x1, x2 = input
        loss = jnp.maximum(0.0, -target * (x1 - x2) + self.margin)
        return _reduce(loss, self.size_average)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target)
    (reference nn/MultiCriterion.scala)."""

    def __init__(self):
        super().__init__()
        self.crits = ModuleList([])
        self.crit_weights = ()

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.crits.append(criterion)
        self.crit_weights = self.crit_weights + (float(weight),)
        return self

    def forward(self, input, target):
        total = 0.0
        for c, w in zip(self.crits, self.crit_weights):
            total = total + w * c(input, target)
        return total


class ParallelCriterion(Criterion):
    """i-th criterion applied to i-th (input, target) pair
    (reference nn/ParallelCriterion.scala)."""

    def __init__(self, repeat_target: bool = False):
        super().__init__()
        self.crits = ModuleList([])
        self.crit_weights = ()
        self.repeat_target = repeat_target

    def add(self, criterion: Criterion, weight: float = 1.0):
        self.crits.append(criterion)
        self.crit_weights = self.crit_weights + (float(weight),)
        return self

    def forward(self, input, target):
        total = 0.0
        for i, (c, w) in enumerate(zip(self.crits, self.crit_weights)):
            t = target if self.repeat_target else target[i]
            total = total + w * c(input[i], t)
        return total


class MultiLabelMarginCriterion(Criterion):
    """Multi-class multi-label hinge (reference
    nn/MultiLabelMarginCriterion.scala).  Targets: 1-based label indices
    padded with 0."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def forward(self, input, target):
        t = jnp.asarray(target).astype(jnp.int32)
        # labels stop at the first 0 pad (torch/reference semantics):
        # everything at or after the first zero is invalid
        valid = jnp.cumprod((t > 0).astype(input.dtype), axis=-1)  # [..., J]
        idx = jnp.clip(t - 1, 0, input.shape[-1] - 1)
        target_scores = jnp.take_along_axis(input, idx, axis=-1)  # [..., J]
        # per-class membership mask: 1 where class is one of the targets
        is_target = jnp.clip(
            jnp.sum(jax.nn.one_hot(idx, input.shape[-1])
                    * valid[..., None], axis=-2), 0, 1)           # [..., C]
        margins = jnp.maximum(
            0.0, 1.0 - (target_scores[..., :, None] - input[..., None, :]))
        loss = jnp.sum(
            margins * valid[..., :, None] * (1.0 - is_target)[..., None, :],
            axis=(-1, -2)) / input.shape[-1]
        return _reduce(loss, self.size_average)


class MultiLabelSoftMarginCriterion(Criterion):
    """Sigmoid BCE per label (reference nn/MultiLabelSoftMarginCriterion.scala)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.size_average = size_average
        if weights is not None:
            self.label_weights = jnp.asarray(weights)

    def forward(self, input, target):
        ll = target * jax.nn.log_sigmoid(input) \
            + (1 - target) * jax.nn.log_sigmoid(-input)
        if "label_weights" in self._buffers:
            ll = ll * self.label_weights
        loss = -jnp.mean(ll, axis=-1)
        return _reduce(loss, self.size_average)


class MultiMarginCriterion(Criterion):
    """Multi-class hinge (reference nn/MultiMarginCriterion.scala)."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True):
        super().__init__()
        self.p = p
        self.margin = float(margin)
        self.size_average = size_average
        if weights is not None:
            self.class_weights = jnp.asarray(weights)

    def forward(self, input, target):
        idx = _one_based(target)
        correct = jnp.take_along_axis(input, idx[..., None], axis=-1)
        m = jnp.maximum(0.0, self.margin - (correct - input))
        if self.p == 2:
            m = m * m
        mask = 1.0 - jax.nn.one_hot(idx, input.shape[-1])
        loss = jnp.sum(m * mask, axis=-1) / input.shape[-1]
        if "class_weights" in self._buffers:
            loss = loss * self.class_weights[idx]
        return _reduce(loss, self.size_average)


class SoftMarginCriterion(Criterion):
    """log(1 + exp(-y*x)) (reference nn/SoftMarginCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def forward(self, input, target):
        return _reduce(jax.nn.softplus(-input * target), self.size_average)


class CosineDistanceCriterion(Criterion):
    """1 - cos(input, target) (reference nn/CosineDistanceCriterion.scala)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def forward(self, input, target):
        cos = jnp.sum(input * target, -1) / (
            jnp.linalg.norm(input, axis=-1)
            * jnp.linalg.norm(target, axis=-1) + 1e-12)
        return _reduce(1.0 - cos, self.size_average)


class CosineProximityCriterion(Criterion):
    """-mean(cos) keras-style (reference nn/CosineProximityCriterion.scala)."""

    def forward(self, input, target):
        xn = input / (jnp.linalg.norm(input, axis=-1, keepdims=True) + 1e-12)
        tn = target / (jnp.linalg.norm(target, axis=-1, keepdims=True) + 1e-12)
        return -jnp.mean(jnp.sum(xn * tn, axis=-1))


class DotProductCriterion(Criterion):
    """-sum(x*y) (reference nn/DotProductCriterion.scala; policy gradient)."""

    def __init__(self, size_average: bool = False):
        super().__init__()
        self.size_average = size_average

    def forward(self, input, target):
        return -_reduce(input * target, self.size_average)


class PoissonCriterion(Criterion):
    """Poisson NLL: mean(pred - target*log(pred))
    (reference nn/PoissonCriterion.scala)."""

    def forward(self, input, target):
        return jnp.mean(input - target * jnp.log(input + 1e-8))


class MeanAbsolutePercentageCriterion(Criterion):
    """100 * mean(|t-p| / clip(|t|)) (reference
    nn/MeanAbsolutePercentageCriterion.scala)."""

    def forward(self, input, target):
        diff = jnp.abs(target - input) / jnp.clip(jnp.abs(target), 1e-7, None)
        return 100.0 * jnp.mean(diff)


class MeanSquaredLogarithmicCriterion(Criterion):
    """mean((log(t+1)-log(p+1))^2) (reference
    nn/MeanSquaredLogarithmicCriterion.scala)."""

    def forward(self, input, target):
        a = jnp.log(jnp.clip(input, 1e-7, None) + 1.0)
        b = jnp.log(jnp.clip(target, 1e-7, None) + 1.0)
        return jnp.mean((a - b) ** 2)


class KullbackLeiblerDivergenceCriterion(Criterion):
    """sum(t * log(t/p)) over clipped probs (reference
    nn/KullbackLeiblerDivergenceCriterion.scala)."""

    def forward(self, input, target):
        p = jnp.clip(input, 1e-7, 1.0)
        t = jnp.clip(target, 1e-7, 1.0)
        return jnp.mean(jnp.sum(t * jnp.log(t / p), axis=-1))


class ClassSimplexCriterion(Criterion):
    """MSE against simplex-embedded class targets
    (reference nn/ClassSimplexCriterion.scala)."""

    def __init__(self, n_classes: int):
        super().__init__()
        self.n_classes = n_classes
        # build simplex embedding (Huffman-like construction)
        import numpy as np
        n = n_classes
        mat = np.zeros((n, n), dtype=np.float32)
        mat[0, 0] = 1.0
        for k in range(1, n):
            s = 0.0
            for j in range(k):
                mat[k, j] = (-1.0 / n - np.dot(mat[k], mat[j])) / mat[j, j]
                s += mat[k, j] ** 2
            mat[k, k] = np.sqrt(max(1.0 - s, 0.0))
        self.simplex = jnp.asarray(mat)

    def forward(self, input, target):
        t = self.simplex[_one_based(target)]
        return jnp.mean(jnp.sum((input - t) ** 2, axis=-1))


class L1Cost(Criterion):
    """sum(|x|) ignoring target (reference nn/L1Cost.scala)."""

    def forward(self, input, target=None):
        return jnp.sum(jnp.abs(input))


class DiceCoefficientCriterion(Criterion):
    """1 - dice overlap (reference nn/DiceCoefficientCriterion.scala)."""

    def __init__(self, size_average: bool = True, epsilon: float = 1.0):
        super().__init__()
        self.epsilon = float(epsilon)

    def forward(self, input, target):
        axes = tuple(range(1, input.ndim))
        inter = jnp.sum(input * target, axis=axes)
        union = jnp.sum(input, axis=axes) + jnp.sum(target, axis=axes)
        dice = (2.0 * inter + self.epsilon) / (union + self.epsilon)
        return jnp.mean(1.0 - dice)


class PGCriterion(Criterion):
    """Policy-gradient criterion: -sum(log(p) * reward)
    (reference nn/PGCriterion.scala)."""

    def __init__(self, sizeAverage: bool = False):
        super().__init__()
        self.size_average = sizeAverage

    def forward(self, input, target):
        logp = jnp.log(jnp.clip(input, 1e-8, 1.0))
        return -_reduce(logp * target, self.size_average)


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at every timestep of [batch, time, ...]
    (reference nn/TimeDistributedCriterion.scala)."""

    def __init__(self, critrn: Criterion, size_average: bool = False,
                 dimension: int = 2):
        super().__init__()
        self.critrn = critrn
        self.size_average = size_average
        self.dimension = dimension

    def forward(self, input, target):
        t_axis = self.dimension - 1
        n = input.shape[t_axis]
        # apply the inner criterion per timestep (vmap over the time axis)
        # and sum, exactly the reference's updateOutput loop; sizeAverage
        # divides the summed loss by nstep.
        x = jnp.moveaxis(input, t_axis, 0)
        t = jnp.asarray(target)
        t = jnp.moveaxis(t, t_axis, 0) if t.ndim > 1 else \
            jnp.broadcast_to(t, (n,) + t.shape)
        losses = jax.vmap(lambda xi, ti: self.critrn(xi, ti))(x, t)
        total = jnp.sum(losses)
        return total / n if self.size_average else total


class TimeDistributedMaskCriterion(TimeDistributedCriterion):
    """Masked variant (reference nn/TimeDistributedMaskCriterion.scala);
    padding handled by the inner criterion's paddingValue."""


class TransformerCriterion(Criterion):
    """Apply transforms to input/target before an inner criterion
    (reference nn/TransformerCriterion.scala)."""

    def __init__(self, criterion: Criterion,
                 input_transformer: Optional[Module] = None,
                 target_transformer: Optional[Module] = None):
        super().__init__()
        self.criterion = criterion
        if input_transformer is not None:
            self.input_transformer = input_transformer
        if target_transformer is not None:
            self.target_transformer = target_transformer

    def forward(self, input, target):
        if "input_transformer" in self._modules:
            input = self.input_transformer.forward(input)
        if "target_transformer" in self._modules:
            target = self.target_transformer.forward(target)
        return self.criterion(input, target)
