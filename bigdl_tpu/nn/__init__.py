"""bigdl_tpu.nn — the layer & criterion zoo.

TPU-native re-design of the reference's nn/ package (234 Torch-style
layers, spark/dl/.../nn/).  Every public class mirrors a reference layer
by name and semantics; docstrings cite the Scala file they correspond to.
"""

from bigdl_tpu.nn.activation import *      # noqa: F401,F403
from bigdl_tpu.nn.linear import *          # noqa: F401,F403
from bigdl_tpu.nn.containers import *      # noqa: F401,F403
from bigdl_tpu.nn.shape_ops import *       # noqa: F401,F403
from bigdl_tpu.nn.table_ops import *       # noqa: F401,F403
from bigdl_tpu.nn.conv import *            # noqa: F401,F403
from bigdl_tpu.nn.pooling import *         # noqa: F401,F403
from bigdl_tpu.nn.normalization import *   # noqa: F401,F403
from bigdl_tpu.nn.regularization import *  # noqa: F401,F403
from bigdl_tpu.nn.criterion import *       # noqa: F401,F403
from bigdl_tpu.nn.rnn import *             # noqa: F401,F403
from bigdl_tpu.nn.attention import *       # noqa: F401,F403
from bigdl_tpu.nn.moe import *             # noqa: F401,F403
from bigdl_tpu.nn.quantized import *       # noqa: F401,F403
from bigdl_tpu.nn.detection import *       # noqa: F401,F403
from bigdl_tpu.nn.sparse import *          # noqa: F401,F403
from bigdl_tpu.nn.tree import *            # noqa: F401,F403
