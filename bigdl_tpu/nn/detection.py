"""Object-detection layer stack: anchors, NMS, RoiAlign, FPN, RPN,
Box/Mask heads, SSD PriorBox + DetectionOutput.

Reference: nn/Anchor.scala, nn/Nms.scala, nn/RoiAlign.scala:45,
nn/RoiPooling.scala, nn/FPN.scala:41, nn/Pooler.scala:33,
nn/RegionProposal.scala:40, nn/BoxHead.scala:30, nn/MaskHead.scala:24,
nn/PriorBox.scala:42, nn/DetectionOutputSSD.scala:49,
nn/DetectionOutputFrcnn.scala, nn/Proposal.scala,
nn/SmoothL1CriterionWithWeights.scala, nn/SoftmaxWithCriterion.scala,
transform/vision/image/util/BboxUtil.scala.

TPU-first design notes
----------------------
The reference implements these with data-dependent Scala loops (variable
numbers of surviving boxes, per-ROI scalar loops).  That shape dynamism
would force recompilation or host round-trips under XLA, so everything
here is re-designed around *static shapes + validity masks*:

* :func:`nms` keeps a fixed ``max_output`` slots and returns
  ``(indices, valid)``; suppression runs as a ``lax.fori_loop`` over the
  score-sorted IoU matrix (vector ops per step, no dynamic shapes).
* :class:`RoiAlign` is a vectorised bilinear gather over a static
  ``(pooled_h, pooled_w, sampling, sampling)`` sample grid — the MXU-free
  parts (gathers) batch over all ROIs at once instead of per-ROI loops.
* Boxes use corner format ``(x1, y1, x2, y2)``; padded/invalid slots carry
  zero boxes and ``-inf``/zero scores so downstream masked ops stay exact.

Everything is jittable; nothing here leaves the device.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.module import Module, ModuleList, Parameter
from bigdl_tpu.core import init as init_methods
from bigdl_tpu.nn.conv import (SpatialConvolution,
                               SpatialDilatedConvolution,
                               SpatialFullConvolution)
from bigdl_tpu.nn.linear import Linear

def _group_norm(n_out):
    # deferred import: normalization.py sits later in nn/__init__
    from bigdl_tpu.nn.normalization import GroupNorm
    return GroupNorm(n_out)


__all__ = [
    "Anchor", "Nms", "nms", "box_iou", "bbox_transform_inv", "bbox_encode",
    "clip_boxes", "RoiAlign", "RoiPooling", "FPN", "Pooler",
    "RegionProposal", "Proposal", "BoxHead", "MaskHead", "PriorBox",
    "DetectionOutputSSD", "DetectionOutputFrcnn",
    "SmoothL1CriterionWithWeights", "SoftmaxWithCriterion",
]


# --------------------------------------------------------------------------
# Box utilities (reference transform/vision/image/util/BboxUtil.scala)
# --------------------------------------------------------------------------

def box_iou(a, b):
    """Pairwise IoU between ``a: (N, 4)`` and ``b: (M, 4)`` corner boxes."""
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def bbox_transform_inv(boxes, deltas,
                       weights=(1.0, 1.0, 1.0, 1.0),
                       clip_h: float = math.log(1000.0 / 16)):
    """Decode regression ``deltas (N, 4)`` against anchor ``boxes (N, 4)``
    (reference BboxUtil.bboxTransformInv)."""
    wx, wy, ww, wh = weights
    widths = boxes[:, 2] - boxes[:, 0] + 1.0
    heights = boxes[:, 3] - boxes[:, 1] + 1.0
    ctr_x = boxes[:, 0] + 0.5 * widths
    ctr_y = boxes[:, 1] + 0.5 * heights
    dx, dy, dw, dh = (deltas[:, 0] / wx, deltas[:, 1] / wy,
                      deltas[:, 2] / ww, deltas[:, 3] / wh)
    dw = jnp.minimum(dw, clip_h)
    dh = jnp.minimum(dh, clip_h)
    pred_ctr_x = dx * widths + ctr_x
    pred_ctr_y = dy * heights + ctr_y
    pred_w = jnp.exp(dw) * widths
    pred_h = jnp.exp(dh) * heights
    return jnp.stack([
        pred_ctr_x - 0.5 * pred_w,
        pred_ctr_y - 0.5 * pred_h,
        pred_ctr_x + 0.5 * pred_w - 1.0,
        pred_ctr_y + 0.5 * pred_h - 1.0,
    ], axis=1)


def bbox_encode(ex_boxes, gt_boxes, weights=(1.0, 1.0, 1.0, 1.0)):
    """Inverse of :func:`bbox_transform_inv` (training targets)."""
    wx, wy, ww, wh = weights
    ex_w = ex_boxes[:, 2] - ex_boxes[:, 0] + 1.0
    ex_h = ex_boxes[:, 3] - ex_boxes[:, 1] + 1.0
    ex_cx = ex_boxes[:, 0] + 0.5 * ex_w
    ex_cy = ex_boxes[:, 1] + 0.5 * ex_h
    gt_w = gt_boxes[:, 2] - gt_boxes[:, 0] + 1.0
    gt_h = gt_boxes[:, 3] - gt_boxes[:, 1] + 1.0
    gt_cx = gt_boxes[:, 0] + 0.5 * gt_w
    gt_cy = gt_boxes[:, 1] + 0.5 * gt_h
    return jnp.stack([
        wx * (gt_cx - ex_cx) / ex_w,
        wy * (gt_cy - ex_cy) / ex_h,
        ww * jnp.log(gt_w / ex_w),
        wh * jnp.log(gt_h / ex_h),
    ], axis=1)


def clip_boxes(boxes, height: float, width: float):
    """Clip corner boxes into ``[0, w-1] x [0, h-1]``."""
    x1 = jnp.clip(boxes[:, 0], 0, width - 1)
    y1 = jnp.clip(boxes[:, 1], 0, height - 1)
    x2 = jnp.clip(boxes[:, 2], 0, width - 1)
    y2 = jnp.clip(boxes[:, 3], 0, height - 1)
    return jnp.stack([x1, y1, x2, y2], axis=1)


# --------------------------------------------------------------------------
# NMS (reference nn/Nms.scala — serial greedy loop → masked fori_loop)
# --------------------------------------------------------------------------

def nms(boxes, scores, iou_threshold: float, max_output: int,
        pre_topk: Optional[int] = None):
    """Greedy NMS with static output size.

    Returns ``(indices, valid)`` where ``indices: (max_output,) int32``
    point into the input arrays (score-descending) and ``valid`` is a
    boolean mask.  Invalid slots repeat index 0 with ``valid=False``.

    ``pre_topk`` caps the suppression to the top-k-scoring boxes so the
    IoU matrix is k x k instead of n x n (with SSD's 8,732 priors the
    full matrix is ~300MB per class under vmap; the reference applies
    NMS to the top nmsTopk boxes only, DetectionOutputSSD.scala:49).
    """
    n = boxes.shape[0]
    if pre_topk is not None and pre_topk < n:
        top_s, top_i = jax.lax.top_k(scores, pre_topk)
        sub_idx, sub_valid = nms(boxes[top_i], top_s, iou_threshold,
                                 max_output)
        return top_i[sub_idx], sub_valid
    order = jnp.argsort(-scores)
    sboxes = boxes[order]
    sscores = scores[order]
    iou = box_iou(sboxes, sboxes)
    pos = jnp.arange(n)

    def body(i, keep):
        # if slot i survives, suppress every later slot overlapping it
        suppress = (iou[i] > iou_threshold) & (pos > i) & keep[i]
        return keep & ~suppress

    keep = jax.lax.fori_loop(0, n, body,
                             jnp.ones((n,), bool) & (sscores > -jnp.inf))
    # compact: kept slots first, preserving score order
    perm = jnp.argsort(~keep, stable=True)
    perm = perm[:max_output] if n >= max_output else jnp.pad(
        perm, (0, max_output - n))
    valid = keep[perm] & (jnp.arange(max_output) < n)
    indices = jnp.where(valid, order[perm], 0)
    return indices, valid


class Nms(Module):
    """Module wrapper (reference nn/Nms.scala:26): callable
    ``(scores, boxes) -> (indices, valid)``."""

    def __init__(self, iou_threshold: float = 0.5, max_output: int = 100):
        super().__init__()
        self.iou_threshold = float(iou_threshold)
        self.max_output = int(max_output)

    def forward(self, scores, boxes):
        return nms(boxes, scores, self.iou_threshold, self.max_output)


# --------------------------------------------------------------------------
# Anchor generation (reference nn/Anchor.scala:26)
# --------------------------------------------------------------------------

class Anchor:
    """Classic Faster-R-CNN anchor generator: a base box of ``base_size``
    is enumerated over aspect ratios and scales, then shifted across the
    feature grid.  (reference nn/Anchor.scala generateAnchors/getAllAnchors)
    """

    def __init__(self, ratios: Sequence[float], scales: Sequence[float]):
        self.ratios = np.asarray(ratios, np.float32)
        self.scales = np.asarray(scales, np.float32)

    @property
    def anchor_num(self) -> int:
        return len(self.ratios) * len(self.scales)

    def base_anchors(self, base_size: float) -> np.ndarray:
        base = np.array([0, 0, base_size - 1, base_size - 1], np.float32)
        w = base[2] - base[0] + 1
        h = base[3] - base[1] + 1
        cx = base[0] + 0.5 * (w - 1)
        cy = base[1] + 0.5 * (h - 1)
        size = w * h
        out = []
        for r in self.ratios:
            ws = np.round(np.sqrt(size / r))
            hs = np.round(ws * r)
            for s in self.scales:
                wss, hss = ws * s, hs * s
                out.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                            cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
        return np.asarray(out, np.float32)

    def generate(self, feat_h: int, feat_w: int, stride: float) -> jnp.ndarray:
        """All anchors for an ``feat_h x feat_w`` grid: ``(H*W*A, 4)``."""
        base = self.base_anchors(stride)
        shift_x = np.arange(feat_w, dtype=np.float32) * stride
        shift_y = np.arange(feat_h, dtype=np.float32) * stride
        sx, sy = np.meshgrid(shift_x, shift_y)
        shifts = np.stack([sx.ravel(), sy.ravel(),
                           sx.ravel(), sy.ravel()], axis=1)
        all_anchors = (shifts[:, None, :] + base[None, :, :])
        return jnp.asarray(all_anchors.reshape(-1, 4))


# --------------------------------------------------------------------------
# RoiAlign / RoiPooling (reference nn/RoiAlign.scala:45, nn/RoiPooling.scala)
# --------------------------------------------------------------------------

class RoiAlign(Module):
    """ROI-Align over an NHWC feature map.

    ``forward((features (1, H, W, C), rois (N, 4)))`` →
    ``(N, pooled_h, pooled_w, C)``.  rois are corner boxes in *image*
    coordinates; ``spatial_scale`` maps them to feature coordinates.
    The reference's per-ROI scalar loops (RoiAlign.scala poolOneRoiFloat)
    become one batched bilinear gather over a static sample grid.

    ``sampling_ratio`` must be > 0 (static grid); the reference's
    adaptive ``ceil(roi/bin)`` mode is shape-dynamic and is approximated
    by the MaskRCNN-standard value 2 when 0 is passed.
    """

    def __init__(self, spatial_scale: float, sampling_ratio: int,
                 pooled_h: int, pooled_w: int, mode: str = "avg",
                 aligned: bool = True):
        super().__init__()
        self.spatial_scale = float(spatial_scale)
        self.sampling_ratio = int(sampling_ratio) if sampling_ratio > 0 else 2
        self.pooled_h, self.pooled_w = int(pooled_h), int(pooled_w)
        assert mode in ("avg", "max")
        self.mode = mode
        self.aligned = bool(aligned)

    def forward(self, inputs):
        feat, rois = inputs
        if feat.ndim == 4:
            feat = feat[0]
        h, w = feat.shape[0], feat.shape[1]
        off = 0.5 if self.aligned else 0.0
        x1 = rois[:, 0] * self.spatial_scale - off
        y1 = rois[:, 1] * self.spatial_scale - off
        x2 = rois[:, 2] * self.spatial_scale - off
        y2 = rois[:, 3] * self.spatial_scale - off
        roi_w = x2 - x1
        roi_h = y2 - y1
        if not self.aligned:
            roi_w = jnp.maximum(roi_w, 1.0)
            roi_h = jnp.maximum(roi_h, 1.0)
        bin_h = roi_h / self.pooled_h
        bin_w = roi_w / self.pooled_w
        sr = self.sampling_ratio
        # sample coordinates: (N, pooled, sr)
        py = jnp.arange(self.pooled_h, dtype=jnp.float32)
        px = jnp.arange(self.pooled_w, dtype=jnp.float32)
        iy = (jnp.arange(sr, dtype=jnp.float32) + 0.5) / sr
        ix = (jnp.arange(sr, dtype=jnp.float32) + 0.5) / sr
        ys = (y1[:, None, None]
              + (py[None, :, None] + iy[None, None, :]) * bin_h[:, None, None])
        xs = (x1[:, None, None]
              + (px[None, :, None] + ix[None, None, :]) * bin_w[:, None, None])
        vals = _bilinear_gather(feat, ys, xs)  # (N, ph, sr, pw, sr, C)
        if self.mode == "avg":
            return vals.mean(axis=(2, 4))
        return vals.max(axis=(2, 4))


def _bilinear_gather(feat, ys, xs):
    """feat (H, W, C); ys (N, ph, sr); xs (N, pw, sr) →
    (N, ph, sr, pw, sr, C) bilinear samples, zero outside the map."""
    h, w = feat.shape[0], feat.shape[1]
    ys_b = ys[:, :, :, None, None]          # (N, ph, sr, 1, 1)
    xs_b = xs[:, None, None, :, :]          # (N, 1, 1, pw, sr)
    inside = ((ys_b >= -1.0) & (ys_b <= h) & (xs_b >= -1.0) & (xs_b <= w))
    y = jnp.clip(ys_b, 0.0, h - 1)
    x = jnp.clip(xs_b, 0.0, w - 1)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    ly = y - y0
    lx = x - x0
    hy, hx = 1.0 - ly, 1.0 - lx
    y0, y1, x0, x1 = (jnp.broadcast_to(a, jnp.broadcast_shapes(
        y0.shape, x0.shape)) for a in (y0, y1, x0, x1))
    v00 = feat[y0, x0]
    v01 = feat[y0, x1]
    v10 = feat[y1, x0]
    v11 = feat[y1, x1]
    wgt = lambda a, b: (a * b)[..., None]
    out = (wgt(hy, hx) * v00 + wgt(hy, lx) * v01
           + wgt(ly, hx) * v10 + wgt(ly, lx) * v11)
    return jnp.where(inside[..., None], out, 0.0)


class RoiPooling(Module):
    """Max ROI-pooling (reference nn/RoiPooling.scala): rois are
    ``(N, 5)`` rows ``[batch_idx, x1, y1, x2, y2]``.  Implemented as
    dense max over a per-bin membership mask — static shapes, MXU-free.
    """

    def __init__(self, pooled_w: int, pooled_h: int, spatial_scale: float):
        super().__init__()
        self.pooled_w, self.pooled_h = int(pooled_w), int(pooled_h)
        self.spatial_scale = float(spatial_scale)

    def forward(self, inputs):
        feat, rois = inputs  # feat (B, H, W, C)
        b, h, w, c = feat.shape
        scale = self.spatial_scale
        x1 = jnp.round(rois[:, 1] * scale)
        y1 = jnp.round(rois[:, 2] * scale)
        x2 = jnp.round(rois[:, 3] * scale)
        y2 = jnp.round(rois[:, 4] * scale)
        roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
        roi_h = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_w = roi_w / self.pooled_w
        bin_h = roi_h / self.pooled_h

        ph = jnp.arange(self.pooled_h, dtype=jnp.float32)
        pw = jnp.arange(self.pooled_w, dtype=jnp.float32)
        # bin bounds per roi: (N, p)
        hstart = jnp.clip(jnp.floor(ph[None] * bin_h[:, None]) + y1[:, None],
                          0, h)
        hend = jnp.clip(jnp.ceil((ph[None] + 1) * bin_h[:, None])
                        + y1[:, None], 0, h)
        wstart = jnp.clip(jnp.floor(pw[None] * bin_w[:, None]) + x1[:, None],
                          0, w)
        wend = jnp.clip(jnp.ceil((pw[None] + 1) * bin_w[:, None])
                        + x1[:, None], 0, w)
        ygrid = jnp.arange(h, dtype=jnp.float32)
        xgrid = jnp.arange(w, dtype=jnp.float32)
        # membership masks: (N, p, H) / (N, p, W)
        ymask = ((ygrid[None, None] >= hstart[..., None])
                 & (ygrid[None, None] < hend[..., None]))
        xmask = ((xgrid[None, None] >= wstart[..., None])
                 & (xgrid[None, None] < wend[..., None]))
        batch_idx = rois[:, 0].astype(jnp.int32)
        per_roi = feat[batch_idx]  # (N, H, W, C)
        neg = jnp.finfo(feat.dtype).min
        # separable max: reduce H under ymask, then W under xmask —
        # peak intermediate is (N, ph, W, C), not (N, ph, pw, H, W, C)
        rows = jnp.where(ymask[:, :, :, None, None],
                         per_roi[:, None], neg).max(axis=2)  # (N, ph, W, C)
        out = jnp.where(xmask[:, None, :, :, None],
                        rows[:, :, None], neg).max(axis=3)   # (N, ph, pw, C)
        empty = ((hend <= hstart)[:, :, None, None]
                 | (wend <= wstart)[:, None, :, None])
        return jnp.where(empty, 0.0, out)


# --------------------------------------------------------------------------
# FPN (reference nn/FPN.scala:41)
# --------------------------------------------------------------------------

class FPN(Module):
    """Feature Pyramid Network.  ``forward([C_i]) -> [P_i] (+ extra)``.

    ``top_blocks=1`` appends max-pooled P6 (MaskRCNN); ``top_blocks=2``
    appends conv P6/P7 from ``in_channels_p6p7`` (RetinaNet).
    """

    def __init__(self, in_channels: Sequence[int], out_channels: int,
                 top_blocks: int = 0, in_channels_p6p7: int = 0,
                 out_channels_p6p7: int = 0):
        super().__init__()
        self.top_blocks = int(top_blocks)
        inner, layer = [], []
        for c in in_channels:
            inner.append(SpatialConvolution(c, out_channels, 1, 1))
            layer.append(SpatialConvolution(
                out_channels, out_channels, 3, 3, 1, 1, 1, 1))
        self.inner_blocks = ModuleList(inner)
        self.layer_blocks = ModuleList(layer)
        if top_blocks == 2:
            self.p6 = SpatialConvolution(
                in_channels_p6p7, out_channels_p6p7, 3, 3, 2, 2, 1, 1)
            self.p7 = SpatialConvolution(
                out_channels_p6p7, out_channels_p6p7, 3, 3, 2, 2, 1, 1)

    def forward(self, features: Sequence[jnp.ndarray]) -> List[jnp.ndarray]:
        laterals = [blk(f) for blk, f in zip(self.inner_blocks, features)]
        # top-down: upsample (nearest 2x) + add
        merged = [laterals[-1]]
        for lat in laterals[-2::-1]:
            up = _nearest_upsample2(merged[0], lat.shape[1], lat.shape[2])
            merged.insert(0, lat + up)
        outs = [blk(m) for blk, m in zip(self.layer_blocks, merged)]
        if self.top_blocks == 1:
            outs.append(jax.lax.reduce_window(
                outs[-1], -jnp.inf, jax.lax.max, (1, 1, 1, 1),
                (1, 2, 2, 1), "VALID"))
        elif self.top_blocks == 2:
            p6 = self.p6(features[-1])
            outs.append(p6)
            outs.append(self.p7(jax.nn.relu(p6)))
        return outs


def _nearest_upsample2(x, out_h, out_w):
    b, h, w, c = x.shape
    y = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
    return y[:, :out_h, :out_w, :]


# --------------------------------------------------------------------------
# Pooler (reference nn/Pooler.scala:33) — multi-level ROI pooling
# --------------------------------------------------------------------------

class Pooler(Module):
    """Assigns each ROI to an FPN level by the canonical heuristic
    ``lvl = 4 + log2(sqrt(area)/224)`` and RoiAligns it from that level.

    TPU-first: instead of dynamically partitioning ROIs by level (dynamic
    shapes), every ROI is pooled from every level and the right level is
    selected by mask — levels are few (≤5), shapes stay static.
    """

    def __init__(self, resolution: int, scales: Sequence[float],
                 sampling_ratio: int):
        super().__init__()
        self.resolution = int(resolution)
        self.scales = tuple(float(s) for s in scales)
        self.poolers = ModuleList([
            RoiAlign(s, sampling_ratio, resolution, resolution)
            for s in self.scales])
        self.lvl_min = int(-math.log2(self.scales[0]))
        self.lvl_max = int(-math.log2(self.scales[-1]))

    def level_of(self, rois):
        area = (jnp.clip(rois[:, 2] - rois[:, 0], 0)
                * jnp.clip(rois[:, 3] - rois[:, 1], 0))
        lvl = jnp.floor(4.0 + jnp.log2(jnp.sqrt(area) / 224.0 + 1e-6))
        return jnp.clip(lvl, self.lvl_min, self.lvl_max).astype(jnp.int32)

    def forward(self, inputs):
        features, rois = inputs
        lvl = self.level_of(rois)
        out = None
        for i, pooler in enumerate(self.poolers):
            pooled = pooler((features[i], rois))
            sel = (lvl == (self.lvl_min + i))[:, None, None, None]
            out = jnp.where(sel, pooled, 0.0 if out is None else out)
        return out


# --------------------------------------------------------------------------
# RPN (reference nn/RegionProposal.scala:40 + ProposalPostProcessor)
# --------------------------------------------------------------------------

class RegionProposal(Module):
    """Region Proposal Network over FPN levels.

    ``forward((features: [P_i], im_info (2,)))`` →
    ``(proposals (post_nms_topn, 4), scores (post_nms_topn,))`` where
    padded slots carry ``-inf`` score.
    """

    def __init__(self, in_channels: int, anchor_sizes: Sequence[float],
                 aspect_ratios: Sequence[float],
                 anchor_stride: Sequence[float],
                 pre_nms_topn_test: int = 1000,
                 post_nms_topn_test: int = 1000,
                 pre_nms_topn_train: int = 2000,
                 post_nms_topn_train: int = 2000,
                 nms_thresh: float = 0.7, min_size: int = 0):
        super().__init__()
        assert len(anchor_sizes) == len(anchor_stride)
        self.anchor_sizes = tuple(float(s) for s in anchor_sizes)
        self.anchor_stride = tuple(float(s) for s in anchor_stride)
        self.anchors = [Anchor(aspect_ratios, [s / st])
                        for s, st in zip(self.anchor_sizes,
                                         self.anchor_stride)]
        a = self.anchors[0].anchor_num
        self.conv = SpatialConvolution(
            in_channels, in_channels, 3, 3, 1, 1, 1, 1,
            init_method=init_methods.RandomNormal(0, 0.01))
        self.cls_logits = SpatialConvolution(
            in_channels, a, 1, 1,
            init_method=init_methods.RandomNormal(0, 0.01))
        self.bbox_pred = SpatialConvolution(
            in_channels, a * 4, 1, 1,
            init_method=init_methods.RandomNormal(0, 0.01))
        self.pre_nms_topn_test = pre_nms_topn_test
        self.post_nms_topn_test = post_nms_topn_test
        self.pre_nms_topn_train = pre_nms_topn_train
        self.post_nms_topn_train = post_nms_topn_train
        self.nms_thresh = float(nms_thresh)
        self.min_size = float(min_size)

    def _level_proposals(self, feat, anchor: Anchor, stride, im_info,
                         pre_nms, post_nms):
        t = jax.nn.relu(self.conv(feat))
        logits = self.cls_logits(t)     # (1, H, W, A)
        deltas = self.bbox_pred(t)      # (1, H, W, 4A)
        h, w = feat.shape[1], feat.shape[2]
        a = anchor.anchor_num
        scores = logits.reshape(-1)
        deltas = deltas.reshape(h, w, a, 4).reshape(-1, 4)
        anchors = anchor.generate(h, w, stride)
        n = scores.shape[0]
        k = min(pre_nms, n)
        top_scores, idx = jax.lax.top_k(scores, k)
        boxes = bbox_transform_inv(anchors[idx], deltas[idx])
        boxes = clip_boxes(boxes, im_info[0], im_info[1])
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        ok = (ws >= self.min_size) & (hs >= self.min_size)
        top_scores = jnp.where(ok, top_scores, -jnp.inf)
        keep_idx, valid = nms(boxes, top_scores, self.nms_thresh,
                              min(post_nms, k))
        sel_boxes = jnp.where(valid[:, None], boxes[keep_idx], 0.0)
        # sigmoid the logits of valid slots; padding stays -inf so the
        # documented "padded slots carry -inf score" contract holds
        sel_scores = jnp.where(valid,
                               jax.nn.sigmoid(top_scores[keep_idx]),
                               -jnp.inf)
        return sel_boxes, sel_scores

    def forward(self, inputs):
        features, im_info = inputs
        train = self.training
        pre = self.pre_nms_topn_train if train else self.pre_nms_topn_test
        post = self.post_nms_topn_train if train else self.post_nms_topn_test
        all_boxes, all_scores = [], []
        n_lvl = min(len(self.anchors), len(features))
        for i in range(n_lvl):
            b, s = self._level_proposals(
                features[i], self.anchors[i], self.anchor_stride[i],
                im_info, pre, post)
            all_boxes.append(b)
            all_scores.append(s)
        boxes = jnp.concatenate(all_boxes, 0)
        scores = jnp.concatenate(all_scores, 0)
        k = min(post, scores.shape[0])
        top_scores, idx = jax.lax.top_k(scores, k)
        return boxes[idx], top_scores


class Proposal(Module):
    """Single-level proposal layer (reference nn/Proposal.scala — classic
    Faster-R-CNN): input ``(cls_prob (1, H, W, 2A), bbox_pred (1, H, W, 4A),
    im_info)``; output fixed ``post_nms_topn`` proposals ``(N, 5)`` with a
    leading batch-index column plus their scores."""

    def __init__(self, pre_nms_topn: int, post_nms_topn: int,
                 ratios: Sequence[float], scales: Sequence[float],
                 rpn_pre_nms_topn_train: int = 12000,
                 rpn_post_nms_topn_train: int = 2000,
                 base_size: float = 16.0, nms_thresh: float = 0.7,
                 min_size: float = 16.0):
        super().__init__()
        self.anchor = Anchor(ratios, scales)
        self.pre_nms_topn = pre_nms_topn
        self.post_nms_topn = post_nms_topn
        self.pre_nms_topn_train = rpn_pre_nms_topn_train
        self.post_nms_topn_train = rpn_post_nms_topn_train
        self.base_size = base_size
        self.nms_thresh = nms_thresh
        self.min_size = min_size

    def forward(self, inputs):
        cls_prob, bbox_pred, im_info = inputs
        h, w = cls_prob.shape[1], cls_prob.shape[2]
        a = self.anchor.anchor_num
        # foreground scores = second half of the 2A channels
        scores = cls_prob[0, :, :, a:].reshape(-1)
        deltas = bbox_pred[0].reshape(h, w, a, 4).reshape(-1, 4)
        anchors = self.anchor.generate(h, w, self.base_size)
        pre = self.pre_nms_topn_train if self.training else self.pre_nms_topn
        post = (self.post_nms_topn_train if self.training
                else self.post_nms_topn)
        k = min(pre, scores.shape[0])
        top_scores, idx = jax.lax.top_k(scores, k)
        boxes = bbox_transform_inv(anchors[idx], deltas[idx])
        boxes = clip_boxes(boxes, im_info[0], im_info[1])
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        min_sz = self.min_size * im_info[2]
        top_scores = jnp.where((ws >= min_sz) & (hs >= min_sz),
                               top_scores, -jnp.inf)
        keep, valid = nms(boxes, top_scores, self.nms_thresh, min(post, k))
        out_boxes = jnp.where(valid[:, None], boxes[keep], 0.0)
        rois = jnp.concatenate(
            [jnp.zeros((out_boxes.shape[0], 1)), out_boxes], axis=1)
        return rois, jnp.where(valid, top_scores[keep], -jnp.inf)


# --------------------------------------------------------------------------
# BoxHead / MaskHead (reference nn/BoxHead.scala:30, nn/MaskHead.scala:24)
# --------------------------------------------------------------------------

class BoxHead(Module):
    """Second-stage box head: Pooler → 2-MLP feature extractor →
    class + box predictors → per-class NMS post-processing.

    ``forward((features, proposals, im_info))`` →
    ``(boxes (max_per_image, 4), labels, scores, valid)``.
    """

    def __init__(self, in_channels: int, resolution: int,
                 scales: Sequence[float], sampling_ratio: int,
                 score_thresh: float, nms_thresh: float,
                 max_per_image: int, output_size: int, num_classes: int):
        super().__init__()
        self.num_classes = num_classes
        self.score_thresh = float(score_thresh)
        self.nms_thresh = float(nms_thresh)
        self.max_per_image = int(max_per_image)
        self.pooler = Pooler(resolution, scales, sampling_ratio)
        flat = in_channels * resolution * resolution
        self.fc1 = Linear(flat, output_size)
        self.fc2 = Linear(output_size, output_size)
        self.cls_score = Linear(
            output_size, num_classes,
            init_method=init_methods.RandomNormal(0, 0.01))
        self.bbox_pred = Linear(
            output_size, num_classes * 4,
            init_method=init_methods.RandomNormal(0, 0.001))
        self.box_weights = (10.0, 10.0, 5.0, 5.0)

    def features_of(self, features, proposals):
        x = self.pooler((features, proposals))
        x = x.reshape(x.shape[0], -1)
        x = jax.nn.relu(self.fc1(x))
        return jax.nn.relu(self.fc2(x))

    def forward(self, inputs):
        # optional 4th element: proposal validity (True = real proposal).
        # RegionProposal pads its fixed-shape output with -inf-score
        # slots; without the mask those padded (zero) boxes would be
        # classified and could enter the top-k as spurious detections.
        if len(inputs) == 4:
            features, proposals, im_info, prop_valid = inputs
        else:
            features, proposals, im_info = inputs
            prop_valid = None
        feats = self.features_of(features, proposals)
        logits = self.cls_score(feats)
        deltas = self.bbox_pred(feats)
        probs = jax.nn.softmax(logits, axis=-1)
        n = proposals.shape[0]
        # decode per class (skip background class 0)
        deltas = deltas.reshape(n, self.num_classes, 4)
        cand_boxes, cand_scores, cand_labels = [], [], []
        per_class_keep = max(1, self.max_per_image)
        for c in range(1, self.num_classes):
            dec = bbox_transform_inv(proposals, deltas[:, c, :],
                                     self.box_weights)
            dec = clip_boxes(dec, im_info[0], im_info[1])
            sc = jnp.where(probs[:, c] > self.score_thresh,
                           probs[:, c], -jnp.inf)
            if prop_valid is not None:
                sc = jnp.where(prop_valid, sc, -jnp.inf)
            keep, valid = nms(dec, sc, self.nms_thresh,
                              min(per_class_keep, n))
            cand_boxes.append(jnp.where(valid[:, None], dec[keep], 0.0))
            cand_scores.append(jnp.where(valid, probs[keep, c], -jnp.inf))
            cand_labels.append(jnp.full((keep.shape[0],), c, jnp.int32))
        boxes = jnp.concatenate(cand_boxes, 0)
        scores = jnp.concatenate(cand_scores, 0)
        labels = jnp.concatenate(cand_labels, 0)
        k = min(self.max_per_image, scores.shape[0])
        top_scores, idx = jax.lax.top_k(scores, k)
        valid = top_scores > -jnp.inf
        return (jnp.where(valid[:, None], boxes[idx], 0.0),
                jnp.where(valid, labels[idx], 0),
                jnp.where(valid, top_scores, 0.0), valid)


class MaskHead(Module):
    """Mask branch: Pooler → dilated conv tower → deconv ×2 → per-class
    mask logits; returns the sigmoid mask of each box's predicted class.

    ``forward((features, boxes, labels))`` →
    ``(masks (N, 2*resolution, 2*resolution), logits (N, C, 2r, 2r))``.
    """

    def __init__(self, in_channels: int, resolution: int,
                 scales: Sequence[float], sampling_ratio: int,
                 layers: Sequence[int], dilation: int, num_classes: int,
                 use_gn: bool = False):
        super().__init__()
        self.pooler = Pooler(resolution, scales, sampling_ratio)
        convs, norms = [], []
        nin = in_channels
        for nout in layers:
            if dilation == 1:
                convs.append(SpatialConvolution(
                    nin, nout, 3, 3, 1, 1, 1, 1,
                    init_method=init_methods.MsraFiller(False)))
            else:
                convs.append(SpatialDilatedConvolution(
                    nin, nout, 3, 3, 1, 1, dilation, dilation,
                    dilation, dilation))
            if use_gn:
                norms.append(_group_norm(nout))
            nin = nout
        self.convs = ModuleList(convs)
        self.norms = ModuleList(norms)
        self.use_gn = bool(use_gn)
        self.dilation = int(dilation)
        self.deconv = SpatialFullConvolution(nin, nin, 2, 2, 2, 2)
        self.predictor = SpatialConvolution(
            nin, num_classes, 1, 1,
            init_method=init_methods.MsraFiller(False))
        self.num_classes = num_classes

    def forward(self, inputs):
        features, boxes, labels = inputs
        x = self.pooler((features, boxes))
        for i, conv in enumerate(self.convs):
            x = conv(x)
            if self.use_gn:
                x = self.norms[i](x)
            x = jax.nn.relu(x)
        x = jax.nn.relu(self.deconv(x))
        logits = self.predictor(x)             # (N, 2r, 2r, C)
        n = boxes.shape[0]
        sel = logits[jnp.arange(n), :, :, labels]
        return jax.nn.sigmoid(sel), jnp.transpose(logits, (0, 3, 1, 2))


# --------------------------------------------------------------------------
# SSD: PriorBox + DetectionOutputSSD (reference nn/PriorBox.scala:42,
# nn/DetectionOutputSSD.scala:49)
# --------------------------------------------------------------------------

class PriorBox(Module):
    """Caffe-SSD prior (default box) generator for one feature map.

    ``forward(feature (B, H, W, C))`` → ``(2, H*W*num_priors*4)`` with
    row 0 the normalized corner boxes and row 1 the variances —
    matching the reference's Caffe-layout output.
    """

    def __init__(self, min_sizes: Sequence[float],
                 max_sizes: Optional[Sequence[float]] = None,
                 aspect_ratios: Optional[Sequence[float]] = None,
                 is_flip: bool = True, is_clip: bool = False,
                 variances: Optional[Sequence[float]] = None,
                 offset: float = 0.5, img_h: int = 0, img_w: int = 0,
                 img_size: int = 0, step_h: float = 0.0,
                 step_w: float = 0.0, step: float = 0.0):
        super().__init__()
        self.min_sizes = [float(s) for s in min_sizes]
        self.max_sizes = [float(s) for s in (max_sizes or [])]
        ars = [1.0]
        for ar in (aspect_ratios or []):
            if not any(abs(ar - a) < 1e-6 for a in ars):
                ars.append(float(ar))
                if is_flip:
                    ars.append(1.0 / float(ar))
        self.aspect_ratios = ars
        self.is_clip = is_clip
        self.variances = list(variances or [0.1])
        self.offset = float(offset)
        self.img_h = img_h or img_size
        self.img_w = img_w or img_size
        self.step_h = step_h or step
        self.step_w = step_w or step
        if self.max_sizes:
            assert len(self.max_sizes) == len(self.min_sizes)
        self.num_priors = (len(ars) * len(self.min_sizes)
                           + len(self.max_sizes))

    def forward(self, feature):
        layer_h, layer_w = int(feature.shape[1]), int(feature.shape[2])
        img_h, img_w = self.img_h, self.img_w
        step_h = self.step_h or img_h / layer_h
        step_w = self.step_w or img_w / layer_w
        boxes = []
        for hi in range(layer_h):
            for wi in range(layer_w):
                cx = (wi + self.offset) * step_w
                cy = (hi + self.offset) * step_h
                for i, mn in enumerate(self.min_sizes):
                    bw = bh = mn
                    boxes.append(_prior(cx, cy, bw, bh, img_w, img_h))
                    if self.max_sizes:
                        sz = math.sqrt(mn * self.max_sizes[i])
                        boxes.append(_prior(cx, cy, sz, sz, img_w, img_h))
                    for ar in self.aspect_ratios:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        bw = mn * math.sqrt(ar)
                        bh = mn / math.sqrt(ar)
                        boxes.append(_prior(cx, cy, bw, bh, img_w, img_h))
        out = np.asarray(boxes, np.float32).reshape(-1)
        if self.is_clip:
            out = np.clip(out, 0.0, 1.0)
        if len(self.variances) == 1:
            var = np.full_like(out, self.variances[0])
        else:
            var = np.tile(np.asarray(self.variances, np.float32),
                          out.size // 4)
        return jnp.asarray(np.stack([out, var]))


def _prior(cx, cy, bw, bh, img_w, img_h):
    return [(cx - bw / 2.0) / img_w, (cy - bh / 2.0) / img_h,
            (cx + bw / 2.0) / img_w, (cy + bh / 2.0) / img_h]


def _decode_ssd(priors, variances, loc, variance_encoded: bool):
    """Decode SSD loc predictions against center-form priors."""
    pw = priors[:, 2] - priors[:, 0]
    ph = priors[:, 3] - priors[:, 1]
    pcx = (priors[:, 0] + priors[:, 2]) / 2
    pcy = (priors[:, 1] + priors[:, 3]) / 2
    if variance_encoded:
        v = jnp.ones((loc.shape[0], 4))
    else:
        v = variances
    cx = v[:, 0] * loc[:, 0] * pw + pcx
    cy = v[:, 1] * loc[:, 1] * ph + pcy
    w = jnp.exp(v[:, 2] * loc[:, 2]) * pw
    h = jnp.exp(v[:, 3] * loc[:, 3]) * ph
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], 1)


class DetectionOutputSSD(Module):
    """SSD post-processing (reference nn/DetectionOutputSSD.scala:49).

    ``forward((loc (B, nPriors*4), conf (B, nPriors*nClasses),
    priors (2, nPriors*4)))`` → ``(B, keep_top_k, 6)`` rows
    ``[label, score, x1, y1, x2, y2]``; empty slots are all-zero.
    """

    def __init__(self, n_classes: int = 21, share_location: bool = True,
                 bg_label: int = 0, nms_thresh: float = 0.45,
                 nms_topk: int = 400, keep_top_k: int = 200,
                 conf_thresh: float = 0.01,
                 variance_encoded_in_target: bool = False,
                 conf_post_process: bool = True):
        super().__init__()
        assert share_location, "only shared-location SSD is supported"
        self.n_classes = n_classes
        self.bg_label = bg_label
        self.nms_thresh = float(nms_thresh)
        self.nms_topk = int(nms_topk)
        self.keep_top_k = int(keep_top_k)
        self.conf_thresh = float(conf_thresh)
        self.variance_encoded = variance_encoded_in_target

    def _one_image(self, loc, conf, priors, variances):
        n_priors = priors.shape[0]
        loc = loc.reshape(n_priors, 4)
        conf = conf.reshape(n_priors, self.n_classes)
        boxes = _decode_ssd(priors, variances, loc, self.variance_encoded)
        all_scores, all_boxes, all_labels = [], [], []
        per_cls = min(self.nms_topk, n_priors)
        for c in range(self.n_classes):
            if c == self.bg_label:
                continue
            sc = jnp.where(conf[:, c] > self.conf_thresh, conf[:, c],
                           -jnp.inf)
            keep, valid = nms(boxes, sc, self.nms_thresh, per_cls,
                              pre_topk=self.nms_topk)
            all_boxes.append(jnp.where(valid[:, None], boxes[keep], 0.0))
            all_scores.append(jnp.where(valid, conf[keep, c], -jnp.inf))
            all_labels.append(jnp.full((per_cls,), c, jnp.int32))
        scores = jnp.concatenate(all_scores)
        bxs = jnp.concatenate(all_boxes, 0)
        lbls = jnp.concatenate(all_labels)
        k = min(self.keep_top_k, scores.shape[0])
        top, idx = jax.lax.top_k(scores, k)
        valid = top > -jnp.inf
        row = jnp.concatenate([
            jnp.where(valid, lbls[idx], 0).astype(jnp.float32)[:, None],
            jnp.where(valid, top, 0.0)[:, None],
            jnp.where(valid[:, None], bxs[idx], 0.0)], axis=1)
        if k < self.keep_top_k:
            row = jnp.pad(row, ((0, self.keep_top_k - k), (0, 0)))
        return row

    def forward(self, inputs):
        loc, conf, prior = inputs
        priors = prior[0].reshape(-1, 4)
        variances = prior[1].reshape(-1, 4)
        if loc.ndim == 1:
            loc, conf = loc[None], conf[None]
        return jax.vmap(
            lambda l, c: self._one_image(l, c, priors, variances))(loc, conf)


class DetectionOutputFrcnn(Module):
    """Faster-R-CNN post-processing (reference
    nn/DetectionOutputFrcnn.scala): per-class decode + NMS over ROI-head
    outputs.  ``forward((im_info, cls_prob (N, C), bbox_pred (N, 4C),
    rois (N, 5)))`` → ``(keep_top_k, 6)`` rows [label, score, box]."""

    def __init__(self, n_classes: int = 21, nms_thresh: float = 0.3,
                 max_per_image: int = 100, thresh: float = 0.05):
        super().__init__()
        self.n_classes = n_classes
        self.nms_thresh = float(nms_thresh)
        self.max_per_image = int(max_per_image)
        self.thresh = float(thresh)

    def forward(self, inputs):
        im_info, cls_prob, bbox_pred, rois = inputs
        n = rois.shape[0]
        deltas = bbox_pred.reshape(n, self.n_classes, 4)
        boxes_in = rois[:, 1:5]
        all_scores, all_boxes, all_labels = [], [], []
        per_cls = min(self.max_per_image, n)
        for c in range(1, self.n_classes):
            dec = bbox_transform_inv(boxes_in, deltas[:, c, :])
            dec = clip_boxes(dec, im_info[0], im_info[1])
            sc = jnp.where(cls_prob[:, c] > self.thresh, cls_prob[:, c],
                           -jnp.inf)
            keep, valid = nms(dec, sc, self.nms_thresh, per_cls)
            all_boxes.append(jnp.where(valid[:, None], dec[keep], 0.0))
            all_scores.append(jnp.where(valid, cls_prob[keep, c], -jnp.inf))
            all_labels.append(jnp.full((per_cls,), c, jnp.int32))
        scores = jnp.concatenate(all_scores)
        bxs = jnp.concatenate(all_boxes, 0)
        lbls = jnp.concatenate(all_labels)
        k = min(self.max_per_image, scores.shape[0])
        top, idx = jax.lax.top_k(scores, k)
        valid = top > -jnp.inf
        return jnp.concatenate([
            jnp.where(valid, lbls[idx], 0).astype(jnp.float32)[:, None],
            jnp.where(valid, top, 0.0)[:, None],
            jnp.where(valid[:, None], bxs[idx], 0.0)], axis=1)


# --------------------------------------------------------------------------
# Detection criterions (reference nn/SmoothL1CriterionWithWeights.scala,
# nn/SoftmaxWithCriterion.scala)
# --------------------------------------------------------------------------

class SmoothL1CriterionWithWeights(Module):
    """Smooth-L1 with per-element inside/outside weights, normalized by
    ``num`` (reference nn/SmoothL1CriterionWithWeights.scala)."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        super().__init__()
        self.sigma2 = float(sigma) ** 2
        self.num = num

    def forward(self, input, target):
        if isinstance(target, (tuple, list)):
            tgt, in_w, out_w = target[0], target[1], target[2]
        else:
            tgt, in_w, out_w = target, 1.0, 1.0
        d = in_w * (input - tgt)
        ad = jnp.abs(d)
        loss = jnp.where(ad < 1.0 / self.sigma2,
                         0.5 * self.sigma2 * d * d,
                         ad - 0.5 / self.sigma2)
        loss = jnp.sum(out_w * loss)
        return loss / self.num if self.num > 0 else loss

    __call__ = forward


class SoftmaxWithCriterion(Module):
    """Softmax + NLL over spatial maps with ignore-label support
    (reference nn/SoftmaxWithCriterion.scala).  input (B, C, H, W) or
    (B, C); target 1-based labels."""

    def __init__(self, ignore_label: Optional[int] = None,
                 normalize_mode: str = "VALID"):
        super().__init__()
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode

    def forward(self, input, target):
        if input.ndim == 2:
            logp = jax.nn.log_softmax(input, axis=1)
            tgt = target.astype(jnp.int32) - 1
            picked = jnp.take_along_axis(logp, tgt[:, None], 1)[:, 0]
        else:
            logp = jax.nn.log_softmax(input, axis=1)
            tgt = target.astype(jnp.int32) - 1
            picked = jnp.take_along_axis(
                logp, tgt[:, None, :, :], 1)[:, 0]
        if self.ignore_label is not None:
            mask = (target != self.ignore_label)
            picked = jnp.where(mask, picked, 0.0)
            count = jnp.maximum(jnp.sum(mask), 1)
        else:
            mask = jnp.ones_like(picked, bool)
            count = picked.size
        if self.normalize_mode == "VALID":
            return -jnp.sum(picked) / count
        elif self.normalize_mode == "FULL":
            return -jnp.sum(picked) / picked.size
        elif self.normalize_mode == "BATCH_SIZE":
            return -jnp.sum(picked) / input.shape[0]
        return -jnp.sum(picked)

    __call__ = forward
