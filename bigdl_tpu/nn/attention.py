"""Multi-head attention + Transformer stack.

Reference: nn/Attention.scala (multi-head attention as a graph of
MM/SoftMax/Dropout layers), nn/FeedForwardNetwork.scala,
nn/TransformerOperation.scala (position encoding, padding/causal bias,
shiftRight3D), nn/Transformer.scala (LanguageModel + Translation
topologies, pre-norm blocks, shared embedding/softmax weights),
nn/SequenceBeamSearch.scala.

TPU-first redesign: attention scores never materialize at [B,H,T,T] on
the hot path — :func:`bigdl_tpu.ops.dot_product_attention` dispatches to
a Pallas flash kernel (blockwise online softmax) on TPU.  Decode uses a
fixed-size KV cache updated with ``lax.dynamic_update_slice`` so the
beam-search loop stays jittable (static shapes, no concat-growing
tensors like the reference's JoinTable cache, Attention.scala joinK/V).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, ModuleList, Parameter, \
    next_rng_key
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.normalization import LayerNormalization
from bigdl_tpu.ops import dot_product_attention
from bigdl_tpu.ops.attention_kernels import _NEG_INF

__all__ = [
    "Attention", "FeedForwardNetwork", "TransformerEncoderLayer",
    "TransformerDecoderLayer", "Transformer", "SequenceBeamSearch",
    "position_encoding", "padding_bias", "causal_bias",
    "incremental_bias", "chunk_incremental_bias", "shift_right_3d",
]


# ---------------------------------------------------------------------------
# TransformerOperation equivalents (reference nn/TransformerOperation.scala)
# ---------------------------------------------------------------------------

def position_encoding(length: int, hidden_size: int,
                      min_timescale: float = 1.0,
                      max_timescale: float = 1.0e4,
                      dtype=jnp.float32):
    """Sinusoidal position encoding [length, hidden_size]
    (reference TransformerOperation.getPositionEncode:118)."""
    position = jnp.arange(length, dtype=jnp.float32)
    num_timescales = hidden_size // 2
    log_inc = math.log(max_timescale / min_timescale) / max(
        num_timescales - 1, 1)
    inv_timescales = min_timescale * jnp.exp(
        jnp.arange(num_timescales, dtype=jnp.float32) * -log_inc)
    scaled = position[:, None] * inv_timescales[None, :]
    signal = jnp.concatenate([jnp.sin(scaled), jnp.cos(scaled)], axis=1)
    if signal.shape[1] < hidden_size:  # odd hidden size
        signal = jnp.pad(signal, ((0, 0), (0, hidden_size - signal.shape[1])))
    return signal.astype(dtype)


def padding_bias(tokens, padding_value: float = 0.0):
    """[B, 1, 1, T] additive bias: -1e9 at padding positions
    (reference TransformerOperation.getPaddingBias:74)."""
    pad = (tokens == padding_value).astype(jnp.float32) * _NEG_INF
    return pad[:, None, None, :]


def causal_bias(length: int, dtype=jnp.float32):
    """[1, 1, T, T] lower-triangle attention bias (reference
    TransformerOperation.attentionBiasLowerTriangle:156)."""
    mask = jnp.tril(jnp.ones((length, length), bool))
    return jnp.where(mask, 0.0, _NEG_INF).astype(dtype)[None, None]


def incremental_bias(max_len: int, index, pad=None, dtype=jnp.float32):
    """Additive attention bias over a fixed-size KV cache for one decode
    step at position ``index``: slots beyond ``index`` (not yet written)
    are masked, and so are per-batch padding slots when ``pad``
    ([B, max_len] bool) is given.  Returns [1,1,1,max_len] (no pad) or
    [B,1,1,max_len].  Shared by every incremental decoder so the
    cache-masking logic has one home."""
    invalid = jnp.arange(max_len) > index
    if pad is not None:
        invalid = invalid[None, :] | pad
        return jnp.where(invalid, _NEG_INF, 0.0).astype(dtype)[
            :, None, None, :]
    return jnp.where(invalid, _NEG_INF, 0.0).astype(dtype)[
        None, None, None, :]


def chunk_incremental_bias(max_len: int, index, width: int, pad,
                           dtype=jnp.float32):
    """Additive attention bias for a ``width``-token chunk written at
    positions ``[index, index+width)`` of a fixed-size KV cache: query
    ``i`` (global position ``index+i``) may attend cache slots
    ``j <= index+i`` that are not padding (``pad``: [B, max_len] bool,
    including the chunk's own freshly written flags).  The ``width==1``
    row is exactly :func:`incremental_bias` — decode is the degenerate
    chunk.  Returns [B, 1, width, max_len]."""
    qpos = index + jnp.arange(width)[:, None]
    invalid = jnp.arange(max_len)[None, :] > qpos          # [W, max_len]
    invalid = invalid[None, :, :] | pad[:, None, :]        # [B, W, max_len]
    return jnp.where(invalid, _NEG_INF, 0.0).astype(dtype)[:, None, :, :]


def shift_right_3d(x):
    """Shift the time axis right by one, zero-filling position 0
    (reference TransformerOperation.shiftRight3D:94 — decoder input
    shifting)."""
    return jnp.pad(x, ((0, 0), (1, 0), (0, 0)))[:, :-1, :]


# ---------------------------------------------------------------------------
# Attention
# ---------------------------------------------------------------------------

class Attention(Module):
    """Multi-head (self/cross) attention (reference nn/Attention.scala).

    ``forward(x, y=None, bias=None, cache=None, cache_index=None)``:

    * x: queries [B, Tq, H]; y: keys/values source (defaults to x —
      self-attention, like the reference feeding inputX=inputY).
    * bias: additive attention bias broadcastable to [B, h, Tq, Tk]
      (padding mask and/or causal mask).
    * cache: optional dict {"k": [B, h, Tmax, d], "v": ...} for
      incremental decoding; cache_index is the current step.  Returns
      (output, new_cache) when a cache is passed, else output.
    """

    def __init__(self, hidden_size: int, num_heads: int,
                 attention_dropout: float = 0.0):
        super().__init__()
        if hidden_size % num_heads:
            raise ValueError("hidden_size must be divisible by num_heads")
        self.hidden_size = hidden_size
        self.num_heads = num_heads
        self.attention_dropout = attention_dropout
        self.q_layer = Linear(hidden_size, hidden_size, with_bias=False)
        self.k_layer = Linear(hidden_size, hidden_size, with_bias=False)
        self.v_layer = Linear(hidden_size, hidden_size, with_bias=False)
        self.output_layer = Linear(hidden_size, hidden_size, with_bias=False)

    def _split_heads(self, x):
        b, t, _ = x.shape
        d = self.hidden_size // self.num_heads
        return x.reshape(b, t, self.num_heads, d).transpose(0, 2, 1, 3)

    def _combine_heads(self, x):
        b, h, t, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)

    def forward(self, x, y=None, bias=None, cache=None, cache_index=None,
                causal=False):
        """``causal=True`` applies the lower-triangular mask inside the
        attention kernel instead of via an additive bias — on TPU the
        flash path then skips above-diagonal blocks entirely and never
        materializes/streams a [B, H, Tq, Tk] bias."""
        self_attention = y is None
        y = x if self_attention else y
        q = self._split_heads(self.q_layer(x))
        d = self.hidden_size // self.num_heads
        # reference scales q by 1/sqrt(depth) before the MM
        # (Attention.scala createModule); we fold it into the kernel scale.

        new_cache = None
        if cache is not None:
            if self_attention:
                if causal:
                    # The kernel mask is end-aligned (k = tk - tq): with
                    # a decode cache tq=1 vs tk=max_len it would admit
                    # every slot, including uninitialized future ones —
                    # silently wrong logits.  Decode callers must pass
                    # the position mask as an additive bias (the
                    # TransformerLM decode_step path does).
                    raise ValueError(
                        "causal=True is unsupported with a decode cache: "
                        "the kernel mask cannot know the cache fill; "
                        "pass the decode position mask as `bias` instead")
                k_step = self._split_heads(self.k_layer(y))
                v_step = self._split_heads(self.v_layer(y))
                k = jax.lax.dynamic_update_slice(
                    cache["k"], k_step.astype(cache["k"].dtype),
                    (0, 0, cache_index, 0))
                v = jax.lax.dynamic_update_slice(
                    cache["v"], v_step.astype(cache["v"].dtype),
                    (0, 0, cache_index, 0))
                new_cache = {"k": k, "v": v}
            else:
                # cross-attention: cache holds the projected encoder K/V
                k, v = cache["k"], cache["v"]
                new_cache = cache
        else:
            k = self._split_heads(self.k_layer(y))
            v = self._split_heads(self.v_layer(y))

        if self.training and self.attention_dropout > 0.0:
            # dropout on the softmax weights forces the materialized path
            # (reference dropLayer after softMaxLayer)
            logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                                preferred_element_type=jnp.float32)
            logits = logits / math.sqrt(d)
            if bias is not None:
                logits = logits + bias.astype(jnp.float32)
            if causal:
                tq, tk = logits.shape[-2], logits.shape[-1]
                mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
                logits = jnp.where(mask, logits, _NEG_INF)
            w = jax.nn.softmax(logits, axis=-1)
            keep = jax.random.bernoulli(
                next_rng_key(), 1.0 - self.attention_dropout, w.shape)
            w = jnp.where(keep, w / (1.0 - self.attention_dropout), 0.0)
            ctxt = jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v)
        else:
            ctxt = dot_product_attention(q, k, v, bias, causal=causal)
        out = self.output_layer(self._combine_heads(ctxt))
        if cache is not None:
            return out, new_cache
        return out

    def init_cache(self, batch: int, max_length: int, dtype=jnp.float32):
        d = self.hidden_size // self.num_heads
        shape = (batch, self.num_heads, max_length, d)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


class FeedForwardNetwork(Module):
    """Position-wise FFN: Linear→ReLU→Dropout→Linear
    (reference nn/FeedForwardNetwork.scala)."""

    def __init__(self, hidden_size: int, filter_size: int,
                 relu_dropout: float = 0.0):
        super().__init__()
        self.relu_dropout = relu_dropout
        self.filter_layer = Linear(hidden_size, filter_size, with_bias=True)
        self.output_layer = Linear(filter_size, hidden_size, with_bias=True)

    def forward(self, x):
        h = jax.nn.relu(self.filter_layer(x))
        if self.training and self.relu_dropout > 0.0:
            keep = jax.random.bernoulli(
                next_rng_key(), 1.0 - self.relu_dropout, h.shape)
            h = jnp.where(keep, h / (1.0 - self.relu_dropout), 0.0)
        return self.output_layer(h)


def _residual_dropout(x, p, training):
    if training and p > 0.0:
        keep = jax.random.bernoulli(next_rng_key(), 1.0 - p, x.shape)
        return jnp.where(keep, x / (1.0 - p), 0.0)
    return x


class TransformerEncoderLayer(Module):
    """Pre-norm encoder block: LN→self-attn→dropout→residual;
    LN→FFN→dropout→residual (reference Transformer.scala block(),
    encode branch)."""

    def __init__(self, hidden_size, num_heads, filter_size,
                 attention_dropout=0.0, ffn_dropout=0.0):
        super().__init__()
        self.ffn_dropout = ffn_dropout
        self.attn_norm = LayerNormalization(hidden_size)
        self.attn = Attention(hidden_size, num_heads, attention_dropout)
        self.ffn_norm = LayerNormalization(hidden_size)
        self.ffn = FeedForwardNetwork(hidden_size, filter_size, ffn_dropout)

    def forward(self, x, bias=None):
        y = self.attn(self.attn_norm(x), None, bias)
        x = x + _residual_dropout(y, self.ffn_dropout, self.training)
        y = self.ffn(self.ffn_norm(x))
        return x + _residual_dropout(y, self.ffn_dropout, self.training)


class TransformerDecoderLayer(Module):
    """Pre-norm decoder block: self-attn (causal) [+ cross-attn] + FFN
    (reference Transformer.scala block(), decode branch)."""

    def __init__(self, hidden_size, num_heads, filter_size,
                 attention_dropout=0.0, ffn_dropout=0.0,
                 with_cross_attention=True):
        super().__init__()
        self.ffn_dropout = ffn_dropout
        self.with_cross_attention = with_cross_attention
        self.self_norm = LayerNormalization(hidden_size)
        self.self_attn = Attention(hidden_size, num_heads, attention_dropout)
        if with_cross_attention:
            self.cross_norm = LayerNormalization(hidden_size)
            self.cross_attn = Attention(hidden_size, num_heads,
                                        attention_dropout)
        self.ffn_norm = LayerNormalization(hidden_size)
        self.ffn = FeedForwardNetwork(hidden_size, filter_size, ffn_dropout)

    def forward(self, x, self_bias=None, enc_out=None, enc_bias=None,
                cache=None, cache_index=None, self_causal=False):
        new_cache = None
        if cache is not None:
            if self_causal and self_bias is None:
                # the intent cannot be honored on the cache path (see
                # Attention.forward): decode callers carry causality in
                # the position bias
                raise ValueError(
                    "self_causal with a decode cache needs the decode "
                    "position mask passed as self_bias; the kernel-side "
                    "causal mask only applies to full-sequence forwards")
            y, self_cache = self.self_attn(
                self.self_norm(x), None, self_bias,
                cache=cache["self"], cache_index=cache_index)
            new_cache = dict(cache)
            new_cache["self"] = self_cache
        else:
            y = self.self_attn(self.self_norm(x), None, self_bias,
                               causal=self_causal)
        x = x + _residual_dropout(y, self.ffn_dropout, self.training)
        if self.with_cross_attention and enc_out is not None:
            if cache is not None and "cross" in cache:
                y, _ = self.cross_attn(self.cross_norm(x), enc_out, enc_bias,
                                       cache=cache["cross"])
            else:
                y = self.cross_attn(self.cross_norm(x), enc_out, enc_bias)
            x = x + _residual_dropout(y, self.ffn_dropout, self.training)
        y = self.ffn(self.ffn_norm(x))
        x = x + _residual_dropout(y, self.ffn_dropout, self.training)
        if cache is not None:
            return x, new_cache
        return x


class Transformer(Module):
    """Full transformer (reference nn/Transformer.scala:53).

    transformer_type:
      * "lm" — decoder-only language model: ``forward(tokens[B,T])`` →
        logits [B,T,vocab] when with_share_weights_linear (shared
        embedding/softmax matrix, reference shareWeights) else hidden
        [B,T,H].
      * "translation" — encoder-decoder: ``forward(src[B,Ts],
        tgt[B,Tt])`` → decoder hidden/logits.

    Token ids are 1-based with ``padding_value`` (default 0) as padding,
    matching the reference's LookupTable(paddingValue, maskZero=true).
    """

    def __init__(self, vocab_size: int, hidden_size: int, num_heads: int,
                 filter_size: int, num_hidden_layers: int,
                 embedding_dropout: float = 0.0,
                 attention_dropout: float = 0.0,
                 ffn_dropout: float = 0.0,
                 padding_value: float = 0.0,
                 with_share_weights_linear: bool = False,
                 transformer_type: str = "lm"):
        super().__init__()
        if transformer_type not in ("lm", "translation"):
            raise ValueError(transformer_type)
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.embedding_dropout = embedding_dropout
        self.padding_value = padding_value
        self.with_share_weights_linear = with_share_weights_linear
        self.transformer_type = transformer_type
        from bigdl_tpu.utils.rng import next_key
        self.embedding = Parameter(
            jax.random.normal(next_key(), (vocab_size, hidden_size))
            * (hidden_size ** -0.5))
        if transformer_type == "translation":
            self.encoder_layers = ModuleList([
                TransformerEncoderLayer(hidden_size, num_heads, filter_size,
                                        attention_dropout, ffn_dropout)
                for _ in range(num_hidden_layers)])
            self.encoder_norm = LayerNormalization(hidden_size)
        self.decoder_layers = ModuleList([
            TransformerDecoderLayer(
                hidden_size, num_heads, filter_size, attention_dropout,
                ffn_dropout,
                with_cross_attention=(transformer_type == "translation"))
            for _ in range(num_hidden_layers)])
        self.decoder_norm = LayerNormalization(hidden_size)

    # -- embedding ---------------------------------------------------------

    def embed(self, tokens):
        """LookupTable(padding→0) * sqrt(H) (reference buildLM embedding)."""
        idx = jnp.clip(tokens.astype(jnp.int32) - 1, 0, self.vocab_size - 1)
        emb = self.embedding[idx] * math.sqrt(self.hidden_size)
        mask = (tokens != self.padding_value)
        return emb * mask[..., None].astype(emb.dtype)

    def logits(self, hidden):
        """Project to vocab with the shared embedding matrix
        (reference linearSharedWeigths/shareWeights)."""
        return jnp.einsum("bth,vh->btv", hidden, self.embedding)

    # -- topologies --------------------------------------------------------

    def _decoder_input(self, emb):
        t = emb.shape[1]
        x = shift_right_3d(emb) + position_encoding(
            t, self.hidden_size, dtype=emb.dtype)
        return _residual_dropout(x, self.embedding_dropout, self.training)

    def encode(self, src):
        emb = self.embed(src)
        bias = padding_bias(src, self.padding_value)
        x = emb + position_encoding(emb.shape[1], self.hidden_size,
                                    dtype=emb.dtype)
        x = _residual_dropout(x, self.embedding_dropout, self.training)
        for layer in self.encoder_layers:
            x = layer(x, bias)
        return self.encoder_norm(x), bias

    def decode(self, tgt, enc_out=None, enc_bias=None):
        emb = self.embed(tgt)
        x = self._decoder_input(emb)
        self_bias = causal_bias(x.shape[1], x.dtype)
        for layer in self.decoder_layers:
            x = layer(x, self_bias, enc_out, enc_bias)
        x = self.decoder_norm(x)
        if self.with_share_weights_linear:
            return self.logits(x)
        return x

    def forward(self, *inputs):
        if self.transformer_type == "lm":
            (tokens,) = inputs
            return self.decode(tokens)
        src, tgt = inputs
        enc_out, enc_bias = self.encode(src)
        return self.decode(tgt, enc_out, enc_bias)

    # -- incremental decoding (used by SequenceBeamSearch) -----------------

    def init_decode_cache(self, batch: int, max_length: int,
                          dtype=jnp.float32, enc_out=None):
        """Fixed-size decode cache; when ``enc_out`` (encoder output) is
        given, each layer's cross-attention K/V is projected ONCE and
        cached (the reference re-projects per step via joinK/joinV)."""
        cache = []
        for layer in self.decoder_layers:
            entry = {"self": layer.self_attn.init_cache(
                batch, max_length, dtype)}
            if enc_out is not None and layer.with_cross_attention:
                ca = layer.cross_attn
                entry["cross"] = {
                    "k": ca._split_heads(ca.k_layer(enc_out)).astype(dtype),
                    "v": ca._split_heads(ca.v_layer(enc_out)).astype(dtype),
                }
            cache.append(entry)
        return cache

    def decode_step(self, token, step, cache, enc_out=None, enc_bias=None):
        """One decode step: token [B, 1] at position ``step`` (0-based
        traced int), fixed-size cache.  Returns (out [B, vocab] when
        with_share_weights_linear else hidden [B, H], new_cache) —
        consistent with decode()/forward(); wire an external head in
        your logits_fn when weights aren't shared.  ≙ reference
        Transformer.symbols (Transformer.scala) but with static
        shapes."""
        emb = self.embed(token)  # [B, 1, H]
        max_len = cache[0]["self"]["k"].shape[2]
        pos = position_encoding(max_len, self.hidden_size, dtype=emb.dtype)
        x = emb + jax.lax.dynamic_slice_in_dim(pos, step, 1, axis=0)[None]
        self_bias = incremental_bias(max_len, step)
        new_cache = []
        for layer, layer_cache in zip(self.decoder_layers, cache):
            x, lc = layer(x, self_bias, enc_out, enc_bias,
                          cache=layer_cache, cache_index=step)
            new_cache.append(lc)
        x = self.decoder_norm(x)
        if self.with_share_weights_linear:
            return self.logits(x)[:, 0, :], new_cache
        return x[:, 0, :], new_cache


# ---------------------------------------------------------------------------
# Beam search (reference nn/SequenceBeamSearch.scala)
# ---------------------------------------------------------------------------

class SequenceBeamSearch(Module):
    """Length-normalized beam search over a ``symbols_to_logits`` step
    function (reference nn/SequenceBeamSearch.scala:37).

    The search state is a fixed-shape pytree advanced by a jitted step;
    the loop runs ``lax.while_loop`` with the reference's early-stop
    condition (best alive score can no longer beat worst finished score
    under length normalization ``((5+len)/6)^alpha``,
    SequenceBeamSearch.scala lengthNormalization:89).
    """

    def __init__(self, vocab_size: int, beam_size: int, alpha: float,
                 max_decode_length: int, eos_id: int,
                 padding_value: float = 0.0):
        super().__init__()
        self.vocab_size = vocab_size
        self.beam_size = beam_size
        self.alpha = alpha
        self.max_decode_length = max_decode_length
        self.eos_id = eos_id
        self.padding_value = padding_value
        self._logits_fn = None

    def set_logit_fn(self, fn):
        """fn(flat_ids[B*beam, 1], step, cache) -> (logits[B*beam, V],
        cache)  (reference setLogitFn:309)."""
        self._logits_fn = fn
        return self

    def _length_norm(self, length):
        return ((5.0 + length) / 6.0) ** self.alpha

    def search(self, batch_size: int, initial_cache):
        """Run the search; returns (seq [B, beam, T+1], scores [B, beam])."""
        assert self._logits_fn is not None, "call set_logit_fn first"
        beam, vocab = self.beam_size, self.vocab_size
        tmax = self.max_decode_length

        def flatten(x):  # [B, beam, ...] -> [B*beam, ...]
            return x.reshape((batch_size * beam,) + x.shape[2:])

        def unflatten(x):
            return x.reshape((batch_size, beam) + x.shape[1:])

        neg = jnp.float32(_NEG_INF)
        alive_seq = jnp.zeros((batch_size, beam, tmax + 1), jnp.int32)
        alive_log_probs = jnp.tile(
            jnp.array([[0.0] + [float(_NEG_INF)] * (beam - 1)], jnp.float32),
            (batch_size, 1))
        finished_seq = jnp.zeros_like(alive_seq)
        finished_scores = jnp.full((batch_size, beam), neg)
        finished_flags = jnp.zeros((batch_size, beam), bool)
        # replicate the cache across beams
        cache = jax.tree_util.tree_map(
            lambda x: flatten(jnp.broadcast_to(
                x[:, None], (batch_size, beam) + x.shape[1:])),
            initial_cache)

        state = (jnp.int32(0), alive_seq, alive_log_probs, finished_seq,
                 finished_scores, finished_flags, cache)

        def cond(state):
            i, _, alive_lp, _, fin_scores, fin_flags, _ = state
            max_alive = alive_lp[:, 0] / self._length_norm(tmax)
            worst_fin = jnp.min(
                jnp.where(fin_flags, fin_scores, neg), axis=1)
            worst_fin = jnp.where(jnp.any(fin_flags, 1), worst_fin, neg)
            bound_met = jnp.all(worst_fin >= max_alive)
            return jnp.logical_and(i < tmax, jnp.logical_not(bound_met))

        def body(state):
            i, alive_seq, alive_lp, fin_seq, fin_scores, fin_flags, cache \
                = state
            ids = jax.lax.dynamic_slice_in_dim(alive_seq, i, 1, axis=2)
            logits, cache = self._logits_fn(flatten(ids), i, cache)
            log_probs = jax.nn.log_softmax(logits.astype(jnp.float32))
            log_probs = unflatten(log_probs) + alive_lp[:, :, None]
            flat_lp = log_probs.reshape(batch_size, beam * vocab)
            # 2*beam candidates so EOS-heavy rows keep enough alive beams
            top_lp, top_idx = jax.lax.top_k(flat_lp, 2 * beam)
            beam_idx = top_idx // vocab
            token_id = top_idx % vocab
            cand_seq = jnp.take_along_axis(
                alive_seq, beam_idx[:, :, None], axis=1)
            cand_seq = jax.lax.dynamic_update_slice_in_dim(
                cand_seq, token_id[:, :, None].astype(jnp.int32), i + 1,
                axis=2)
            is_eos = token_id == self.eos_id
            # new alive = best beam non-EOS candidates
            alive_cand_lp = jnp.where(is_eos, neg, top_lp)
            new_alive_lp, alive_sel = jax.lax.top_k(alive_cand_lp, beam)
            new_alive_seq = jnp.take_along_axis(
                cand_seq, alive_sel[:, :, None], axis=1)
            sel_beam = jnp.take_along_axis(beam_idx, alive_sel, axis=1)
            cache = jax.tree_util.tree_map(
                lambda x: flatten(jnp.take_along_axis(
                    unflatten(x),
                    sel_beam.reshape(sel_beam.shape + (1,) * (x.ndim - 1)),
                    axis=1)),
                cache)
            # finished pool = old finished + EOS candidates, keep top beam
            cand_scores = jnp.where(
                is_eos, top_lp / self._length_norm(i + 1), neg)
            pool_scores = jnp.concatenate([fin_scores, cand_scores], 1)
            pool_flags = jnp.concatenate(
                [fin_flags, is_eos], 1)
            pool_seq = jnp.concatenate([fin_seq, cand_seq], 1)
            new_fin_scores, fin_sel = jax.lax.top_k(pool_scores, beam)
            new_fin_seq = jnp.take_along_axis(
                pool_seq, fin_sel[:, :, None], axis=1)
            new_fin_flags = jnp.take_along_axis(pool_flags, fin_sel, axis=1)
            return (i + 1, new_alive_seq, new_alive_lp, new_fin_seq,
                    new_fin_scores, new_fin_flags, cache)

        (i, alive_seq, alive_lp, fin_seq, fin_scores, fin_flags, _) = \
            jax.lax.while_loop(cond, body, state)
        # rows with no finished hypothesis fall back to alive beams
        any_fin = jnp.any(fin_flags, axis=1, keepdims=True)
        seq = jnp.where(any_fin[:, :, None], fin_seq, alive_seq)
        scores = jnp.where(any_fin, fin_scores,
                           alive_lp / self._length_norm(tmax))
        return seq[:, :, 1:], scores

    def forward(self, batch_size, initial_cache):
        return self.search(int(batch_size), initial_cache)
