"""Recurrent layers: cells + time-iteration containers.

Reference: nn/Cell.scala (the recurrent-cell contract), nn/RnnCell
(RNN.scala), nn/LSTM.scala, nn/LSTMPeephole.scala, nn/GRU.scala,
nn/ConvLSTMPeephole.scala, nn/Recurrent.scala:47 (unrolls a Cell over
time), nn/BiRecurrent.scala, nn/RecurrentDecoder.scala,
nn/MultiRNNCell.scala, nn/TimeDistributed.scala.

TPU-first: the reference unrolls time steps in a sequential JVM loop
(Recurrent.scala:243); here iteration is ``lax.scan``, which XLA compiles
into a single fused loop with the cell's matmuls on the MXU.  The input
gate matmul for all timesteps is hoisted out of the scan (one big
[B*T, 4H] gemm) — the standard TPU trick the reference cannot do.

Layout: [batch, time, feature] (reference batchNormParams default).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, ModuleList, Parameter, next_rng_key
from bigdl_tpu.utils.rng import next_key

__all__ = [
    "Cell", "RnnCell", "RNN", "LSTM", "LSTMPeephole", "GRU",
    "ConvLSTMPeephole", "ConvLSTMPeephole3D",
    "Recurrent", "BiRecurrent", "RecurrentDecoder", "MultiRNNCell",
    "TimeDistributed",
]


class Cell(Module):
    """Recurrent cell protocol (reference nn/Cell.scala): ``step(x_t,
    state) -> (output_t, new_state)`` + ``init_state(batch)``."""

    def init_state(self, batch_size: int, dtype=jnp.float32):
        raise NotImplementedError

    def init_state_for(self, xproj, dtype=jnp.float32):
        """State for a hoisted projection ``xproj [B, T, ...]`` — cells
        whose state has spatial dims derive them from the projection."""
        return self.init_state(xproj.shape[0], dtype)

    def step(self, x_t, state):
        raise NotImplementedError

    def precompute_inputs(self, x):
        """Optional whole-sequence input projection hoisted out of the
        scan ([B,T,F] → [B,T,proj]); default identity."""
        return x

    def step_single(self, x_t, state):
        """step() on a raw (un-projected) single timestep."""
        proj = self.precompute_inputs(x_t[:, None])[:, 0]
        return self.step(proj, state)

    def _input_dropout(self, x, p: float):
        """Input-connection dropout (the reference cells' ``p`` param,
        nn/LSTM.scala); applied on the whole sequence before the hoisted
        projection."""
        if p <= 0.0 or not self.training:
            return x
        keep = jax.random.bernoulli(next_rng_key(), 1.0 - p, x.shape)
        return jnp.where(keep, x / (1.0 - p), 0.0)

    def forward(self, x, state=None):
        if state is None:
            state = self.init_state(x.shape[0], x.dtype)
        return self.step_single(x, state)


class RnnCell(Cell):
    """Vanilla RNN: h' = act(W x + U h + b) (reference nn/RNN.scala
    RnnCell)."""

    def __init__(self, input_size: int, hidden_size: int,
                 activation: Optional[Module] = None,
                 isInputWithBias: bool = True,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        super().__init__()
        self.hidden_size = hidden_size
        stdv = 1.0 / math.sqrt(hidden_size)
        self.w_input = Parameter(jax.random.uniform(
            next_key(), (input_size, hidden_size), minval=-stdv, maxval=stdv))
        self.w_hidden = Parameter(jax.random.uniform(
            next_key(), (hidden_size, hidden_size), minval=-stdv, maxval=stdv))
        self.bias = Parameter(jnp.zeros(hidden_size))
        self.activation = activation

    def init_state(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def precompute_inputs(self, x):
        return x @ self.w_input + self.bias

    def step(self, xproj_t, h):
        pre = xproj_t + h @ self.w_hidden
        h_new = self.activation(pre) if self.activation is not None \
            else jnp.tanh(pre)
        return h_new, h_new


class LSTM(Cell):
    """Standard LSTM (reference nn/LSTM.scala). Gate order i,f,g,o."""

    def __init__(self, input_size: int, hidden_size: int,
                 p: float = 0.0,
                 activation: Optional[Module] = None,
                 inner_activation: Optional[Module] = None,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.p = float(p)
        stdv = 1.0 / math.sqrt(hidden_size)
        self.w_input = Parameter(jax.random.uniform(
            next_key(), (input_size, 4 * hidden_size),
            minval=-stdv, maxval=stdv))
        self.w_hidden = Parameter(jax.random.uniform(
            next_key(), (hidden_size, 4 * hidden_size),
            minval=-stdv, maxval=stdv))
        self.bias = Parameter(jnp.zeros(4 * hidden_size))
        self.activation = activation
        self.inner_activation = inner_activation

    def init_state(self, batch_size, dtype=jnp.float32):
        return (jnp.zeros((batch_size, self.hidden_size), dtype),
                jnp.zeros((batch_size, self.hidden_size), dtype))

    def precompute_inputs(self, x):
        x = self._input_dropout(x, self.p)
        return x @ self.w_input + self.bias

    def step(self, xproj_t, state):
        h, c = state
        gates = xproj_t + h @ self.w_hidden
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        act = (lambda v: self.activation(v)) if self.activation \
            else jnp.tanh
        inner = (lambda v: self.inner_activation(v)) \
            if self.inner_activation else jax.nn.sigmoid
        c_new = inner(f) * c + inner(i) * act(g)
        h_new = inner(o) * act(c_new)
        return h_new, (h_new, c_new)


class LSTMPeephole(Cell):
    """LSTM with peephole connections from the cell state to the gates
    (reference nn/LSTMPeephole.scala)."""

    def __init__(self, input_size: int, hidden_size: int, p: float = 0.0,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.p = float(p)
        stdv = 1.0 / math.sqrt(hidden_size)
        self.w_input = Parameter(jax.random.uniform(
            next_key(), (input_size, 4 * hidden_size),
            minval=-stdv, maxval=stdv))
        self.w_hidden = Parameter(jax.random.uniform(
            next_key(), (hidden_size, 4 * hidden_size),
            minval=-stdv, maxval=stdv))
        self.bias = Parameter(jnp.zeros(4 * hidden_size))
        self.peep_i = Parameter(jnp.zeros(hidden_size))
        self.peep_f = Parameter(jnp.zeros(hidden_size))
        self.peep_o = Parameter(jnp.zeros(hidden_size))

    def init_state(self, batch_size, dtype=jnp.float32):
        return (jnp.zeros((batch_size, self.hidden_size), dtype),
                jnp.zeros((batch_size, self.hidden_size), dtype))

    def precompute_inputs(self, x):
        x = self._input_dropout(x, self.p)
        return x @ self.w_input + self.bias

    def step(self, xproj_t, state):
        h, c = state
        gates = xproj_t + h @ self.w_hidden
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i + self.peep_i * c)
        f = jax.nn.sigmoid(f + self.peep_f * c)
        c_new = f * c + i * jnp.tanh(g)
        o = jax.nn.sigmoid(o + self.peep_o * c_new)
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class GRU(Cell):
    """GRU (reference nn/GRU.scala). Gate order r,z then candidate."""

    def __init__(self, input_size: int, output_size: int, p: float = 0.0,
                 activation: Optional[Module] = None,
                 inner_activation: Optional[Module] = None,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        super().__init__()
        self.hidden_size = output_size
        self.p = float(p)
        self.activation = activation
        self.inner_activation = inner_activation
        stdv = 1.0 / math.sqrt(output_size)
        self.w_input = Parameter(jax.random.uniform(
            next_key(), (input_size, 3 * output_size),
            minval=-stdv, maxval=stdv))
        self.w_hidden = Parameter(jax.random.uniform(
            next_key(), (output_size, 2 * output_size),
            minval=-stdv, maxval=stdv))
        self.w_candidate = Parameter(jax.random.uniform(
            next_key(), (output_size, output_size),
            minval=-stdv, maxval=stdv))
        self.bias = Parameter(jnp.zeros(3 * output_size))

    def init_state(self, batch_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def precompute_inputs(self, x):
        x = self._input_dropout(x, self.p)
        return x @ self.w_input + self.bias

    def step(self, xproj_t, h):
        H = self.hidden_size
        inner = (lambda v: self.inner_activation(v)) \
            if self.inner_activation else jax.nn.sigmoid
        act = (lambda v: self.activation(v)) if self.activation \
            else jnp.tanh
        x_rz, x_g = xproj_t[..., :2 * H], xproj_t[..., 2 * H:]
        rz = inner(x_rz + h @ self.w_hidden)
        r, z = jnp.split(rz, 2, axis=-1)
        g = act(x_g + (r * h) @ self.w_candidate)
        h_new = (1 - z) * g + z * h
        return h_new, h_new


class ConvLSTMPeephole(Cell):
    """Convolutional LSTM over NHWC feature maps
    (reference nn/ConvLSTMPeephole.scala)."""

    def __init__(self, input_size: int, output_size: int,
                 kernel_i: int = 3, kernel_c: int = 3, stride: int = 1,
                 padding: int = -1, with_peephole: bool = True,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        super().__init__()
        from bigdl_tpu.nn.conv import SpatialConvolution
        self.output_size = output_size
        self.with_peephole = with_peephole
        # padding=-1 means SAME (the reference's default); an explicit
        # padding is honored on the input conv.  The hidden conv must be
        # shape-preserving, so it is always SAME.
        self.conv_input = SpatialConvolution(
            input_size, 4 * output_size, kernel_i, kernel_i,
            stride, stride, padding, padding)
        self.conv_hidden = SpatialConvolution(
            output_size, 4 * output_size, kernel_c, kernel_c,
            1, 1, -1, -1, with_bias=False)
        if with_peephole:
            self.peep_i = Parameter(jnp.zeros(output_size))
            self.peep_f = Parameter(jnp.zeros(output_size))
            self.peep_o = Parameter(jnp.zeros(output_size))

    def init_state(self, batch_size, dtype=jnp.float32,
                   spatial: Optional[Tuple[int, int]] = None):
        if spatial is None:
            raise ValueError("ConvLSTMPeephole needs spatial dims; pass "
                             "state explicitly or use Recurrent")
        h, w = spatial
        z = jnp.zeros((batch_size, h, w, self.output_size), dtype)
        return (z, z)

    def init_state_for(self, xproj, dtype=jnp.float32):
        # hidden spatial dims follow the (possibly strided) projection
        return self.init_state(xproj.shape[0], dtype,
                               spatial=tuple(xproj.shape[2:-1]))

    def precompute_inputs(self, x):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        proj = self.conv_input(flat)
        return proj.reshape((b, t) + proj.shape[1:])

    def step(self, xproj_t, state):
        h, c = state
        gates = xproj_t + self.conv_hidden(h)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if self.with_peephole:
            i = jax.nn.sigmoid(i + self.peep_i * c)
            f = jax.nn.sigmoid(f + self.peep_f * c)
        else:
            i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        c_new = f * c + i * jnp.tanh(g)
        if self.with_peephole:
            o = jax.nn.sigmoid(o + self.peep_o * c_new)
        else:
            o = jax.nn.sigmoid(o)
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class MultiRNNCell(Cell):
    """Stack of cells applied per timestep (reference nn/MultiRNNCell.scala)."""

    def __init__(self, cells):
        super().__init__()
        self.cells = ModuleList(list(cells))

    def init_state(self, batch_size, dtype=jnp.float32):
        return tuple(c.init_state(batch_size, dtype) for c in self.cells)

    def step(self, x_t, states):
        new_states = []
        out = x_t
        for cell, state in zip(self.cells, states):
            out, s = cell.step_single(out, state)
            new_states.append(s)
        return out, tuple(new_states)


class Recurrent(Module):
    """Iterate a Cell over the time axis of [batch, time, ...] via
    lax.scan (reference nn/Recurrent.scala:47).  Returns the full output
    sequence [batch, time, hidden]."""

    def __init__(self, cell: Cell):
        super().__init__()
        self.cell = cell

    def forward(self, x, init_state=None):
        cell = self.cell
        xproj = cell.precompute_inputs(x)
        if init_state is None:
            init_state = cell.init_state_for(xproj, x.dtype)
        xs = jnp.swapaxes(xproj, 0, 1)  # [T, B, ...]

        def body(state, x_t):
            # single cells consume the hoisted projection; MultiRNNCell's
            # precompute is identity and it projects per layer inside step
            out, new_state = cell.step(x_t, state)
            return new_state, out

        _, outs = jax.lax.scan(body, init_state, xs)
        return jnp.swapaxes(outs, 0, 1)


class BiRecurrent(Module):
    """Bidirectional wrapper merging forward and time-reversed passes
    (reference nn/BiRecurrent.scala; default merge = concat)."""

    def __init__(self, merge: Optional[Module] = None, cell: Cell = None,
                 cell_reverse: Cell = None):
        super().__init__()
        # convenience: BiRecurrent(cell) / BiRecurrent(cellA, cellB)
        if isinstance(merge, Cell):
            if cell is not None and cell_reverse is None:
                cell_reverse = cell
            merge, cell = None, merge
        if cell is None:
            raise ValueError("BiRecurrent needs a cell: "
                             "BiRecurrent(merge, cell=...) or "
                             "BiRecurrent(cell)")
        self.fwd = Recurrent(cell)
        self.bwd = Recurrent(cell_reverse if cell_reverse is not None
                             else cell.clone())
        if merge is not None:
            self.merge = merge
        self.use_concat = merge is None

    def forward(self, x):
        f = self.fwd(x)
        b = jnp.flip(self.bwd(jnp.flip(x, axis=1)), axis=1)
        if self.use_concat:
            return jnp.concatenate([f, b], axis=-1)
        return self.merge((f, b))


class RecurrentDecoder(Module):
    """Autoregressive unroll feeding the output back as the next input
    for ``output_length`` steps (reference nn/RecurrentDecoder.scala).
    Input: the first-step input [batch, ...]."""

    def __init__(self, output_length: int, cell: Cell = None):
        super().__init__()
        self.output_length = output_length
        self.cell = cell

    def forward(self, x, init_state=None):
        cell = self.cell
        if init_state is None:
            init_state = cell.init_state(x.shape[0], x.dtype)

        def body(carry, _):
            inp, state = carry
            out, new_state = cell.step_single(inp, state)
            return (out, new_state), out

        (_, _), outs = jax.lax.scan(
            body, (x, init_state), None, length=self.output_length)
        return jnp.swapaxes(outs, 0, 1)


class TimeDistributed(Module):
    """Apply a module independently at every timestep by folding time
    into batch (reference nn/TimeDistributed.scala)."""

    def __init__(self, layer: Module):
        super().__init__()
        self.layer = layer

    def forward(self, x):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        y = self.layer(flat)
        return y.reshape((b, t) + y.shape[1:])


class ConvLSTMPeephole3D(Cell):
    """Volumetric convolutional LSTM over NDHWC feature maps
    (reference nn/ConvLSTMPeephole3D.scala); same gate structure as the
    2-D variant with 3-D convs."""

    def __init__(self, input_size: int, output_size: int,
                 kernel_i: int = 3, kernel_c: int = 3, stride: int = 1,
                 padding: int = -1, with_peephole: bool = True,
                 w_regularizer=None, u_regularizer=None, b_regularizer=None):
        super().__init__()
        from bigdl_tpu.nn.conv import VolumetricConvolution
        self.output_size = output_size
        self.with_peephole = with_peephole
        self.conv_input = VolumetricConvolution(
            input_size, 4 * output_size, kernel_i, kernel_i, kernel_i,
            stride, stride, stride, padding, padding, padding)
        self.conv_hidden = VolumetricConvolution(
            output_size, 4 * output_size, kernel_c, kernel_c, kernel_c,
            1, 1, 1, -1, -1, -1, with_bias=False)
        if with_peephole:
            self.peep_i = Parameter(jnp.zeros(output_size))
            self.peep_f = Parameter(jnp.zeros(output_size))
            self.peep_o = Parameter(jnp.zeros(output_size))

    def init_state(self, batch_size, dtype=jnp.float32,
                   spatial=None):
        if spatial is None:
            raise ValueError("ConvLSTMPeephole3D needs (D, H, W) dims")
        d, h, w = spatial
        z = jnp.zeros((batch_size, d, h, w, self.output_size), dtype)
        return (z, z)

    def init_state_for(self, xproj, dtype=jnp.float32):
        return self.init_state(xproj.shape[0], dtype,
                               spatial=tuple(xproj.shape[2:-1]))

    def precompute_inputs(self, x):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        proj = self.conv_input(flat)
        return proj.reshape((b, t) + proj.shape[1:])

    def step(self, xproj_t, state):
        h, c = state
        gates = xproj_t + self.conv_hidden(h)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if self.with_peephole:
            i = jax.nn.sigmoid(i + self.peep_i * c)
            f = jax.nn.sigmoid(f + self.peep_f * c)
        else:
            i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f)
        c_new = f * c + i * jnp.tanh(g)
        if self.with_peephole:
            o = jax.nn.sigmoid(o + self.peep_o * c_new)
        else:
            o = jax.nn.sigmoid(o)
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


# Inventory alias: the reference's vanilla recurrent cell file is
# nn/RNN.scala (RnnCell class); both names resolve here.
RNN = RnnCell
