"""Pointwise activation layers.

Reference: the Torch-style activation zoo under spark/dl/.../nn/
(ReLU.scala, Tanh.scala, HardTanh.scala, ELU.scala, …).  On TPU these
are pure ``jnp`` elementwise ops that XLA fuses into neighbouring
matmuls/convs — the reference's MKL-VML dispatch (TensorNumeric.scala:542)
has no equivalent cost here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, Parameter, next_rng_key, has_rng
from bigdl_tpu.core import init as init_methods

__all__ = [
    "ReLU", "ReLU6", "Tanh", "Sigmoid", "HardSigmoid", "HardTanh",
    "LeakyReLU", "PReLU", "RReLU", "SReLU", "ELU", "SoftPlus", "SoftSign",
    "SoftShrink", "HardShrink", "TanhShrink", "SoftMax", "SoftMin",
    "LogSoftMax", "LogSigmoid", "Threshold", "BinaryThreshold", "Clamp",
    "Power", "Square", "Sqrt", "Log", "Exp", "Abs", "Negative",
    "GradientReversal", "AddConstant", "MulConstant", "GELU", "Swish",
]


class ReLU(Module):
    """max(0, x) (reference nn/ReLU.scala)."""

    def __init__(self, ip: bool = False):
        super().__init__()

    def forward(self, x):
        return jnp.maximum(x, 0)


class ReLU6(Module):
    """min(max(0, x), 6) (reference nn/ReLU6.scala)."""

    def forward(self, x):
        return jnp.clip(x, 0, 6)


class Tanh(Module):
    def forward(self, x):
        return jnp.tanh(x)


class Sigmoid(Module):
    def forward(self, x):
        return jax.nn.sigmoid(x)


class HardSigmoid(Module):
    """clip(0.2*x + 0.5, 0, 1) (reference nn/HardSigmoid.scala)."""

    def forward(self, x):
        return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


class HardTanh(Module):
    """clip(x, min_value, max_value) (reference nn/HardTanh.scala)."""

    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 ip: bool = False):
        super().__init__()
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def forward(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class LeakyReLU(Module):
    """x if x>0 else negval*x (reference nn/LeakyReLU.scala)."""

    def __init__(self, negval: float = 0.01, ip: bool = False):
        super().__init__()
        self.negval = float(negval)

    def forward(self, x):
        return jnp.where(x > 0, x, self.negval * x)


class PReLU(Module):
    """Learnable leaky slope, one weight (shared) or per channel
    (reference nn/PReLU.scala; channel dim is the last axis in NHWC)."""

    def __init__(self, n_output_plane: int = 0):
        super().__init__()
        self.n_output_plane = n_output_plane
        n = max(n_output_plane, 1)
        self.weight = Parameter(jnp.full((n,), 0.25))

    def forward(self, x):
        w = self.weight if self.n_output_plane > 0 else self.weight[0]
        return jnp.where(x > 0, x, w * x)


class RReLU(Module):
    """Randomized leaky ReLU: slope ~ U(lower, upper) in training,
    fixed mean slope in eval (reference nn/RReLU.scala)."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 ip: bool = False):
        super().__init__()
        self.lower, self.upper = float(lower), float(upper)

    def forward(self, x):
        if self.training and has_rng():
            a = jax.random.uniform(next_rng_key(), x.shape, x.dtype,
                                   self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(x >= 0, x, a * x)


class SReLU(Module):
    """S-shaped ReLU with 4 learnable per-channel params t_r, a_r, t_l, a_l
    (reference nn/SReLU.scala)."""

    def __init__(self, shape):
        super().__init__()
        shape = tuple(shape)
        self.t_left = Parameter(jnp.zeros(shape))
        self.a_left = Parameter(jnp.ones(shape))
        self.t_right = Parameter(jnp.ones(shape))
        self.a_right = Parameter(jnp.ones(shape))

    def forward(self, x):
        y = jnp.where(x >= self.t_right,
                      self.t_right + self.a_right * (x - self.t_right), x)
        return jnp.where(y <= self.t_left,
                         self.t_left + self.a_left * (y - self.t_left), y)


class ELU(Module):
    """alpha*(exp(x)-1) for x<0 else x (reference nn/ELU.scala)."""

    def __init__(self, alpha: float = 1.0, ip: bool = False):
        super().__init__()
        self.alpha = float(alpha)

    def forward(self, x):
        return jnp.where(x > 0, x, self.alpha * jnp.expm1(x))


class SoftPlus(Module):
    """log(1+exp(beta*x))/beta with linear tail for large x
    (reference nn/SoftPlus.scala)."""

    def __init__(self, beta: float = 1.0):
        super().__init__()
        self.beta = float(beta)

    def forward(self, x):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(Module):
    def forward(self, x):
        return x / (1.0 + jnp.abs(x))


class SoftShrink(Module):
    def __init__(self, lambd: float = 0.5):
        super().__init__()
        self.lambd = float(lambd)

    def forward(self, x):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.lambd, 0.0)


class HardShrink(Module):
    def __init__(self, lambd: float = 0.5):
        super().__init__()
        self.lambd = float(lambd)

    def forward(self, x):
        return jnp.where(jnp.abs(x) > self.lambd, x, 0.0)


class TanhShrink(Module):
    def forward(self, x):
        return x - jnp.tanh(x)


class SoftMax(Module):
    """Softmax over the feature axis (last axis; reference nn/SoftMax.scala
    normalizes dim 1 of NCHW — NHWC-native here)."""

    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return jax.nn.softmax(x, axis=self.axis)


class SoftMin(Module):
    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return jax.nn.softmax(-x, axis=self.axis)


class LogSoftMax(Module):
    def __init__(self, axis: int = -1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return jax.nn.log_softmax(x, axis=self.axis)


class LogSigmoid(Module):
    def forward(self, x):
        return jax.nn.log_sigmoid(x)


class Threshold(Module):
    """x if x > th else value (reference nn/Threshold.scala)."""

    def __init__(self, th: float = 1e-6, v: float = 0.0, ip: bool = False):
        super().__init__()
        self.th, self.v = float(th), float(v)

    def forward(self, x):
        return jnp.where(x > self.th, x, self.v)


class BinaryThreshold(Module):
    """1 if x > th else 0 (reference nn/BinaryThreshold.scala)."""

    def __init__(self, th: float = 1e-6):
        super().__init__()
        self.th = float(th)

    def forward(self, x):
        return (x > self.th).astype(x.dtype)


class Clamp(HardTanh):
    """Alias of HardTanh with int bounds (reference nn/Clamp.scala)."""

    def __init__(self, min_value: int, max_value: int):
        super().__init__(float(min_value), float(max_value))


class Power(Module):
    """(shift + scale*x)^power (reference nn/Power.scala)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0):
        super().__init__()
        self.power, self.scale, self.shift = float(power), float(scale), float(shift)

    def forward(self, x):
        return jnp.power(self.shift + self.scale * x, self.power)


class Square(Module):
    def forward(self, x):
        return x * x


class Sqrt(Module):
    def forward(self, x):
        return jnp.sqrt(x)


class Log(Module):
    def forward(self, x):
        return jnp.log(x)


class Exp(Module):
    def forward(self, x):
        return jnp.exp(x)


class Abs(Module):
    def forward(self, x):
        return jnp.abs(x)


class Negative(Module):
    def __init__(self, inplace: bool = False):
        super().__init__()

    def forward(self, x):
        return -x


@jax.custom_vjp
def _grad_reverse(x, lambd):
    return x


def _grad_reverse_fwd(x, lambd):
    return x, lambd


def _grad_reverse_bwd(lambd, g):
    return (-lambd * g, None)


_grad_reverse.defvjp(_grad_reverse_fwd, _grad_reverse_bwd)


class GradientReversal(Module):
    """Identity forward, -lambda * grad backward (reference
    nn/GradientReversal.scala; domain-adversarial training)."""

    def __init__(self, lambd: float = 1.0):
        super().__init__()
        self.lambd = float(lambd)

    def forward(self, x):
        return _grad_reverse(x, self.lambd)


class AddConstant(Module):
    def __init__(self, constant_scalar: float, ip: bool = False):
        super().__init__()
        self.constant_scalar = float(constant_scalar)

    def forward(self, x):
        return x + self.constant_scalar


class MulConstant(Module):
    def __init__(self, scalar: float, ip: bool = False):
        super().__init__()
        self.scalar = float(scalar)

    def forward(self, x):
        return x * self.scalar


class GELU(Module):
    """Gaussian error linear unit (used by the reference Transformer,
    nn/Transformer.scala gelu)."""

    def __init__(self, approximate: bool = True):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return jax.nn.gelu(x, approximate=self.approximate)


class Swish(Module):
    def forward(self, x):
        return x * jax.nn.sigmoid(x)
