"""Dense / parameterized elementwise layers.

Reference: nn/Linear.scala, nn/Bilinear.scala, nn/Add.scala, nn/Mul.scala,
nn/CMul.scala, nn/CAdd.scala, nn/Cosine.scala, nn/Euclidean.scala,
nn/LookupTable.scala, nn/Maxout.scala, nn/Highway.scala.

Weight layout is Torch-style (out, in) so gemm maps x @ W.T onto the MXU;
init defaults mirror the reference (uniform 1/sqrt(fan_in) unless an
InitializationMethod is set, Linear.scala setInitMethod).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, Parameter
from bigdl_tpu.core import init as init_methods
from bigdl_tpu.utils.rng import next_key

__all__ = [
    "Linear", "Bilinear", "Add", "Mul", "CMul", "CAdd", "Cosine",
    "Euclidean", "LookupTable", "Maxout", "Highway", "Identity", "Echo",
]


class Linear(Module):
    """y = x W^T + b (reference nn/Linear.scala)."""

    def __init__(self, input_size: int, output_size: int,
                 with_bias: bool = True,
                 w_regularizer=None, b_regularizer=None,
                 init_weight=None, init_bias=None,
                 init_method=None):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        im = init_method or init_methods.RandomUniform()
        if init_weight is not None:
            self.weight = Parameter(init_weight)
        else:
            self.weight = Parameter(
                im(next_key(), (output_size, input_size),
                   fan_in=input_size, fan_out=output_size))
        if with_bias:
            if init_bias is not None:
                self.bias = Parameter(init_bias)
            else:
                bound = 1.0 / math.sqrt(input_size)
                self.bias = Parameter(jax.random.uniform(
                    next_key(), (output_size,), minval=-bound, maxval=bound))

    def forward(self, x):
        squeeze = x.ndim == 1
        if squeeze:
            x = x[None]
        y = x @ self.weight.T
        if self.with_bias:
            y = y + self.bias
        return y[0] if squeeze else y


class Identity(Module):
    """Pass-through (reference nn/Identity.scala)."""

    def forward(self, *xs):
        return xs[0] if len(xs) == 1 else xs


class Echo(Module):
    """Identity that prints activation shape when tracing — debugging aid
    (reference nn/Echo.scala)."""

    def forward(self, x):
        print(f"[Echo {self.name}] shape={getattr(x, 'shape', None)} "
              f"dtype={getattr(x, 'dtype', None)}")
        return x


class Bilinear(Module):
    """y_k = x1^T W_k x2 + b_k over two table inputs
    (reference nn/Bilinear.scala)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.bias_res = bias_res
        stdv = 1.0 / math.sqrt(input_size1)
        self.weight = Parameter(jax.random.uniform(
            next_key(), (output_size, input_size1, input_size2),
            minval=-stdv, maxval=stdv))
        if bias_res:
            self.bias = Parameter(jax.random.uniform(
                next_key(), (output_size,), minval=-stdv, maxval=stdv))

    def forward(self, inputs):
        x1, x2 = inputs[0], inputs[1]
        y = jnp.einsum("bi,oij,bj->bo", x1, self.weight, x2)
        if self.bias_res:
            y = y + self.bias
        return y


class Add(Module):
    """Learnable per-element additive bias (reference nn/Add.scala)."""

    def __init__(self, input_size: int):
        super().__init__()
        stdv = 1.0 / math.sqrt(input_size)
        self.bias = Parameter(jax.random.uniform(
            next_key(), (input_size,), minval=-stdv, maxval=stdv))

    def forward(self, x):
        return x + self.bias


class Mul(Module):
    """Single learnable scalar gain (reference nn/Mul.scala)."""

    def __init__(self):
        super().__init__()
        self.weight = Parameter(jax.random.uniform(
            next_key(), (1,), minval=-1.0, maxval=1.0))

    def forward(self, x):
        return x * self.weight[0]


class CMul(Module):
    """Learnable componentwise gain, broadcast over batch
    (reference nn/CMul.scala)."""

    def __init__(self, size):
        super().__init__()
        size = tuple(size)
        n = 1
        for s in size:
            n *= s
        stdv = 1.0 / math.sqrt(n)
        self.weight = Parameter(jax.random.uniform(
            next_key(), size, minval=-stdv, maxval=stdv))

    def forward(self, x):
        return x * self.weight


class CAdd(Module):
    """Learnable componentwise bias (reference nn/CAdd.scala)."""

    def __init__(self, size, b_regularizer=None):
        super().__init__()
        size = tuple(size)
        n = 1
        for s in size:
            n *= s
        stdv = 1.0 / math.sqrt(n)
        self.bias = Parameter(jax.random.uniform(
            next_key(), size, minval=-stdv, maxval=stdv))

    def forward(self, x):
        return x + self.bias


class Cosine(Module):
    """Cosine similarity of input to each weight row
    (reference nn/Cosine.scala)."""

    def __init__(self, input_size: int, output_size: int):
        super().__init__()
        stdv = 1.0 / math.sqrt(input_size)
        self.weight = Parameter(jax.random.uniform(
            next_key(), (output_size, input_size), minval=-stdv, maxval=stdv))

    def forward(self, x):
        xn = x / (jnp.linalg.norm(x, axis=-1, keepdims=True) + 1e-12)
        wn = self.weight / (
            jnp.linalg.norm(self.weight, axis=-1, keepdims=True) + 1e-12)
        return xn @ wn.T


class Euclidean(Module):
    """L2 distance of input to each weight column
    (reference nn/Euclidean.scala)."""

    def __init__(self, input_size: int, output_size: int,
                 fast_backward: bool = True):
        super().__init__()
        stdv = 1.0 / math.sqrt(input_size)
        self.weight = Parameter(jax.random.uniform(
            next_key(), (output_size, input_size), minval=-stdv, maxval=stdv))

    def forward(self, x):
        diff = x[:, None, :] - self.weight[None, :, :]
        return jnp.linalg.norm(diff, axis=-1)


class LookupTable(Module):
    """Embedding lookup with optional max-norm renorm and padding index
    (reference nn/LookupTable.scala).  Indices are 1-based as in the
    reference/Torch convention."""

    def __init__(self, n_index: int, n_output: int,
                 padding_value: float = 0.0,
                 max_norm: float = float("inf"),
                 norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False,
                 w_regularizer=None,
                 mask_zero: bool = False):
        super().__init__()
        self.n_index, self.n_output = n_index, n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.mask_zero = mask_zero
        self.weight = Parameter(jax.random.normal(
            next_key(), (n_index, n_output)))

    def forward(self, indices):
        idx = jnp.asarray(indices).astype(jnp.int32) - 1  # 1-based → 0-based
        idx = jnp.clip(idx, 0, self.n_index - 1)
        w = self.weight
        if self.max_norm != float("inf"):
            norms = jnp.linalg.norm(w, ord=self.norm_type, axis=1,
                                    keepdims=True)
            w = w * jnp.minimum(1.0, self.max_norm / (norms + 1e-7))
        out = w[idx]
        if self.mask_zero and self.padding_value != 0:
            mask = (jnp.asarray(indices) != self.padding_value)
            out = out * mask[..., None].astype(out.dtype)
        return out


class Maxout(Module):
    """Linear to maxout_number pieces, max over pieces
    (reference nn/Maxout.scala)."""

    def __init__(self, input_size: int, output_size: int, maxout_number: int,
                 with_bias: bool = True, w_regularizer=None,
                 b_regularizer=None, init_weight=None, init_bias=None):
        super().__init__()
        self.output_size = output_size
        self.maxout_number = maxout_number
        self.layer = Linear(input_size, output_size * maxout_number,
                            with_bias=with_bias,
                            init_weight=init_weight, init_bias=init_bias)

    def forward(self, x):
        y = self.layer(x)
        y = y.reshape(y.shape[:-1] + (self.maxout_number, self.output_size))
        return jnp.max(y, axis=-2)


class Highway(Module):
    """Highway network layer: t*g(Wx) + (1-t)*x
    (reference nn/Highway.scala)."""

    def __init__(self, size: int, with_bias: bool = True,
                 activation: Optional[Module] = None,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.gate = Linear(size, size, with_bias=with_bias)
        self.transform = Linear(size, size, with_bias=with_bias)
        self.activation = activation

    def forward(self, x):
        t = jax.nn.sigmoid(self.gate(x))
        h = self.transform(x)
        if self.activation is not None:
            h = self.activation(h)
        return t * h + (1.0 - t) * x
