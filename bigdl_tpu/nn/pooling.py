"""Pooling / resize layers.

Reference: nn/SpatialMaxPooling.scala, nn/SpatialAveragePooling.scala,
nn/TemporalMaxPooling.scala, nn/VolumetricMaxPooling.scala,
nn/VolumetricAveragePooling.scala, nn/UpSampling1D.scala,
nn/UpSampling2D.scala, nn/UpSampling3D.scala, nn/ResizeBilinear.scala.

Built on ``lax.reduce_window`` (XLA's native pooling primitive).
Layout NHWC by default, NCHW accepted.  ``ceil_mode`` mirrors the
reference's setCeilMode (SpatialMaxPooling.scala ceil/floor output size).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module

__all__ = [
    "SpatialMaxPooling", "SpatialAveragePooling", "TemporalMaxPooling",
    "VolumetricMaxPooling", "VolumetricAveragePooling",
    "UpSampling1D", "UpSampling2D", "UpSampling3D", "ResizeBilinear",
    "GlobalAveragePooling2D", "GlobalAveragePooling3D",
    "GlobalMaxPooling3D",
]


def _pool_pads(in_size, k, s, pad, ceil_mode):
    """Explicit (lo, hi) padding per spatial dim implementing the
    reference's floor/ceil output-size formula."""
    if pad == -1:  # SAME
        out = -(-in_size // s)
        total = max((out - 1) * s + k - in_size, 0)
        return (total // 2, total - total // 2)
    if ceil_mode:
        out = int(math.ceil((in_size + 2 * pad - k) / s)) + 1
        # Torch: ensure last window starts inside the (padded) input
        if (out - 1) * s >= in_size + pad:
            out -= 1
    else:
        out = int(math.floor((in_size + 2 * pad - k) / s)) + 1
    hi = max((out - 1) * s + k - in_size - pad, pad)
    return (pad, hi)


class SpatialMaxPooling(Module):
    """2-D max pool (reference nn/SpatialMaxPooling.scala)."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None,
                 dh: Optional[int] = None, pad_w: int = 0, pad_h: int = 0,
                 data_format: str = "NHWC"):
        super().__init__()
        self.kernel = (kh, kw)
        self.stride = (dh or kh, dw or kw)
        self.pad = (pad_h, pad_w)
        self.ceil_mode = False
        self.data_format = data_format

    def ceil(self):
        self.ceil_mode = True
        return self

    def floor(self):
        self.ceil_mode = False
        return self

    def forward(self, x):
        nchw = self.data_format == "NCHW"
        if nchw:
            x = jnp.transpose(x, (0, 2, 3, 1))
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.pad
        pads = ((0, 0),
                _pool_pads(x.shape[1], kh, sh, ph, self.ceil_mode),
                _pool_pads(x.shape[2], kw, sw, pw, self.ceil_mode),
                (0, 0))
        y = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1, kh, kw, 1),
            window_strides=(1, sh, sw, 1),
            padding=pads)
        return jnp.transpose(y, (0, 3, 1, 2)) if nchw else y


class SpatialAveragePooling(Module):
    """2-D average pool (reference nn/SpatialAveragePooling.scala;
    count_include_pad + divide toggles)."""

    def __init__(self, kw: int, kh: int, dw: int = 1, dh: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 global_pooling: bool = False,
                 ceil_mode: bool = False,
                 count_include_pad: bool = True,
                 divide: bool = True,
                 data_format: str = "NHWC"):
        super().__init__()
        self.kernel = (kh, kw)
        self.stride = (dh, dw)
        self.pad = (pad_h, pad_w)
        self.global_pooling = global_pooling
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide
        self.data_format = data_format

    def ceil(self):
        self.ceil_mode = True
        return self

    def forward(self, x):
        nchw = self.data_format == "NCHW"
        if nchw:
            x = jnp.transpose(x, (0, 2, 3, 1))
        if self.global_pooling:
            kh, kw = x.shape[1], x.shape[2]
            sh, sw = 1, 1
            ph = pw = 0
        else:
            kh, kw = self.kernel
            sh, sw = self.stride
            ph, pw = self.pad
        pads = ((0, 0),
                _pool_pads(x.shape[1], kh, sh, ph, self.ceil_mode),
                _pool_pads(x.shape[2], kw, sw, pw, self.ceil_mode),
                (0, 0))
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add,
            window_dimensions=(1, kh, kw, 1),
            window_strides=(1, sh, sw, 1),
            padding=pads)
        if self.divide:
            if self.count_include_pad:
                y = summed / (kh * kw)
            else:
                ones = jnp.ones_like(x)
                counts = jax.lax.reduce_window(
                    ones, 0.0, jax.lax.add,
                    window_dimensions=(1, kh, kw, 1),
                    window_strides=(1, sh, sw, 1),
                    padding=pads)
                y = summed / counts
        else:
            y = summed
        return jnp.transpose(y, (0, 3, 1, 2)) if nchw else y


class GlobalAveragePooling2D(SpatialAveragePooling):
    """Keras-style global average pool, squeezing spatial dims."""

    def __init__(self, data_format: str = "NHWC"):
        super().__init__(1, 1, global_pooling=True, data_format=data_format)

    def forward(self, x):
        y = super().forward(x)
        if self.data_format == "NHWC":
            return y[:, 0, 0, :]
        return y[:, :, 0, 0]


class GlobalAveragePooling3D(Module):
    """Global average over the three spatial dims of NDHWC
    (keras GlobalAveragePooling3D; reduces to [batch, channels])."""

    def forward(self, x):
        return jnp.mean(x, axis=(1, 2, 3))


class GlobalMaxPooling3D(Module):
    """Global max over the three spatial dims of NDHWC
    (keras GlobalMaxPooling3D)."""

    def forward(self, x):
        return jnp.max(x, axis=(1, 2, 3))


class TemporalMaxPooling(Module):
    """1-D max pool over [batch, time, feat]
    (reference nn/TemporalMaxPooling.scala)."""

    def __init__(self, k_w: int, d_w: Optional[int] = None):
        super().__init__()
        self.k_w = k_w
        self.d_w = d_w or k_w

    def forward(self, x):
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1, self.k_w, 1),
            window_strides=(1, self.d_w, 1),
            padding="VALID")


class VolumetricMaxPooling(Module):
    """3-D max pool over NDHWC (reference nn/VolumetricMaxPooling.scala)."""

    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: Optional[int] = None, d_w: Optional[int] = None,
                 d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)

    def forward(self, x):
        kt, kh, kw = self.kernel
        st, sh, sw = self.stride
        pt, ph, pw = self.pad
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1, kt, kh, kw, 1),
            window_strides=(1, st, sh, sw, 1),
            padding=((0, 0), (pt, pt), (ph, ph), (pw, pw), (0, 0)))


class VolumetricAveragePooling(Module):
    """3-D average pool (reference nn/VolumetricAveragePooling.scala)."""

    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: Optional[int] = None, d_w: Optional[int] = None,
                 d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 count_include_pad: bool = True, ceil_mode: bool = False):
        super().__init__()
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.count_include_pad = count_include_pad
        self.ceil_mode = ceil_mode

    def forward(self, x):
        kt, kh, kw = self.kernel
        st, sh, sw = self.stride
        pt, ph, pw = self.pad
        pads = ((0, 0),
                _pool_pads(x.shape[1], kt, st, pt, self.ceil_mode),
                _pool_pads(x.shape[2], kh, sh, ph, self.ceil_mode),
                _pool_pads(x.shape[3], kw, sw, pw, self.ceil_mode),
                (0, 0))
        dims = (1, kt, kh, kw, 1)
        strides = (1, st, sh, sw, 1)
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, window_dimensions=dims,
            window_strides=strides, padding=pads)
        if self.count_include_pad:
            return summed / (kt * kh * kw)
        counts = jax.lax.reduce_window(
            jnp.ones_like(x), 0.0, jax.lax.add, window_dimensions=dims,
            window_strides=strides, padding=pads)
        return summed / counts


class UpSampling1D(Module):
    """Repeat timesteps length times (reference nn/UpSampling1D.scala)."""

    def __init__(self, length: int):
        super().__init__()
        self.length = length

    def forward(self, x):
        return jnp.repeat(x, self.length, axis=1)


class UpSampling2D(Module):
    """Nearest-neighbour upsample (reference nn/UpSampling2D.scala)."""

    def __init__(self, size: Tuple[int, int], data_format: str = "NHWC"):
        super().__init__()
        self.size = tuple(size)
        self.data_format = data_format

    def forward(self, x):
        h, w = self.size
        if self.data_format == "NHWC":
            return jnp.repeat(jnp.repeat(x, h, axis=1), w, axis=2)
        return jnp.repeat(jnp.repeat(x, h, axis=2), w, axis=3)


class UpSampling3D(Module):
    """Nearest-neighbour 3-D upsample (reference nn/UpSampling3D.scala)."""

    def __init__(self, size: Tuple[int, int, int]):
        super().__init__()
        self.size = tuple(size)

    def forward(self, x):
        t, h, w = self.size
        x = jnp.repeat(x, t, axis=1)
        x = jnp.repeat(x, h, axis=2)
        return jnp.repeat(x, w, axis=3)


class ResizeBilinear(Module):
    """Bilinear resize to (out_height, out_width)
    (reference nn/ResizeBilinear.scala; align_corners semantics)."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, data_format: str = "NHWC"):
        super().__init__()
        self.out_size = (output_height, output_width)
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        nchw = self.data_format == "NCHW"
        if nchw:
            x = jnp.transpose(x, (0, 2, 3, 1))
        oh, ow = self.out_size
        if self.align_corners:
            # jax.image has no align_corners; do explicit gather math
            h, w = x.shape[1], x.shape[2]
            ys = jnp.linspace(0, h - 1, oh)
            xs = jnp.linspace(0, w - 1, ow)
            y0 = jnp.floor(ys).astype(jnp.int32)
            x0 = jnp.floor(xs).astype(jnp.int32)
            y1 = jnp.minimum(y0 + 1, h - 1)
            x1 = jnp.minimum(x0 + 1, w - 1)
            wy = (ys - y0)[None, :, None, None]
            wx = (xs - x0)[None, None, :, None]
            g = lambda yi, xi: x[:, yi][:, :, xi]
            y = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1, x0) * wy * (1 - wx)
                 + g(y0, x1) * (1 - wy) * wx + g(y1, x1) * wy * wx)
        else:
            y = jax.image.resize(
                x, (x.shape[0], oh, ow, x.shape[3]), method="bilinear")
        return jnp.transpose(y, (0, 3, 1, 2)) if nchw else y
