"""Stochastic regularization layers.

Reference: nn/Dropout.scala, nn/SpatialDropout1D.scala,
nn/SpatialDropout2D.scala, nn/SpatialDropout3D.scala,
nn/GaussianDropout.scala, nn/GaussianNoise.scala,
nn/GaussianSampler.scala, nn/ActivityRegularization.scala,
nn/L1Penalty.scala, nn/NegativeEntropyPenalty.scala.

All stochastic layers draw from the ambient ``forward_context`` RNG
(see core/module.py); in eval mode they are deterministic pass-throughs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import Module, next_rng_key

__all__ = [
    "Dropout", "SpatialDropout1D", "SpatialDropout2D", "SpatialDropout3D",
    "GaussianDropout", "GaussianNoise", "GaussianSampler",
    "ActivityRegularization", "L1Penalty", "NegativeEntropyPenalty",
]


class Dropout(Module):
    """Zero with prob init_p, scale kept values by 1/(1-p) when
    scale=True (reference nn/Dropout.scala)."""

    def __init__(self, init_p: float = 0.5, inplace: bool = False,
                 scale: bool = True):
        super().__init__()
        self.p = float(init_p)
        self.scale = scale

    def forward(self, x):
        if not self.training or self.p <= 0.0:
            return x
        keep = jax.random.bernoulli(next_rng_key(), 1.0 - self.p, x.shape)
        y = jnp.where(keep, x, 0.0)
        return y / (1.0 - self.p) if self.scale else y


class _SpatialDropoutND(Module):
    """Drop whole feature maps (channel-last convention)."""

    def __init__(self, init_p: float = 0.5):
        super().__init__()
        self.p = float(init_p)

    def forward(self, x):
        if not self.training or self.p <= 0.0:
            return x
        # mask shape: broadcast over all spatial dims, per (batch, channel)
        mask_shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        keep = jax.random.bernoulli(next_rng_key(), 1.0 - self.p, mask_shape)
        return jnp.where(keep, x, 0.0)


class SpatialDropout1D(_SpatialDropoutND):
    """(reference nn/SpatialDropout1D.scala)"""


class SpatialDropout2D(_SpatialDropoutND):
    """(reference nn/SpatialDropout2D.scala)"""

    def __init__(self, init_p: float = 0.5, data_format: str = "NHWC"):
        super().__init__(init_p)
        self.data_format = data_format

    def forward(self, x):
        if self.data_format == "NCHW":
            if not self.training or self.p <= 0.0:
                return x
            mask_shape = (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2)
            keep = jax.random.bernoulli(next_rng_key(), 1.0 - self.p,
                                        mask_shape)
            return jnp.where(keep, x, 0.0)
        return super().forward(x)


class SpatialDropout3D(SpatialDropout2D):
    """(reference nn/SpatialDropout3D.scala)"""


class GaussianDropout(Module):
    """Multiply by N(1, p/(1-p)) in training
    (reference nn/GaussianDropout.scala)."""

    def __init__(self, rate: float):
        super().__init__()
        self.rate = float(rate)

    def forward(self, x):
        if not self.training:
            return x
        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + stddev * jax.random.normal(next_rng_key(), x.shape)
        return x * noise


class GaussianNoise(Module):
    """Additive N(0, stddev) noise in training
    (reference nn/GaussianNoise.scala)."""

    def __init__(self, stddev: float):
        super().__init__()
        self.stddev = float(stddev)

    def forward(self, x):
        if not self.training:
            return x
        return x + self.stddev * jax.random.normal(next_rng_key(), x.shape)


class GaussianSampler(Module):
    """Reparameterized sampling from (mean, log_var) table — VAE latent
    (reference nn/GaussianSampler.scala)."""

    def forward(self, inputs):
        mean, log_var = inputs
        eps = jax.random.normal(next_rng_key(), mean.shape)
        return mean + jnp.exp(0.5 * log_var) * eps


class ActivityRegularization(Module):
    """Identity that records an activity penalty, exposed via .loss
    (reference nn/ActivityRegularization.scala).  The stored penalty is
    a buffer so it rides out of jit with the updated module."""

    def __init__(self, l1: float = 0.0, l2: float = 0.0):
        super().__init__()
        self.l1, self.l2 = float(l1), float(l2)
        self.loss = jnp.zeros(())

    def forward(self, x):
        self.loss = self.l1 * jnp.sum(jnp.abs(x)) \
            + self.l2 * jnp.sum(x * x)
        return x


class L1Penalty(Module):
    """Identity adding L1 sparsity penalty on activations
    (reference nn/L1Penalty.scala)."""

    def __init__(self, l1weight: float, size_average: bool = False,
                 provide_output: bool = True):
        super().__init__()
        self.l1weight = float(l1weight)
        self.size_average = size_average
        self.loss = jnp.zeros(())

    def forward(self, x):
        penalty = self.l1weight * jnp.sum(jnp.abs(x))
        if self.size_average:
            penalty = penalty / x.size
        self.loss = penalty
        return x


class NegativeEntropyPenalty(Module):
    """Identity adding -beta*H(p) penalty to encourage exploration
    (reference nn/NegativeEntropyPenalty.scala)."""

    def __init__(self, beta: float = 0.01):
        super().__init__()
        self.beta = float(beta)
        self.loss = jnp.zeros(())

    def forward(self, x):
        self.loss = self.beta * jnp.sum(x * jnp.log(x + 1e-8))
        return x
