"""Normalization layers.

Reference: nn/BatchNormalization.scala, nn/SpatialBatchNormalization.scala,
nn/LayerNormalization.scala, nn/Normalize.scala, nn/NormalizeScale.scala,
nn/SpatialCrossMapLRN.scala, nn/SpatialWithinChannelLRN.scala,
nn/SpatialContrastiveNormalization.scala,
nn/SpatialDivisiveNormalization.scala,
nn/SpatialSubtractiveNormalization.scala.

BatchNorm running stats are module *buffers*: forward in training mode
mutates them on the traced copy, and the updated module comes back out of
the jitted step (see core/module.py design note).  In a data-parallel
mesh the batch axis is global because XLA computes the mean/var over the
full sharded batch — matching the reference's per-replica BN only if you
ask for it via sync=False (local shard stats via shard_map is a later
extension; XLA's default here is *sync* BN, strictly better).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from bigdl_tpu.core.module import Module, Parameter
from bigdl_tpu.utils.rng import next_key

__all__ = [
    "BatchNormalization", "SpatialBatchNormalization", "LayerNormalization",
    "Normalize", "NormalizeScale", "SpatialCrossMapLRN",
    "SpatialWithinChannelLRN", "Scale", "SpatialSubtractiveNormalization",
    "SpatialDivisiveNormalization", "SpatialContrastiveNormalization",
    "GroupNorm",
]


class BatchNormalization(Module):
    """BatchNorm over the feature (last) axis of [batch, feat]
    (reference nn/BatchNormalization.scala; eps/momentum defaults match)."""

    reduce_axes = (0,)

    def __init__(self, n_output: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 init_weight=None, init_bias=None,
                 init_grad_weight=None, init_grad_bias=None):
        super().__init__()
        self.n_output = n_output
        self.eps = float(eps)
        self.momentum = float(momentum)
        self.affine = affine
        if affine:
            self.weight = Parameter(
                init_weight if init_weight is not None
                else jax.random.uniform(next_key(), (n_output,)))
            self.bias = Parameter(
                init_bias if init_bias is not None else jnp.zeros(n_output))
        self.running_mean = jnp.zeros(n_output)
        self.running_var = jnp.ones(n_output)

    def batch_stats(self, x):
        """Shifted one-pass statistics: with K = running_mean (a
        constant under autodiff), E[x-K] and E[(x-K)^2] are
        *independent* reductions, so XLA multi-output-fuses them
        into a single sweep over the activation; jnp.var(x) needs
        E[x] first, forcing a second full read — measurably slower
        on HBM-bound BN-heavy convnets.  var = E[(x-K)^2] -
        E[x-K]^2 is exact algebra whose f32 cancellation error
        scales with |E[x]-K|/std, small both at init (K=0 and conv
        outputs are zero-centered) and in steady state (K tracks
        the batch mean) — unlike the unshifted E[x^2]-E[x]^2 fast
        path, which loses all precision for |mean|/std >~ 3e3.
        Stats accumulate in f32 regardless of compute dtype.

        Exposed separately so the fused conv+BN Pallas path
        (ops/conv_bn_kernels.py) can produce the same (d_mean, d_sq)
        as a kernel epilogue and share :meth:`fold_stats`."""
        xf = x.astype(jnp.float32)
        k = jax.lax.stop_gradient(
            self.running_mean.astype(jnp.float32))
        xs = xf - k
        d_mean = jnp.mean(xs, axis=self.reduce_axes)
        d_sq = jnp.mean(jnp.square(xs), axis=self.reduce_axes)
        return d_mean, d_sq

    def fold_stats(self, d_mean, d_sq, n: int):
        """Turn shifted stats into (mean, var) and update the running
        buffers (momentum + unbiased correction, exactly the reference's
        BatchNormalization.scala update)."""
        k = jax.lax.stop_gradient(
            self.running_mean.astype(jnp.float32))
        var = jnp.maximum(d_sq - jnp.square(d_mean), 0.0)
        mean = k + d_mean
        # Remat anchors (no-ops outside a names-policy checkpoint):
        # batch stats are C-sized — saving them costs nothing and
        # spares the backward a full re-reduction over the
        # activation when the normalize chain is rematerialized.
        mean = checkpoint_name(mean, "bn_stat")
        var = checkpoint_name(var, "bn_stat")
        m = self.momentum
        self.running_mean = (1 - m) * self.running_mean + m * mean
        unbiased = var * n / max(n - 1, 1)
        self.running_var = (1 - m) * self.running_var + m * unbiased
        return mean, var

    def normalize(self, x, mean, var):
        """Normalize subtract-first in f32: (x - mean) of two nearby
        values is exact, whereas folding mean into a shift vector
        (x*scale + (bias - mean*scale)) differences two large
        intermediates and loses the output to cancellation for
        large-|mean| channels — fatal in bf16.  The whole chain is one
        fused elementwise pass either way (reads x in its dtype,
        writes y in its dtype), so f32 register math costs nothing."""
        xf = x.astype(jnp.float32)
        inv = jax.lax.rsqrt(var.astype(jnp.float32) + self.eps)
        scale = (inv * self.weight.astype(jnp.float32) if self.affine
                 else inv)
        y = (xf - mean.astype(jnp.float32)) * scale
        if self.affine:
            y = y + self.bias.astype(jnp.float32)
        return y.astype(x.dtype)

    def stat_count(self, x) -> int:
        n = 1
        for a in self.reduce_axes:
            n *= x.shape[a]
        return n

    def forward(self, x):
        if self.training:
            d_mean, d_sq = self.batch_stats(x)
            mean, var = self.fold_stats(d_mean, d_sq, self.stat_count(x))
        else:
            mean, var = self.running_mean, self.running_var
        return self.normalize(x, mean, var)


class SpatialBatchNormalization(BatchNormalization):
    """BatchNorm over NHWC images, per channel
    (reference nn/SpatialBatchNormalization.scala)."""

    reduce_axes = (0, 1, 2)

    def __init__(self, n_output: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 init_weight=None, init_bias=None,
                 init_grad_weight=None, init_grad_bias=None,
                 data_format: str = "NHWC"):
        super().__init__(n_output, eps, momentum, affine,
                         init_weight, init_bias)
        self.data_format = data_format

    def forward(self, x):
        if self.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
            y = super().forward(x)
            return jnp.transpose(y, (0, 3, 1, 2))
        return super().forward(x)


class LayerNormalization(Module):
    """LayerNorm over the last axis (reference nn/LayerNormalization.scala,
    used by the Transformer stack)."""

    def __init__(self, hidden_size: int, eps: float = 1e-6):
        super().__init__()
        self.eps = eps
        self.weight = Parameter(jnp.ones(hidden_size))
        self.bias = Parameter(jnp.zeros(hidden_size))

    def forward(self, x):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + self.eps) * self.weight \
            + self.bias


class Normalize(Module):
    """Lp-normalize over the feature axis (reference nn/Normalize.scala)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10):
        super().__init__()
        self.p, self.eps = float(p), float(eps)

    def forward(self, x):
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(x) ** self.p, axis=-1,
                           keepdims=True) ** (1.0 / self.p)
        return x / (norm + self.eps)


class NormalizeScale(Module):
    """L2 normalize across channels then learnable per-channel scale
    (reference nn/NormalizeScale.scala; SSD conv4_3 trick)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10,
                 scale: float = 1.0, size=None,
                 w_regularizer=None):
        super().__init__()
        self.p, self.eps = float(p), float(eps)
        size = tuple(size) if size is not None else (1,)
        self.weight = Parameter(jnp.full(size, float(scale)))

    def forward(self, x):
        norm = jnp.sum(jnp.abs(x) ** self.p, axis=-1,
                       keepdims=True) ** (1.0 / self.p)
        return (x / (norm + self.eps)) * self.weight.reshape(
            (1,) * (x.ndim - 1) + (-1,)) if self.weight.size == x.shape[-1] \
            else (x / (norm + self.eps)) * self.weight


class SpatialCrossMapLRN(Module):
    """AlexNet-style local response normalization across channels
    (reference nn/SpatialCrossMapLRN.scala; NHWC channel-last here)."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75, k: float = 1.0,
                 data_format: str = "NHWC"):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        if self.data_format == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))
        sq = x * x
        half = (self.size - 1) // 2
        # sum over a channel window via reduce_window on last axis
        acc = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add,
            window_dimensions=(1, 1, 1, self.size),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0),
                     (half, self.size - 1 - half)))
        y = x * jnp.power(self.k + self.alpha / self.size * acc, -self.beta)
        if self.data_format == "NCHW":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y


class SpatialWithinChannelLRN(Module):
    """LRN within each channel over a spatial window
    (reference nn/SpatialWithinChannelLRN.scala)."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75):
        super().__init__()
        self.size, self.alpha, self.beta = size, alpha, beta

    def forward(self, x):
        sq = x * x
        half = (self.size - 1) // 2
        acc = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add,
            window_dimensions=(1, self.size, self.size, 1),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (half, self.size - 1 - half),
                     (half, self.size - 1 - half), (0, 0)))
        return x * jnp.power(
            1.0 + self.alpha / (self.size * self.size) * acc, -self.beta)


class Scale(Module):
    """Learnable per-feature affine: broadcastable mul weight + add bias
    (reference nn/Scale.scala = CMul followed by CAdd)."""

    def __init__(self, size):
        super().__init__()
        from bigdl_tpu.nn.linear import CMul, CAdd
        self.cmul = CMul(size)
        self.cadd = CAdd(size)

    def forward(self, x):
        return self.cadd(self.cmul(x))


def _local_kernel_sum(x, kernel):
    """Weighted local sum over (H, W) and *all channels* of NHWC ``x``
    with a 2-D kernel, SAME padding — the building block of the classic
    Torch spatial normalization layers."""
    kh, kw = kernel.shape
    summed = jnp.sum(x, axis=-1, keepdims=True)  # (B, H, W, 1)
    k = kernel.reshape(kh, kw, 1, 1).astype(x.dtype)
    return jax.lax.conv_general_dilated(
        summed, k, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class SpatialSubtractiveNormalization(Module):
    """Subtract the kernel-weighted local mean across channels
    (reference nn/SpatialSubtractiveNormalization.scala).  Border pixels
    divide by the actual kernel mass inside the image (coef map)."""

    def __init__(self, n_input_plane: int = 1, kernel=None):
        super().__init__()
        if kernel is None:
            kernel = jnp.ones((9, 9))
        kernel = jnp.asarray(kernel, jnp.float32)
        if kernel.ndim == 1:
            kernel = kernel[:, None] * kernel[None, :]
        self.n_input_plane = n_input_plane
        # pre-normalize: local mean over kernel mass × channels
        self.kernel = kernel / (kernel.sum() * n_input_plane)

    def forward(self, x):
        # normalized kernel ⇒ interior coef == 1; border coef < 1
        # corrects for the kernel mass falling outside the image
        mean = _local_kernel_sum(x, self.kernel)
        coef = _local_kernel_sum(jnp.ones_like(x), self.kernel)
        return x - mean / jnp.maximum(coef, 1e-12)


class SpatialDivisiveNormalization(Module):
    """Divide by the thresholded local standard deviation
    (reference nn/SpatialDivisiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: Optional[float] = None,
                 thresval: Optional[float] = None):
        super().__init__()
        if kernel is None:
            kernel = jnp.ones((9, 9))
        kernel = jnp.asarray(kernel, jnp.float32)
        if kernel.ndim == 1:
            kernel = kernel[:, None] * kernel[None, :]
        self.n_input_plane = n_input_plane
        self.kernel = kernel / (kernel.sum() * n_input_plane)
        self.threshold = threshold
        self.thresval = thresval

    def forward(self, x):
        sq = _local_kernel_sum(x * x, self.kernel)
        coef = _local_kernel_sum(jnp.ones_like(x), self.kernel)
        # border-corrected weighted mean of x² → local std
        localstd = jnp.sqrt(jnp.maximum(sq / jnp.maximum(coef, 1e-12), 0.0))
        meanstd = jnp.mean(localstd)
        if self.threshold is None:
            thr = meanstd
            val = meanstd
        else:
            thr = self.threshold
            val = self.thresval if self.thresval is not None else thr
        denom = jnp.where(localstd < thr, val, localstd)
        return x / jnp.maximum(denom, 1e-12)


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive local normalization
    (reference nn/SpatialContrastiveNormalization.scala)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: Optional[float] = None,
                 thresval: Optional[float] = None):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel,
                                                threshold, thresval)

    def forward(self, x):
        return self.div(self.sub(x))


class GroupNorm(Module):
    """Group normalization over the channel (last) axis of NHWC maps —
    backs the reference's useGn option (nn/MaskHead.scala, FPN variants
    built on MaskRCNN's GN recipe)."""

    def __init__(self, n_output: int, n_groups: int = 32,
                 eps: float = 1e-5, affine: bool = True):
        super().__init__()
        while n_output % n_groups != 0:
            n_groups //= 2
        self.n_groups = max(n_groups, 1)
        self.eps = float(eps)
        self.affine = affine
        if affine:
            self.weight = Parameter(jnp.ones(n_output))
            self.bias = Parameter(jnp.zeros(n_output))

    def forward(self, x):
        shape = x.shape
        c = shape[-1]
        g = self.n_groups
        xg = x.reshape(shape[:-1] + (g, c // g))
        axes = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        y = ((xg - mean) * jax.lax.rsqrt(var + self.eps)).reshape(shape)
        if self.affine:
            y = y * self.weight + self.bias
        return y
