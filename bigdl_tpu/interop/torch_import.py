"""Import PyTorch weights into bigdl_tpu modules.

Reference parity: utils/TorchFile.scala (Torch7 model import) — the
modern equivalent surface is a PyTorch ``state_dict``.  Layout
conversions are per-layer-class converters in a registry
(≙ utils/caffe/Converter.scala's per-layer converter pattern):

* torch Linear weight [out, in]  → ours [out, in] (identity)
* torch Conv2d weight OIHW       → ours HWIO (transpose 2,3,1,0)
* torch BatchNorm{1,2}d          → weight/bias + running stats
* torch Embedding [n, dim]       → LookupTable weight (identity)
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

import numpy as np

import jax.numpy as jnp

from bigdl_tpu.core.module import Module

__all__ = ["load_torch_state_dict", "register_torch_converter"]


# our-class-name → (our_leaf_names, converter(module, group_arrays))
_CONVERTERS: Dict[str, Callable[[Module, Dict[str, np.ndarray]], None]] = {}


def register_torch_converter(class_name: str):
    def deco(fn):
        _CONVERTERS[class_name] = fn
        return fn
    return deco


@register_torch_converter("Linear")
def _linear(mod, group):
    mod._params["weight"] = jnp.asarray(group["weight"])
    if "bias" in group and "bias" in mod._params:
        mod._params["bias"] = jnp.asarray(group["bias"])


@register_torch_converter("SpatialConvolution")
def _conv2d(mod, group):
    w = np.asarray(group["weight"])          # OIHW
    mod._params["weight"] = jnp.asarray(w.transpose(2, 3, 1, 0))  # HWIO
    if "bias" in group and "bias" in mod._params:
        mod._params["bias"] = jnp.asarray(group["bias"])


@register_torch_converter("BatchNormalization")
def _bn(mod, group):
    if "weight" in group and "weight" in mod._params:
        mod._params["weight"] = jnp.asarray(group["weight"])
    if "bias" in group and "bias" in mod._params:
        mod._params["bias"] = jnp.asarray(group["bias"])
    mod._buffers["running_mean"] = jnp.asarray(group["running_mean"])
    mod._buffers["running_var"] = jnp.asarray(group["running_var"])


_CONVERTERS["SpatialBatchNormalization"] = _CONVERTERS["BatchNormalization"]


@register_torch_converter("LookupTable")
def _embedding(mod, group):
    mod._params["weight"] = jnp.asarray(group["weight"])


@register_torch_converter("LayerNormalization")
def _layernorm(mod, group):
    mod._params["weight"] = jnp.asarray(group["weight"])
    mod._params["bias"] = jnp.asarray(group["bias"])


def _stateful_leaves(module: Module, prefix: str = "") \
        -> List[Tuple[str, Module]]:
    """Depth-first leaf modules that own parameters or buffers."""
    from bigdl_tpu.core.module import ModuleList
    out = []
    own = bool(module._params) or bool(module._buffers)
    children = []
    for n, v in module._modules.items():
        if isinstance(v, ModuleList):
            for i, m in enumerate(v._items):
                children.append((f"{prefix}{n}[{i}].", m))
        else:
            children.append((f"{prefix}{n}.", m))
    if own:
        out.append((prefix.rstrip("."), module))
    for p, c in children:
        out.extend(_stateful_leaves(c, p))
    return out


def _group_state_dict(state_dict) -> List[Tuple[str, Dict[str, np.ndarray]]]:
    """Group torch entries by module prefix, preserving insertion order
    (state_dict order is the torch module tree order)."""
    groups: Dict[str, Dict[str, np.ndarray]] = {}
    for key, tensor in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        prefix, _, leaf = key.rpartition(".")
        arr = tensor.detach().cpu().numpy() \
            if hasattr(tensor, "detach") else np.asarray(tensor)
        groups.setdefault(prefix, {})[leaf] = arr
    return list(groups.items())


def load_torch_state_dict(module: Module, state_dict,
                          path_map: Dict[str, str] = None) -> Module:
    """Load a PyTorch ``state_dict`` into ``module`` in place.

    Without ``path_map``, torch parameter groups are zipped against this
    model's stateful leaf modules in tree order (both frameworks emit
    depth-first order, so architecturally-matching models align).  With
    ``path_map`` ({our_path: torch_prefix}), only the listed pairs load.
    """
    groups = _group_state_dict(state_dict)
    leaves = _stateful_leaves(module)
    if path_map is not None:
        by_path = dict(leaves)
        by_prefix = dict(groups)
        pairs = []
        for ours, theirs in path_map.items():
            if ours not in by_path:
                raise KeyError(f"no module at path {ours!r}")
            if theirs not in by_prefix:
                raise KeyError(f"no torch group {theirs!r}")
            pairs.append((by_path[ours], by_prefix[theirs], ours))
    else:
        if len(groups) != len(leaves):
            raise ValueError(
                f"structure mismatch: model has {len(leaves)} stateful "
                f"modules, state_dict has {len(groups)} groups; pass "
                f"path_map to align manually")
        pairs = [(m, g, p) for (p, m), (_, g) in zip(leaves, groups)]

    for mod, group, path in pairs:
        cls = type(mod).__name__
        conv = _CONVERTERS.get(cls)
        if conv is None:
            raise NotImplementedError(
                f"no torch converter for {cls} (at {path!r}); "
                f"register one with register_torch_converter")
        conv(mod, group)
    return module
