"""Torch7 ``.t7`` serialization (read + write).

Reference: utils/TorchFile.scala (1,102 LoC — loadTorch/saveTorch with
type tags, refcounted objects, tensor/storage records, and module
conversion).  Same binary format here: little-endian type-tagged
records with object-index reuse.

``load_t7`` returns plain Python values (numbers, strings, dicts for
lua tables, numpy arrays for torch tensors, :class:`TorchObject` for
other torch classes); ``load_torch_module`` additionally converts
common nn.* records into bigdl_tpu modules.  ``save_t7`` writes
numbers/strings/tables/numpy arrays back.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, List, Optional

import numpy as np

__all__ = ["load_t7", "save_t7", "load_torch_module", "TorchObject"]

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5

_TENSOR_DTYPES = {
    "torch.DoubleTensor": np.float64, "torch.FloatTensor": np.float32,
    "torch.LongTensor": np.int64, "torch.IntTensor": np.int32,
    "torch.ShortTensor": np.int16, "torch.ByteTensor": np.uint8,
    "torch.CharTensor": np.int8,
}
_STORAGE_DTYPES = {
    "torch.DoubleStorage": np.float64, "torch.FloatStorage": np.float32,
    "torch.LongStorage": np.int64, "torch.IntStorage": np.int32,
    "torch.ShortStorage": np.int16, "torch.ByteStorage": np.uint8,
    "torch.CharStorage": np.int8,
}


class TorchObject:
    """A torch class instance that has no native mapping: class name +
    its serialized payload (usually a table dict)."""

    def __init__(self, torch_type: str, payload):
        self.torch_type = torch_type
        self.payload = payload

    def __repr__(self):
        return f"TorchObject({self.torch_type})"


class _Reader:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.refs: Dict[int, Any] = {}

    def _int(self) -> int:
        return struct.unpack("<i", self.f.read(4))[0]

    def _long(self) -> int:
        return struct.unpack("<q", self.f.read(8))[0]

    def _double(self) -> float:
        return struct.unpack("<d", self.f.read(8))[0]

    def _string(self) -> str:
        n = self._int()
        return self.f.read(n).decode("latin-1")

    def read(self):
        tag = self._int()
        if tag == TYPE_NIL:
            return None
        if tag == TYPE_NUMBER:
            v = self._double()
            return int(v) if v.is_integer() else v
        if tag == TYPE_STRING:
            return self._string()
        if tag == TYPE_BOOLEAN:
            return self._int() == 1
        if tag == TYPE_TABLE:
            idx = self._int()
            if idx in self.refs:
                return self.refs[idx]
            out: Dict[Any, Any] = {}
            self.refs[idx] = out
            n = self._int()
            for _ in range(n):
                k = self.read()
                v = self.read()
                out[k] = v
            return out
        if tag == TYPE_TORCH:
            idx = self._int()
            if idx in self.refs:
                return self.refs[idx]
            version = self._string()
            cls = self._string() if version.startswith("V ") else version
            obj = self._read_torch(cls, idx)
            return obj
        raise ValueError(f"t7: unknown type tag {tag}")

    def _read_torch(self, cls: str, idx: int):
        if cls in _TENSOR_DTYPES:
            ndim = self._int()
            sizes = [self._long() for _ in range(ndim)]
            strides = [self._long() for _ in range(ndim)]
            offset = self._long() - 1  # 1-based
            storage = self.read()     # Storage object (numpy array)
            if storage is None or ndim == 0:
                arr = np.zeros(sizes, _TENSOR_DTYPES[cls])
            else:
                arr = np.lib.stride_tricks.as_strided(
                    storage[offset:],
                    shape=sizes,
                    strides=[s * storage.itemsize for s in strides]).copy()
            self.refs[idx] = arr
            return arr
        if cls in _STORAGE_DTYPES:
            n = self._long()
            dt = np.dtype(_STORAGE_DTYPES[cls]).newbyteorder("<")
            arr = np.frombuffer(self.f.read(n * dt.itemsize),
                                dt).astype(_STORAGE_DTYPES[cls])
            self.refs[idx] = arr
            return arr
        # register BEFORE reading the payload: a cyclic reference back to
        # this object (e.g. container.modules[i].parent) must resolve to
        # the same instance instead of re-parsing the byte stream
        obj = TorchObject(cls, None)
        self.refs[idx] = obj
        obj.payload = self.read()
        return obj


def load_t7(path: str):
    """Read one serialized Torch7 value (≙ File.loadTorch,
    TorchFile.scala)."""
    with open(path, "rb") as f:
        return _Reader(f).read()


class _Writer:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.next_idx = 1
        # id(obj) -> (obj, idx): written tables are recorded so shared or
        # cyclic references serialize as an index reuse, matching the
        # reader (and Torch7 itself); retaining obj keeps ids stable
        self.memo: Dict[int, Any] = {}

    def _int(self, v: int):
        self.f.write(struct.pack("<i", v))

    def _long(self, v: int):
        self.f.write(struct.pack("<q", v))

    def _double(self, v: float):
        self.f.write(struct.pack("<d", v))

    def _string(self, s: str):
        b = s.encode("latin-1")
        self._int(len(b))
        self.f.write(b)

    def write(self, v):
        if v is None:
            self._int(TYPE_NIL)
        elif isinstance(v, bool):
            self._int(TYPE_BOOLEAN)
            self._int(1 if v else 0)
        elif isinstance(v, (int, float)):
            self._int(TYPE_NUMBER)
            self._double(float(v))
        elif isinstance(v, str):
            self._int(TYPE_STRING)
            self._string(v)
        elif isinstance(v, dict):
            self._int(TYPE_TABLE)
            if id(v) in self.memo:
                self._int(self.memo[id(v)][1])
                return
            idx = self._idx()
            self.memo[id(v)] = (v, idx)
            self._int(idx)
            self._int(len(v))
            for k, val in v.items():
                self.write(k)
                self.write(val)
        elif isinstance(v, np.ndarray):
            self._write_tensor(v)
        else:
            raise TypeError(f"save_t7: unsupported type {type(v)}")

    def _idx(self) -> int:
        i = self.next_idx
        self.next_idx += 1
        return i

    def _write_tensor(self, arr: np.ndarray):
        self._int(TYPE_TORCH)
        orig = arr
        if id(orig) in self.memo:
            self._int(self.memo[id(orig)][1])
            return
        cls = {np.dtype(np.float64): "torch.DoubleTensor",
               np.dtype(np.float32): "torch.FloatTensor",
               np.dtype(np.int64): "torch.LongTensor",
               np.dtype(np.int32): "torch.IntTensor",
               np.dtype(np.uint8): "torch.ByteTensor"}.get(arr.dtype)
        if cls is None:
            arr = arr.astype(np.float32)
            cls = "torch.FloatTensor"
        idx = self._idx()
        self.memo[id(orig)] = (orig, idx)
        self._int(idx)
        self._string("V 1")
        self._string(cls)
        arr_c = np.ascontiguousarray(arr)
        self._int(arr.ndim)
        for s in arr.shape:
            self._long(s)
        stride = [st // arr_c.itemsize for st in arr_c.strides]
        for s in stride:
            self._long(s)
        self._long(1)  # storage offset, 1-based
        # storage record
        self._int(TYPE_TORCH)
        self._int(self._idx())
        self._string("V 1")
        self._string(cls.replace("Tensor", "Storage"))
        self._long(arr_c.size)
        self.f.write(arr_c.tobytes())


def save_t7(path: str, value) -> None:
    """Write a value in Torch7 format (≙ File.saveTorch)."""
    with open(path, "wb") as f:
        _Writer(f).write(value)


# --------------------------------------------------------------------------
# nn.* module conversion (≙ TorchFile readModule branches)
# --------------------------------------------------------------------------

def load_torch_module(path: str):
    """Load a .t7 file holding a torch nn module tree and convert the
    supported classes to bigdl_tpu modules."""
    return _convert(load_t7(path))


def _get(tbl, key):
    return tbl.get(key) if isinstance(tbl, dict) else None


def _convert(obj):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.core.module import Parameter

    if not isinstance(obj, TorchObject):
        return obj
    t = obj.torch_type
    tbl = obj.payload if isinstance(obj.payload, dict) else {}

    if t in ("nn.Sequential",):
        mods = tbl.get("modules", {})
        items = [mods[k] for k in sorted(k for k in mods
                                         if isinstance(k, (int, float)))]
        return nn.Sequential(*[_convert(m) for m in items])
    if t == "nn.Linear":
        w = np.asarray(tbl["weight"], np.float32)
        b = tbl.get("bias")
        m = nn.Linear(w.shape[1], w.shape[0], with_bias=b is not None)
        m.weight = Parameter(w)
        if b is not None:
            m.bias = Parameter(np.asarray(b, np.float32))
        return m
    if t == "nn.SpatialConvolution":
        w = np.asarray(tbl["weight"], np.float32)
        # torch: (out, in, kh, kw)
        out_p, in_p, kh, kw = w.shape
        m = nn.SpatialConvolution(
            in_p, out_p, kw, kh,
            int(tbl.get("dW", 1)), int(tbl.get("dH", 1)),
            int(tbl.get("padW", 0)), int(tbl.get("padH", 0)),
            data_format="NCHW",
            with_bias="bias" in tbl and tbl["bias"] is not None)
        m.weight = Parameter(np.transpose(w, (2, 3, 1, 0)))
        if m.with_bias:
            m.bias = Parameter(np.asarray(tbl["bias"], np.float32))
        return m
    if t == "nn.ReLU":
        return nn.ReLU()
    if t == "nn.Tanh":
        return nn.Tanh()
    if t == "nn.Sigmoid":
        return nn.Sigmoid()
    if t == "nn.SoftMax":
        return nn.SoftMax(axis=1)
    if t == "nn.LogSoftMax":
        return nn.LogSoftMax(axis=1)
    if t == "nn.Dropout":
        return nn.Dropout(float(tbl.get("p", 0.5)))
    if t == "nn.Reshape":
        size = tbl.get("size")
        dims = [int(v) for _, v in sorted(size.items())] \
            if isinstance(size, dict) else [int(s) for s in
                                            np.asarray(size).reshape(-1)]
        return nn.Reshape(dims)
    if t == "nn.SpatialMaxPooling":
        m = nn.SpatialMaxPooling(
            int(tbl.get("kW", 2)), int(tbl.get("kH", 2)),
            int(tbl.get("dW", 2)), int(tbl.get("dH", 2)),
            int(tbl.get("padW", 0)), int(tbl.get("padH", 0)),
            data_format="NCHW")
        if tbl.get("ceil_mode"):
            m.ceil()
        return m
    if t == "nn.SpatialAveragePooling":
        return nn.SpatialAveragePooling(
            int(tbl.get("kW", 2)), int(tbl.get("kH", 2)),
            int(tbl.get("dW", 2)), int(tbl.get("dH", 2)),
            int(tbl.get("padW", 0)), int(tbl.get("padH", 0)),
            data_format="NCHW")
    raise ValueError(f"load_torch_module: unsupported torch class {t!r}")
