"""Model import from other frameworks (≙ reference utils/{caffe,tf},
TorchFile.scala — re-targeted at the formats that matter today)."""

from bigdl_tpu.interop.torch_import import (  # noqa: F401
    load_torch_state_dict, register_torch_converter,
)
