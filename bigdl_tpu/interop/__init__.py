"""Model interop (≙ reference utils/{caffe,tf,serializer}, nn/onnx,
TorchFile.scala).

* Caffe: prototxt+caffemodel import, caffemodel export.
* TensorFlow: GraphDef import (op loaders + fusions) and export.
* ONNX: the reference's three op shims (Gemm, Reshape, Shape).
* PyTorch: state-dict import (torch_import).
All binary protobuf handling goes through the generic wire codec in
protowire.py — no generated proto classes.
"""

from bigdl_tpu.interop.caffe import (  # noqa: F401
    load_caffe, load_caffe_weights, parse_prototxt, read_caffemodel,
    register_caffe_converter, save_caffemodel,
)
from bigdl_tpu.interop.onnx import Gemm, OnnxReshape, OnnxShape  # noqa: F401
from bigdl_tpu.interop.tensorflow import (  # noqa: F401
    load_tf_graph, parse_graphdef, register_tf_converter, save_tf_graph,
)
from bigdl_tpu.interop.torch_import import (  # noqa: F401
    load_torch_state_dict, register_torch_converter,
)
from bigdl_tpu.interop.torch_file import (  # noqa: F401
    load_t7, load_torch_module, save_t7, TorchObject,
)
