"""Caffe model import/export.

Reference: utils/caffe/CaffeLoader.scala:57-563 (prototxt+caffemodel →
Graph with per-layer Converters, V1 and V2 layer formats),
utils/caffe/CaffePersister.scala (export).  The reference leans on
95k LoC of generated Caffe.java; here the binary format is read/written
through the generic wire codec (bigdl_tpu/interop/protowire.py) and the
topology comes from a recursive-descent prototxt parser.

Two entry points mirroring the reference:
* :func:`load_caffe_weights(model, prototxt, caffemodel)` — copy weights
  into an existing model by layer name (≙ Module.loadCaffe).
* :func:`load_caffe(prototxt, caffemodel)` — build a Graph from the
  prototxt and fill its weights (≙ CaffeLoader.loadCaffe).

Caffe is NCHW; built layers use data_format="NCHW" so imported models
consume NCHW inputs exactly like the source network.  (XLA transposes
to the TPU-native layout internally at negligible cost.)
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module, Parameter
from bigdl_tpu.interop.protowire import (BYTES, VARINT, as_floats, as_ints,
                                         as_string, decode_message,
                                         encode_message, varint)

__all__ = ["load_caffe", "load_caffe_weights", "parse_prototxt",
           "read_caffemodel", "save_caffemodel", "register_caffe_converter"]


# --------------------------------------------------------------------------
# prototxt (text format) parser
# --------------------------------------------------------------------------

def _tokenize(text: str) -> List[str]:
    tokens: List[str] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c in " \t\r\n,":
            i += 1
        elif c in "{}:":
            tokens.append(c)
            i += 1
        elif c in "\"'":
            j = i + 1
            while j < n and text[j] != c:
                j += 1
            tokens.append(text[i:j + 1])
            i = j + 1
        else:
            j = i
            while j < n and text[j] not in " \t\r\n:{}#,":
                j += 1
            tokens.append(text[i:j])
            i = j
    return tokens


def _parse_block(tokens: List[str], pos: int) -> Tuple[Dict, int]:
    out: Dict[str, list] = {}
    while pos < len(tokens) and tokens[pos] != "}":
        key = tokens[pos]
        pos += 1
        if pos < len(tokens) and tokens[pos] == ":":
            pos += 1
            val = tokens[pos]
            pos += 1
            if val and val[0] in "\"'":
                parsed = val[1:-1]
            else:
                try:
                    parsed = int(val)
                except ValueError:
                    try:
                        parsed = float(val)
                    except ValueError:
                        parsed = {"true": True, "false": False}.get(
                            val, val)
            out.setdefault(key, []).append(parsed)
        elif pos < len(tokens) and tokens[pos] == "{":
            sub, pos = _parse_block(tokens, pos + 1)
            assert tokens[pos] == "}"
            pos += 1
            out.setdefault(key, []).append(sub)
        else:
            raise ValueError(f"prototxt parse error near {key!r}")
    return out, pos


def parse_prototxt(text: str) -> Dict:
    """Caffe text format → nested dict of {key: [values]}."""
    tokens = _tokenize(text)
    out, pos = _parse_block(tokens, 0)
    if pos != len(tokens):
        raise ValueError("prototxt: trailing tokens")
    return out


def _one(d: Dict, key: str, default=None):
    v = d.get(key)
    return v[0] if v else default


# --------------------------------------------------------------------------
# caffemodel (binary NetParameter) reader/writer
# --------------------------------------------------------------------------

# NetParameter field numbers (caffe.proto)
_NET_NAME, _NET_LAYERS_V1, _NET_LAYER_V2 = 1, 2, 100
# LayerParameter (v2)
_L_NAME, _L_TYPE, _L_BOTTOM, _L_TOP, _L_BLOBS = 1, 2, 3, 4, 7
# V1LayerParameter
_V1_BOTTOM, _V1_TOP, _V1_NAME, _V1_TYPE, _V1_BLOBS = 2, 3, 4, 5, 6
# BlobProto
_B_NUM, _B_CH, _B_H, _B_W, _B_DATA, _B_SHAPE = 1, 2, 3, 4, 5, 7

# V1LayerParameter.LayerType enum values (caffe.proto)
_V1_TYPE_NAMES = {
    3: "Concat", 4: "Convolution", 5: "Data", 6: "Dropout", 8: "Flatten",
    14: "InnerProduct", 15: "LRN", 17: "Pooling", 18: "ReLU",
    19: "Sigmoid", 20: "Softmax", 21: "SoftmaxWithLoss", 23: "TanH",
    25: "Eltwise", 26: "Power",
}


def _blob_to_array(blob: Dict[int, list]) -> np.ndarray:
    data = as_floats(blob.get(_B_DATA, []))
    if _B_SHAPE in blob:
        dims = as_ints(decode_message(blob[_B_SHAPE][0]).get(1, []))
    else:
        dims = [x for x in (_one_int(blob, _B_NUM), _one_int(blob, _B_CH),
                            _one_int(blob, _B_H), _one_int(blob, _B_W))
                if x is not None]
        # legacy blobs default absent dims to 1; strip leading 1s
        while len(dims) > 1 and dims[0] == 1 and np.prod(dims) != len(data):
            dims = dims[1:]
    if dims and int(np.prod(dims)) == data.size:
        return data.reshape(dims)
    return data


def _one_int(d: Dict[int, list], key: int) -> Optional[int]:
    v = d.get(key)
    return int(v[0]) if v else None


def read_caffemodel(path: str) -> Dict[str, Dict]:
    """caffemodel → {layer_name: {"type": str, "blobs": [ndarray],
    "bottom": [...], "top": [...]}} handling both V1 and V2 layers
    (reference CaffeLoader V1/V2 dual path)."""
    with open(path, "rb") as f:
        net = decode_message(f.read())
    layers: Dict[str, Dict] = {}
    for raw in net.get(_NET_LAYER_V2, []):
        msg = decode_message(raw)
        name = as_string(msg[_L_NAME][0])
        layers[name] = {
            "type": as_string(msg[_L_TYPE][0]) if _L_TYPE in msg else "",
            "bottom": [as_string(b) for b in msg.get(_L_BOTTOM, [])],
            "top": [as_string(t) for t in msg.get(_L_TOP, [])],
            "blobs": [_blob_to_array(decode_message(b))
                      for b in msg.get(_L_BLOBS, [])],
        }
    for raw in net.get(_NET_LAYERS_V1, []):
        msg = decode_message(raw)
        name = as_string(msg[_V1_NAME][0])
        t = _one_int(msg, _V1_TYPE) or 0
        layers[name] = {
            "type": _V1_TYPE_NAMES.get(t, str(t)),
            "bottom": [as_string(b) for b in msg.get(_V1_BOTTOM, [])],
            "top": [as_string(x) for x in msg.get(_V1_TOP, [])],
            "blobs": [_blob_to_array(decode_message(b))
                      for b in msg.get(_V1_BLOBS, [])],
        }
    return layers


def save_caffemodel(path: str, layers: Dict[str, Dict]) -> None:
    """{name: {type, bottom, top, blobs}} → V2 caffemodel (reference
    CaffePersister)."""
    layer_msgs = []
    for name, spec in layers.items():
        fields = [(_L_NAME, BYTES, name.encode()),
                  (_L_TYPE, BYTES, spec.get("type", "").encode())]
        for b in spec.get("bottom", []):
            fields.append((_L_BOTTOM, BYTES, b.encode()))
        for t in spec.get("top", []):
            fields.append((_L_TOP, BYTES, t.encode()))
        for arr in spec.get("blobs", []):
            arr = np.asarray(arr, np.float32)
            shape_msg = encode_message(
                [(1, BYTES, b"".join(varint(d) for d in arr.shape))])
            blob = encode_message([
                (_B_SHAPE, BYTES, shape_msg),
                (_B_DATA, BYTES, arr.astype("<f4").tobytes()),
            ])
            fields.append((_L_BLOBS, BYTES, blob))
        layer_msgs.append(encode_message(fields))
    out = encode_message([(_NET_LAYER_V2, BYTES, m) for m in layer_msgs])
    with open(path, "wb") as f:
        f.write(out)


# --------------------------------------------------------------------------
# layer converters (prototxt params + blobs → modules)
# --------------------------------------------------------------------------

_CONVERTERS = {}


def register_caffe_converter(*type_names: str):
    """Custom converter hook (≙ CaffeLoader customized converters,
    CaffeLoader.scala:456)."""
    def deco(fn):
        for t in type_names:
            _CONVERTERS[t.lower()] = fn
        return fn
    return deco


def _conv_param(p: Dict):
    def get(key, default=None):
        return _one(p, key, default)
    ks = get("kernel_size")
    kh = get("kernel_h", ks)
    kw = get("kernel_w", ks)
    s = get("stride", 1)
    sh, sw = get("stride_h", s), get("stride_w", s)
    pad = get("pad", 0)
    ph, pw = get("pad_h", pad), get("pad_w", pad)
    return kh, kw, sh, sw, ph, pw



def _need_blobs(spec, blobs, n, lname=""):
    if len(blobs) < n:
        raise ValueError(
            f"caffe layer {_one(spec, 'name', lname)!r} needs {n} weight "
            f"blob(s) but got {len(blobs)} — pass caffemodel_path with "
            f"the trained weights")


@register_caffe_converter("Convolution")
def _convert_conv(spec, params, blobs):
    p = _one(params, "convolution_param", {})
    kh, kw, sh, sw, ph, pw = _conv_param(p)
    n_out = _one(p, "num_output")
    group = _one(p, "group", 1)
    bias = _one(p, "bias_term", True)
    _need_blobs(spec, blobs, 1)
    if bias:
        _need_blobs(spec, blobs, 2)  # bias_term=true requires the blob
    w = blobs[0]  # caffe: (out, in/group, kh, kw)
    n_in = w.shape[1] * group
    m = nn.SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph,
                              n_group=group, with_bias=bias,
                              data_format="NCHW")
    m.weight = Parameter(np.transpose(w, (2, 3, 1, 0)))  # → HWIO
    if bias:
        m.bias = Parameter(blobs[1].reshape(-1))
    return m


@register_caffe_converter("InnerProduct")
def _convert_linear(spec, params, blobs):
    p = _one(params, "inner_product_param", {})
    n_out = _one(p, "num_output")
    bias = _one(p, "bias_term", True)
    _need_blobs(spec, blobs, 1)
    if bias:
        _need_blobs(spec, blobs, 2)
    w = blobs[0].reshape(n_out, -1)
    m = nn.Linear(w.shape[1], n_out, with_bias=bias)
    m.weight = Parameter(w)
    if bias:
        m.bias = Parameter(blobs[1].reshape(-1))
    # caffe flattens (B, C, H, W) → (B, C*H*W) implicitly
    return nn.Sequential(nn.Flatten(), m)


@register_caffe_converter("Pooling")
def _convert_pool(spec, params, blobs):
    p = _one(params, "pooling_param", {})
    kh, kw, sh, sw, ph, pw = _conv_param(p)
    pool = _one(p, "pool", "MAX")
    if _one(p, "global_pooling", False):
        # caffe keeps (B, C, 1, 1)
        return _GlobalPool("avg" if pool == "AVE" else "max")
    cls = nn.SpatialMaxPooling if pool == "MAX" \
        else nn.SpatialAveragePooling
    # caffe uses ceil output sizing
    return cls(kw, kh, sw, sh, pw, ph, data_format="NCHW").ceil()


class _GlobalPool(Module):
    """Caffe-style global pool keeping (B, C, 1, 1)."""

    def __init__(self, mode: str):
        super().__init__()
        self.mode = mode

    def forward(self, x):
        import jax.numpy as jnp
        fn = jnp.mean if self.mode == "avg" else jnp.max
        return fn(x, axis=(2, 3), keepdims=True)


@register_caffe_converter("ReLU")
def _convert_relu(spec, params, blobs):
    return nn.ReLU()


@register_caffe_converter("TanH")
def _convert_tanh(spec, params, blobs):
    return nn.Tanh()


@register_caffe_converter("Sigmoid")
def _convert_sigmoid(spec, params, blobs):
    return nn.Sigmoid()


@register_caffe_converter("ELU")
def _convert_elu(spec, params, blobs):
    return nn.ELU()


@register_caffe_converter("Softmax", "SoftmaxWithLoss")
def _convert_softmax(spec, params, blobs):
    return nn.SoftMax(axis=1)


@register_caffe_converter("Dropout")
def _convert_dropout(spec, params, blobs):
    p = _one(params, "dropout_param", {})
    return nn.Dropout(_one(p, "dropout_ratio", 0.5))


@register_caffe_converter("LRN")
def _convert_lrn(spec, params, blobs):
    p = _one(params, "lrn_param", {})
    return nn.SpatialCrossMapLRN(
        _one(p, "local_size", 5), _one(p, "alpha", 1.0),
        _one(p, "beta", 0.75), _one(p, "k", 1.0), data_format="NCHW")


@register_caffe_converter("Concat")
def _convert_concat(spec, params, blobs):
    p = _one(params, "concat_param", {})
    return nn.JoinTable(_one(p, "axis", 1) + 1)  # 1-based dim


@register_caffe_converter("Eltwise")
def _convert_eltwise(spec, params, blobs):
    p = _one(params, "eltwise_param", {})
    op = _one(p, "operation", "SUM")
    return {"SUM": nn.CAddTable, "PROD": nn.CMulTable,
            "MAX": nn.CMaxTable}[op]()


@register_caffe_converter("BatchNorm")
def _convert_bn(spec, params, blobs):
    _need_blobs(spec, blobs, 2)
    mean, var = blobs[0].reshape(-1), blobs[1].reshape(-1)
    sf = float(blobs[2].reshape(-1)[0]) if len(blobs) > 2 else 1.0
    sf = 1.0 / sf if sf != 0 else 1.0
    m = nn.SpatialBatchNormalization(mean.size, eps=1e-5, affine=False,
                                     data_format="NCHW")
    m.running_mean = np.asarray(mean * sf, np.float32)
    m.running_var = np.asarray(var * sf, np.float32)
    return m


@register_caffe_converter("Scale")
def _convert_scale(spec, params, blobs):
    _need_blobs(spec, blobs, 1)
    gamma = blobs[0].reshape(-1)
    m = nn.Scale((1, gamma.size, 1, 1))
    m.cmul.weight = Parameter(gamma.reshape(1, -1, 1, 1))
    beta = (blobs[1].reshape(-1) if len(blobs) > 1
            else np.zeros_like(gamma))
    m.cadd.bias = Parameter(beta.reshape(1, -1, 1, 1))
    return m


@register_caffe_converter("Flatten")
def _convert_flatten(spec, params, blobs):
    return nn.Flatten()


@register_caffe_converter("Power")
def _convert_power(spec, params, blobs):
    p = _one(params, "power_param", {})
    return nn.Power(_one(p, "power", 1.0), _one(p, "scale", 1.0),
                    _one(p, "shift", 0.0))


# --------------------------------------------------------------------------
# loaders
# --------------------------------------------------------------------------

def load_caffe(prototxt_path: str, caffemodel_path: Optional[str] = None):
    """Build a Graph from a deploy prototxt, filling weights from the
    caffemodel (≙ CaffeLoader.loadCaffe).  Returns (model, layer_map)."""
    with open(prototxt_path) as f:
        net = parse_prototxt(f.read())
    weights = (read_caffemodel(caffemodel_path)
               if caffemodel_path else {})

    layer_defs = net.get("layer", net.get("layers", []))
    # blob name → producing Node
    from bigdl_tpu.nn import Input, Graph
    from bigdl_tpu.nn.containers import node_of
    blob_nodes: Dict[str, Node] = {}
    inputs: List[Node] = []
    for name in net.get("input", []):
        node = Input()
        blob_nodes[name] = node
        inputs.append(node)
    layer_map: Dict[str, Module] = {}

    consumed_ids = set()
    for spec in layer_defs:
        lname = _one(spec, "name", "")
        ltype = _one(spec, "type", "")
        if isinstance(ltype, int):
            ltype = _V1_TYPE_NAMES.get(ltype, str(ltype))
        bottoms = [str(b) for b in spec.get("bottom", [])]
        tops = [str(t) for t in spec.get("top", [])]
        if ltype in ("Input", "Data"):
            node = Input()
            for t in tops:
                blob_nodes[t] = node
            inputs.append(node)
            continue
        conv = _CONVERTERS.get(str(ltype).lower())
        if conv is None:
            raise ValueError(f"no Caffe converter for layer type "
                             f"{ltype!r} (layer {lname!r}); register one "
                             f"with register_caffe_converter")
        blobs = weights.get(lname, {}).get("blobs", [])
        module = conv(spec, spec, blobs)
        module.set_name(lname)
        layer_map[lname] = module
        prev = [blob_nodes[b] for b in bottoms if b in blob_nodes]
        # consumption is per (node, blob-name) pair: an in-place layer
        # (top == bottom, e.g. ReLU) consumes the OLD producer under that
        # name while its own same-named output stays an output candidate,
        # and a multi-top layer with one top consumed keeps the others
        consumed_ids.update((id(blob_nodes[b]), b) for b in bottoms
                            if b in blob_nodes)
        node = node_of(module, *prev)
        for t in tops:
            blob_nodes[t] = node
    outputs = _find_outputs(blob_nodes, consumed_ids)
    model = Graph(inputs, outputs)
    return model, layer_map


def _find_outputs(blob_nodes, consumed_ids):
    outs = [n for name, n in blob_nodes.items()
            if (id(n), name) not in consumed_ids]
    # dedup preserving order
    seen, uniq = set(), []
    for n in outs:
        if id(n) not in seen:
            seen.add(id(n))
            uniq.append(n)
    return uniq


def load_caffe_weights(model: Module, prototxt_path: Optional[str],
                       caffemodel_path: str, match_all: bool = True):
    """Copy caffemodel weights into an existing model by layer name
    (≙ Module.loadCaffe / CaffeLoader.load, CaffeLoader.scala:57-73).

    ``prototxt_path`` is optional: when given, it is parsed and its
    layer names cross-checked against the caffemodel (catching
    mismatched prototxt/caffemodel pairs early)."""
    weights = read_caffemodel(caffemodel_path)
    if prototxt_path:
        with open(prototxt_path) as f:
            net = parse_prototxt(f.read())
        proto_names = {_one(s, "name") for s in
                       net.get("layer", net.get("layers", []))}
        stray = [n for n in weights if n not in proto_names]
        if stray:
            raise ValueError(
                f"caffemodel layers absent from the prototxt: "
                f"{stray[:5]} — mismatched model pair?")
    named = {m.get_name(): m for _, m in model.named_modules()}
    copied = []
    for lname, spec in weights.items():
        if lname not in named:
            continue
        m = named[lname]
        blobs = spec["blobs"]
        if not blobs:
            continue
        w = blobs[0]
        if hasattr(m, "weight"):
            cur = np.asarray(m.weight)
            if w.ndim == 4 and cur.ndim == 4:   # conv: OIHW → HWIO
                w = np.transpose(w, (2, 3, 1, 0))
            m.weight = Parameter(w.reshape(cur.shape))
            copied.append(lname)
        if len(blobs) > 1 and getattr(m, "bias", None) is not None:
            m.bias = Parameter(blobs[1].reshape(
                np.asarray(m.bias).shape))
    missing = [n for n in weights if n not in named]
    if match_all and missing:
        raise ValueError(f"caffemodel layers not found in model: "
                         f"{missing[:5]}{'...' if len(missing) > 5 else ''}"
                         f" (pass match_all=False to ignore)")
    return model, copied
