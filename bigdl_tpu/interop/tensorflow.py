"""TensorFlow GraphDef import/export.

Reference: utils/tf/TensorflowLoader.scala:43-358 (parse GraphDef,
pattern-match node sub-graphs to modules via ~160 op loaders in
utils/tf/loaders/), utils/tf/TensorflowSaver.scala (BigDL → GraphDef
export).  Protos are read/written with the generic wire codec
(bigdl_tpu/interop/protowire.py) instead of generated classes.

Import supports the inference-graph op set (Const/Placeholder/Conv2D/
DepthwiseConv2dNative/BiasAdd/MatMul/Relu(6)/Elu/Sigmoid/Tanh/Softmax/
MaxPool/AvgPool/FusedBatchNorm(V2,V3)/LRN/Reshape/Squeeze/Pad/ConcatV2/
Mean/Add(V2)/Sub/Mul/RealDiv/Maximum/Minimum/Identity/NoOp) with the
reference's key fusions: Conv2D+BiasAdd → one conv, MatMul+BiasAdd →
one Linear.  TF graphs are NHWC by default — already the TPU-native
layout, no transposition needed.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.core.module import Module, Parameter
from bigdl_tpu.interop.protowire import (BYTES, FIXED32, VARINT, as_floats,
                                         as_ints, as_string,
                                         decode_message, encode_message,
                                         varint)

__all__ = ["load_tf_graph", "parse_graphdef", "save_tf_graph",
           "register_tf_converter", "TFSession"]

# NodeDef fields
_N_NAME, _N_OP, _N_INPUT, _N_DEVICE, _N_ATTR = 1, 2, 3, 4, 5

# ops whose converters return a tuple of outputs (':N' port refs index it)
_TUPLE_OUT_OPS = frozenset({"Split", "SplitV", "Unpack"})
# attr map entry
_MAP_KEY, _MAP_VALUE = 1, 2
# AttrValue
_A_LIST, _A_S, _A_I, _A_F, _A_B, _A_TYPE, _A_SHAPE, _A_TENSOR = \
    1, 2, 3, 4, 5, 6, 7, 8
# TensorProto
_T_DTYPE, _T_SHAPE, _T_CONTENT, _T_HALF, _T_FLOAT, _T_DOUBLE, _T_INT = \
    1, 2, 4, 13, 5, 6, 7
# DataType enum values
_DT_FLOAT, _DT_DOUBLE, _DT_INT32, _DT_INT64 = 1, 2, 3, 9

_DTYPES = {_DT_FLOAT: np.float32, _DT_DOUBLE: np.float64,
           _DT_INT32: np.int32, _DT_INT64: np.int64}


class TFNode:
    __slots__ = ("name", "op", "inputs", "attrs")

    def __init__(self, name, op, inputs, attrs):
        self.name = name
        self.op = op
        self.inputs = inputs
        self.attrs = attrs

    def __repr__(self):
        return f"TFNode({self.op}:{self.name})"


def _decode_attr(raw: bytes):
    msg = decode_message(raw)
    if _A_S in msg:
        return msg[_A_S][0].decode("utf-8", "replace")
    if _A_I in msg:
        v = msg[_A_I][0]
        return v - (1 << 64) if v >= (1 << 63) else v
    if _A_F in msg:
        return struct.unpack("<f", msg[_A_F][0])[0]
    if _A_B in msg:
        return bool(msg[_A_B][0])
    if _A_TYPE in msg:
        return int(msg[_A_TYPE][0])
    if _A_TENSOR in msg:
        return _decode_tensor(msg[_A_TENSOR][0])
    if _A_LIST in msg:
        lst = decode_message(msg[_A_LIST][0])
        if 3 in lst:   # ints
            return [x - (1 << 64) if x >= (1 << 63) else x
                    for x in as_ints(lst[3])]
        if 4 in lst:   # floats
            return list(as_floats(lst[4]))
        if 2 in lst:   # strings
            return [s.decode() for s in lst[2]]
        return []
    if _A_SHAPE in msg:
        return _decode_shape(msg[_A_SHAPE][0])
    return None


def _decode_shape(raw: bytes) -> List[int]:
    msg = decode_message(raw)
    dims = []
    for d in msg.get(2, []):
        dm = decode_message(d)
        v = int(dm.get(1, [0])[0]) if 1 in dm else 0
        dims.append(v - (1 << 64) if v >= (1 << 63) else v)
    return dims


def _decode_tensor(raw: bytes) -> np.ndarray:
    msg = decode_message(raw)
    dt = int(msg.get(_T_DTYPE, [_DT_FLOAT])[0])
    np_dt = _DTYPES.get(dt, np.float32)
    shape = _decode_shape(msg[_T_SHAPE][0]) if _T_SHAPE in msg else []
    if _T_CONTENT in msg and msg[_T_CONTENT][0]:
        arr = np.frombuffer(msg[_T_CONTENT][0], np_dt).copy()
    elif _T_FLOAT in msg:
        arr = as_floats(msg[_T_FLOAT])
    elif _T_INT in msg:
        arr = np.asarray(as_ints(msg[_T_INT]), np_dt)
    else:
        arr = np.zeros(0, np_dt)
    n = int(np.prod(shape)) if shape else arr.size
    if arr.size == 1 and n > 1:
        arr = np.full(n, arr[0], np_dt)  # splat scalar
    if shape:
        return arr.reshape(shape)
    if _T_SHAPE in msg and arr.size == 1:
        # explicitly-empty shape proto = rank-0 scalar; (1,) here breaks
        # shape agreement, e.g. a while-loop carry init vs body output
        return arr.reshape(())
    return arr


def parse_graphdef(data: bytes) -> List[TFNode]:
    """GraphDef bytes → list of TFNodes."""
    g = decode_message(data)
    nodes = []
    for raw in g.get(1, []):
        msg = decode_message(raw)
        attrs = {}
        for entry in msg.get(_N_ATTR, []):
            e = decode_message(entry)
            key = as_string(e[_MAP_KEY][0])
            attrs[key] = _decode_attr(e[_MAP_VALUE][0])
        nodes.append(TFNode(
            as_string(msg[_N_NAME][0]), as_string(msg[_N_OP][0]),
            [as_string(i) for i in msg.get(_N_INPUT, [])], attrs))
    return nodes


# --------------------------------------------------------------------------
# conversion to modules
# --------------------------------------------------------------------------

_TF_CONVERTERS = {}


def register_tf_converter(*ops):
    """Custom op loader hook (≙ utils/tf/loaders registry)."""
    def deco(fn):
        for op in ops:
            _TF_CONVERTERS[op] = fn
        return fn
    return deco


class _Lambda(Module):
    def __init__(self, fn, name=""):
        super().__init__()
        self._fn = fn
        if name:
            self.set_name(name)

    def forward(self, *xs):
        if len(xs) == 1 and isinstance(xs[0], (tuple, list)):
            return self._fn(*xs[0])
        return self._fn(*xs)


def _clean(name: str) -> str:
    name = name.split(":")[0]
    return name[1:] if name.startswith("^") else name


def _check_nhwc(n: "TFNode") -> None:
    fmt = n.attrs.get("data_format", "NHWC")
    if fmt not in ("NHWC", None, ""):
        raise ValueError(f"{n.op} {n.name!r}: data_format={fmt!r} import "
                         f"not supported (NHWC only)")


def const_of_nodes(nodes, consts, name: str) -> Optional[np.ndarray]:
    """Resolve a node reference to a constant, walking Identity chains."""
    name = _clean(name)
    n = nodes.get(name)
    while n is not None and n.op == "Identity":
        name = _clean(n.inputs[0])
        n = nodes.get(name)
    return consts.get(name)


def load_tf_graph(path_or_bytes, inputs: Sequence[str],
                  outputs: Sequence[str]):
    """GraphDef (file path or bytes) → (Graph model, {name: module}).

    ``inputs``: placeholder node names (become Graph inputs, in order);
    ``outputs``: node names whose values the Graph returns.
    (≙ TensorflowLoader.load, TensorflowLoader.scala:43)
    """
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    nodes = {n.name: n for n in parse_graphdef(data)}
    model, layer_map = _build_graph(nodes, inputs, outputs)
    # strip converter-internal dunder cache entries (e.g. while frames)
    layer_map = {k: v for k, v in layer_map.items()
                 if not k.startswith("__")}
    return model, layer_map


def _build_graph(nodes: Dict[str, "TFNode"], inputs: Sequence[str],
                 outputs: Sequence[str]):
    """Build a Graph model over an already-parsed node dict.  ``inputs``
    become Input placeholders; also called re-entrantly by the
    while-loop importer to construct cond/body subgraphs whose
    boundaries are frame nodes (Merge/Switch/invariant-Enter)."""
    # Work on a private copy: the fusion pre-pass annotates node attrs
    # (_fused_bias), and a re-entrant subgraph build must not override
    # the enclosing build's fusion decisions (its consumer counts bump
    # different outputs).
    nodes = {name: TFNode(nd.name, nd.op, list(nd.inputs),
                          dict(nd.attrs))
             for name, nd in nodes.items()}
    consts: Dict[str, np.ndarray] = {}
    for n in nodes.values():
        if n.op == "Const":
            consts[n.name] = n.attrs.get("value")

    import jax.numpy as jnp
    from bigdl_tpu.nn import Graph, Input
    from bigdl_tpu.nn.containers import node_of

    graph_nodes: Dict[str, object] = {}
    layer_map: Dict[str, Module] = {}
    input_nodes = []
    for name in inputs:
        gn = Input()
        graph_nodes[name] = gn
        input_nodes.append(gn)

    def resolve(name: str):
        base = _clean(name)
        producer = nodes.get(base)
        # ':N' selects output port N of a tuple-producing op (Split &c);
        # single-output ops ignore the port (Switch's two ports collapse
        # to one passthrough — selection happens at Merge)
        port = 0
        if ":" in name:
            suffix = name.rsplit(":", 1)[1]
            if suffix.isdigit():
                port = int(suffix)
        tuple_out = producer is not None and producer.op in _TUPLE_OUT_OPS
        key = f"{base}:{port}" if tuple_out else base
        if key in graph_nodes:
            return graph_nodes[key]
        if base in graph_nodes:
            gn = graph_nodes[base]
        else:
            if producer is None:
                raise ValueError(f"unknown node {name!r}")
            gn = build(producer)
            graph_nodes[base] = gn
        if tuple_out:
            # _Lambda unpacks a tuple input into positional args
            sel = _Lambda(lambda *parts, p=port: parts[p],
                          f"{base}:{port}")
            gn = node_of(sel, gn)
            graph_nodes[key] = gn
        return gn

    def data_inputs(n: TFNode):
        return [i for i in n.inputs if not i.startswith("^")]

    def const_of(name: str) -> Optional[np.ndarray]:
        return const_of_nodes(nodes, consts, name)

    def build(n: TFNode):
        conv = _TF_CONVERTERS.get(n.op)
        if conv is None:
            raise ValueError(f"no TF converter for op {n.op!r} "
                             f"(node {n.name!r}); register one with "
                             f"register_tf_converter")
        return conv(n, nodes, const_of, resolve, node_of, layer_map)

    # pre-pass: mark BiasAdd whose input is Conv2D/MatMul for fusion —
    # only when the BiasAdd is the producer's SOLE consumer (another
    # consumer would otherwise observe post-bias values) and the bias
    # is a resolvable constant
    consumers: Dict[str, int] = {}
    for n in nodes.values():
        for i in n.inputs:
            if not i.startswith("^"):
                consumers[_clean(i)] = consumers.get(_clean(i), 0) + 1
    # requested outputs are external consumers: a producer whose
    # pre-bias value is observed must not absorb the bias
    for name in outputs:
        consumers[_clean(name)] = consumers.get(_clean(name), 0) + 1
    fused_into: Dict[str, TFNode] = {}
    for n in nodes.values():
        if n.op == "BiasAdd":
            src = nodes.get(_clean(n.inputs[0]))
            if (src is not None
                    and src.op in ("Conv2D", "MatMul",
                                   "DepthwiseConv2dNative")
                    and consumers.get(src.name, 0) == 1
                    and const_of_nodes(nodes, consts, n.inputs[1])
                    is not None):
                fused_into[src.name] = n

    # expose fusion info to converters via attribute
    for src_name, badd in fused_into.items():
        nodes[src_name].attrs["_fused_bias"] = const_of(badd.inputs[1])

    out_nodes = []
    for name in outputs:
        n = nodes.get(_clean(name))
        if n is not None and n.op == "BiasAdd":
            src = nodes.get(_clean(n.inputs[0]))
            if src is not None and src.name in fused_into:
                out_nodes.append(resolve(src.name))
                continue
        out_nodes.append(resolve(name))

    # BiasAdd nodes that were fused: make their name resolve to the conv
    model = Graph(input_nodes, out_nodes)
    return model, layer_map


def _register_defaults():
    import jax
    import jax.numpy as jnp
    from bigdl_tpu.nn.containers import node_of

    def simple(fn):
        def cv(n, nodes, const_of, resolve, node_of, layer_map):
            ins = [resolve(i) for i in n.inputs if not i.startswith("^")]
            m = _Lambda(fn, n.name)
            layer_map[n.name] = m
            return node_of(m, *ins)
        return cv

    _TF_CONVERTERS.update({
        "Relu": simple(jax.nn.relu),
        "Relu6": simple(lambda x: jnp.clip(x, 0, 6)),
        "Elu": simple(jax.nn.elu),
        "Sigmoid": simple(jax.nn.sigmoid),
        "Tanh": simple(jnp.tanh),
        "Softmax": simple(lambda x: jax.nn.softmax(x, axis=-1)),
        "Identity": simple(lambda x: x),
        "NoOp": simple(lambda *x: x[0] if x else None),
        "Add": simple(jnp.add), "AddV2": simple(jnp.add),
        "Sub": simple(jnp.subtract), "Mul": simple(jnp.multiply),
        "RealDiv": simple(jnp.divide),
        "Maximum": simple(jnp.maximum), "Minimum": simple(jnp.minimum),
        "Rsqrt": simple(jax.lax.rsqrt), "Sqrt": simple(jnp.sqrt),
        "Square": simple(jnp.square), "Exp": simple(jnp.exp),
        "Log": simple(jnp.log), "Neg": simple(jnp.negative),
        "Abs": simple(jnp.abs),
    })

    def conv2d(n, nodes, const_of, resolve, node_of, layer_map):
        _check_nhwc(n)
        w = const_of(n.inputs[1])
        assert w is not None, f"Conv2D {n.name}: non-const filter"
        strides = n.attrs.get("strides", [1, 1, 1, 1])
        padding = n.attrs.get("padding", "SAME")
        dil = list(n.attrs.get("dilations", [1, 1, 1, 1]))
        bias = n.attrs.get("_fused_bias")
        kh, kw, cin, cout = w.shape
        pad = -1 if padding == "SAME" else 0
        depthwise = n.op == "DepthwiseConv2dNative"
        if depthwise:
            if dil != [1, 1, 1, 1]:
                raise ValueError(f"{n.name}: dilated depthwise conv "
                                 f"import not supported")
            cout = cin * w.shape[3]
            mod = nn.SpatialConvolution(
                cin, cout, kw, kh, strides[2], strides[1], pad, pad,
                n_group=cin, with_bias=bias is not None)
            # depthwise HWIM → grouped HWIO: (kh, kw, 1, cout)
            mod.weight = Parameter(w.reshape(kh, kw, 1, cout))
        elif dil != [1, 1, 1, 1]:
            if padding == "SAME":
                # SAME pad for dilated conv: effective kernel size
                pad_h = ((kh - 1) * dil[1]) // 2
                pad_w = ((kw - 1) * dil[2]) // 2
            else:
                pad_h = pad_w = 0
            mod = nn.SpatialDilatedConvolution(
                cin, cout, kw, kh, strides[2], strides[1], pad_w, pad_h,
                dil[2], dil[1])
            mod.weight = Parameter(w)
            # this layer always carries a bias param — zero it when the
            # graph has no (fused) bias so numerics match exactly
            mod.bias = Parameter(bias.reshape(-1) if bias is not None
                                 else np.zeros(cout, np.float32))
            mod.set_name(n.name)
            layer_map[n.name] = mod
            return node_of(mod, resolve(n.inputs[0]))
        else:
            mod = nn.SpatialConvolution(
                cin, cout, kw, kh, strides[2], strides[1], pad, pad,
                with_bias=bias is not None)
            mod.weight = Parameter(w)
        if bias is not None:
            mod.bias = Parameter(bias.reshape(-1))
        mod.set_name(n.name)
        layer_map[n.name] = mod
        return node_of(mod, resolve(n.inputs[0]))

    _TF_CONVERTERS["Conv2D"] = conv2d
    _TF_CONVERTERS["DepthwiseConv2dNative"] = conv2d

    def matmul(n, nodes, const_of, resolve, node_of, layer_map):
        w = const_of(n.inputs[1])
        assert w is not None, f"MatMul {n.name}: non-const weights"
        if n.attrs.get("transpose_a", False):
            raise ValueError(f"MatMul {n.name}: transpose_a=True import "
                             f"not supported")
        if n.attrs.get("transpose_b", False):
            w = w.T
        bias = n.attrs.get("_fused_bias")
        mod = nn.Linear(w.shape[0], w.shape[1],
                        with_bias=bias is not None)
        mod.weight = Parameter(w.T)  # ours is (out, in)
        if bias is not None:
            mod.bias = Parameter(bias.reshape(-1))
        mod.set_name(n.name)
        layer_map[n.name] = mod
        return node_of(mod, resolve(n.inputs[0]))

    _TF_CONVERTERS["MatMul"] = matmul

    def bias_add(n, nodes, const_of, resolve, node_of, layer_map):
        _check_nhwc(n)
        src = nodes.get(_clean(n.inputs[0]))
        if src is not None and src.attrs.get("_fused_bias") is not None:
            return resolve(src.name)  # fused into producer
        b = const_of(n.inputs[1])
        if b is None:
            # non-const bias: plain elementwise add of two graph values
            m = _Lambda(_jnp.add, n.name)
            layer_map[n.name] = m
            return node_of(m, resolve(n.inputs[0]),
                           resolve(n.inputs[1]))
        m = _Lambda(lambda x, b=jnp_asarray(b): x + b, n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    import jax.numpy as _jnp

    def jnp_asarray(x):
        return _jnp.asarray(x)

    _TF_CONVERTERS["BiasAdd"] = bias_add

    def pool(n, nodes, const_of, resolve, node_of, layer_map):
        _check_nhwc(n)
        ks = n.attrs.get("ksize", [1, 2, 2, 1])
        st = n.attrs.get("strides", [1, 2, 2, 1])
        pad = n.attrs.get("padding", "VALID")
        p = -1 if pad == "SAME" else 0
        if n.op == "MaxPool":
            mod = nn.SpatialMaxPooling(ks[2], ks[1], st[2], st[1], p, p)
        else:
            # TF AvgPool excludes padded cells from the divisor
            mod = nn.SpatialAveragePooling(ks[2], ks[1], st[2], st[1],
                                           p, p,
                                           count_include_pad=False)
        mod.set_name(n.name)
        layer_map[n.name] = mod
        return node_of(mod, resolve(n.inputs[0]))

    _TF_CONVERTERS["MaxPool"] = pool
    _TF_CONVERTERS["AvgPool"] = pool

    def fused_bn(n, nodes, const_of, resolve, node_of, layer_map):
        gamma = const_of(n.inputs[1])
        beta = const_of(n.inputs[2])
        mean = const_of(n.inputs[3])
        var = const_of(n.inputs[4])
        eps = n.attrs.get("epsilon", 1e-3)
        mod = nn.SpatialBatchNormalization(
            mean.size, eps=float(eps),
            init_weight=gamma, init_bias=beta)
        mod.running_mean = np.asarray(mean, np.float32)
        mod.running_var = np.asarray(var, np.float32)
        mod.set_name(n.name)
        layer_map[n.name] = mod
        return node_of(mod, resolve(n.inputs[0]))

    _TF_CONVERTERS["FusedBatchNorm"] = fused_bn
    _TF_CONVERTERS["FusedBatchNormV2"] = fused_bn
    _TF_CONVERTERS["FusedBatchNormV3"] = fused_bn

    def reshape(n, nodes, const_of, resolve, node_of, layer_map):
        shape = const_of(n.inputs[1])
        assert shape is not None, f"Reshape {n.name}: dynamic shape"
        shape = [int(s) for s in shape.reshape(-1)]
        # jnp.reshape resolves a single -1 like TF does
        m = _Lambda(lambda x: x.reshape(shape), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["Reshape"] = reshape

    def squeeze(n, nodes, const_of, resolve, node_of, layer_map):
        dims = n.attrs.get("squeeze_dims", n.attrs.get("axis", []))
        m = _Lambda(lambda x: _jnp.squeeze(
            x, axis=tuple(dims) if dims else None), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["Squeeze"] = squeeze

    def mean(n, nodes, const_of, resolve, node_of, layer_map):
        axes = const_of(n.inputs[1])
        keep = n.attrs.get("keep_dims", n.attrs.get("keepdims", False))
        ax = tuple(int(a) for a in np.asarray(axes).reshape(-1))
        m = _Lambda(lambda x: _jnp.mean(x, axis=ax, keepdims=bool(keep)),
                    n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["Mean"] = mean

    def pad(n, nodes, const_of, resolve, node_of, layer_map):
        p = const_of(n.inputs[1])
        pads = [(int(a), int(b)) for a, b in np.asarray(p)]
        m = _Lambda(lambda x: _jnp.pad(x, pads), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["Pad"] = pad

    def concat(n, nodes, const_of, resolve, node_of, layer_map):
        data = [i for i in n.inputs if not i.startswith("^")]
        axis = const_of(data[-1])  # last DATA input is the axis
        ax = int(np.asarray(axis).reshape(-1)[0])
        ins = [resolve(i) for i in data[:-1]]
        m = _Lambda(lambda *xs: _jnp.concatenate(xs, axis=ax), n.name)
        layer_map[n.name] = m
        return node_of(m, *ins)

    _TF_CONVERTERS["ConcatV2"] = concat

    def lrn(n, nodes, const_of, resolve, node_of, layer_map):
        r = int(n.attrs.get("depth_radius", 5))
        mod = nn.SpatialCrossMapLRN(
            2 * r + 1, float(n.attrs.get("alpha", 1.0)) * (2 * r + 1),
            float(n.attrs.get("beta", 0.5)),
            float(n.attrs.get("bias", 1.0)))
        mod.set_name(n.name)
        layer_map[n.name] = mod
        return node_of(mod, resolve(n.inputs[0]))

    _TF_CONVERTERS["LRN"] = lrn

    def const(n, nodes, const_of, resolve, node_of, layer_map):
        v = n.attrs.get("value")
        m = _Lambda(lambda *a, v=_jnp.asarray(v): v, n.name)
        layer_map[n.name] = m
        return node_of(m)

    _TF_CONVERTERS["Const"] = const

    def placeholder(n, nodes, const_of, resolve, node_of, layer_map):
        raise ValueError(f"Placeholder {n.name!r} must be listed in "
                         f"`inputs`")

    _TF_CONVERTERS["Placeholder"] = placeholder

    # ---- extended op set (toward the reference's ~160 loaders,
    # utils/tf/loaders/) -------------------------------------------------

    _TF_CONVERTERS.update({
        "Pow": simple(_jnp.power), "Floor": simple(_jnp.floor),
        "Ceil": simple(_jnp.ceil), "Round": simple(_jnp.round),
        "Sign": simple(_jnp.sign), "Softplus": simple(jax.nn.softplus),
        "Softsign": simple(jax.nn.soft_sign),
        "LogSoftmax": simple(lambda x: jax.nn.log_softmax(x, axis=-1)),
        "Erf": simple(jax.lax.erf), "Sin": simple(_jnp.sin),
        "Cos": simple(_jnp.cos), "Tan": simple(_jnp.tan),
        "Atan": simple(_jnp.arctan), "Asin": simple(_jnp.arcsin),
        "Acos": simple(_jnp.arccos), "Sinh": simple(_jnp.sinh),
        "Cosh": simple(_jnp.cosh), "Log1p": simple(_jnp.log1p),
        "Expm1": simple(_jnp.expm1),
        "Reciprocal": simple(lambda x: 1.0 / x), "Inv": simple(
            lambda x: 1.0 / x),
        "FloorDiv": simple(_jnp.floor_divide),
        "FloorMod": simple(_jnp.mod), "Mod": simple(_jnp.mod),
        "SquaredDifference": simple(lambda a, b: (a - b) ** 2),
        "AddN": simple(lambda *xs: sum(xs)),
        "Equal": simple(_jnp.equal), "NotEqual": simple(_jnp.not_equal),
        "Greater": simple(_jnp.greater),
        "GreaterEqual": simple(_jnp.greater_equal),
        "Less": simple(_jnp.less), "LessEqual": simple(_jnp.less_equal),
        "LogicalAnd": simple(_jnp.logical_and),
        "LogicalOr": simple(_jnp.logical_or),
        "LogicalNot": simple(_jnp.logical_not),
        "Select": simple(_jnp.where), "SelectV2": simple(_jnp.where),
        "ZerosLike": simple(_jnp.zeros_like),
        "OnesLike": simple(_jnp.ones_like),
        "Shape": simple(lambda x: _jnp.asarray(x.shape, _jnp.int32)),
        "Rank": simple(lambda x: _jnp.asarray(x.ndim, _jnp.int32)),
        "Size": simple(lambda x: _jnp.asarray(x.size, _jnp.int32)),
        "BatchMatMul": simple(_jnp.matmul),
        "BatchMatMulV2": simple(_jnp.matmul),
    })

    def leaky_relu(n, nodes, const_of, resolve, node_of, layer_map):
        alpha = float(n.attrs.get("alpha", 0.2))
        m = _Lambda(lambda x: jax.nn.leaky_relu(x, alpha), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["LeakyRelu"] = leaky_relu

    def reduction(jfn):
        def cv(n, nodes, const_of, resolve, node_of, layer_map):
            axes = const_of(n.inputs[1])
            assert axes is not None, \
                f"{n.op} {n.name}: dynamic reduction axes"
            keep = bool(n.attrs.get("keep_dims",
                                    n.attrs.get("keepdims", False)))
            ax = tuple(int(a) for a in np.asarray(axes).reshape(-1))
            m = _Lambda(lambda x: jfn(x, axis=ax, keepdims=keep), n.name)
            layer_map[n.name] = m
            return node_of(m, resolve(n.inputs[0]))
        return cv

    for _op, _f in (("Sum", _jnp.sum), ("Max", _jnp.max),
                    ("Min", _jnp.min), ("Prod", _jnp.prod),
                    ("All", _jnp.all), ("Any", _jnp.any)):
        _TF_CONVERTERS[_op] = reduction(_f)

    def argminmax(jfn):
        def cv(n, nodes, const_of, resolve, node_of, layer_map):
            axis = const_of(n.inputs[1])
            assert axis is not None, f"{n.op} {n.name}: dynamic axis"
            ax = int(np.asarray(axis).reshape(-1)[0])
            m = _Lambda(lambda x: jfn(x, axis=ax).astype(_jnp.int64),
                        n.name)
            layer_map[n.name] = m
            return node_of(m, resolve(n.inputs[0]))
        return cv

    _TF_CONVERTERS["ArgMax"] = argminmax(_jnp.argmax)
    _TF_CONVERTERS["ArgMin"] = argminmax(_jnp.argmin)

    def expand_dims(n, nodes, const_of, resolve, node_of, layer_map):
        axis = const_of(n.inputs[1])
        ax = int(np.asarray(axis).reshape(-1)[0])
        m = _Lambda(lambda x: _jnp.expand_dims(x, ax), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["ExpandDims"] = expand_dims

    def transpose(n, nodes, const_of, resolve, node_of, layer_map):
        perm = const_of(n.inputs[1])
        p = tuple(int(a) for a in np.asarray(perm).reshape(-1))
        m = _Lambda(lambda x: _jnp.transpose(x, p), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["Transpose"] = transpose

    def tf_slice(n, nodes, const_of, resolve, node_of, layer_map):
        begin = const_of(n.inputs[1])
        size = const_of(n.inputs[2])
        assert begin is not None and size is not None, \
            f"Slice {n.name}: dynamic begin/size"
        b = [int(x) for x in np.asarray(begin).reshape(-1)]
        s = [int(x) for x in np.asarray(size).reshape(-1)]

        def fn(x):
            idx = tuple(slice(bi, x.shape[i] if si == -1 else bi + si)
                        for i, (bi, si) in enumerate(zip(b, s)))
            return x[idx]
        m = _Lambda(fn, n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["Slice"] = tf_slice

    def strided_slice(n, nodes, const_of, resolve, node_of, layer_map):
        begin = const_of(n.inputs[1])
        end = const_of(n.inputs[2])
        strides = const_of(n.inputs[3]) if len(n.inputs) > 3 else None
        assert begin is not None and end is not None, \
            f"StridedSlice {n.name}: dynamic bounds"
        for unsupported in ("ellipsis_mask", "new_axis_mask"):
            if int(n.attrs.get(unsupported, 0) or 0):
                raise ValueError(f"StridedSlice {n.name}: "
                                 f"{unsupported} import not supported")
        bm = int(n.attrs.get("begin_mask", 0))
        em = int(n.attrs.get("end_mask", 0))
        sa = int(n.attrs.get("shrink_axis_mask", 0))
        b = [int(x) for x in np.asarray(begin).reshape(-1)]
        e = [int(x) for x in np.asarray(end).reshape(-1)]
        s = ([int(x) for x in np.asarray(strides).reshape(-1)]
             if strides is not None else [1] * len(b))

        def fn(x):
            idx = []
            for i in range(len(b)):
                if sa & (1 << i):
                    idx.append(b[i])
                    continue
                lo = None if bm & (1 << i) else b[i]
                hi = None if em & (1 << i) else e[i]
                idx.append(slice(lo, hi, s[i]))
            return x[tuple(idx)]
        m = _Lambda(fn, n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["StridedSlice"] = strided_slice

    def split(n, nodes, const_of, resolve, node_of, layer_map):
        # Split: inputs = (axis, value); SplitV: (value, sizes, axis)
        if n.op == "Split":
            axis = const_of(n.inputs[0])
            val = n.inputs[1]
            parts = int(n.attrs.get("num_split", 1))
            sizes = None
        else:
            val = n.inputs[0]
            sizes = [int(x) for x in
                     np.asarray(const_of(n.inputs[1])).reshape(-1)]
            axis = const_of(n.inputs[2])
            parts = len(sizes)
        ax = int(np.asarray(axis).reshape(-1)[0])

        def fn(x):
            if sizes is None:
                return tuple(_jnp.split(x, parts, axis=ax))
            cuts = np.cumsum(sizes)[:-1].tolist()
            return tuple(_jnp.split(x, cuts, axis=ax))
        m = _Lambda(fn, n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(val))

    _TF_CONVERTERS["Split"] = split
    _TF_CONVERTERS["SplitV"] = split

    def pack(n, nodes, const_of, resolve, node_of, layer_map):
        ax = int(n.attrs.get("axis", 0))
        ins = [resolve(i) for i in n.inputs if not i.startswith("^")]
        m = _Lambda(lambda *xs: _jnp.stack(xs, axis=ax), n.name)
        layer_map[n.name] = m
        return node_of(m, *ins)

    _TF_CONVERTERS["Pack"] = pack

    def unpack(n, nodes, const_of, resolve, node_of, layer_map):
        ax = int(n.attrs.get("axis", 0))
        num = int(n.attrs.get("num", 0))
        m = _Lambda(lambda x: tuple(
            _jnp.squeeze(p, axis=ax)
            for p in _jnp.split(x, num or x.shape[ax], axis=ax)), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["Unpack"] = unpack

    def tile(n, nodes, const_of, resolve, node_of, layer_map):
        reps = const_of(n.inputs[1])
        r = tuple(int(x) for x in np.asarray(reps).reshape(-1))
        m = _Lambda(lambda x: _jnp.tile(x, r), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["Tile"] = tile

    def gather(n, nodes, const_of, resolve, node_of, layer_map):
        ax = 0
        if n.op == "GatherV2" and len(n.inputs) > 2:
            a = const_of(n.inputs[2])
            if a is not None:
                ax = int(np.asarray(a).reshape(-1)[0])
        m = _Lambda(lambda x, i: _jnp.take(x, i.astype(_jnp.int32),
                                           axis=ax), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]), resolve(n.inputs[1]))

    _TF_CONVERTERS["Gather"] = gather
    _TF_CONVERTERS["GatherV2"] = gather

    def one_hot(n, nodes, const_of, resolve, node_of, layer_map):
        depth = int(np.asarray(const_of(n.inputs[1])).reshape(-1)[0])
        on = float(np.asarray(const_of(n.inputs[2])).reshape(-1)[0]) \
            if const_of(n.inputs[2]) is not None else 1.0
        off = float(np.asarray(const_of(n.inputs[3])).reshape(-1)[0]) \
            if const_of(n.inputs[3]) is not None else 0.0
        ax_attr = n.attrs.get("axis")
        ax = -1 if ax_attr is None else int(ax_attr)

        def fn(x):
            y = jax.nn.one_hot(x.astype(_jnp.int32), depth) \
                * (on - off) + off
            return y if ax == -1 else _jnp.moveaxis(y, -1, ax)
        m = _Lambda(fn, n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["OneHot"] = one_hot

    def cast(n, nodes, const_of, resolve, node_of, layer_map):
        dst = int(n.attrs.get("DstT", _DT_FLOAT))
        # TF DataType enum values
        np_t = {_DT_FLOAT: _jnp.float32, 2: _jnp.float64, 3: _jnp.int32,
                4: _jnp.uint8, 5: _jnp.int16, 6: _jnp.int8,
                9: _jnp.int64, 10: _jnp.bool_, 14: _jnp.bfloat16,
                17: _jnp.uint16, 19: _jnp.float16,
                22: _jnp.uint32, 23: _jnp.uint64}.get(dst)
        if np_t is None:
            raise ValueError(f"Cast {n.name}: unsupported DstT={dst}")
        m = _Lambda(lambda x: x.astype(np_t), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["Cast"] = cast

    def fill(n, nodes, const_of, resolve, node_of, layer_map):
        dims = const_of(n.inputs[0])
        assert dims is not None, f"Fill {n.name}: dynamic shape"
        shape = tuple(int(x) for x in np.asarray(dims).reshape(-1))
        m = _Lambda(lambda v: _jnp.full(shape, v), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[1]))

    _TF_CONVERTERS["Fill"] = fill

    def _resize_coords(out_n, in_n, align_corners, half_pixel):
        i = _jnp.arange(out_n, dtype=_jnp.float32)
        if align_corners and out_n > 1:
            return i * ((in_n - 1) / (out_n - 1))
        if half_pixel:
            return (i + 0.5) * (in_n / out_n) - 0.5
        return i * (in_n / out_n)

    def _tf1_resize(x, h, w, method, align_corners, half_pixel):
        """TF1-exact resize: honors align_corners / half_pixel_centers /
        asymmetric (the TF1 default) coordinate mappings, which differ
        from jax.image.resize's fixed half-pixel sampling."""
        in_h, in_w = x.shape[1], x.shape[2]
        ys = _resize_coords(h, in_h, align_corners, half_pixel)
        xs = _resize_coords(w, in_w, align_corners, half_pixel)
        if method == "nearest":
            yi = (_jnp.floor(ys + 0.5) if half_pixel
                  else _jnp.floor(ys)).astype(_jnp.int32)
            xi = (_jnp.floor(xs + 0.5) if half_pixel
                  else _jnp.floor(xs)).astype(_jnp.int32)
            yi = _jnp.clip(yi, 0, in_h - 1)
            xi = _jnp.clip(xi, 0, in_w - 1)
            return x[:, yi][:, :, xi]
        ys = _jnp.clip(ys, 0.0, in_h - 1)
        xs = _jnp.clip(xs, 0.0, in_w - 1)
        y0 = _jnp.floor(ys).astype(_jnp.int32)
        x0 = _jnp.floor(xs).astype(_jnp.int32)
        y1 = _jnp.minimum(y0 + 1, in_h - 1)
        x1 = _jnp.minimum(x0 + 1, in_w - 1)
        wy = (ys - y0)[None, :, None, None]
        wx = (xs - x0)[None, None, :, None]
        top = x[:, y0][:, :, x0] * (1 - wx) + x[:, y0][:, :, x1] * wx
        bot = x[:, y1][:, :, x0] * (1 - wx) + x[:, y1][:, :, x1] * wx
        return top * (1 - wy) + bot * wy

    def resize(n, nodes, const_of, resolve, node_of, layer_map):
        size = const_of(n.inputs[1])
        h, w = (int(x) for x in np.asarray(size).reshape(-1))
        method = ("bilinear" if n.op == "ResizeBilinear"
                  else "nearest")
        ac = bool(n.attrs.get("align_corners", False))
        hp = bool(n.attrs.get("half_pixel_centers", False))
        m = _Lambda(lambda x: _tf1_resize(x, h, w, method, ac, hp),
                    n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["ResizeBilinear"] = resize
    _TF_CONVERTERS["ResizeNearestNeighbor"] = resize

    def tf_switch(n, nodes, const_of, resolve, node_of, layer_map):
        """Switch passes its data input through; branch selection
        happens at the matching Merge (under XLA both branches compute
        and a select picks one — nn/tf/ControlOps.scala's dead-tensor
        routing has no compiled equivalent, and needs none for
        side-effect-free math graphs)."""
        m = _Lambda(lambda x: x, n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["Switch"] = tf_switch

    def tf_merge(n, nodes, const_of, resolve, node_of, layer_map):
        data_ins = [i for i in n.inputs if not i.startswith("^")]
        if len(data_ins) != 2:
            raise ValueError(f"Merge {n.name}: only 2-way cond merges "
                             f"are importable")

        def find_switch(name, depth=0):
            base = _clean(name)
            nd = nodes.get(base)
            if nd is None or depth > 50:
                return None, None
            if nd.op == "Switch":
                return nd, 1 if name.endswith(":1") else 0
            for i in nd.inputs:
                if i.startswith("^"):
                    continue
                sw, port = find_switch(i, depth + 1)
                if sw is not None:
                    return sw, port
            return None, None

        sw0, p0 = find_switch(data_ins[0])
        sw1, p1 = find_switch(data_ins[1])
        if sw0 is None or sw1 is None or sw0.name != sw1.name \
                or {p0, p1} != {0, 1}:
            raise ValueError(
                f"Merge {n.name}: unsupported control-flow pattern — "
                f"only the Switch/Merge cond pair imports; loops should "
                f"be rebuilt with bigdl_tpu.ops.WhileLoop")
        false_in = data_ins[0] if p0 == 0 else data_ins[1]
        true_in = data_ins[1] if p0 == 0 else data_ins[0]
        m = _Lambda(lambda f, t, p: _jnp.where(
            _jnp.asarray(p).astype(bool), t, f), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(false_in), resolve(true_in),
                       resolve(sw0.inputs[1]))

    _TF_CONVERTERS["Merge"] = tf_merge

    def tf_exit(n, nodes, const_of, resolve, node_of, layer_map):
        """Import a whole TF-v1 while-loop frame as ONE lax.while_loop.

        The reference executes Enter/Merge/Switch/Exit/NextIteration
        frames with a dynamic Scheduler/FrameManager
        (nn/Scheduler.scala, nn/FrameManager.scala, nn/tf/
        ControlOps.scala); under XLA the whole frame compiles to a
        single `lax.while_loop`, so the importer pattern-matches the
        frame once (triggered at its first Exit) and every Exit selects
        its variable from the loop's carry tuple."""
        sw = nodes.get(_clean(n.inputs[0]))
        if sw is None or sw.op != "Switch":
            raise ValueError(f"Exit {n.name}: expected a Switch input")
        merge = nodes.get(_clean(sw.inputs[0]))
        loopcond = nodes.get(_clean(sw.inputs[1]))
        if merge is None or merge.op != "Merge" \
                or loopcond is None or loopcond.op != "LoopCond":
            raise ValueError(
                f"Exit {n.name}: not a canonical while-loop frame "
                f"(Switch must read a Merge and a LoopCond)")
        enter = next((nodes[_clean(i)] for i in merge.inputs
                      if nodes.get(_clean(i)) is not None
                      and nodes[_clean(i)].op == "Enter"), None)
        if enter is None:
            raise ValueError(f"Exit {n.name}: loop Merge has no Enter")
        frame = enter.attrs.get("frame_name", "")
        key = f"__tf_while__:{frame}"
        if key not in layer_map:
            by_consumer: Dict[str, list] = {}
            for nd in nodes.values():
                for i in nd.inputs:
                    if not i.startswith("^"):
                        by_consumer.setdefault(_clean(i), []).append(nd)

            def consumers_of(name, op):
                return [nd for nd in by_consumer.get(name, [])
                        if nd.op == op]

            enters = sorted(
                (nd for nd in nodes.values() if nd.op == "Enter"
                 and nd.attrs.get("frame_name", "") == frame),
                key=lambda nd: nd.name)
            carried, invariant = [], []
            for e in enters:
                merges = consumers_of(e.name, "Merge")
                if not merges:
                    invariant.append(e)  # loop-invariant capture
                    continue
                mg = merges[0]
                nis = [nodes[_clean(i)] for i in mg.inputs
                       if nodes.get(_clean(i)) is not None
                       and nodes[_clean(i)].op == "NextIteration"]
                sws = consumers_of(mg.name, "Switch")
                if not nis or not sws:
                    raise ValueError(
                        f"while frame {frame!r}: variable {e.name} has "
                        f"no NextIteration/Switch")
                exits = consumers_of(sws[0].name, "Exit")
                carried.append((e, mg, sws[0], nis[0], exits))
            merge_names = [c[1].name for c in carried]
            switch_names = [c[2].name for c in carried]
            inv_names = [e.name for e in invariant]

            def reachable_seeds(out_names, seed_names):
                """Static walk from outputs to find which boundary
                seeds a subgraph actually consumes, in stable seed
                order (Graph rejects unconnected inputs)."""
                seed_set, seen = set(seed_names), set()
                stack = [_clean(o) for o in out_names]
                while stack:
                    nm = stack.pop()
                    if nm in seen:
                        continue
                    seen.add(nm)
                    if nm in seed_set:
                        continue
                    nd = nodes.get(nm)
                    if nd is not None:
                        stack.extend(_clean(i) for i in nd.inputs
                                     if not i.startswith("^"))
                return [s for s in seed_names if s in seen]

            all_seeds = merge_names + switch_names + inv_names
            cond_outs = [loopcond.inputs[0]]
            body_outs = [c[3].inputs[0] for c in carried]
            cond_in = reachable_seeds(cond_outs, all_seeds)
            body_in = reachable_seeds(body_outs, all_seeds)
            cond_model, _ = _build_graph(nodes, cond_in, cond_outs)
            body_model, _ = _build_graph(nodes, body_in, body_outs)
            nvars, ninv = len(carried), len(inv_names)

            def run(*args):
                inits, invs = args[:nvars], args[nvars:]

                def env(carry):
                    e = {}
                    for i, c in enumerate(carry):
                        e[merge_names[i]] = c
                        e[switch_names[i]] = c
                    for j, v in enumerate(invs):
                        e[inv_names[j]] = v
                    return e

                def cond_fn(carry):
                    p = cond_model.forward(
                        *[env(carry)[nm] for nm in cond_in])
                    return _jnp.reshape(_jnp.asarray(p).astype(bool), ())

                def body_fn(carry):
                    out = body_model.forward(
                        *[env(carry)[nm] for nm in body_in])
                    if not isinstance(out, (tuple, list)):
                        out = (out,)
                    return tuple(out)

                import jax as _jax
                return _jax.lax.while_loop(cond_fn, body_fn,
                                           tuple(inits))

            loop_mod = _Lambda(run, f"while:{frame}")
            layer_map[f"while:{frame}"] = loop_mod
            init_gns = [resolve(c[0].inputs[0]) for c in carried]
            inv_gns = [resolve(e.inputs[0]) for e in invariant]
            exit_idx = {ex.name: i for i, c in enumerate(carried)
                        for ex in c[4]}
            layer_map[key] = (node_of(loop_mod, *init_gns, *inv_gns),
                              exit_idx)
        loop_gn, exit_idx = layer_map[key]
        if n.name not in exit_idx:
            raise ValueError(
                f"Exit {n.name}: not reachable from frame {frame!r}'s "
                f"loop variables (unsupported multi-Switch frame "
                f"layout?)")
        sel = _Lambda(lambda *parts, p=exit_idx[n.name]: parts[p], n.name)
        layer_map[n.name] = sel
        return node_of(sel, loop_gn)

    _TF_CONVERTERS["Exit"] = tf_exit
    # frame plumbing that is only ever reached through tf_exit's
    # subgraph seeding; direct passthrough keeps stray references sane
    _TF_CONVERTERS["LoopCond"] = simple(lambda x: x)
    _TF_CONVERTERS["Enter"] = simple(lambda x: x)

    def mirror_pad(n, nodes, const_of, resolve, node_of, layer_map):
        p = const_of(n.inputs[1])
        pads = [(int(a), int(b)) for a, b in np.asarray(p)]
        mode = n.attrs.get("mode", "REFLECT")
        jmode = "reflect" if mode == "REFLECT" else "symmetric"
        m = _Lambda(lambda x: _jnp.pad(x, pads, mode=jmode), n.name)
        layer_map[n.name] = m
        return node_of(m, resolve(n.inputs[0]))

    _TF_CONVERTERS["MirrorPad"] = mirror_pad

    # identity-like runtime-check/annotation ops common in exported
    # graphs (≙ nn/tf/Assert, CheckNumerics handling): the check has no
    # compiled equivalent worth a host sync — pass the value through
    _TF_CONVERTERS["StopGradient"] = simple(jax.lax.stop_gradient)
    _TF_CONVERTERS["CheckNumerics"] = simple(lambda x: x)
    _TF_CONVERTERS["PlaceholderWithDefault"] = simple(lambda x: x)
    _TF_CONVERTERS["Assert"] = simple(lambda *xs: None)


_register_defaults()


class TFSession:
    """Train an imported TF graph with the framework Optimizer
    (≙ BigDLSessionImpl.train, utils/tf/Session.scala:43-132 — the
    reference assembles a DistriOptimizer over the imported graph; here
    the imported Graph IS a Module whose Conv/MatMul/BN nodes carry real
    Parameters, so the Optimizer trains it directly)."""

    def __init__(self, graph, inputs: Sequence[str],
                 outputs: Sequence[str]):
        self.model, self.layer_map = load_tf_graph(graph, inputs, outputs)

    def train(self, dataset, criterion, optim_method=None,
              end_when=None, batch_size: Optional[int] = None,
              mesh_config=None) -> Module:
        from bigdl_tpu.optim import Optimizer, SGD, Trigger
        opt = Optimizer(self.model, dataset, criterion,
                        batch_size=batch_size)
        opt.set_optim_method(optim_method or SGD(0.01))
        opt.set_end_when(end_when or Trigger.max_epoch(1))
        if mesh_config is not None:
            opt.set_mesh(mesh_config)
        opt.optimize()
        return self.model

    def predict(self, x):
        return self.model.eval_mode().forward(x)


# --------------------------------------------------------------------------
# export (≙ TensorflowSaver)
# --------------------------------------------------------------------------

def _attr_entry(key: str, value_fields) -> bytes:
    return encode_message([
        (_MAP_KEY, BYTES, key.encode()),
        (_MAP_VALUE, BYTES, encode_message(value_fields)),
    ])


def _tensor_proto(arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    dt = {np.dtype(np.float32): _DT_FLOAT,
          np.dtype(np.int32): _DT_INT32,
          np.dtype(np.int64): _DT_INT64}.get(arr.dtype, _DT_FLOAT)
    if dt == _DT_FLOAT:
        arr = arr.astype("<f4")
    shape = encode_message([
        (2, BYTES, encode_message([(1, VARINT, int(d))]))
        for d in arr.shape])
    return encode_message([
        (_T_DTYPE, VARINT, dt),
        (_T_SHAPE, BYTES, shape),
        (_T_CONTENT, BYTES, arr.tobytes()),
    ])


def _node_def(name: str, op: str, inputs: Sequence[str],
              attrs: Dict[str, bytes] = ()) -> bytes:
    fields = [(_N_NAME, BYTES, name.encode()), (_N_OP, BYTES, op.encode())]
    for i in inputs:
        fields.append((_N_INPUT, BYTES, i.encode()))
    for entry in (attrs or []):
        fields.append((_N_ATTR, BYTES, entry))
    return encode_message(fields)


def save_tf_graph(model: Module, path: str, input_name: str = "input",
                  input_shape: Optional[Sequence[int]] = None) -> List[str]:
    """Export a Sequential of supported layers to a TF GraphDef
    (≙ TensorflowSaver.saveGraph).  Returns the node names in order."""
    node_defs: List[bytes] = []
    names: List[str] = []

    def add(name, op, inputs, attrs=()):
        node_defs.append(_node_def(name, op, inputs, attrs))
        names.append(name)
        return name

    dtype_attr = _attr_entry("dtype", [(_A_TYPE, VARINT, _DT_FLOAT)])
    t_attr = _attr_entry("T", [(_A_TYPE, VARINT, _DT_FLOAT)])
    add(input_name, "Placeholder", [], [dtype_attr])
    cur = input_name

    mods = (list(model.modules()) if isinstance(model, nn.Sequential)
            else [model])
    for pos, m in enumerate(mods):
        # position suffix keeps node names unique even for repeated
        # unnamed layers (duplicate names corrupt a GraphDef)
        base = f"{m.get_name() or type(m).__name__}_{pos + 1}"
        if isinstance(m, nn.Linear):
            w = np.asarray(m.weight).T  # TF: (in, out)
            wn = add(f"{base}/weights", "Const", [],
                     [dtype_attr,
                      _attr_entry("value", [(_A_TENSOR, BYTES,
                                             _tensor_proto(w))])])
            cur = add(f"{base}/MatMul", "MatMul", [cur, wn], [t_attr])
            if getattr(m, "with_bias", False):
                b = np.asarray(m.bias)
                bn = add(f"{base}/bias", "Const", [],
                         [dtype_attr,
                          _attr_entry("value", [(_A_TENSOR, BYTES,
                                                 _tensor_proto(b))])])
                cur = add(f"{base}/BiasAdd", "BiasAdd", [cur, bn],
                          [t_attr])
        elif isinstance(m, nn.ReLU):
            cur = add(f"{base}/Relu", "Relu", [cur], [t_attr])
        elif isinstance(m, nn.Tanh):
            cur = add(f"{base}/Tanh", "Tanh", [cur], [t_attr])
        elif isinstance(m, nn.Sigmoid):
            cur = add(f"{base}/Sigmoid", "Sigmoid", [cur], [t_attr])
        elif isinstance(m, (nn.SoftMax, nn.LogSoftMax)):
            cur = add(f"{base}/Softmax", "Softmax", [cur], [t_attr])
            if isinstance(m, nn.LogSoftMax):
                cur = add(f"{base}/Log", "Log", [cur], [t_attr])
        elif isinstance(m, (nn.Reshape, nn.Flatten, nn.View)):
            if isinstance(m, nn.Reshape):
                dims = list(m.size)
            elif isinstance(m, nn.View):
                dims = list(m.sizes)
            else:  # Flatten: infer the feature size from the next Linear
                nxt = next((x for x in mods[pos + 1:]
                            if isinstance(x, nn.Linear)), None)
                if nxt is None:
                    raise ValueError(
                        "save_tf_graph: Flatten needs a following Linear "
                        "to infer its target size — use Reshape instead")
                dims = [nxt.input_size]
            shape = np.asarray([-1] + dims, np.int32)
            sn = add(f"{base}/shape", "Const", [],
                     [_attr_entry("dtype", [(_A_TYPE, VARINT, _DT_INT32)]),
                      _attr_entry("value", [(_A_TENSOR, BYTES,
                                             _tensor_proto(shape))])])
            cur = add(f"{base}/Reshape", "Reshape", [cur, sn], [t_attr])
        elif isinstance(m, nn.SpatialConvolution):
            w = np.asarray(m.weight)  # HWIO already
            wn = add(f"{base}/weights", "Const", [],
                     [dtype_attr,
                      _attr_entry("value", [(_A_TENSOR, BYTES,
                                             _tensor_proto(w))])])
            sh, sw = m.stride
            ph, pw = m.pad
            pad = b"SAME" if ph == -1 else b"VALID"
            strides = _attr_entry("strides", [(_A_LIST, BYTES,
                encode_message([(3, VARINT, 1), (3, VARINT, sh),
                                (3, VARINT, sw), (3, VARINT, 1)]))])
            padding = _attr_entry("padding", [(_A_S, BYTES, pad)])
            cur = add(f"{base}/Conv2D", "Conv2D", [cur, wn],
                      [t_attr, strides, padding])
            if getattr(m, "with_bias", False):
                b = np.asarray(m.bias)
                bn = add(f"{base}/bias", "Const", [],
                         [dtype_attr,
                          _attr_entry("value", [(_A_TENSOR, BYTES,
                                                 _tensor_proto(b))])])
                cur = add(f"{base}/BiasAdd", "BiasAdd", [cur, bn],
                          [t_attr])
        else:
            raise ValueError(f"save_tf_graph: unsupported layer "
                             f"{type(m).__name__}")
    graph = encode_message([(1, BYTES, nd) for nd in node_defs])
    with open(path, "wb") as f:
        f.write(graph)
    return names
