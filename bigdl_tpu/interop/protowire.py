"""Generic protobuf wire-format codec (no generated classes).

Reference: the reference ships ~120k LoC of protoc-generated Java
(caffe/Caffe.java, serialization/Bigdl.java, tensorflow framework
protos) to read/write Caffe, TensorFlow and BigDL model files.  Here the
same formats are handled with a ~200-line generic wire codec: messages
decode to ``{field_number: [values]}`` dicts and encode from
``[(field_number, wire_type, value)]`` lists; the schema knowledge
(which field number means what) lives in the importers.

Wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterable, List, Tuple, Union

import numpy as np

__all__ = [
    "decode_message", "encode_message", "varint", "zigzag",
    "as_string", "as_floats", "as_ints", "Field",
    "VARINT", "FIXED64", "BYTES", "FIXED32",
]

VARINT, FIXED64, BYTES, FIXED32 = 0, 1, 2, 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def decode_message(buf: bytes) -> Dict[int, list]:
    """Decode one message into {field_number: [raw values]}.
    Varint fields → int; fixed32/64 → raw 4/8 bytes; length-delimited →
    bytes (caller interprets as sub-message/string/packed array)."""
    out: Dict[int, list] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == VARINT:
            val, pos = _read_varint(buf, pos)
        elif wire == FIXED64:
            val = buf[pos:pos + 8]
            pos += 8
        elif wire == BYTES:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == FIXED32:
            val = buf[pos:pos + 4]
            pos += 4
        elif wire in (3, 4):  # group start/end (deprecated) — skip
            continue
        else:
            raise ValueError(f"unknown wire type {wire} at {pos}")
        out.setdefault(field, []).append(val)
    return out


def varint(x: int) -> bytes:
    if x < 0:
        # proto2/3 semantics: negative ints go out as 10-byte two's
        # complement (Python's arithmetic shift would loop forever)
        x &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def zigzag(x: int) -> int:
    return (x << 1) ^ (x >> 63)


Field = Tuple[int, int, Union[int, bytes]]


def encode_message(fields: Iterable[Field]) -> bytes:
    """[(field_number, wire_type, value)] → bytes.  wire_type BYTES
    values must already be encoded (sub-message bytes / utf-8 / packed)."""
    out = bytearray()
    for num, wire, val in fields:
        out += varint((num << 3) | wire)
        if wire == VARINT:
            out += varint(int(val))
        elif wire == BYTES:
            out += varint(len(val))
            out += val
        elif wire == FIXED32:
            out += (val if isinstance(val, bytes)
                    else struct.pack("<f", val))
        elif wire == FIXED64:
            out += (val if isinstance(val, bytes)
                    else struct.pack("<d", val))
        else:
            raise ValueError(f"unsupported wire type {wire}")
    return bytes(out)


# ---- interpretation helpers ----------------------------------------------

def as_string(v: bytes) -> str:
    return v.decode("utf-8")


def as_floats(values: list) -> np.ndarray:
    """Repeated float field: either packed (one bytes blob) or a list of
    fixed32 values."""
    if not values:
        return np.zeros(0, np.float32)
    if len(values) == 1 and isinstance(values[0], bytes) \
            and len(values[0]) % 4 == 0:
        # packed (N floats in one blob) — also covers a single fixed32
        return np.frombuffer(values[0], "<f4").copy()
    return np.asarray([struct.unpack("<f", v)[0] for v in values],
                      np.float32)


def as_ints(values: list) -> List[int]:
    """Repeated varint field: packed blob or list of ints."""
    out: List[int] = []
    for v in values:
        if isinstance(v, bytes):
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(x)
        else:
            out.append(int(v))
    return out
