"""Minimal ONNX op shims.

Reference: nn/onnx/{Gemm,Reshape,Shape}.scala (235 LoC — the reference
exposes exactly these three ops to its Python ONNX bridge).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.module import Module, Parameter

__all__ = ["Gemm", "OnnxReshape", "OnnxShape"]


class Gemm(Module):
    """Y = alpha * A' * B' + beta * C (reference nn/onnx/Gemm.scala)."""

    def __init__(self, alpha: float = 1.0, beta: float = 1.0,
                 trans_a: bool = False, trans_b: bool = False,
                 matrix_b=None, matrix_c=None):
        super().__init__()
        self.alpha, self.beta = float(alpha), float(beta)
        self.trans_a, self.trans_b = trans_a, trans_b
        if matrix_b is not None:
            self.matrix_b = Parameter(matrix_b)
        else:
            self.matrix_b = None
        if matrix_c is not None:
            self.matrix_c = Parameter(matrix_c)
        else:
            self.matrix_c = None

    def forward(self, inputs):
        if isinstance(inputs, (tuple, list)):
            a = inputs[0]
            b = inputs[1] if len(inputs) > 1 else self.matrix_b
            c = inputs[2] if len(inputs) > 2 else self.matrix_c
        else:
            a, b, c = inputs, self.matrix_b, self.matrix_c
        if self.trans_a:
            a = a.T
        if self.trans_b:
            b = b.T
        y = self.alpha * (a @ b)
        if c is not None:
            y = y + self.beta * c
        return y


class OnnxReshape(Module):
    """ONNX Reshape with 0 = copy-input-dim semantics
    (reference nn/onnx/Reshape.scala)."""

    def __init__(self, shape=None):
        super().__init__()
        self.shape = tuple(int(s) for s in shape) if shape is not None \
            else None

    def forward(self, inputs):
        if isinstance(inputs, (tuple, list)):
            x, shape = inputs[0], [int(s) for s in np.asarray(inputs[1])]
        else:
            x, shape = inputs, list(self.shape)
        shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
        return x.reshape(shape)


class OnnxShape(Module):
    """Returns the input's shape as an int64 tensor
    (reference nn/onnx/Shape.scala)."""

    def forward(self, x):
        return jnp.asarray(x.shape, jnp.int64)
