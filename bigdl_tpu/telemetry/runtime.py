"""Host/device runtime sampling: RSS, GC, accelerator memory.

A training job that OOMs the host (input pipeline buffering) or the
device (stacked dispatch windows) usually telegraphed it for minutes in
exactly these numbers.  ``sample_runtime()`` takes one reading into the
telemetry registry; :class:`RuntimeSampler` does it on a cadence.

Everything degrades gracefully: no ``/proc`` (non-Linux) falls back to
``resource.getrusage``, and device memory stats are skipped wherever
``jax.local_devices()`` or ``memory_stats()`` is unavailable (CPU
backends, older runtimes) — sampling never raises.
"""

from __future__ import annotations

import gc
import os
import threading
import time
from typing import Any, Dict, List, Optional

from bigdl_tpu.telemetry import families

__all__ = ["sample_runtime", "RuntimeSampler", "hbm_peaks",
           "reset_hbm_peaks", "device_memory_snapshot",
           "oom_forensics_report"]

_PAGESIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096

# per-device high-water marks over sampled bytes_in_use — the fallback
# when the backend's memory_stats() carries no peak_bytes_in_use of its
# own.  Sampled peaks undercount between samples; backend peaks (used
# whenever present) are exact.
_HBM_PEAKS: Dict[str, float] = {}
_PEAKS_LOCK = threading.Lock()


def _rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGESIZE
    except Exception:
        pass
    try:
        import resource
        import sys
        ru = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux, bytes on macOS; either way it is a
        # high-water mark, not current RSS — better than nothing
        scale = 1 if sys.platform == "darwin" else 1024
        return int(ru.ru_maxrss) * scale
    except Exception:
        return None


def sample_runtime(include_devices: bool = True) -> None:
    """One reading of host RSS, GC collection counts, and (where the
    backend exposes ``memory_stats``) per-device memory into the
    telemetry registry."""
    rss = _rss_bytes()
    if rss is not None:
        families.process_rss_bytes().set(rss)
    try:
        stats = gc.get_stats()
        ctr = families.gc_collections_total()
        for gen, st in enumerate(stats):
            ctr.labels(gen).set_total(st.get("collections", 0))
    except Exception:
        pass
    if not include_devices:
        return
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return
    in_use = families.device_memory_bytes_in_use()
    limit = families.device_memory_bytes_limit()
    peak = families.hbm_bytes_peak()
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            continue
        if not ms:
            continue
        key = f"{d.platform}:{d.id}"
        if "bytes_in_use" in ms:
            in_use.labels(key).set(ms["bytes_in_use"])
        if "bytes_limit" in ms:
            limit.labels(key).set(ms["bytes_limit"])
        # peak watermark: the backend's own high-water mark when it
        # keeps one (exact), else a max over our sampled in-use values
        # (a lower bound); missing both keys -> skip, never invent
        if "peak_bytes_in_use" in ms:
            with _PEAKS_LOCK:
                _HBM_PEAKS[key] = float(ms["peak_bytes_in_use"])
            peak.labels(key).set(ms["peak_bytes_in_use"])
        elif "bytes_in_use" in ms:
            with _PEAKS_LOCK:
                p = max(_HBM_PEAKS.get(key, 0.0),
                        float(ms["bytes_in_use"]))
                _HBM_PEAKS[key] = p
            peak.labels(key).set(p)


def hbm_peaks() -> Dict[str, float]:
    """The per-device peak watermarks sampled so far this process."""
    with _PEAKS_LOCK:
        return dict(_HBM_PEAKS)


def reset_hbm_peaks() -> None:
    """Forget the sampled watermarks (tests; a new run's baseline)."""
    with _PEAKS_LOCK:
        _HBM_PEAKS.clear()


def device_memory_snapshot() -> List[Dict[str, Any]]:
    """Every local device's full ``memory_stats()`` dict (empty list
    when the backend exposes none) — the raw material of the OOM
    forensics report."""
    out: List[Dict[str, Any]] = []
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return out
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            continue
        if not ms:
            continue
        out.append({"device": f"{d.platform}:{d.id}",
                    "device_kind": getattr(d, "device_kind", None),
                    "memory_stats": dict(ms)})
    return out


def _live_array_census(max_groups: int = 20) -> Dict[str, Any]:
    """What is actually holding HBM right now: live jax arrays grouped
    by (shape, dtype), largest first — the census that turns "OOM at
    step N" into "the 4096 stacked window copies never freed"."""
    try:
        import jax
        arrays = jax.live_arrays()
    except Exception:
        return {"available": False}
    groups: Dict[Any, Dict[str, Any]] = {}
    total = 0
    for a in arrays:
        try:
            nbytes = int(a.nbytes)
            key = (str(a.dtype), tuple(a.shape))
        except Exception:
            continue
        g = groups.setdefault(key, {"dtype": key[0],
                                    "shape": list(key[1]),
                                    "count": 0, "bytes": 0})
        g["count"] += 1
        g["bytes"] += nbytes
        total += nbytes
    top = sorted(groups.values(), key=lambda g: -g["bytes"])
    return {"available": True, "arrays": sum(g["count"] for g in top),
            "total_bytes": total, "groups_total": len(top),
            "top_groups": top[:max_groups]}


def oom_forensics_report(error: Optional[str] = None,
                         last_window: Optional[Dict[str, Any]] = None,
                         max_groups: int = 20) -> Dict[str, Any]:
    """The artifact a RESOURCE_EXHAUSTED crash leaves behind: device
    memory_stats, the peak watermarks, a live-array census, and the
    last attribution window — everything the postmortem needs that
    evaporates with the process.  Pure dict builder (the optimizer
    writes it beside the flight recorder); never raises."""
    report: Dict[str, Any] = {
        "kind": "oom_forensics",
        "time": time.time(),
        "pid": os.getpid(),
        "error": error,
        "rss_bytes": _rss_bytes(),
    }
    try:
        report["devices"] = device_memory_snapshot()
    except Exception:  # pragma: no cover - forensics is best effort
        report["devices"] = []
    report["hbm_bytes_peak"] = hbm_peaks()
    try:
        report["live_arrays"] = _live_array_census(max_groups)
    except Exception:  # pragma: no cover
        report["live_arrays"] = {"available": False}
    if last_window is not None:
        report["last_window"] = dict(last_window)
    return report


class RuntimeSampler:
    """Daemon thread calling :func:`sample_runtime` every
    ``interval_s``; ``stop()`` joins cleanly (one final sample)."""

    def __init__(self, interval_s: float = 10.0,
                 include_devices: bool = True):
        self.interval_s = float(interval_s)
        self.include_devices = include_devices
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            sample_runtime(self.include_devices)
            self.samples += 1
        sample_runtime(self.include_devices)
        self.samples += 1

    def start(self) -> "RuntimeSampler":
        if self._thread is not None:
            raise RuntimeError("sampler already started")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="bigdl-telemetry-runtime")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        self._thread = None

    def __enter__(self) -> "RuntimeSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
