"""Host/device runtime sampling: RSS, GC, accelerator memory.

A training job that OOMs the host (input pipeline buffering) or the
device (stacked dispatch windows) usually telegraphed it for minutes in
exactly these numbers.  ``sample_runtime()`` takes one reading into the
telemetry registry; :class:`RuntimeSampler` does it on a cadence.

Everything degrades gracefully: no ``/proc`` (non-Linux) falls back to
``resource.getrusage``, and device memory stats are skipped wherever
``jax.local_devices()`` or ``memory_stats()`` is unavailable (CPU
backends, older runtimes) — sampling never raises.
"""

from __future__ import annotations

import gc
import os
import threading
from typing import Optional

from bigdl_tpu.telemetry import families

__all__ = ["sample_runtime", "RuntimeSampler"]

_PAGESIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGESIZE
    except Exception:
        pass
    try:
        import resource
        import sys
        ru = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux, bytes on macOS; either way it is a
        # high-water mark, not current RSS — better than nothing
        scale = 1 if sys.platform == "darwin" else 1024
        return int(ru.ru_maxrss) * scale
    except Exception:
        return None


def sample_runtime(include_devices: bool = True) -> None:
    """One reading of host RSS, GC collection counts, and (where the
    backend exposes ``memory_stats``) per-device memory into the
    telemetry registry."""
    rss = _rss_bytes()
    if rss is not None:
        families.process_rss_bytes().set(rss)
    try:
        stats = gc.get_stats()
        ctr = families.gc_collections_total()
        for gen, st in enumerate(stats):
            ctr.labels(gen).set_total(st.get("collections", 0))
    except Exception:
        pass
    if not include_devices:
        return
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return
    in_use = families.device_memory_bytes_in_use()
    limit = families.device_memory_bytes_limit()
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            continue
        if not ms:
            continue
        key = f"{d.platform}:{d.id}"
        if "bytes_in_use" in ms:
            in_use.labels(key).set(ms["bytes_in_use"])
        if "bytes_limit" in ms:
            limit.labels(key).set(ms["bytes_limit"])


class RuntimeSampler:
    """Daemon thread calling :func:`sample_runtime` every
    ``interval_s``; ``stop()`` joins cleanly (one final sample)."""

    def __init__(self, interval_s: float = 10.0,
                 include_devices: bool = True):
        self.interval_s = float(interval_s)
        self.include_devices = include_devices
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            sample_runtime(self.include_devices)
            self.samples += 1
        sample_runtime(self.include_devices)
        self.samples += 1

    def start(self) -> "RuntimeSampler":
        if self._thread is not None:
            raise RuntimeError("sampler already started")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="bigdl-telemetry-runtime")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        self._thread = None

    def __enter__(self) -> "RuntimeSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
