"""Exposition: Prometheus text, JSON snapshots, TensorBoard bridge,
and a periodic background exporter.

* :func:`prometheus_text` — the text exposition format (0.0.4) a
  Prometheus scrape expects; served by ``examples/serve.py /metrics``.
* :func:`json_snapshot` — one JSON-able dict of every metric (plus a
  span-buffer summary); ``bench.py`` drops this next to its BENCH
  artifact so perf regressions can be attributed to data-wait vs
  compute without a TPU profile.
* :func:`publish_summary` — writes the snapshot through a
  ``visualization.Summary`` (see ``TelemetrySummary``) so telemetry
  lands in the same TensorBoard run as train/validation/serving
  scalars.
* :class:`PeriodicExporter` — a daemon thread exporting every
  ``interval_s`` with a clean ``stop()`` (final export included, so a
  short run's tail is never lost).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, Optional

from bigdl_tpu.telemetry import events as _events
from bigdl_tpu.telemetry import tracing
from bigdl_tpu.telemetry.metrics import (
    Counter, Gauge, Histogram, TelemetryRegistry, get_registry,
)

__all__ = ["prometheus_text", "json_snapshot", "publish_summary",
           "PeriodicExporter"]


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _esc(s: str) -> str:
    return (str(s).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _labelstr(names, values, extra: str = "") -> str:
    parts = [f'{n}="{_esc(v)}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(registry: Optional[TelemetryRegistry] = None) -> str:
    """Render every metric in the Prometheus text exposition format.
    Collectors (e.g. the serving bridge) run first, so reservoir
    quantiles are fresh as of this scrape."""
    registry = registry or get_registry()
    registry.run_collectors()
    lines = []
    for m in registry.metrics():
        if m.help:
            lines.append(f"# HELP {m.name} {_esc(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            for labels, snap in m.samples():
                cum = 0
                for le, n in zip(snap["buckets"], snap["counts"]):
                    cum += n
                    ls = _labelstr(m.labelnames, labels,
                                   f'le="{_fmt_value(le)}"')
                    lines.append(f"{m.name}_bucket{ls} {cum}")
                ls = _labelstr(m.labelnames, labels)
                lines.append(f"{m.name}_sum{ls} {_fmt_value(snap['sum'])}")
                lines.append(f"{m.name}_count{ls} {snap['count']}")
        else:
            for labels, v in m.samples():
                ls = _labelstr(m.labelnames, labels)
                lines.append(f"{m.name}{ls} {_fmt_value(v)}")
    return "\n".join(lines) + "\n"


def json_snapshot(registry: Optional[TelemetryRegistry] = None) -> Dict:
    """One coherent JSON-able dict: every metric (collectors included)
    plus summaries of the span ring buffer and the flight recorder —
    the latter is how ``BENCH_telemetry.json`` carries a bench run's
    retry/fault/checkpoint event history."""
    registry = registry or get_registry()
    spans = tracing.finished_spans()
    by_name: Dict[str, Dict] = {}
    for s in spans:
        agg = by_name.setdefault(s.name, {"count": 0, "total_s": 0.0})
        agg["count"] += 1
        agg["total_s"] += s.duration_s
    ev = _events.events_summary(50)
    return {
        "time": time.time(),
        "metrics": registry.snapshot(),
        "spans": {"buffered": len(spans),
                  "dropped": tracing.dropped_spans(),
                  "by_name": by_name},
        "events": {"buffered": ev["buffered"], "dropped": ev["dropped"],
                   "by_kind": ev["counts"], "recent": ev["recent"]},
    }


def publish_summary(summary, step: int,
                    registry: Optional[TelemetryRegistry] = None) -> None:
    """Write the current metric values through a ``visualization``
    Summary (``TelemetrySummary`` puts them under a ``telemetry`` tag
    directory in the same TensorBoard run as train/val/serving).
    Counters/gauges become scalars tagged ``telemetry/<name>`` (label
    values joined into the tag); histograms become TB histograms
    weighted by bucket counts."""
    import numpy as np
    registry = registry or get_registry()
    registry.run_collectors()
    for m in registry.metrics():
        if isinstance(m, Histogram):
            for labels, snap in m.samples():
                if not snap["count"]:
                    continue
                tag = "/".join(("telemetry", m.name) + labels)
                # bucket representative = upper bound (finite), lower
                # neighbor for the +Inf bucket
                values, weights = [], []
                prev = 0.0
                for le, n in zip(snap["buckets"], snap["counts"]):
                    if n:
                        values.append(prev if le == float("inf") else le)
                        weights.append(n)
                    if le != float("inf"):
                        prev = le
                summary.add_histogram(tag, np.asarray(values, np.float64),
                                      step, weights=weights)
        else:
            for labels, v in m.samples():
                tag = "/".join(("telemetry", m.name) + labels)
                summary.add_scalar(tag, float(v), step)


class PeriodicExporter:
    """Background exporter thread.

    >>> exp = PeriodicExporter(interval_s=30, path="telemetry.json")
    >>> exp.start()
    ...
    >>> exp.stop()          # joins the thread; writes one final export

    Exactly one of ``path`` (JSON snapshot written atomically-enough
    via truncate+rename-free rewrite) or ``fn`` (called with the
    snapshot dict) must be given.  ``prometheus=True`` with ``path``
    writes text exposition instead of JSON (node-exporter textfile
    style)."""

    def __init__(self, interval_s: float,
                 path: Optional[str] = None,
                 fn: Optional[Callable[[Dict], None]] = None,
                 prometheus: bool = False,
                 registry: Optional[TelemetryRegistry] = None):
        if (path is None) == (fn is None):
            raise ValueError("give exactly one of path= or fn=")
        self.interval_s = float(interval_s)
        self.path = path
        self.fn = fn
        self.prometheus = prometheus
        self.registry = registry or get_registry()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.exports = 0
        self.errors = 0

    def _export_once(self) -> None:
        try:
            if self.path is not None:
                if self.prometheus:
                    data = prometheus_text(self.registry)
                else:
                    data = json.dumps(json_snapshot(self.registry))
                with open(self.path, "w", encoding="utf-8") as f:
                    f.write(data)
            else:
                self.fn(json_snapshot(self.registry))
            self.exports += 1
        except Exception:
            # an unwritable disk must not kill the exporter (next
            # interval may succeed); errors are counted, not raised
            self.errors += 1

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._export_once()
        self._export_once()  # final export on clean shutdown

    def start(self) -> "PeriodicExporter":
        if self._thread is not None:
            raise RuntimeError("exporter already started")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="bigdl-telemetry-export")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Signal the thread, wait for its final export, join."""
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout)
        self._thread = None

    def __enter__(self) -> "PeriodicExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
