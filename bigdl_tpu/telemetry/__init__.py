"""``bigdl_tpu.telemetry`` — unified metrics, tracing, and runtime
observability across training and serving.

One substrate replaces three disconnected fragments (the serving-local
``MetricsRegistry``, the eager module timer, log-line-only retry/chaos
events): thread-safe Counter/Gauge/Histogram primitives in a
process-global registry, ``span()`` tracing with Chrome-trace export,
Prometheus/JSON/TensorBoard exposition, and host/device runtime
sampling.  See ``docs/observability.md`` for the full metric and span
catalog.

**Disabled by default.**  Every instrumentation site in the hot path
guards with :func:`enabled` — a single module-global bool read — so a
training step pays a few branch checks and nothing else until an
operator opts in::

    from bigdl_tpu import telemetry
    telemetry.enable()                    # or BIGDL_TPU_TELEMETRY=1
    ... train / serve ...
    print(telemetry.prometheus_text())
    telemetry.write_chrome_trace("trace.json")
"""

from __future__ import annotations

import os as _os

from bigdl_tpu.telemetry.metrics import (      # noqa: F401
    Counter, Gauge, Histogram, TelemetryRegistry, get_registry,
)
from bigdl_tpu.telemetry.tracing import (      # noqa: F401
    span, record_span, current_span, propagate, finished_spans,
    dropped_spans, reset_spans, set_ring_capacity, chrome_trace,
    write_chrome_trace, merge_chrome_traces,
)
from bigdl_tpu.telemetry.request_trace import (  # noqa: F401
    TraceContext, assemble_trace, write_trace_shard, reset_traces,
)
from bigdl_tpu.telemetry.export import (       # noqa: F401
    prometheus_text, json_snapshot, publish_summary, PeriodicExporter,
)
from bigdl_tpu.telemetry.events import (       # noqa: F401
    record_event, recent_events, event_counts, dropped_events,
    reset_events, dump_events,
)

__all__ = [
    "enable", "disable", "enabled", "reset",
    "Counter", "Gauge", "Histogram", "TelemetryRegistry", "get_registry",
    "span", "record_span", "current_span", "propagate", "finished_spans",
    "dropped_spans", "reset_spans", "set_ring_capacity", "chrome_trace",
    "write_chrome_trace", "merge_chrome_traces",
    "TraceContext", "assemble_trace", "write_trace_shard",
    "reset_traces",
    "prometheus_text", "json_snapshot", "publish_summary",
    "PeriodicExporter",
    "record_event", "recent_events", "event_counts", "dropped_events",
    "reset_events", "dump_events",
]

# THE hot-path switch: instrumentation sites read this through
# enabled(); everything else in the package is cold-path.
_ENABLED = False


def enable() -> None:
    """Turn instrumentation on and pre-register the full metric
    catalog (so exports immediately show every family, at zero)."""
    global _ENABLED
    from bigdl_tpu.telemetry import families
    families.preregister()
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    """Is instrumentation on?  Hot paths call this once per decision —
    it must stay a bare global read."""
    return _ENABLED


def reset() -> None:
    """Test-friendly full reset: zero every metric in place (handles
    stay valid), drop all buffered spans, request traces, and the
    flight recorder."""
    get_registry().reset()
    reset_spans()
    reset_events()
    reset_traces()


if _os.environ.get("BIGDL_TPU_TELEMETRY", "").lower() in (
        "1", "true", "on", "yes"):
    enable()
