"""Span tracing: where a step's wall time actually goes.

``span("optimizer/step")`` is a context manager recording one timed
interval.  Spans nest via a thread-local stack (a span opened inside
another becomes its child), finished spans land in a bounded ring
buffer, and the whole buffer exports to Chrome trace-event JSON —
loadable in Perfetto / ``chrome://tracing`` — so the data-wait /
compiled-step / validation / checkpoint-commit breakdown of a training
run is one file away instead of unanswerable.

Clock: ``time.perf_counter()``, the same clock the serving scheduler
and optimizer already stamp with, so :func:`record_span` can adopt
timestamps measured elsewhere (e.g. a request's ``t_enqueue``)
retroactively.  Trace timestamps are exported relative to the module's
load instant; ``wall_time_of`` converts to epoch seconds when needed.

Cross-thread propagation: a worker thread adopts a parent with::

    token = tracing.current_span()          # in the submitting thread
    with tracing.propagate(token):          # in the worker
        with tracing.span("serving/execute"):
            ...

When telemetry is disabled (the default) ``span`` yields a shared
no-op — the hot path pays one bool read and one dict-free function
call, nothing else.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional

__all__ = ["span", "record_span", "current_span", "propagate",
           "finished_spans", "dropped_spans", "reset_spans",
           "set_ring_capacity", "chrome_trace", "write_chrome_trace",
           "merge_chrome_traces", "wall_time_of"]

# The clock contract (enforced tree-wide by graftlint's
# clock-discipline pass, docs/static_analysis.md):
#
#   * DURATIONS and span endpoints live on ``time.perf_counter()`` —
#     monotonic, NTP-immune, the only clock two in-process stamps may
#     be subtracted on;
#   * TIMESTAMPS (event records, checkpoint manifests, cross-process
#     staleness checks) live on ``time.time()`` — epoch-meaningful,
#     comparable across processes, never subtracted from a
#     perf_counter value.
#
# ``(_EPOCH_PERF, _EPOCH_WALL)`` is the one sanctioned bridge between
# the two: a paired reading captured once at import, so
# :func:`wall_time_of` can render a perf_counter stamp as approximate
# epoch seconds for humans.  Code must cross the bridge through that
# function, not by mixing clocks ad hoc — PR 3's review round found
# optimizer spans stranded ~an epoch off the trace timeline from
# exactly such a mix.
_EPOCH_PERF = time.perf_counter()
_EPOCH_WALL = time.time()

_DEFAULT_CAPACITY = 16384

_ids = itertools.count(1)
_tls = threading.local()

_buf_lock = threading.Lock()
_buffer: deque = deque(maxlen=_DEFAULT_CAPACITY)
_dropped = 0


class SpanRecord:
    """One finished span.  Plain object, not a dataclass: this is
    allocated on every traced interval."""

    __slots__ = ("name", "t_start", "t_end", "span_id", "parent_id",
                 "thread", "args")

    def __init__(self, name, t_start, t_end, span_id, parent_id,
                 thread, args):
        self.name = name
        self.t_start = t_start
        self.t_end = t_end
        self.span_id = span_id
        self.parent_id = parent_id
        self.thread = thread
        self.args = args

    @property
    def duration_s(self) -> float:
        return self.t_end - self.t_start


def _stack() -> List[int]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _record(rec: SpanRecord) -> None:
    global _dropped
    with _buf_lock:
        if len(_buffer) == _buffer.maxlen:
            _dropped += 1
        _buffer.append(rec)


def current_span() -> Optional[int]:
    """The innermost open span id on THIS thread (a propagation token
    for worker threads), or None."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


@contextmanager
def propagate(parent_id: Optional[int]) -> Iterator[None]:
    """Adopt ``parent_id`` as this thread's span parent for the block —
    the cross-thread half of parent/child propagation."""
    st = _stack()
    if parent_id is None:
        yield
        return
    st.append(parent_id)
    try:
        yield
    finally:
        st.pop()


@contextmanager
def span(name: str, **args) -> Iterator[Optional[int]]:
    """Record one timed interval.  Yields the span id (None when
    telemetry is disabled).  ``args`` become Chrome-trace args."""
    from bigdl_tpu import telemetry
    if not telemetry.enabled():
        yield None
        return
    st = _stack()
    parent = st[-1] if st else None
    sid = next(_ids)
    st.append(sid)
    t0 = time.perf_counter()
    try:
        yield sid
    finally:
        t1 = time.perf_counter()
        st.pop()
        _record(SpanRecord(name, t0, t1, sid, parent,
                           threading.get_ident(), args or None))


def record_span(name: str, t_start: float, t_end: float,
                parent_id: Optional[int] = None, **args) -> Optional[int]:
    """Record a span from timestamps measured elsewhere (both on the
    ``time.perf_counter`` clock).  Used where the interval's endpoints
    are only known after the fact — e.g. the optimizer's async loss
    drain learns a window's completion time in a worker thread, and a
    serving request's queue wait starts at its ``t_enqueue``."""
    from bigdl_tpu import telemetry
    if not telemetry.enabled():
        return None
    if parent_id is None:
        parent_id = current_span()
    sid = next(_ids)
    _record(SpanRecord(name, t_start, t_end, sid, parent_id,
                       threading.get_ident(), args or None))
    return sid


# ---- reading / export ------------------------------------------------------

def finished_spans() -> List[SpanRecord]:
    with _buf_lock:
        return list(_buffer)


def dropped_spans() -> int:
    with _buf_lock:
        return _dropped


def reset_spans() -> None:
    global _dropped
    with _buf_lock:
        _buffer.clear()
        _dropped = 0


def set_ring_capacity(n: int) -> None:
    """Resize the finished-span ring (keeps the newest spans)."""
    global _buffer
    if n < 1:
        raise ValueError("ring capacity must be >= 1")
    with _buf_lock:
        _buffer = deque(_buffer, maxlen=n)


def wall_time_of(t_perf: float) -> float:
    """perf_counter timestamp -> epoch seconds (approximate: anchored
    at module import)."""
    return _EPOCH_WALL + (t_perf - _EPOCH_PERF)


def chrome_trace() -> Dict:
    """The ring buffer as a Chrome trace-event object: complete ("X")
    events with microsecond ts/dur, pid/tid, and span/parent ids in
    args — ``json.dump`` it and load in Perfetto."""
    events = []
    for rec in finished_spans():
        args = {"span_id": rec.span_id}
        if rec.parent_id is not None:
            args["parent_id"] = rec.parent_id
        if rec.args:
            args.update(rec.args)
        events.append({
            "ph": "X",
            "name": rec.name,
            "cat": "bigdl_tpu",
            "ts": (rec.t_start - _EPOCH_PERF) * 1e6,
            "dur": max(rec.t_end - rec.t_start, 0.0) * 1e6,
            "pid": os.getpid(),
            "tid": rec.thread,
            "args": args,
        })
    # epoch_wall anchors this file's ts=0 on the shared wall clock, so
    # merge_chrome_traces can re-base per-process timelines onto one
    # axis (each process's perf_counter starts at an arbitrary zero)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": dropped_spans(),
                          "epoch_wall": _EPOCH_WALL}}


def write_chrome_trace(path: str) -> str:
    """Serialize :func:`chrome_trace` to ``path`` (JSON)."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(chrome_trace(), f)
    return path


def merge_chrome_traces(paths) -> Dict:
    """Merge per-process Chrome trace files into ONE Perfetto-loadable
    timeline.  Each file's ``ts`` values are relative to its own
    process's perf_counter zero; the ``otherData.epoch_wall`` anchor
    (written by :func:`chrome_trace`) says where that zero sits on the
    shared wall clock, so every file is shifted onto the earliest
    anchor's axis.  A file with no anchor (pre-anchor export) merges
    unshifted.  Distinct pids keep their own tracks; drop counters
    sum."""
    loaded = []
    dropped = 0
    for p in paths:
        with open(p, "r", encoding="utf-8") as f:
            data = json.load(f)
        other = data.get("otherData") or {}
        loaded.append((data, other.get("epoch_wall")))
        try:
            dropped += int(other.get("dropped_spans", 0) or 0)
        except (TypeError, ValueError):
            pass
    anchors = [a for _, a in loaded if a is not None]
    base = min(anchors) if anchors else None
    events: List[Dict] = []
    for data, anchor in loaded:
        shift_us = (0.0 if anchor is None or base is None
                    else (float(anchor) - base) * 1e6)
        for ev in data.get("traceEvents", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + shift_us
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    out = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"dropped_spans": dropped,
                         "merged_files": len(loaded)}}
    if base is not None:
        out["otherData"]["epoch_wall"] = base
    return out
