"""Training-health watchdog: interpret the signals, live.

PR 3 gave the repo raw signals (step-phase histograms, spans, the
completion-timestamp stream); this module is the layer above that
*judges* them while the run is still cheap to save.  Four anomaly
classes, each with a configurable policy:

``nonfinite``
    The loss or the global gradient norm came back NaN/Inf.  Detection
    is **in-graph** (``jnp.isfinite`` reductions fused into the
    existing train step; the norm reuses the grad-clip norm when
    ``grad_clip_norm`` is set, so it is computed once) and surfaces on
    the host with the per-step loss readback.  Policies: ``warn``,
    ``skip_step`` (the update is discarded in-graph — params, optimizer
    state, and buffers keep their pre-step values via a fused
    ``jnp.where`` — and training continues), ``checkpoint_and_halt``.

``loss_spike``
    Finite loss far above its EWMA (mean + deviation tracking): the
    divergence signature that precedes NaN by many steps.  Policies:
    ``warn``, ``checkpoint_and_halt``.

``step_time_outlier``
    A completion-to-completion window whose per-iteration time is a
    large multiple of its EWMA — a mid-run recompile, a contended chip,
    a collective stall.  Policies: ``warn``, ``checkpoint_and_halt``.

``data_starvation``
    Data-wait fraction over a rolling window of flushed readback
    windows above a threshold: the step is waiting on the input
    pipeline.  Policies: ``warn``, ``checkpoint_and_halt``.

``checkpoint_and_halt`` reuses the PR-2 preemption machinery — the
optimizer writes a final checkpoint at the next step boundary (good by
construction for ``nonfinite``: the poisoned update was discarded
in-graph) and returns cleanly with ``watchdog_halted`` set, after
dumping the flight recorder next to the checkpoint.

Every verdict increments ``training_anomalies_total{kind}`` (plus
``training_nonfinite_total`` for the nonfinite kinds), records a
flight-recorder event, and lands in a bounded history that ``/statusz``
serves.  The watchdog is **off by default**; a run without one pays
nothing new (see ``Optimizer.set_health_watchdog``).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from bigdl_tpu.telemetry import events as _events

__all__ = ["HealthWatchdog", "Verdict", "POLICIES", "ANOMALY_CLASSES"]

logger = logging.getLogger("bigdl_tpu.health")

POLICIES = ("warn", "skip_step", "checkpoint_and_halt")

# policy classes -> the verdict kinds they govern
ANOMALY_CLASSES = {
    "nonfinite": ("nonfinite_loss", "nonfinite_grad"),
    "loss_spike": ("loss_spike",),
    "step_time_outlier": ("step_time_outlier",),
    "data_starvation": ("data_starvation",),
    "straggler": ("straggler",),
}


class Verdict:
    """One anomaly judgment: what was seen, at which step, and what the
    configured policy did about it."""

    __slots__ = ("kind", "action", "step", "value", "message", "t_wall")

    def __init__(self, kind: str, action: str, step: int, value: float,
                 message: str):
        self.kind = kind
        self.action = action
        self.step = step
        self.value = value
        self.message = message
        self.t_wall = time.time()

    def to_dict(self) -> Dict:
        # value may be the offending NaN/Inf itself: json_safe keeps
        # /statusz (watchdog.recent_verdicts) strict JSON during the
        # incident it reports
        return {"kind": self.kind, "action": self.action,
                "step": self.step, "value": _events.json_safe(self.value),
                "message": self.message, "time": self.t_wall}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Verdict({self.kind}, action={self.action}, "
                f"step={self.step}, value={self.value!r})")


class _Ewma:
    """EWMA of a stream plus EWMA of its absolute deviation — the cheap
    robust-ish baseline an outlier is judged against."""

    __slots__ = ("alpha", "mean", "dev", "n")

    def __init__(self, alpha: float):
        self.alpha = alpha
        self.mean: Optional[float] = None
        self.dev = 0.0
        self.n = 0

    def update(self, v: float) -> None:
        if self.mean is None:
            self.mean = v
        else:
            self.dev += self.alpha * (abs(v - self.mean) - self.dev)
            self.mean += self.alpha * (v - self.mean)
        self.n += 1


class HealthWatchdog:
    """Host-side anomaly judge.  The optimizer calls ``observe_step``
    with each iteration's (loss, grad-norm) readback and
    ``observe_window`` with each flushed readback window's timing;
    everything else (state for ``/statusz``, the halt flag the loop
    polls) is derived.  Thread-safe: the loop writes, a ``/statusz``
    scrape reads."""

    def __init__(self,
                 nonfinite: str = "checkpoint_and_halt",
                 loss_spike: str = "warn",
                 step_time_outlier: str = "warn",
                 data_starvation: str = "warn",
                 straggler: str = "warn",
                 ewma_alpha: float = 0.1,
                 spike_factor: float = 10.0,
                 spike_grace_steps: int = 10,
                 step_time_factor: float = 10.0,
                 step_time_grace_windows: int = 5,
                 starvation_fraction: float = 0.6,
                 starvation_windows: int = 16,
                 straggler_ratio: float = 2.0,
                 max_history: int = 64):
        policies = {"nonfinite": nonfinite, "loss_spike": loss_spike,
                    "step_time_outlier": step_time_outlier,
                    "data_starvation": data_starvation,
                    "straggler": straggler}
        for cls, pol in policies.items():
            if pol not in POLICIES:
                raise ValueError(
                    f"unknown watchdog policy {pol!r} for {cls!r}; pick "
                    f"from {POLICIES}")
            if pol == "skip_step" and cls != "nonfinite":
                # only a nonfinite update can be skipped: the in-graph
                # guard decides before the update lands; host-side
                # classes judge AFTER the update already applied
                raise ValueError(
                    f"policy 'skip_step' only applies to 'nonfinite' "
                    f"(got it for {cls!r}); host-side anomalies are "
                    f"judged after the update is already applied")
        self.policies = policies
        self.ewma_alpha = float(ewma_alpha)
        self.spike_factor = float(spike_factor)
        self.spike_grace_steps = int(spike_grace_steps)
        self.step_time_factor = float(step_time_factor)
        self.step_time_grace_windows = int(step_time_grace_windows)
        self.starvation_fraction = float(starvation_fraction)
        self.starvation_windows = int(starvation_windows)
        self.straggler_ratio = float(straggler_ratio)
        self._lock = threading.Lock()
        self.history: deque = deque(maxlen=int(max_history))
        self.counts: Dict[str, int] = {}
        self.halt_requested = False
        self.steps_seen = 0
        self._loss = _Ewma(self.ewma_alpha)
        self._step_t = _Ewma(self.ewma_alpha)
        self._data_win: deque = deque(maxlen=self.starvation_windows)

    # ---- configuration-derived -------------------------------------------

    @property
    def guard_updates(self) -> bool:
        """Should the train step discard nonfinite updates in-graph?
        True for both ``skip_step`` (training continues on the last
        good params) and ``checkpoint_and_halt`` (the final checkpoint
        must hold pre-anomaly weights to be worth resuming from)."""
        return self.policies["nonfinite"] != "warn"

    # ---- run lifecycle ----------------------------------------------------

    def start_run(self) -> None:
        """Reset the per-attempt baselines (EWMA, rolling windows, halt
        flag).  History and counts persist across retries — the anomaly
        record is the run's, not the attempt's."""
        with self._lock:
            self.halt_requested = False
            self._loss = _Ewma(self.ewma_alpha)
            self._step_t = _Ewma(self.ewma_alpha)
            self._data_win.clear()

    # ---- observations -----------------------------------------------------

    def observe_step(self, step: int, loss: float,
                     grad_norm: Optional[float] = None) -> List[Verdict]:
        """Judge one iteration's host-side loss (and, when the in-graph
        monitor is wired, global grad norm) readback."""
        verdicts: List[Verdict] = []
        self.steps_seen += 1
        if not math.isfinite(loss):
            verdicts.append(self._verdict(
                "nonfinite_loss", self.policies["nonfinite"], step, loss,
                f"loss is {loss} at iteration {step}"))
        if grad_norm is not None and not math.isfinite(grad_norm):
            verdicts.append(self._verdict(
                "nonfinite_grad", self.policies["nonfinite"], step,
                grad_norm,
                f"global gradient norm is {grad_norm} at iteration "
                f"{step}"))
        if math.isfinite(loss):
            ew = self._loss
            if ew.n >= self.spike_grace_steps and ew.mean is not None:
                floor = max(ew.dev, 1e-3 * max(abs(ew.mean), 1e-6))
                if loss - ew.mean > self.spike_factor * floor:
                    verdicts.append(self._verdict(
                        "loss_spike", self.policies["loss_spike"], step,
                        loss,
                        f"loss {loss:.6g} spiked above its EWMA "
                        f"{ew.mean:.6g} (dev {ew.dev:.3g}) at iteration "
                        f"{step}"))
            # a spiking loss still feeds the EWMA (the baseline must
            # follow a genuinely shifting loss, or one spike would
            # condemn every later step); a nonfinite one must not
            # (NaN poisons the mean permanently)
            ew.update(loss)
        return verdicts

    def observe_window(self, window_s: float, data_wait_s: float,
                       n_iterations: int,
                       step: Optional[int] = None) -> List[Verdict]:
        """Judge one flushed readback window from the completion-
        timestamp stream: per-iteration step time vs its EWMA, and the
        data-wait fraction over a rolling window of windows."""
        verdicts: List[Verdict] = []
        step = -1 if step is None else int(step)
        per_iter = window_s / max(n_iterations, 1)
        ew = self._step_t
        if ew.n >= self.step_time_grace_windows and ew.mean is not None:
            floor = max(ew.dev, 0.05 * max(ew.mean, 1e-6))
            if per_iter - ew.mean > self.step_time_factor * floor:
                verdicts.append(self._verdict(
                    "step_time_outlier",
                    self.policies["step_time_outlier"], step, per_iter,
                    f"per-iteration time {per_iter:.4g}s is an outlier "
                    f"vs EWMA {ew.mean:.4g}s (recompile? contended "
                    f"chip? collective stall?)"))
        ew.update(per_iter)
        self._data_win.append((max(data_wait_s, 0.0), max(window_s, 0.0)))
        if len(self._data_win) == self._data_win.maxlen:
            tot = sum(w for _d, w in self._data_win)
            waited = sum(d for d, _w in self._data_win)
            if tot > 0 and waited / tot >= self.starvation_fraction:
                verdicts.append(self._verdict(
                    "data_starvation",
                    self.policies["data_starvation"], step, waited / tot,
                    f"input pipeline starvation: {waited / tot:.0%} of "
                    f"the last {len(self._data_win)} windows' wall time "
                    f"was spent waiting on data"))
                self._data_win.clear()  # don't re-fire every step
        return verdicts

    def observe_fleet(self, step: int, skew: float,
                      slowest_process: int,
                      detail: str = "") -> List[Verdict]:
        """Judge one fleet sample from :class:`telemetry.fleet
        .FleetMonitor`: the slowest-host/median ratio against
        ``straggler_ratio``.  Unlike the EWMA classes there is no
        baseline to learn — skew 1.0 is the definition of balanced, so
        the threshold is absolute."""
        verdicts: List[Verdict] = []
        if math.isfinite(skew) and skew >= self.straggler_ratio:
            verdicts.append(self._verdict(
                "straggler", self.policies["straggler"], step, skew,
                f"process {slowest_process} is a straggler: fleet skew "
                f"{skew:.2f}x >= {self.straggler_ratio:.2f}x"
                + (f" ({detail})" if detail else "")))
        return verdicts

    # ---- verdicts ---------------------------------------------------------

    def _verdict(self, kind: str, action: str, step: int, value: float,
                 message: str) -> Verdict:
        v = Verdict(kind, action, step, value, message)
        with self._lock:
            self.history.append(v)
            self.counts[kind] = self.counts.get(kind, 0) + 1
            if action == "checkpoint_and_halt":
                self.halt_requested = True
        logger.warning("watchdog: %s -> %s", message, action)
        _events.record_event("watchdog", anomaly=kind, action=action,
                             step=step, value=value, message=message)
        from bigdl_tpu import telemetry
        if telemetry.enabled():
            from bigdl_tpu.telemetry import families
            families.training_anomalies_total().labels(kind).inc()
            if kind in ANOMALY_CLASSES["nonfinite"]:
                families.training_nonfinite_total().inc()
        return v

    # ---- introspection ----------------------------------------------------

    def state(self) -> Dict:
        """The watchdog's judgment so far, JSON-able — what ``/statusz``
        serves under ``watchdog``."""
        with self._lock:
            return {
                "policies": dict(self.policies),
                "halt_requested": self.halt_requested,
                "steps_seen": self.steps_seen,
                "anomaly_counts": dict(self.counts),
                "loss_ewma": self._loss.mean,
                "step_time_ewma": self._step_t.mean,
                "recent_verdicts": [v.to_dict() for v in self.history],
            }
