"""Metric primitives: Counter / Gauge / Histogram with label support.

One process-global (but resettable) registry unifies the three
observability fragments this repo grew separately — the serving-local
``MetricsRegistry``, the eager per-module timer in ``optim/profiling``,
and the fault-tolerance layer's retry/chaos events — so ONE Prometheus
scrape (or JSON snapshot) answers "where does a step's wall time go"
across training and serving.

Design constraints, in priority order:

* **Zero hot-path cost when disabled.**  Instrumentation sites guard
  with :func:`bigdl_tpu.telemetry.enabled` (one module-global bool
  read); nothing here is imported into a jit trace.
* **Thread-safe.**  The optimizer's loss-drain worker, the serving
  scheduler, the prefetch producer, and a Prometheus scrape thread all
  record/read concurrently; every mutation and every snapshot takes the
  owning metric's lock.
* **Resettable, not re-creatable.**  ``reset()`` zeroes values IN PLACE
  so module-level metric handles cached by instrumented code stay valid
  across tests (a registry swap would leave them writing into a ghost).

Metric names follow Prometheus conventions: ``snake_case``, ``_total``
suffix on counters, ``_seconds``/``_bytes`` units.  Every name is
declared exactly once, in :mod:`bigdl_tpu.telemetry.families` —
``scripts/metrics_lint.py`` enforces both rules statically.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "TelemetryRegistry",
           "get_registry", "DEFAULT_BUCKETS"]

# Latency-oriented default buckets (seconds): sub-millisecond dispatch
# overheads through minute-scale checkpoint commits.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, float("inf"))


class _Child:
    """Per-label-set value holder.  The parent metric's lock guards all
    mutation; children never outlive their parent."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0


class _Metric:
    """Base: name, help text, label names, and a child per label-value
    tuple (the no-label case uses the single ``()`` child)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        return _Child(self._lock)

    def labels(self, *values) -> "_Metric":
        """Bound view for one label-value tuple; children are created on
        first use and cached (bounded cardinality is the caller's
        contract — label values should be enums, not request ids)."""
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes {len(self.labelnames)} "
                f"label value(s) {self.labelnames}, got {len(values)}")
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
        return _Bound(self, child)

    def _default_child(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name!r} has labels {self.labelnames}; "
                f"call .labels(...) first")
        # graftlint: disable=lock-discipline -- the () child is created
        # once at construction and never replaced; this read races with
        # nothing (labelled children are the ones minted under the lock)
        return self._children[()]

    def _reset(self) -> None:
        with self._lock:
            for child in self._children.values():
                self._zero(child)

    @staticmethod
    def _zero(child) -> None:
        child.value = 0.0

    def samples(self) -> List[Tuple[Tuple[str, ...], float]]:
        """[(label_values, value)] under one lock acquisition."""
        with self._lock:
            return [(k, c.value) for k, c in sorted(self._children.items())]


class _Bound:
    """A metric narrowed to one label set: forwards the value ops."""

    __slots__ = ("_metric", "_child")

    def __init__(self, metric: _Metric, child):
        self._metric = metric
        self._child = child

    def __getattr__(self, item):
        op = getattr(type(self._metric), "_op_" + item, None)
        if op is None:
            raise AttributeError(item)
        metric, child = self._metric, self._child
        return lambda *a, **k: op(metric, child, *a, **k)


class Counter(_Metric):
    """Monotonically increasing count (``_total`` names)."""

    kind = "counter"

    def _op_inc(self, child, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            child.value += n

    def _op_value(self, child) -> float:
        with self._lock:
            return child.value

    # collectors mirroring an external monotonic count (serving bridge)
    def _op_set_total(self, child, v: float) -> None:
        with self._lock:
            child.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self._op_inc(self._default_child(), n)

    def set_total(self, v: float) -> None:
        self._op_set_total(self._default_child(), v)

    def value(self) -> float:
        return self._op_value(self._default_child())


class Gauge(_Metric):
    """A value that goes up and down (queue depth, RSS)."""

    kind = "gauge"

    def _op_set(self, child, v: float) -> None:
        with self._lock:
            child.value = float(v)

    def _op_inc(self, child, n: float = 1.0) -> None:
        with self._lock:
            child.value += n

    def _op_dec(self, child, n: float = 1.0) -> None:
        with self._lock:
            child.value -= n

    def _op_value(self, child) -> float:
        with self._lock:
            return child.value

    def set(self, v: float) -> None:
        self._op_set(self._default_child(), v)

    def inc(self, n: float = 1.0) -> None:
        self._op_inc(self._default_child(), n)

    def dec(self, n: float = 1.0) -> None:
        self._op_dec(self._default_child(), n)

    def value(self) -> float:
        return self._op_value(self._default_child())


class _HistChild:
    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets
        self.sum = 0.0
        self.count = 0
        # bucket index -> {"value", "trace_id", "time"}: the most
        # recent exemplar-tagged observation landing in that bucket,
        # so a histogram breach resolves to the trace that caused it.
        # Lazily populated; {} until an observe passes an exemplar.
        self.exemplars: Dict[int, Dict] = {}


class Histogram(_Metric):
    """Prometheus-style cumulative-bucket histogram.  ``observe`` is a
    bisect + three in-place updates under the metric lock — cheap enough
    for per-iteration phase timings."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        bs = tuple(buckets) if buckets is not None else DEFAULT_BUCKETS
        if not bs or sorted(bs) != list(bs):
            raise ValueError("histogram buckets must be sorted")
        if bs[-1] != float("inf"):
            bs = bs + (float("inf"),)
        self.buckets = bs
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistChild(len(self.buckets))

    @staticmethod
    def _zero(child) -> None:
        child.counts = [0] * len(child.counts)
        child.sum = 0.0
        child.count = 0
        child.exemplars = {}

    def _op_observe(self, child, v: float,
                    exemplar: Optional[str] = None) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            child.counts[i] += 1
            child.sum += v
            child.count += 1
            if exemplar is not None:
                # graftlint: disable=clock-discipline -- an exemplar's
                # timestamp is a cross-process record (it names a trace
                # another process may assemble), so it lives on the
                # shared wall clock, not this process's perf_counter
                child.exemplars[i] = {"value": float(v),
                                      "trace_id": str(exemplar),
                                      "time": time.time()}

    @staticmethod
    def _child_dump(buckets, c) -> Dict:
        out = {"buckets": list(buckets), "counts": list(c.counts),
               "sum": c.sum, "count": c.count}
        if c.exemplars:
            out["exemplars"] = {i: dict(e)
                                for i, e in c.exemplars.items()}
        return out

    def _op_snapshot(self, child) -> Dict:
        with self._lock:
            return self._child_dump(self.buckets, child)

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        self._op_observe(self._default_child(), v, exemplar=exemplar)

    def snapshot(self) -> Dict:
        return self._op_snapshot(self._default_child())

    def samples(self) -> List[Tuple[Tuple[str, ...], Dict]]:
        with self._lock:
            return [(k, self._child_dump(self.buckets, c))
                    for k, c in sorted(self._children.items())]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

# a collector returning this sentinel is dropped from the registry —
# how a bridge whose weakref'd source died retires itself instead of
# running (and accumulating) forever
COLLECTOR_DONE = object()


class TelemetryRegistry:
    """Get-or-create home for every metric in the process.

    ``collectors`` are pull hooks run before every snapshot/export —
    the serving ``MetricsRegistry`` bridge lives there, so its
    reservoir quantiles land in this registry at read time with zero
    cost on the serving hot path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], None]] = []

    # ---- registration ----------------------------------------------------

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labelnames, **kw)
                return m
        if type(m) is not cls:
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}, "
                f"cannot re-register as {cls.kind}")
        if tuple(labelnames) != m.labelnames:
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{m.labelnames}, got {tuple(labelnames)}")
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def register_collector(self, fn: Callable[[], object]) -> None:
        """``fn()`` runs before every snapshot/export; it should pull
        from its source and write into this registry.  Exceptions are
        swallowed (a dead source must not break a scrape).  A collector
        returning :data:`COLLECTOR_DONE` is unregistered — sources held
        by weakref retire their collector once garbage collected."""
        with self._lock:
            self._collectors.append(fn)

    # ---- reading ---------------------------------------------------------

    def run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        done = []
        for fn in collectors:
            try:
                if fn() is COLLECTOR_DONE:
                    done.append(fn)
            except Exception:
                pass
        if done:
            with self._lock:
                for fn in done:
                    try:
                        self._collectors.remove(fn)
                    except ValueError:
                        pass

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, Dict]:
        """JSON-able dump: {name: {kind, help, labels, values}}, with
        histogram values as {buckets, counts, sum, count}.  The +Inf
        bucket bound is rendered as the string ``"+Inf"`` — a float
        inf would make ``json.dumps`` emit the bare ``Infinity`` token,
        which strict RFC-8259 parsers (jq, JSON.parse) reject."""
        self.run_collectors()
        out: Dict[str, Dict] = {}
        inf = float("inf")
        for m in self.metrics():
            values = []
            for k, v in m.samples():
                if isinstance(v, dict) and "buckets" in v:
                    v = dict(v, buckets=["+Inf" if b == inf else b
                                         for b in v["buckets"]])
                values.append({"labels": dict(zip(m.labelnames, k)),
                               "value": v})
            out[m.name] = {"kind": m.kind, "help": m.help,
                           "label_names": list(m.labelnames),
                           "values": values}
        return out

    # ---- lifecycle -------------------------------------------------------

    def reset(self) -> None:
        """Zero every metric IN PLACE (handles stay valid); collectors
        are kept — their sources decide their own reset story."""
        for m in self.metrics():
            m._reset()

    def clear(self) -> None:
        """Forget everything, including collectors (tests that assert
        exact exposition content start from an empty registry)."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()


_REGISTRY = TelemetryRegistry()


def get_registry() -> TelemetryRegistry:
    return _REGISTRY
