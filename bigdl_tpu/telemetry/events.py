"""Flight recorder: a bounded ring of structured runtime events.

Metrics aggregate (how many retries?) and spans time (how long was the
step?); neither answers the postmortem question "what happened to this
run, in order, just before it died?".  The flight recorder does: every
operationally interesting occurrence — a transient-failure retry, an
injected chaos fault, a preemption notice, a checkpoint commit or
``latest_good()`` walkback, an admission-control shed, a watchdog
verdict — lands here as one structured record, and the whole ring dumps
to JSON next to the checkpoint when the watchdog halts a run or the
optimizer loop dies, so a dead run leaves a black box.

Unlike metrics/tracing, recording is **always on**: every call site is
cold-path (events fire on failures and lifecycle edges, never per
step), one record is an append into a bounded deque under a lock, and
the whole point is that the black box exists even for the run where
nobody thought to enable telemetry.  When the ring is full the oldest
record is evicted and ``dropped_events()`` counts it — the recorder
never grows without bound and never throws away the *newest* history,
which is the part a postmortem reads first.

    from bigdl_tpu.telemetry import events
    events.record_event("retry", error="XlaRuntimeError: ...",
                        resume_from="ckpt/checkpoint.12.npz")
    ...
    events.dump_events("flight_recorder.json")

Request-scoped cross-reference: events describing one routed request's
journey (``request_retry`` / ``request_hedge`` / ``router_shed`` /
``generation_failover``) carry an optional ``trace_id`` field naming
the request's distributed trace when telemetry is on (None otherwise)
— a failover event in the black box and its assembled timeline at
``/tracez?trace=<id>`` point at each other.  It is an ordinary field:
the recorder itself stays trace-agnostic, and each event kind keeps
its single emission site.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["EVENT_KINDS", "record_event", "recent_events",
           "event_counts", "dropped_events", "reset_events",
           "set_event_capacity", "event_capacity", "events_summary",
           "events_dict", "dumps_events", "dump_events", "json_safe"]

# The stable event-kind vocabulary — the query keys a postmortem greps
# for, documented in docs/observability.md "Flight recorder".  New
# kinds are added here AND to the docs table; ``record_event`` does
# NOT enforce membership (a broken recorder must never break the path
# it documents), but tests pin that every shipped call site records a
# kind from this list.
EVENT_KINDS = (
    "retry", "chaos_fault", "oom", "preemption", "reshard",
    "checkpoint_commit", "checkpoint_walkback",
    "pipeline_snapshot", "pipeline_restore",
    "admission_shed", "watchdog", "watchdog_halt",
    "flight_recorder_dump",
    "replica_join", "replica_drain", "router_shed",
    "scale_up", "scale_down", "hot_deploy", "controller_hold",
    "request_retry", "request_hedge", "breaker_transition",
    "generation_failover",
)

_DEFAULT_CAPACITY = 2048

_lock = threading.Lock()
_buffer: deque = deque(maxlen=_DEFAULT_CAPACITY)
_dropped = 0


class EventRecord:
    """One recorded occurrence.  ``kind`` is a stable snake_case tag
    (the query key of a postmortem); ``fields`` carry the specifics and
    must be JSON-serializable-ish (str() is the fallback on dump)."""

    __slots__ = ("kind", "t_wall", "fields")

    def __init__(self, kind: str, t_wall: float, fields: Optional[Dict]):
        self.kind = kind
        self.t_wall = t_wall
        self.fields = fields

    def to_dict(self) -> Dict:
        d = {"kind": self.kind, "time": self.t_wall}
        if self.fields:
            d.update(self.fields)
        return d


def json_safe(v):
    """Non-finite floats become strings, so every serialization that
    carries the value (statusz page, flight-recorder dump,
    json_snapshot) stays strict RFC-8259 JSON — a bare ``NaN`` token
    would break jq/JSON.parse exactly when an operator scrapes a
    NaN-loss incident.  THE one implementation of that rule: the
    watchdog's verdicts and the optimizer's statusz reuse it.  Numpy
    scalars unwrap to their Python value first (np.float32 is not a
    ``float`` subclass)."""
    if type(v).__module__ == "numpy" and getattr(v, "shape", None) == ():
        v = v.item()
    if isinstance(v, float) and not math.isfinite(v):
        return str(v)
    return v


def record_event(kind: str, **fields) -> None:
    """Append one event to the ring (thread-safe, never raises into the
    caller's path — a broken recorder must not break a checkpoint
    commit).  Field values are made JSON-safe at record time."""
    global _dropped
    try:
        if fields:
            fields = {k: json_safe(v) for k, v in fields.items()}
        rec = EventRecord(kind, time.time(), fields or None)
        with _lock:
            if len(_buffer) == _buffer.maxlen:
                _dropped += 1
            _buffer.append(rec)
    except Exception:  # pragma: no cover - recorder must stay inert
        pass


def recent_events(n: Optional[int] = None) -> List[Dict]:
    """The newest ``n`` events (all, if None), oldest first, as dicts."""
    with _lock:
        recs = list(_buffer)
    if n is not None and n >= 0:
        # NOT recs[-n:]: a -0 slice is the WHOLE list, and n=0 must
        # mean "none"
        recs = recs[len(recs) - min(n, len(recs)):]
    return [r.to_dict() for r in recs]


def event_counts() -> Dict[str, int]:
    """{kind: occurrences currently buffered} — the one-line shape of a
    run's history (note: evicted events are not re-counted here)."""
    with _lock:
        recs = list(_buffer)
    out: Dict[str, int] = {}
    for r in recs:
        out[r.kind] = out.get(r.kind, 0) + 1
    return out


def dropped_events() -> int:
    with _lock:
        return _dropped


def reset_events() -> None:
    global _dropped
    with _lock:
        _buffer.clear()
        _dropped = 0


def set_event_capacity(n: int) -> None:
    """Resize the ring (keeps the newest events)."""
    global _buffer
    if n < 1:
        raise ValueError("event ring capacity must be >= 1")
    with _lock:
        _buffer = deque(_buffer, maxlen=n)


def event_capacity() -> int:
    with _lock:
        return _buffer.maxlen or 0


def events_summary(recent_n: int = 50) -> Dict:
    """One coherent locked pass over the ring: buffered/dropped
    counters, per-kind counts, and the newest ``recent_n`` events —
    the shape ``/statusz`` and ``json_snapshot`` embed.  A single
    snapshot (not four separate reads) so the numbers can't disagree
    with each other mid-scrape, and only the tail is converted to
    dicts."""
    with _lock:
        recs = list(_buffer)
        dropped = _dropped
        capacity = _buffer.maxlen or 0
    counts: Dict[str, int] = {}
    for r in recs:
        counts[r.kind] = counts.get(r.kind, 0) + 1
    n = max(int(recent_n), 0)
    tail = recs[len(recs) - min(n, len(recs)):]
    return {"buffered": len(recs), "capacity": capacity,
            "dropped": dropped, "counts": counts,
            "recent": [r.to_dict() for r in tail]}


def events_dict() -> Dict:
    """The whole ring as one JSON-able dict — what :func:`dump_events`
    writes and what ``/statusz`` embeds a tail of."""
    with _lock:
        recs = list(_buffer)
        dropped = _dropped
    counts: Dict[str, int] = {}
    for r in recs:
        counts[r.kind] = counts.get(r.kind, 0) + 1
    return {
        "time": time.time(),
        "pid": os.getpid(),
        "dropped": dropped,
        "counts": counts,
        "events": [r.to_dict() for r in recs],
    }


def dumps_events() -> str:
    """:func:`events_dict` serialized as JSON — THE flight-recorder
    wire format, shared by :func:`dump_events` and the optimizer's
    next-to-the-checkpoint dump so the two can never drift.
    Non-serializable field values degrade to ``str()`` rather than
    failing the dump — a postmortem artifact that refuses to write
    because one field held an exception object is worse than one with
    a stringified field."""
    return json.dumps(events_dict(), default=str, indent=2)


def dump_events(path: str) -> str:
    """Serialize the ring to ``path`` as JSON (the black-box dump)."""
    with open(path, "w", encoding="utf-8") as f:
        f.write(dumps_events())
    return path
