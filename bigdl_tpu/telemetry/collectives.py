"""Collective-communication accounting: instrumented wrappers around
the ``jax.lax`` collectives.

The reference framework's whole performance story at 256 nodes was
communication — its FP16 ``CompressedTensor`` wire format and the
BlockManager all-reduce exist because inter-node gradient bytes
dominated (whitepaper.md:150-196).  The TPU-native port moves those
bytes over ICI/DCN instead, but until now it could not *measure* them:
you cannot justify a compression hop (ROADMAP item 3) before you can
measure the hop.

Every explicit collective call site in ``bigdl_tpu/parallel/``,
``nn/moe.py``, and ``optim/`` routes through these wrappers, which
record **trace-time** byte volume and call counts per ``{op, axis}``
into ``collective_bytes_total`` / ``collective_calls_total``:

* Accounting happens while jax TRACES the enclosing jit/shard_map —
  never inside the compiled program, so the compiled step is
  byte-for-byte the bare collective and the zero-step-cost discipline
  holds (asserted in tests).  The counters therefore state the comm
  budget of one compiled step per trace: "this program moves N bytes
  per execution", the same static quantity the HLO cross-check
  (``utils/xla_cost.collective_hlo_bytes``) reads out of the compiled
  module.  A retrace (ragged tail, second batch signature) accounts
  again, exactly as it compiles again.
* A collective inside ``lax.fori_loop`` / ``lax.scan`` is traced once
  and counted once — matching the HLO, where the loop body also
  appears once.  Multiply by the trip count yourself when you want
  wall-clock bytes.

**Byte convention** (exact, testable): bytes = the collective's
per-device OUTPUT payload — the same quantity the compiled HLO's
collective ops carry, so the two sides cross-check directly:

=================  =========================================
op                 bytes per device
=================  =========================================
``psum``/``pmean`` nbytes(x)            (output shape = input)
``all_gather``     axis_size × nbytes(x)
``all_to_all``     nbytes(x)            (same total size)
``ppermute``       nbytes(x)
``psum_scatter``   nbytes(x) / axis_size
=================  =========================================

Wire-level modeling (ring algorithms, 2(n−1)/n factors) is a
presentation concern layered on top — see docs/parallelism.md
"Measuring communication".

Two things these wrappers deliberately do NOT see:

* collectives XLA inserts through sharding propagation (the dp
  gradient psum behind ``NamedSharding``) — those are exactly what the
  HLO-side cross-check exists for;
* host-side collectives (``multihost_utils.process_allgather``) —
  those call :func:`account_host_collective` directly at run time.
"""

from __future__ import annotations

import numpy as np

import jax

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry import families as _fam

__all__ = [
    "psum", "pmean", "all_gather", "all_to_all", "ppermute",
    "psum_scatter", "reduce_scatter", "account_host_collective",
]


def _tree_nbytes(tree) -> int:
    """Total bytes of a pytree of arrays/tracers (trace-time: computed
    from aval shape/dtype, never by materializing anything)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            # graftlint: disable=trace-safety -- trace-TIME fallback
            # for non-array leaves (Python scalars) only; tracers
            # always carry shape/dtype and never reach this branch
            arr = np.asarray(leaf)
            shape, dtype = arr.shape, arr.dtype
        total += int(np.prod(shape, dtype=np.int64)
                     * np.dtype(dtype).itemsize)
    return total


def _axis_size(axis_name) -> int:
    """Static size of a (possibly tuple) mapped axis.  ``psum(1, axis)``
    of a Python constant folds to a concrete int at trace time."""
    names = (axis_name if isinstance(axis_name, (tuple, list))
             else (axis_name,))
    n = 1
    for a in names:
        n *= int(jax.lax.psum(1, a))
    return n


def _axis_label(axis_name) -> str:
    if isinstance(axis_name, (tuple, list)):
        return "+".join(str(a) for a in axis_name)
    return str(axis_name)


def _account(op: str, axis_name, nbytes: float) -> None:
    """One {op, axis} accounting record.  Never raises into the
    collective it describes — a broken counter must not break a psum."""
    try:
        axis = _axis_label(axis_name)
        _fam.collective_bytes_total().labels(op, axis).inc(float(nbytes))
        _fam.collective_calls_total().labels(op, axis).inc()
    except Exception:  # pragma: no cover - accounting is best-effort
        pass


def account_host_collective(op: str, axis, nbytes: float) -> None:
    """Record a HOST-side collective (``process_allgather`` and
    friends) that never appears in a traced program.  Unlike the
    traced wrappers this is run-time accounting: called once per
    actual exchange."""
    if telemetry.enabled():
        _account(op, axis, nbytes)


# ---------------------------------------------------------------------------
# traced wrappers — each compiles to exactly the bare jax.lax op
# ---------------------------------------------------------------------------

def psum(x, axis_name, **kwargs):
    if telemetry.enabled():
        _account("psum", axis_name, _tree_nbytes(x))
    return jax.lax.psum(x, axis_name, **kwargs)


def pmean(x, axis_name, **kwargs):
    if telemetry.enabled():
        _account("pmean", axis_name, _tree_nbytes(x))
    return jax.lax.pmean(x, axis_name, **kwargs)


def all_gather(x, axis_name, **kwargs):
    if telemetry.enabled():
        try:
            _account("all_gather", axis_name,
                     _tree_nbytes(x) * _axis_size(axis_name))
        except Exception:  # pragma: no cover - accounting is best-effort
            pass
    return jax.lax.all_gather(x, axis_name, **kwargs)


def all_to_all(x, axis_name, split_axis, concat_axis, **kwargs):
    if telemetry.enabled():
        _account("all_to_all", axis_name, _tree_nbytes(x))
    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis,
                              **kwargs)


def ppermute(x, axis_name, perm):
    if telemetry.enabled():
        _account("ppermute", axis_name, _tree_nbytes(x))
    return jax.lax.ppermute(x, axis_name, perm)


def psum_scatter(x, axis_name, **kwargs):
    if telemetry.enabled():
        try:
            _account("reduce_scatter", axis_name,
                     _tree_nbytes(x) / max(_axis_size(axis_name), 1))
        except Exception:  # pragma: no cover - accounting is best-effort
            pass
    return jax.lax.psum_scatter(x, axis_name, **kwargs)


# the HLO opcode name, for readers grepping from the cross-check side
reduce_scatter = psum_scatter
