"""Cross-process fleet telemetry: who is the slowest host in the mesh?

Single-process observability (PR 3/4/6) answers "where does MY step's
wall time go"; under SPMD lockstep the question that actually gates
scale-out is different: **which host is holding the collective**.  A
straggling host never shows up in its peers' profiles — their time
appears as device_compute (blocked inside the psum) while the
straggler's appears as data_wait — so the only way to see it is to
compare per-host numbers side by side.  (The reference faced the same
problem at 256 Spark nodes and solved it destructively by *dropping*
stragglers, optim/DistriOptimizer.scala; SPMD cannot drop anyone, so it
must *name* them instead.)

Mechanics: once per readback window (rate-limited by
``every_n_windows``) each process contributes one compact fixed-shape
stats vector — step wall, data-wait, RSS, HBM in use — via a single
``process_allgather``; every process derives the same table, so
``/statusz`` on ANY host shows the whole fleet.  Two skews are derived:

* ``step_skew`` — slowest / median-of-others per-host wall.  Catches
  genuinely async fleets (per-host loops drifting apart).
* ``wait_skew`` — slowest / median-of-others per-host data-wait, with
  a floor of ``wait_floor_fraction`` of the median wall.  Catches the
  lockstep-masked straggler: everyone's wall is identical, but one
  host's wall is data-wait where the others' is collective wait.

``skew = max(step_skew, wait_skew)`` publishes as the
``fleet_step_skew`` gauge and, when a :class:`HealthWatchdog
<bigdl_tpu.telemetry.health.HealthWatchdog>` is armed, feeds its
``straggler`` anomaly class (warn policy by default).

Processes that cannot join a collective (serving replicas, sidecars)
use the file-based path instead: :func:`write_host_snapshot` drops a
per-host JSON into a shared directory and :func:`merge_host_snapshots`
builds the identical table from whatever is there — same derivation
(:func:`fleet_table`), different transport.

Everything is opt-in (``Optimizer.set_fleet_monitor``); an unarmed run
performs no allgather and pays nothing new.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import statistics
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry import families as _fam

__all__ = ["FleetMonitor", "host_stats", "fleet_table",
           "write_host_snapshot", "merge_host_snapshots",
           "read_host_snapshots", "remove_host_snapshot",
           "FLEET_STAT_FIELDS"]

# the fixed-shape per-host vector, in wire order — one float64 each
FLEET_STAT_FIELDS = ("process", "time", "step_wall_s", "data_wait_s",
                     "iterations", "rss_bytes", "hbm_bytes_in_use")

_SNAPSHOT_PREFIX = "fleet_host_"


def _local_hbm_in_use() -> float:
    """Summed ``bytes_in_use`` over this process's devices, 0.0 where
    the backend exposes no memory_stats (CPU) — missing-key→skip, the
    runtime-sampler contract."""
    try:
        import jax
        total = 0.0
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:
                continue
            if ms and "bytes_in_use" in ms:
                total += float(ms["bytes_in_use"])
        return total
    except Exception:
        return 0.0


def host_stats(step_wall_s: float, data_wait_s: float,
               iterations: int = 1,
               process: Optional[int] = None) -> Dict[str, float]:
    """One host's contribution: the window timings the caller measured
    plus locally sampled RSS and HBM-in-use."""
    from bigdl_tpu.telemetry.runtime import _rss_bytes
    if process is None:
        try:
            import jax
            process = jax.process_index()
        except Exception:
            process = 0
    return {
        "process": float(process),
        "time": time.time(),
        "step_wall_s": float(step_wall_s),
        "data_wait_s": float(data_wait_s),
        "iterations": float(max(int(iterations), 1)),
        "rss_bytes": float(_rss_bytes() or 0.0),
        "hbm_bytes_in_use": _local_hbm_in_use(),
    }


def _skew_of(values: List[float], floor: float) -> Tuple[float, int]:
    """(slowest / median-of-the-others, argmax index).  The baseline
    excludes the candidate straggler — with 2 hosts a plain median
    would be dragged halfway toward the straggler and mask it — and is
    floored so uniformly-tiny values can't produce a huge ratio out of
    noise."""
    i_max = max(range(len(values)), key=lambda i: values[i])
    others = [v for i, v in enumerate(values) if i != i_max]
    base = statistics.median(others) if others else values[i_max]
    base = max(base, floor)
    if base <= 0:
        return 1.0, i_max
    return values[i_max] / base, i_max


def fleet_table(rows: List[Dict[str, Any]],
                wait_floor_fraction: float = 0.05) -> Dict[str, Any]:
    """Derive the fleet table from per-host stats dicts (from the
    allgather OR merged snapshots — one derivation for both
    transports).  Deterministic given the rows, so every process that
    holds the same allgather result renders the identical table."""
    hosts = sorted((dict(r) for r in rows),
                   key=lambda r: int(r["process"]))
    for h in hosts:
        iters = max(h.get("iterations", 1.0), 1.0)
        h["step_wall_per_iter_s"] = h["step_wall_s"] / iters
        h["data_wait_per_iter_s"] = h["data_wait_s"] / iters
        wall = max(h["step_wall_s"], 1e-12)
        h["data_wait_fraction"] = min(h["data_wait_s"] / wall, 1.0)
        h["process"] = int(h["process"])
    walls = [h["step_wall_per_iter_s"] for h in hosts]
    waits = [h["data_wait_per_iter_s"] for h in hosts]
    med_wall = max(statistics.median(walls), 1e-12)
    step_skew, i_wall = _skew_of(walls, floor=1e-12)
    wait_skew, i_wait = _skew_of(
        waits, floor=wait_floor_fraction * med_wall)
    if wait_skew >= step_skew:
        skew, slowest = wait_skew, hosts[i_wait]["process"]
    else:
        skew, slowest = step_skew, hosts[i_wall]["process"]
    return {
        "processes": len(hosts),
        "hosts": hosts,
        "median_step_wall_s": med_wall,
        "step_skew": step_skew,
        "wait_skew": wait_skew,
        "skew": skew,
        "slowest_process": slowest,
    }


# ---------------------------------------------------------------------------
# file-based transport (processes that can't share a collective)
# ---------------------------------------------------------------------------

def write_host_snapshot(directory: str,
                        stats: Dict[str, Any]) -> str:
    """Atomically drop one host's stats as
    ``fleet_host_<process>.json`` under ``directory`` (tmp+rename: a
    merger must never read a torn write).  The tmp name is unique per
    writer THREAD: a serving replica publishes from its interval
    thread AND synchronously on state flips (drain), and two writers
    sharing one tmp path race replace-vs-unlink (the loser's rename
    finds its tmp already consumed); with unique tmps both renames are
    atomic and last-writer-wins."""
    os.makedirs(directory, exist_ok=True)
    pid = int(stats["process"])
    path = os.path.join(directory, f"{_SNAPSHOT_PREFIX}{pid}.json")
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(stats, f)
    os.replace(tmp, path)
    return path


def merge_host_snapshots(directory: str,
                         max_age_s: Optional[float] = None) \
        -> Optional[Dict[str, Any]]:
    """The fleet table from whatever per-host snapshots are on disk
    (corrupt files skipped; ``max_age_s`` drops hosts that stopped
    reporting — a dead replica should vanish from the table, not
    freeze it).  None when no usable snapshot exists."""
    rows: List[Dict[str, Any]] = []
    now = time.time()
    for path in sorted(_glob.glob(
            os.path.join(directory, _SNAPSHOT_PREFIX + "*.json"))):
        try:
            with open(path, "r", encoding="utf-8") as f:
                row = json.load(f)
            float(row["process"])
            float(row["step_wall_s"])
        except Exception:
            continue
        if max_age_s is not None:
            # graftlint: disable=clock-discipline -- staleness vs
            # ANOTHER process's epoch stamp: perf_counter is not
            # comparable across processes, the wall clock is the only
            # shared one
            age_s = now - float(row.get("time", now))
            if age_s > max_age_s:
                continue
        rows.append(row)
    if not rows:
        return None
    return fleet_table(rows)


def remove_host_snapshot(directory: str, process: int) -> bool:
    """Remove one host's snapshot file (True if it existed) — a
    cleanly departing process must be FORGOTTEN by mergers and
    registries, not reported as stale forever.  The one place that
    knows the filename scheme, shared by every cleanup site."""
    try:
        os.unlink(os.path.join(
            directory, f"{_SNAPSHOT_PREFIX}{int(process)}.json"))
        return True
    except OSError:
        return False


def read_host_snapshots(directory: str) \
        -> Dict[int, Optional[Dict[str, Any]]]:
    """Raw per-host snapshot rows keyed by process id.  Unlike
    :func:`merge_host_snapshots` (which silently SKIPS unusable files
    to keep the fleet table clean), a corrupt or unparsable snapshot
    surfaces as ``None`` — the serving replica registry treats it as
    an UNHEALTHY replica rather than an absent one, because a replica
    that writes garbage is in worse shape than one that never joined.
    Staleness is left to the caller (the registry applies its own
    ``max_age_s``)."""
    out: Dict[int, Optional[Dict[str, Any]]] = {}
    for path in sorted(_glob.glob(
            os.path.join(directory, _SNAPSHOT_PREFIX + "*.json"))):
        stem = os.path.basename(path)[len(_SNAPSHOT_PREFIX):-len(".json")]
        try:
            pid = int(stem)
        except ValueError:
            continue        # not one of ours
        try:
            with open(path, "r", encoding="utf-8") as f:
                row = json.load(f)
            float(row["process"])
            out[pid] = row
        except Exception:
            out[pid] = None
    return out


# ---------------------------------------------------------------------------
# the collective transport + the monitor the optimizer arms
# ---------------------------------------------------------------------------

class FleetMonitor:
    """Per-window fleet aggregation.  ``contribute()`` is called by the
    optimizer's readback path with each flushed window's (wall,
    data-wait, iterations); every ``every_n_windows``-th call performs
    the allgather, derives the table, publishes the skew gauge, feeds
    the watchdog's ``straggler`` class, and (when ``snapshot_dir`` is
    set) drops this host's file snapshot for collective-less peers.

    In a multi-process run every process must contribute at the same
    window boundaries (the allgather is a collective); the optimizer's
    windows are deterministic under SPMD lockstep, which is exactly
    why the cadence is per-window and not per-wall-clock."""

    def __init__(self, every_n_windows: int = 1,
                 snapshot_dir: Optional[str] = None,
                 wait_floor_fraction: float = 0.05):
        self.every_n_windows = max(int(every_n_windows), 1)
        self.snapshot_dir = snapshot_dir
        self.wait_floor_fraction = float(wait_floor_fraction)
        self._lock = threading.Lock()
        self._windows_seen = 0
        self.samples = 0
        self.last_table: Optional[Dict[str, Any]] = None
        self.last_stats: Optional[Dict[str, Any]] = None

    def contribute(self, step_wall_s: float, data_wait_s: float,
                   iterations: int = 1, step: Optional[int] = None,
                   watchdog=None) -> Optional[Dict[str, Any]]:
        """One window's contribution; returns the fleet table on
        sampling windows, None on rate-limited ones."""
        with self._lock:
            self._windows_seen += 1
            if self._windows_seen % self.every_n_windows:
                return None
        stats = host_stats(step_wall_s, data_wait_s, iterations)
        table = self._aggregate(stats)
        with self._lock:
            self.samples += 1
            self.last_stats = stats
            self.last_table = table
        if self.snapshot_dir:
            try:
                write_host_snapshot(self.snapshot_dir, stats)
            except Exception:  # pragma: no cover - transport best effort
                pass
        if telemetry.enabled():
            try:
                _fam.fleet_step_skew().set(table["skew"])
            except Exception:  # pragma: no cover
                pass
        if watchdog is not None:
            watchdog.observe_fleet(
                -1 if step is None else int(step), table["skew"],
                table["slowest_process"],
                f"{table['processes']} host(s), step_skew "
                f"{table['step_skew']:.2f}, wait_skew "
                f"{table['wait_skew']:.2f}")
        return table

    def _aggregate(self, stats: Dict[str, Any]) -> Dict[str, Any]:
        """One allgather of the fixed-shape vector; single-process this
        degenerates to a reshape (no distributed runtime touched)."""
        import numpy as np
        vec = np.asarray([stats[k] for k in FLEET_STAT_FIELDS],
                         np.float64)
        import jax
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            from bigdl_tpu.telemetry.collectives import (
                account_host_collective,
            )
            gathered = np.asarray(
                multihost_utils.process_allgather(vec))
            gathered = gathered.reshape(-1, len(FLEET_STAT_FIELDS))
            account_host_collective("process_allgather", "process",
                                    gathered.nbytes)
        else:
            gathered = vec.reshape(1, -1)
        rows = [dict(zip(FLEET_STAT_FIELDS, row)) for row in gathered]
        return fleet_table(rows, self.wait_floor_fraction)

    def status(self) -> Optional[Dict[str, Any]]:
        """The ``fleet`` section of ``/statusz``: the latest table plus
        sampling counters (None until the first sample)."""
        with self._lock:
            if self.last_table is None:
                return {"samples": 0, "windows_seen": self._windows_seen,
                        "every_n_windows": self.every_n_windows}
            out = dict(self.last_table)
            out["samples"] = self.samples
            out["windows_seen"] = self._windows_seen
            out["every_n_windows"] = self.every_n_windows
            return out
