"""Canonical metric-family declarations.

Every metric NAME in the codebase is declared exactly once, here, as a
get-or-create accessor; instrumentation sites import the accessor
instead of re-spelling the string.  ``scripts/metrics_lint.py``
enforces this statically (duplicate or non-``snake_case`` names fail,
as do names missing from the table in ``docs/observability.md``).

Two consequences worth the indirection:

* ``preregister()`` can materialize the whole catalog, so a process
  that only serves still exposes the optimizer/checkpoint families
  (at zero) on ``/metrics`` — one scrape config covers every role.
* Renames are single-file diffs that the lint cross-checks against the
  documentation table.
"""

from __future__ import annotations

import weakref
from typing import List

from bigdl_tpu.telemetry.metrics import (
    Counter, Gauge, Histogram, get_registry,
)

__all__ = ["preregister", "bridge_serving_metrics"]


# ---- optimizer step-phase breakdown ---------------------------------------

def optimizer_data_wait_seconds() -> Histogram:
    return get_registry().histogram(
        "optimizer_data_wait_seconds",
        "Host time staging one iteration's batch (fetch + device put)")


def optimizer_step_seconds() -> Histogram:
    return get_registry().histogram(
        "optimizer_step_seconds",
        "Device step time per iteration, amortized over the async "
        "readback window that completed it (completion-to-completion, "
        "minus data-wait)")


def optimizer_validation_seconds() -> Histogram:
    return get_registry().histogram(
        "optimizer_validation_seconds",
        "Wall time of one validation sweep")


def optimizer_retries_total() -> Counter:
    return get_registry().counter(
        "optimizer_retries_total",
        "Transient-failure retries taken by Optimizer.optimize()")


# ---- perf attribution (telemetry.perf) ------------------------------------

def step_phase_seconds() -> Histogram:
    return get_registry().histogram(
        "step_phase_seconds",
        "Per-iteration seconds of each step-time attribution phase "
        "(data_wait / host_staging / device_compute / readback), "
        "amortized over the readback window — one observation per "
        "window per phase",
        labelnames=("phase",),
        buckets=(1e-5, 1e-4, 1e-3, 5e-3, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, float("inf")))


def step_mfu_vs_measured() -> Gauge:
    return get_registry().gauge(
        "step_mfu_vs_measured",
        "Model FLOP utilization of the wall step time against the "
        "same-run measured matmul roofline (set when a harness "
        "computes an attribution report with a measured peak)")


def step_unattributed_fraction() -> Gauge:
    return get_registry().gauge(
        "step_unattributed_fraction",
        "Fraction of the latest readback window's wall time not "
        "covered by any measured attribution phase (the honest "
        "residual, set per window by the loss-drain worker; the run "
        "aggregate lives in the attribution report — see "
        "docs/performance.md 'Attributing an MFU gap')")


def bench_rounds_carried_forward_total() -> Counter:
    return get_registry().counter(
        "bench_rounds_carried_forward_total",
        "Bench rounds that re-published prior confirmed on-device "
        "evidence (carried_forward) because the backend was "
        "unreachable at bench time")


# ---- mesh observability: collectives + fleet -------------------------------

def collective_bytes_total() -> Counter:
    return get_registry().counter(
        "collective_bytes_total",
        "Per-device payload bytes of explicit collectives, accounted "
        "at TRACE time per {op, axis} (one compiled step's comm "
        "budget; see telemetry.collectives for the byte convention)",
        labelnames=("op", "axis"))


def collective_calls_total() -> Counter:
    return get_registry().counter(
        "collective_calls_total",
        "Explicit collective call sites traced, per {op, axis} (one "
        "count per site per trace — loop bodies count once, like the "
        "compiled HLO)",
        labelnames=("op", "axis"))


def fleet_step_skew() -> Gauge:
    return get_registry().gauge(
        "fleet_step_skew",
        "Slowest-host / median-host ratio over the latest fleet "
        "sample (max of the step-wall and data-wait skews; 1.0 = a "
        "balanced fleet, large = a straggler — see telemetry.fleet)")


# ---- training health (watchdog) -------------------------------------------

def training_nonfinite_total() -> Counter:
    return get_registry().counter(
        "training_nonfinite_total",
        "Non-finite loss / gradient-norm detections by the health "
        "watchdog")


def training_anomalies_total() -> Counter:
    return get_registry().counter(
        "training_anomalies_total",
        "Health-watchdog verdicts by anomaly kind",
        labelnames=("kind",))


def grad_norm() -> Histogram:
    return get_registry().histogram(
        "grad_norm",
        "Global (pre-clip-scale) gradient L2 norm per iteration, "
        "observed when the health watchdog is on",
        buckets=(0.0001, 0.001, 0.01, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0,
                 100.0, 1e3, 1e6, float("inf")))


# ---- checkpointing ---------------------------------------------------------

def checkpoint_commit_seconds() -> Histogram:
    return get_registry().histogram(
        "checkpoint_commit_seconds",
        "CheckpointManager.save wall time: payload + manifest + GC")


def checkpoint_torn_generations_total() -> Counter:
    return get_registry().counter(
        "checkpoint_torn_generations_total",
        "Generations latest_good() walked past as corrupt, truncated, "
        "or uncommitted")


def checkpoint_reshard_restores_total() -> Counter:
    return get_registry().counter(
        "checkpoint_reshard_restores_total",
        "Checkpoint restores onto a topology other than the one that "
        "wrote them, by outcome: resharded (N->M resume succeeded), "
        "fallback (pipeline position unportable — epoch-start replay), "
        "failed (a leaf is genuinely unportable and the restore "
        "raised)",
        labelnames=("outcome",))


# ---- chaos (fault injection) ----------------------------------------------

def chaos_faults_injected_total() -> Counter:
    return get_registry().counter(
        "chaos_faults_injected_total",
        "Faults the chaos harness actually fired")


# ---- input pipeline --------------------------------------------------------

def prefetch_queue_depth() -> Gauge:
    return get_registry().gauge(
        "prefetch_queue_depth",
        "Ready minibatches buffered by Prefetch, sampled at each "
        "consumer get")


def prefetch_producer_wait_total() -> Counter:
    return get_registry().counter(
        "prefetch_producer_wait_total",
        "Producer blocked-on-full-queue events (consumer is the "
        "bottleneck)")


def prefetch_consumer_wait_total() -> Counter:
    return get_registry().counter(
        "prefetch_consumer_wait_total",
        "Consumer blocked-on-empty-queue events (input pipeline is the "
        "bottleneck: the step waited on data)")


def pipeline_samples_per_second() -> Gauge:
    return get_registry().gauge(
        "pipeline_samples_per_second",
        "Input-pipeline throughput: global samples consumed per second "
        "over the latest completed readback window")


def device_prefetch_buffer_occupancy() -> Gauge:
    return get_registry().gauge(
        "device_prefetch_buffer_occupancy",
        "Device-resident batches buffered by DevicePrefetch, sampled "
        "at each consumer get (0 = the step waited on H2D staging)")


def pipeline_restore_skipped_batches_total() -> Counter:
    return get_registry().counter(
        "pipeline_restore_skipped_batches_total",
        "Batches skipped while restoring PipelineState (sample-accurate "
        "mid-epoch resume replays the epoch order up to the offset)")


# ---- per-module eager profiling -------------------------------------------

def module_forward_seconds() -> Histogram:
    return get_registry().histogram(
        "module_forward_seconds",
        "Eager per-module forward wall time from optim.profiling",
        labelnames=("module_type",))


# ---- host / device runtime -------------------------------------------------

def process_rss_bytes() -> Gauge:
    return get_registry().gauge(
        "process_rss_bytes", "Resident set size of this process")


def gc_collections_total() -> Counter:
    return get_registry().counter(
        "gc_collections_total",
        "CPython garbage-collector runs", labelnames=("generation",))


def device_memory_bytes_in_use() -> Gauge:
    return get_registry().gauge(
        "device_memory_bytes_in_use",
        "Accelerator memory in use (jax device memory_stats)",
        labelnames=("device",))


def device_memory_bytes_limit() -> Gauge:
    return get_registry().gauge(
        "device_memory_bytes_limit",
        "Accelerator memory capacity (jax device memory_stats)",
        labelnames=("device",))


def hbm_bytes_peak() -> Gauge:
    return get_registry().gauge(
        "hbm_bytes_peak",
        "Peak accelerator memory in use per device: the backend's own "
        "peak_bytes_in_use when memory_stats() provides it, else a "
        "high-water mark over sampled bytes_in_use (telemetry.runtime)",
        labelnames=("device",))


# ---- serving bridge --------------------------------------------------------
# The serving MetricsRegistry keeps its own lock-coherent snapshot (its
# public schema is unchanged); this bridge mirrors that snapshot into
# the telemetry registry at READ time via a collector — the serving hot
# path never touches telemetry.

def serving_latency_ms() -> Gauge:
    return get_registry().gauge(
        "serving_latency_ms",
        "End-to-end request latency quantiles (enqueue to result)",
        labelnames=("quantile",))


def serving_queue_depth() -> Gauge:
    return get_registry().gauge(
        "serving_queue_depth",
        "Mean backlog sampled at each dispatch")


def serving_queue_depth_max() -> Gauge:
    return get_registry().gauge(
        "serving_queue_depth_max", "Max backlog seen at any dispatch")


def serving_requests_total() -> Counter:
    return get_registry().counter(
        "serving_requests_total", "Requests served")


def serving_batches_total() -> Counter:
    return get_registry().counter(
        "serving_batches_total", "Device batches executed")


def serving_shed_total() -> Counter:
    return get_registry().counter(
        "serving_shed_total", "Requests shed by admission control")


def serving_rejected_total() -> Counter:
    return get_registry().counter(
        "serving_rejected_total", "Requests rejected at admission")


def serving_padded_waste_ratio() -> Gauge:
    return get_registry().gauge(
        "serving_padded_waste_ratio",
        "Padded rows / dispatched rows (flops burned on dropped rows)")


def serving_batch_occupancy() -> Gauge:
    return get_registry().gauge(
        "serving_batch_occupancy",
        "Batches executed with this many real rows",
        labelnames=("rows",))


# ---- generation serving (continuous batching, serving.generation) ---------

def generation_tokens_per_second() -> Gauge:
    return get_registry().gauge(
        "generation_tokens_per_second",
        "Aggregate decode throughput of the continuous-batching slot "
        "pool (new tokens only), over a rolling ~0.5 s window")


def generation_slot_occupancy() -> Gauge:
    return get_registry().gauge(
        "generation_slot_occupancy",
        "Active slots / pool size sampled at each pooled decode step "
        "(1.0 = every KV slot is earning tokens; low = admit more or "
        "shrink S)")


def generation_phase_seconds() -> Histogram:
    return get_registry().histogram(
        "generation_phase_seconds",
        "Wall seconds per generation engine phase: one bucketed "
        "prompt prefill+scatter, or one pooled decode step",
        labelnames=("phase",),
        buckets=(1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 10.0, float("inf")))


def generation_queue_to_first_token_seconds() -> Histogram:
    return get_registry().histogram(
        "generation_queue_to_first_token_seconds",
        "Queue-to-first-token latency per generation request (submit "
        "to the first emitted token, the slot-wait + prefill cost a "
        "client observes)",
        buckets=(1e-3, 5e-3, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                 10.0, 30.0, float("inf")))


def generation_inter_token_seconds() -> Histogram:
    return get_registry().histogram(
        "generation_inter_token_seconds",
        "Gap between consecutive emitted tokens of one generation "
        "request (the streaming cadence chunked prefill exists to "
        "bound; the tail shows prefill stalls)",
        buckets=(1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 10.0, float("inf")))


def generation_prefix_cache_events_total() -> Counter:
    return get_registry().counter(
        "generation_prefix_cache_events_total",
        "Prefix KV-cache lookups at admit, labelled hit (>= one cached "
        "chunk copied) or miss", labelnames=("result",))


def generation_prefix_cache_bytes_reused_total() -> Counter:
    return get_registry().counter(
        "generation_prefix_cache_bytes_reused_total",
        "Prefill K/V bytes copied from the prefix cache instead of "
        "recomputed (the prefill compute the cache saved)")


def generation_prefix_cache_resident_bytes() -> Gauge:
    return get_registry().gauge(
        "generation_prefix_cache_resident_bytes",
        "Bytes currently held by the prefix KV cache (LRU-bounded by "
        "its byte budget)")


def generation_prefill_dedup_total() -> Counter:
    return get_registry().counter(
        "generation_prefill_dedup_total",
        "Single-flight prefill dedup decisions at admit: a leader "
        "claimed uncached chunks and prefilled them; a follower "
        "parked on another request's in-flight prefill and re-matched "
        "the cache after its insert (a burst of identical cold "
        "prompts prefills once)", labelnames=("result",))


# ---- serving fabric (router + replica registry, serving.router) -----------

def router_requests_total() -> Counter:
    return get_registry().counter(
        "router_requests_total",
        "Requests reaching a terminal outcome at the router: ok "
        "(served), shed (typed RequestSheddedError under overload), "
        "rejected (no eligible replica / closed / cancelled), failed "
        "(replica-side error)", labelnames=("outcome",))


def router_replica_inflight() -> Gauge:
    return get_registry().gauge(
        "router_replica_inflight",
        "Requests dispatched to a replica and not yet terminal, per "
        "replica id (the quantity the bounded-load affinity fallback "
        "caps)", labelnames=("replica",))


def router_shed_total() -> Counter:
    return get_registry().counter(
        "router_shed_total",
        "Requests shed by the router, by reason: queue_full (bounded "
        "queue overflow, oldest first), slo (every eligible replica "
        "breached its TTFT p99 target), no_replica (nothing healthy "
        "and non-draining), budget (per-model admission budget "
        "exhausted), deadline (the request's end-to-end deadline "
        "budget expired while it waited)", labelnames=("reason",))


# -- request reliability (deadlines, breakers, retry/hedge) ------------------

def router_retries_total() -> Counter:
    return get_registry().counter(
        "router_retries_total",
        "Re-dispatches of a request to a different replica, by "
        "reason: transport (typed submit flake — the request never "
        "reached the replica), replica_failed (the replica failed the "
        "request after admitting it), failover (mid-stream generation "
        "failover — the replay of prompt+emitted onto a survivor)",
        labelnames=("reason",))


def router_hedges_total() -> Counter:
    return get_registry().counter(
        "router_hedges_total",
        "Hedged dispatches (a duplicate sent to a second replica "
        "after the p99-derived delay), by outcome: primary_won, "
        "hedge_won (the duplicate finished first; the loser was "
        "cancelled)", labelnames=("outcome",))


def router_breaker_transitions_total() -> Counter:
    return get_registry().counter(
        "router_breaker_transitions_total",
        "Per-replica circuit-breaker state transitions, by "
        "destination state: open (consecutive submit failures or "
        "stale health snapshots), half_open (open window elapsed; "
        "probe traffic admitted), closed (a probe succeeded)",
        labelnames=("to",))


def request_deadline_exceeded_total() -> Counter:
    return get_registry().counter(
        "request_deadline_exceeded_total",
        "Requests rejected because their end-to-end deadline budget "
        "ran out, by pipeline stage: queue (before a slot was "
        "spent), prefill, decode (evicted mid-stream by the engine "
        "sweep)", labelnames=("stage",))


# -- request-scoped distributed tracing (telemetry.request_trace) ------------

def request_traces_retained_total() -> Counter:
    return get_registry().counter(
        "request_traces_retained_total",
        "Completed request traces kept by tail-based retention, by "
        "reason: deadline (the request's budget expired), shed (typed "
        "rejection under overload), failover (a mid-stream replay "
        "moved it between replicas), hedge_won (the hedged twin beat "
        "the primary), slow_ttft / slow_inter_token (latency above "
        "the rolling percentile watermark) — the p99 requests a "
        "uniform sampler would drop", labelnames=("reason",))


def request_trace_spans_total() -> Counter:
    return get_registry().counter(
        "request_trace_spans_total",
        "Spans recorded into request-scoped traces (admission, "
        "dispatch, queue, prefill, decode, handoff, and every "
        "reliability hop) — volume of the per-trace store, retained "
        "and bulk alike")


def request_traces_dropped_total() -> Counter:
    return get_registry().counter(
        "request_traces_dropped_total",
        "Completed request traces evicted unretained from the bounded "
        "bulk ring (healthy traffic sampled out by design; a retained "
        "trace is never counted here)")


# ---- sharded embedding tables (embedding/) --------------------------------

def embedding_lookup_ids_total() -> Counter:
    return get_registry().counter(
        "embedding_lookup_ids_total",
        "Ids looked up per sharded embedding table (counted at trace "
        "time per compiled batch shape; multiply by executions for "
        "wall totals — the a2a bytes these ids imply are what "
        "collective_bytes_total{op=all_to_all} accounts)",
        labelnames=("table",))


def embedding_unique_id_fraction() -> Gauge:
    return get_registry().gauge(
        "embedding_unique_id_fraction",
        "Unique/total id ratio of the last concrete (non-traced) "
        "lookup batch per table — the dedup leverage: backward "
        "scatters one combined row per UNIQUE id, so 0.3 here means "
        "the sparse gradient is 3.3x smaller than the id count "
        "suggests", labelnames=("table",))


def embedding_shard_rows() -> Gauge:
    return get_registry().gauge(
        "embedding_shard_rows",
        "Rows owned by each shard of a mesh-sharded embedding table "
        "(contiguous-block layout; set at set_mesh time — uniform "
        "today, the gauge exists so a future non-uniform placement "
        "shows its skew)", labelnames=("table", "shard"))


# ---- fleet controller (autoscaler + continuous deployment, fleet/) --------

def fleet_replicas_desired() -> Gauge:
    return get_registry().gauge(
        "fleet_replicas_desired",
        "Replica count the controller currently wants per model pool "
        "(the reconcile target; moves on scale decisions, clamped to "
        "[min_replicas, max_replicas])", labelnames=("model",))


def fleet_replicas_live() -> Gauge:
    return get_registry().gauge(
        "fleet_replicas_live",
        "Healthy, non-draining replicas the registry currently "
        "reports per model pool (the reconcile observation; lags "
        "desired while spawns warm up or drains finish)",
        labelnames=("model",))


def fleet_scale_events_total() -> Counter:
    return get_registry().counter(
        "fleet_scale_events_total",
        "Scaling actions the controller actually took, by direction: "
        "up (spawned a replica — load breach or replacement of a dead "
        "one), down (started a zero-drop drain-out)",
        labelnames=("direction",))


def fleet_deploy_freshness_seconds() -> Gauge:
    return get_registry().gauge(
        "fleet_deploy_freshness_seconds",
        "Train-to-serve freshness: seconds from a checkpoint "
        "generation's commit timestamp (manifest time) to the moment "
        "the LAST serving replica in the pool finished hot-deploying "
        "it — the one number answering how stale serving weights are")


_PREREGISTER = (
    optimizer_data_wait_seconds, optimizer_step_seconds,
    optimizer_validation_seconds, optimizer_retries_total,
    step_phase_seconds, step_mfu_vs_measured,
    step_unattributed_fraction, bench_rounds_carried_forward_total,
    collective_bytes_total, collective_calls_total, fleet_step_skew,
    hbm_bytes_peak,
    training_nonfinite_total, training_anomalies_total, grad_norm,
    checkpoint_commit_seconds, checkpoint_torn_generations_total,
    checkpoint_reshard_restores_total,
    chaos_faults_injected_total,
    prefetch_queue_depth, prefetch_producer_wait_total,
    prefetch_consumer_wait_total,
    pipeline_samples_per_second, device_prefetch_buffer_occupancy,
    pipeline_restore_skipped_batches_total,
    module_forward_seconds,
    process_rss_bytes, gc_collections_total,
    device_memory_bytes_in_use, device_memory_bytes_limit,
    serving_latency_ms, serving_queue_depth, serving_queue_depth_max,
    serving_requests_total, serving_batches_total, serving_shed_total,
    serving_rejected_total, serving_padded_waste_ratio,
    serving_batch_occupancy,
    generation_tokens_per_second, generation_slot_occupancy,
    generation_phase_seconds, generation_queue_to_first_token_seconds,
    generation_inter_token_seconds,
    generation_prefix_cache_events_total,
    generation_prefix_cache_bytes_reused_total,
    generation_prefix_cache_resident_bytes,
    generation_prefill_dedup_total,
    router_requests_total, router_replica_inflight, router_shed_total,
    router_retries_total, router_hedges_total,
    router_breaker_transitions_total, request_deadline_exceeded_total,
    request_traces_retained_total, request_trace_spans_total,
    request_traces_dropped_total,
    fleet_replicas_desired, fleet_replicas_live,
    fleet_scale_events_total, fleet_deploy_freshness_seconds,
    embedding_lookup_ids_total, embedding_unique_id_fraction,
    embedding_shard_rows,
)


def preregister() -> None:
    """Materialize every family so exports show the full catalog (at
    zero) even in a process that hasn't exercised a subsystem yet —
    the /metrics endpoint of a fresh server already names the
    optimizer/checkpoint families a dashboard will chart."""
    for accessor in _PREREGISTER:
        accessor()


def bridge_serving_metrics(serving_registry) -> None:
    """Mirror a serving ``MetricsRegistry`` into the telemetry registry
    via a pull collector.  Holds only a weakref — once a shut-down
    server's registry is garbage collected the collector unregisters
    itself (returning ``COLLECTOR_DONE``), freezing the last-mirrored
    values at their final reading.

    The serving families are unlabeled: with several serving
    registries LIVE in one process the last-registered collector wins
    each scrape.  One data plane per process is the deployment shape
    (``bigdl-tpu-serve``); a multi-server process should construct one
    shared ``MetricsRegistry`` and pass it to each ``ModelServer``."""
    from bigdl_tpu.telemetry.metrics import COLLECTOR_DONE
    ref = weakref.ref(serving_registry)

    def collect():
        from bigdl_tpu import telemetry
        reg = ref()
        if reg is None:
            return COLLECTOR_DONE
        if not telemetry.enabled():
            # the operator opted out (--no-telemetry): stay inert and
            # create NO families, so the exposition really is empty
            return None
        snap = reg.snapshot()
        lat = snap["latency_ms"]
        g = serving_latency_ms()
        for q in ("p50", "p90", "p99"):
            g.labels(q).set(lat[q])
        serving_queue_depth().set(snap["queue_depth_mean"])
        serving_queue_depth_max().set(snap["queue_depth_max"])
        serving_requests_total().set_total(snap["requests"])
        serving_batches_total().set_total(snap["batches"])
        serving_shed_total().set_total(snap["shed"])
        serving_rejected_total().set_total(snap["rejected"])
        serving_padded_waste_ratio().set(snap["padded_waste"])
        occ = serving_batch_occupancy()
        for rows, n in snap["occupancy"].items():
            occ.labels(rows).set(n)

    get_registry().register_collector(collect)
