"""Live introspection endpoints: ``/statusz``, ``/tracez``,
``/profilez``.

The question an operator asks a misbehaving process is not "what is
your p99" (Prometheus already has it) but "what are you doing *right
now*, and what went wrong *recently*?" — answered here without
restarting anything:

* ``GET  /statusz``  — one JSON page: uptime, telemetry state, the
  flight-recorder tail, plus whatever the owning process contributes
  (the optimizer: step/epoch, last good checkpoint generation,
  watchdog state; the serving CLI: model, queue depth, drain state).
* ``GET  /tracez``   — the newest spans from the PR-3 ring buffer
  (``?limit=N``, default 200), so "where did the last second go" is a
  curl away.  ``?name=<prefix>`` filters spans by name prefix
  (``name=request/`` shows only request-journey spans);
  ``?trace=<id>`` instead returns ONE assembled request trace — every
  hop on every replica, stitched across processes — which is how a
  TTFT exemplar's trace id resolves to its timeline in one step.
* ``POST /profilez`` — a time-boxed ``jax.profiler`` capture (body:
  ``{"duration_s": 1.0, "logdir": "..."}``, both optional) via
  ``optim.profiling.profile_trace``; returns the logdir to point
  TensorBoard's profile tab at.  One capture at a time — a concurrent
  POST gets 409.

One :class:`DebugzHandlerMixin` serves all three, mounted on the
``examples/serve.py`` HTTP server and on the opt-in trainer sidecar
(:class:`DebugzServer`, see ``Optimizer.set_debug_server``).  Both are
**off by default** on the trainer; nothing here is imported into a hot
path.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

__all__ = ["Debugz", "DebugzHandlerMixin", "DebugzServer",
           "ProfileBusyError"]

logger = logging.getLogger("bigdl_tpu.debugz")

# profilez duration clamp: long enough to catch a slow step, short
# enough that a stray POST can't wedge an HTTP thread for minutes
_MAX_PROFILE_S = 30.0
_MIN_PROFILE_S = 0.01


class ProfileBusyError(RuntimeError):
    """A profile capture is already in progress (jax.profiler allows
    one trace at a time; concurrent POSTs get 409)."""


class Debugz:
    """The endpoint logic, HTTP-free (unit-testable; the handler mixin
    is glue).  ``statusz_fn`` is the owning process's contribution to
    the status page — a zero-arg callable returning a JSON-able dict,
    merged over the base fields."""

    def __init__(self, statusz_fn: Optional[Callable[[], Dict]] = None,
                 trace_shard_dir: Optional[str] = None):
        self.statusz_fn = statusz_fn
        # where request-trace shards live (the serving snapshot dir):
        # lets /tracez?trace=<id> stitch spans from OTHER processes
        self.trace_shard_dir = trace_shard_dir
        self._t0 = time.perf_counter()
        self._profile_busy = threading.Lock()

    def statusz(self) -> Dict:
        from bigdl_tpu import telemetry
        from bigdl_tpu.telemetry import events, tracing
        ev = events.events_summary(50)
        base: Dict = {
            "time": time.time(),
            "pid": os.getpid(),
            "uptime_s": time.perf_counter() - self._t0,
            "telemetry_enabled": telemetry.enabled(),
            "spans": {"buffered": len(tracing.finished_spans()),
                      "dropped": tracing.dropped_spans()},
            # buffered/capacity/dropped up front: a full ring that has
            # evicted history during an incident must be VISIBLE on the
            # page, or the silent drops hide exactly the events the
            # postmortem needed
            "events": {"buffered": ev["buffered"],
                       "capacity": ev["capacity"],
                       "dropped": ev["dropped"],
                       "counts": ev["counts"],
                       "recent": ev["recent"]},
        }
        # fleet-controller section (autoscaler / deploy watcher /
        # training supervisor) whenever one is live in this process:
        # desired/live counts, the last decision + reason, cooldown
        # remaining — the "the controller did something, why?" page
        try:
            from bigdl_tpu.fleet.controller import controller_statusz
            ctl = controller_statusz()
            if ctl is not None:
                base["controller"] = ctl
        except Exception:  # pragma: no cover - best effort
            pass
        if self.statusz_fn is not None:
            try:
                extra = self.statusz_fn()
            except Exception as e:  # a broken provider must not 500
                extra = {"statusz_error": f"{type(e).__name__}: {e}"}
            if extra:
                base.update(extra)
        return base

    def tracez(self, limit: int = 200,
               name: Optional[str] = None,
               trace: Optional[str] = None) -> Dict:
        from bigdl_tpu.telemetry import tracing
        from bigdl_tpu.telemetry import request_trace
        if trace is not None:
            # assembled-request mode: one stitched timeline, shards
            # read from the serving snapshot dir when one is known
            assembled = request_trace.assemble_trace(
                str(trace), directory=self.trace_shard_dir)
            if assembled is None:
                raise KeyError(f"unknown trace id {trace!r}")
            return {"trace": assembled,
                    "retained": list(request_trace.retained_ids())}
        spans = tracing.finished_spans()
        if name is not None:
            spans = [r for r in spans if r.name.startswith(str(name))]
        limit = max(int(limit), 0)
        out = []
        # NOT spans[-limit:]: a -0 slice is the whole ring, and
        # limit=0 must mean "just the counters, no spans"
        for rec in spans[len(spans) - min(limit, len(spans)):]:
            d = {"name": rec.name,
                 "start_time": tracing.wall_time_of(rec.t_start),
                 "duration_s": rec.duration_s,
                 "span_id": rec.span_id,
                 "thread": rec.thread}
            if rec.parent_id is not None:
                d["parent_id"] = rec.parent_id
            if rec.args:
                d["args"] = rec.args
            out.append(d)
        resp = {"buffered": len(spans),
                "dropped": tracing.dropped_spans(),
                "limit": limit, "spans": out}
        if name is not None:
            resp["name"] = str(name)
        return resp

    def profilez(self, duration_s: float = 1.0,
                 logdir: Optional[str] = None) -> Dict:
        """Run a time-boxed ``jax.profiler`` capture and return the
        logdir.  Device activity dispatched by OTHER threads during the
        window (the training loop, in-flight serving batches) is what
        the trace is for; a token op is issued so the logdir is
        non-empty even on an idle process."""
        duration_s = min(max(float(duration_s), _MIN_PROFILE_S),
                         _MAX_PROFILE_S)
        if not self._profile_busy.acquire(blocking=False):
            raise ProfileBusyError(
                "a profile capture is already in progress")
        try:
            if logdir is None:
                logdir = tempfile.mkdtemp(prefix="bigdl-profilez-")
            import jax
            import jax.numpy as jnp
            from bigdl_tpu.optim.profiling import profile_trace
            t0 = time.perf_counter()
            with profile_trace(logdir):
                jax.block_until_ready(jnp.zeros((1,)))  # idle-proof token
                time.sleep(duration_s)
            n_files = sum(len(files) for _r, _d, files in os.walk(logdir))
            logger.info("profilez: %.2fs capture -> %s (%d files)",
                        duration_s, logdir, n_files)
            return {"logdir": logdir, "duration_s": duration_s,
                    "wall_s": time.perf_counter() - t0,
                    "files": n_files}
        finally:
            self._profile_busy.release()


class DebugzHandlerMixin:
    """Mix into a ``BaseHTTPRequestHandler`` whose server carries a
    ``debugz`` attribute; call ``self.handle_debugz("GET"/"POST")``
    first in ``do_GET``/``do_POST`` — returns True when the request was
    one of ours."""

    def _debugz_json(self, code: int, obj: Dict) -> None:
        body = json.dumps(obj, default=str).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def handle_debugz(self, method: str) -> bool:
        dz: Optional[Debugz] = getattr(self.server, "debugz", None)
        if dz is None:
            return False
        path, _, query = self.path.partition("?")
        if method == "GET" and path == "/statusz":
            self._debugz_json(200, dz.statusz())
            return True
        if method == "GET" and path == "/tracez":
            params = urllib.parse.parse_qs(query)
            unknown = set(params) - {"limit", "name", "trace"}
            if unknown:
                self._debugz_json(
                    400, {"error": "unknown tracez params: "
                          + ", ".join(sorted(unknown))})
                return True
            try:
                limit = int(params.get("limit", ["200"])[0])
            except ValueError:
                self._debugz_json(400, {"error": "limit must be an int"})
                return True
            name = params.get("name", [None])[0]
            trace = params.get("trace", [None])[0]
            try:
                resp = dz.tracez(limit=limit, name=name, trace=trace)
            except KeyError as e:
                # an unknown trace id is the CLIENT's bad parameter,
                # same contract as /profilez's 400 on a bad body
                self._debugz_json(400, {"error": str(e.args[0])})
                return True
            self._debugz_json(200, resp)
            return True
        if method == "POST" and path == "/profilez":
            n = int(self.headers.get("Content-Length", 0) or 0)
            raw = self.rfile.read(n) if n else b""
            try:
                opts = json.loads(raw) if raw.strip() else {}
                if not isinstance(opts, dict):
                    raise ValueError("body must be a JSON object")
            except ValueError as e:
                self._debugz_json(400, {"error": f"bad profilez body: {e}"})
                return True
            try:
                result = dz.profilez(
                    duration_s=opts.get("duration_s", 1.0),
                    logdir=opts.get("logdir"))
            except ProfileBusyError as e:
                self._debugz_json(409, {"error": str(e)})
                return True
            except Exception as e:  # noqa: BLE001 - client-facing error
                self._debugz_json(500,
                                  {"error": f"{type(e).__name__}: {e}"})
                return True
            self._debugz_json(200, result)
            return True
        return False


class DebugzServer:
    """The trainer's opt-in introspection sidecar: a tiny threaded HTTP
    server with the debugz routes plus ``/healthz`` and ``/metrics``
    (Prometheus), so one port answers liveness, scrape, AND "what are
    you doing".  Off by default; see ``Optimizer.set_debug_server``."""

    def __init__(self, debugz: Debugz, host: str = "127.0.0.1",
                 port: int = 0):
        class Handler(DebugzHandlerMixin, BaseHTTPRequestHandler):
            def log_message(self, fmt, *fargs):  # quiet by default
                logger.debug("%s " + fmt, self.address_string(), *fargs)

            def do_GET(self):
                if self.handle_debugz("GET"):
                    return
                if self.path == "/healthz":
                    self._debugz_json(200, {"status": "ok"})
                elif self.path == "/metrics":
                    from bigdl_tpu.telemetry import prometheus_text
                    body = prometheus_text().encode("utf-8")
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._debugz_json(404, {"error": "not found"})

            def do_POST(self):
                if self.handle_debugz("POST"):
                    return
                self._debugz_json(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.debugz = debugz
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_port

    def start(self) -> "DebugzServer":
        if self._thread is not None:
            raise RuntimeError("debug server already started")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="bigdl-debugz")
        self._thread.start()
        logger.info("debug server listening on port %d", self.port)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if self._thread is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout)
        self._thread = None
