"""Perf attribution: where a step's wall time goes, and durable
hardware evidence that survives a wedged chip.

Two halves, one subsystem (the layer every perf round reports through —
ROADMAP item 1):

**Attribution** — :func:`attribute_windows` decomposes the optimizer's
completion-timestamp stream (``Optimizer.window_records``, written by
the loss-drain worker) into four measured phases plus an explicit
*unattributed residual*:

* ``data_wait``       — host blocked pulling batches from the input
  pipeline (decode, augment, a stalled loader);
* ``host_staging``    — host→device transfer + window stacking + rng
  build between fetch and dispatch;
* ``device_compute``  — host blocked on the device completing the
  window (the pure-transfer pin in ``consume_window``; only the
  NON-overlapped device time can show up in wall time, which is
  exactly what attribution of wall time wants);
* ``readback``        — device→host loss transfer + float conversion.

``residual`` is wall minus the measured phases, clamped non-negative —
the honest "we don't know" number.  When host and device genuinely
overlap (async drain), the phases can over-sum the
completion-to-completion wall; the excess is reported as ``overlap``
rather than silently rescaled, so the published invariant is exact::

    sum(phases) + residual - overlap == wall

:func:`attribution_report` pairs the decomposition with the analytic
cost model (``utils/xla_cost.cost_breakdown``: compiled FLOPs + bytes
accessed) to state MFU vs the public spec AND vs the same-run measured
roofline (overall and device-only), plus a compute-bound vs HBM-bound
verdict from bytes/step against the device's HBM bandwidth.

**Durable evidence** — a versioned :data:`RoundArtifact <ROUND_SCHEMA>`
envelope (schema version, device kind, caller-passed timestamp, git
rev, confirmed-on-device vs carried-forward flags) with a writer that
promotes ``scripts/chip_session.py`` outputs (including
``real_jpeg_train``) into BENCH round records, and the
:func:`latest_confirmed` / :func:`carried_forward_result` pair
``bench.py`` uses to re-publish the newest confirmed on-device number
(marked ``carried_forward: true``) instead of emitting 0.0 when the
tunneled backend wedges (VERDICT r05 items 1 and 6: three straight
rounds published zero).

This module never imports jax — harnesses consult it before (and
instead of) touching a possibly-wedged backend.
"""

from __future__ import annotations

import glob as _glob
import json
import logging
import os
import subprocess
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("bigdl_tpu.telemetry")

__all__ = [
    "PHASES", "attribute_windows", "attribution_report",
    "roofline_verdict", "device_peak_flops", "device_hbm_bytes_per_s",
    "device_ici_bytes_per_s", "device_dcn_bytes_per_s",
    "optimizer_perf_status",
    "ROUND_SCHEMA", "ROUND_ARTIFACT_VERSION", "git_revision",
    "make_round_artifact", "write_round_artifact", "load_round_artifact",
    "artifact_payload", "artifact_timestamp", "is_confirmed",
    "latest_confirmed", "carried_forward_result", "promote_chip_session",
]

# The measured phases, in pipeline order.  ``residual`` is not a phase:
# it is defined as what the phases do NOT cover.
PHASES = ("data_wait", "host_staging", "device_compute", "readback")

# Record keys as written by Optimizer's consume_window.
_PHASE_KEYS = {
    "data_wait": "data_wait_s",
    "host_staging": "host_staging_s",
    "device_compute": "device_compute_s",
    "readback": "readback_s",
}

# ---------------------------------------------------------------------------
# Device capability tables (public numbers, per chip)
# ---------------------------------------------------------------------------

# Dense bf16 peak FLOP/s by device_kind substring — the same table
# bench.py's MFU-vs-spec has always used, now declared once.
_PEAK_BF16_FLOPS = (
    ("v6", 918e12), ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v5litepod", 197e12), ("v4", 275e12), ("v3", 123e12), ("v2", 46e12),
)

# HBM bandwidth (bytes/s) by device_kind substring — the denominator of
# the HBM-bound verdict (docs/performance.md measured v5e conv fusions
# at ~94% of the 819 GB/s figure, so these are usable rooflines).
_HBM_BYTES_PER_S = (
    ("v6", 1640e9), ("v5p", 2765e9), ("v5e", 819e9), ("v5 lite", 819e9),
    ("v5litepod", 819e9), ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
)

# Aggregate per-chip ICI bandwidth (bytes/s) by device_kind substring —
# the denominator of the comm-bound verdict: the floor a step's
# inter-chip payload (collective_bytes_total / collective_hlo_bytes)
# puts under its time.  Public interconnect figures, converted from the
# advertised per-chip link Gb/s; treat as rooflines, not guarantees
# (real ring/torus schedules land below them).
_ICI_BYTES_PER_S = (
    ("v6", 448e9), ("v5p", 600e9), ("v5e", 200e9), ("v5 lite", 200e9),
    ("v5litepod", 200e9), ("v4", 300e9), ("v3", 82e9), ("v2", 62e9),
)

# Per-chip DCN bandwidth (bytes/s) by device_kind substring — the slow
# tier BETWEEN slices (data-center network), the denominator of the
# ``dcn_bound`` verdict over the cross-slice payload
# (``xla_cost.cross_group_hlo_bytes`` /
# ``grad_allreduce_bytes(hierarchical=True)["dcn_bytes_per_step"]``).
# Order-of-magnitude figures from published multislice host NIC specs
# amortized per chip — one to two decades below ICI, which is exactly
# why parallel/hierarchy.py exists.  Override with
# ``BIGDL_TPU_DCN_BYTES_PER_S`` (e.g. to pin the table slow in a smoke
# test, or to enter a measured fleet number).
_DCN_BYTES_PER_S = (
    ("v6", 25e9), ("v5p", 25e9), ("v5e", 12.5e9), ("v5 lite", 12.5e9),
    ("v5litepod", 12.5e9), ("v4", 12.5e9), ("v3", 6e9), ("v2", 6e9),
)


def _lookup(table, device_kind: Optional[str]) -> Optional[float]:
    kind = (device_kind or "").lower()
    for key, value in table:
        if key in kind:
            return value
    return None


def device_peak_flops(device_kind: Optional[str]) -> Optional[float]:
    """Public dense bf16 peak FLOP/s for a ``device_kind`` string, or
    None for unknown parts (CPU, new chips)."""
    return _lookup(_PEAK_BF16_FLOPS, device_kind)


def device_hbm_bytes_per_s(device_kind: Optional[str]) -> Optional[float]:
    """Public HBM bandwidth (bytes/s) for a ``device_kind`` string, or
    None when unknown."""
    return _lookup(_HBM_BYTES_PER_S, device_kind)


def device_ici_bytes_per_s(device_kind: Optional[str]) -> Optional[float]:
    """Aggregate per-chip ICI bandwidth (bytes/s) for a ``device_kind``
    string, or None when unknown."""
    return _lookup(_ICI_BYTES_PER_S, device_kind)


def device_dcn_bytes_per_s(device_kind: Optional[str]) -> Optional[float]:
    """Per-chip DCN (inter-slice) bandwidth in bytes/s for a
    ``device_kind`` string, or None when unknown.  The
    ``BIGDL_TPU_DCN_BYTES_PER_S`` env var overrides the table
    unconditionally (measured fleet numbers beat public specs; smoke
    tests pin it slow to force a ``dcn_bound`` verdict)."""
    env = os.environ.get("BIGDL_TPU_DCN_BYTES_PER_S")
    if env:
        try:
            return float(env)
        except ValueError:
            logger.warning(
                "BIGDL_TPU_DCN_BYTES_PER_S=%r is not a number; "
                "ignoring the override and using the spec table "
                "(pass plain bytes/s, e.g. 12.5e9)", env)
    return _lookup(_DCN_BYTES_PER_S, device_kind)


# ---------------------------------------------------------------------------
# Step-time attribution
# ---------------------------------------------------------------------------

def attribute_windows(records: List[Dict[str, Any]],
                      skip_first: int = 1) -> Optional[Dict[str, Any]]:
    """Aggregate the optimizer's per-window phase records into one
    per-step attribution table.

    ``records`` is ``Optimizer.window_records`` — one dict per flushed
    readback window with ``iterations``, ``wall_s``
    (completion-to-completion), and the four measured phase durations.
    The first ``skip_first`` windows bear compile and are excluded when
    enough windows exist; with nothing left the full list is used and
    ``includes_compile_window`` is set so the reader knows the numbers
    carry one-time costs.

    Returns None for an empty stream; otherwise a dict whose exact
    invariant is ``sum(phases_s.values()) + residual_s - overlap_s ==
    wall_step_s`` (see module docstring for why ``overlap`` exists
    instead of rescaling)."""
    if not records:
        return None
    records = list(records)  # accept any sequence (deque included)
    steady = records[skip_first:] if len(records) > skip_first else None
    includes_compile = steady is None
    if steady is None:
        steady = list(records)
    iters = sum(int(r.get("iterations", 1)) for r in steady)
    iters = max(iters, 1)
    wall = sum(float(r.get("wall_s", 0.0)) for r in steady)
    phase_totals = {
        name: sum(max(float(r.get(key, 0.0)), 0.0) for r in steady)
        for name, key in _PHASE_KEYS.items()
    }
    measured = sum(phase_totals.values())
    residual = max(wall - measured, 0.0)
    overlap = max(measured - wall, 0.0)
    wall_step = wall / iters
    phases_s = {k: v / iters for k, v in phase_totals.items()}
    denom = max(wall, 1e-12)
    fractions = {k: v / denom for k, v in phase_totals.items()}
    fractions["residual"] = residual / denom
    # the residual competes for "dominant": when unattributed time
    # dwarfs every measured phase, naming a sliver phase would steer
    # the operator at exactly the wrong target (the runbook's "attack
    # the loop, not the kernels" case)
    dominant = max(fractions, key=fractions.get)
    return {
        "windows": len(steady),
        "iterations": iters,
        "wall_step_s": wall_step,
        "phases_s": phases_s,
        "residual_s": residual / iters,
        "overlap_s": overlap / iters,
        "fractions": fractions,
        "unattributed_fraction": residual / denom,
        "dominant_phase": dominant,
        "includes_compile_window": includes_compile,
    }


def roofline_verdict(flops_per_step: Optional[float],
                     bytes_per_step: Optional[float],
                     peak_flops: Optional[float],
                     hbm_bytes_per_s: Optional[float],
                     comm_bytes_per_step: Optional[float] = None,
                     ici_bytes_per_s: Optional[float] = None,
                     dcn_bytes_per_step: Optional[float] = None,
                     dcn_bytes_per_s: Optional[float] = None) \
        -> Optional[Dict[str, Any]]:
    """Compute-bound vs HBM-bound vs comm-bound vs dcn-bound from the
    analytic cost model: the step's minimum time on the MXU
    (flops/peak) against its minimum time on the memory system
    (bytes/bandwidth), on the interconnect when a comm budget is known
    (``collective_hlo_bytes`` / ``collective_bytes_total`` over ICI
    bandwidth), and — on a two-tier mesh — on the SLOW network tier
    (the cross-slice payload from ``cross_group_hlo_bytes`` or the
    hierarchical ``grad_allreduce_bytes`` floor, over DCN bandwidth).
    The largest floor is the binding resource; ``attainable_step_s``
    is the best step time this program can reach on this device no
    matter how well scheduled.  A ``dcn_bound`` verdict says: compress
    the cross-slice hop or grow the slice — more ICI won't help.
    Returns None when no floor is computable; ``verdict`` is None with
    fewer than two floors (nothing to compare)."""
    t_compute = (flops_per_step / peak_flops
                 if flops_per_step and peak_flops else None)
    t_hbm = (bytes_per_step / hbm_bytes_per_s
             if bytes_per_step and hbm_bytes_per_s else None)
    t_comm = (comm_bytes_per_step / ici_bytes_per_s
              if comm_bytes_per_step and ici_bytes_per_s else None)
    t_dcn = (dcn_bytes_per_step / dcn_bytes_per_s
             if dcn_bytes_per_step and dcn_bytes_per_s else None)
    floors = {"compute_bound": t_compute, "hbm_bound": t_hbm,
              "comm_bound": t_comm, "dcn_bound": t_dcn}
    known = {k: v for k, v in floors.items() if v is not None}
    if not known:
        return None
    verdict = (max(known, key=known.get) if len(known) > 1 else None)
    out: Dict[str, Any] = {
        "verdict": verdict,
        "min_compute_s": t_compute,
        "min_hbm_s": t_hbm,
        "attainable_step_s": max(known.values()),
    }
    if t_comm is not None:
        out["min_comm_s"] = t_comm
    if t_dcn is not None:
        out["min_dcn_s"] = t_dcn
    if flops_per_step and bytes_per_step:
        out["arithmetic_intensity_flops_per_byte"] = (
            flops_per_step / bytes_per_step)
    if peak_flops and hbm_bytes_per_s:
        out["machine_balance_flops_per_byte"] = (
            peak_flops / hbm_bytes_per_s)
    return out


def attribution_report(records: List[Dict[str, Any]],
                       flops_per_step: Optional[float] = None,
                       bytes_per_step: Optional[float] = None,
                       peak_spec_flops: Optional[float] = None,
                       peak_measured_flops: Optional[float] = None,
                       hbm_bytes_per_s: Optional[float] = None,
                       device_kind: Optional[str] = None,
                       skip_first: int = 1,
                       comm_bytes_per_step: Optional[float] = None,
                       ici_bytes_per_s: Optional[float] = None,
                       dcn_bytes_per_step: Optional[float] = None,
                       dcn_bytes_per_s: Optional[float] = None) \
        -> Optional[Dict[str, Any]]:
    """The full perf-attribution table: phase decomposition + MFU
    accounting + roofline verdict, as one JSON-able dict (what
    ``bench.py`` embeds in ``BENCH_telemetry.json`` under
    ``perf_attribution`` and merges into its result line).

    MFU is stated four ways: ``vs_spec`` / ``vs_measured`` use the
    wall step time (the headline — what a user experiences), while
    ``device_vs_spec`` / ``device_vs_measured`` use only the measured
    device-compute phase (what the chip achieves while actually busy);
    the gap between the two pairs is precisely what the host phases
    cost.  ``peak_*`` default from the :func:`device_peak_flops` /
    :func:`device_hbm_bytes_per_s` tables when ``device_kind`` is
    given.  When telemetry is enabled, publishes the
    ``step_mfu_vs_measured`` gauge as a side effect (the
    ``step_unattributed_fraction`` gauge stays per-window, written
    only by the drain worker — one writer, one semantic; the run
    aggregate lives in this report)."""
    report = attribute_windows(records, skip_first=skip_first)
    if report is None:
        return None
    if peak_spec_flops is None:
        peak_spec_flops = device_peak_flops(device_kind)
    if hbm_bytes_per_s is None:
        hbm_bytes_per_s = device_hbm_bytes_per_s(device_kind)
    if ici_bytes_per_s is None:
        ici_bytes_per_s = device_ici_bytes_per_s(device_kind)
    if dcn_bytes_per_s is None:
        dcn_bytes_per_s = device_dcn_bytes_per_s(device_kind)
    if device_kind:
        report["device_kind"] = device_kind
    if flops_per_step:
        report["flops_per_step"] = float(flops_per_step)
    if bytes_per_step:
        report["bytes_per_step"] = float(bytes_per_step)
    if comm_bytes_per_step:
        # comm is a named contributor hiding inside device_compute (the
        # collectives execute on-device) and, when the host can't keep
        # up with the ICI, inside the residual — state how much of the
        # measured device phase the comm floor alone explains
        comm: Dict[str, Any] = {
            "bytes_per_step": float(comm_bytes_per_step)}
        if ici_bytes_per_s:
            t_comm = comm_bytes_per_step / ici_bytes_per_s
            comm["min_comm_s"] = t_comm
            dev_s = report["phases_s"]["device_compute"]
            if dev_s > 0:
                comm["fraction_of_device_compute"] = min(
                    t_comm / dev_s, 1.0)
        report["comm"] = comm
    if dcn_bytes_per_step:
        # the slow-tier slice of the comm budget, stated on its own:
        # the dcn hop has its own (much lower) bandwidth floor, and on
        # a multi-slice step it is usually the one that binds
        dcn: Dict[str, Any] = {
            "bytes_per_step": float(dcn_bytes_per_step)}
        if dcn_bytes_per_s:
            dcn["min_dcn_s"] = dcn_bytes_per_step / dcn_bytes_per_s
        report["dcn"] = dcn
    wall_step = report["wall_step_s"]
    device_step = report["phases_s"]["device_compute"]
    mfu: Dict[str, Optional[float]] = {}
    for tag, peak in (("vs_spec", peak_spec_flops),
                      ("vs_measured", peak_measured_flops)):
        if flops_per_step and peak and wall_step > 0:
            mfu[tag] = flops_per_step / wall_step / peak
        if flops_per_step and peak and device_step > 0:
            mfu["device_" + tag] = flops_per_step / device_step / peak
    if mfu:
        report["mfu"] = mfu
    roof = roofline_verdict(
        flops_per_step, bytes_per_step,
        peak_measured_flops or peak_spec_flops, hbm_bytes_per_s,
        comm_bytes_per_step=comm_bytes_per_step,
        ici_bytes_per_s=ici_bytes_per_s,
        dcn_bytes_per_step=dcn_bytes_per_step,
        dcn_bytes_per_s=dcn_bytes_per_s)
    if roof is not None:
        report["roofline"] = roof
    try:
        from bigdl_tpu import telemetry
        if telemetry.enabled() and mfu.get("vs_measured") is not None:
            from bigdl_tpu.telemetry import families as _tm
            _tm.step_mfu_vs_measured().set(mfu["vs_measured"])
    except Exception:  # pragma: no cover - telemetry must never break
        pass           # the harness computing the report
    return report


def optimizer_perf_status(opt) -> Optional[Dict[str, Any]]:
    """The trainer's ``perf`` contribution to ``GET /statusz``: the
    cumulative attribution over this run's readback windows plus the
    latest window raw, so an operator can see where time is going
    mid-run without waiting for the artifact."""
    records = getattr(opt, "window_records", None)
    if not records:
        return None
    report = attribute_windows(records)
    last = records[-1]
    return {
        "attribution": report,
        "last_window": {
            "iterations": last.get("iterations"),
            "wall_s": last.get("wall_s"),
            **{key: last.get(key) for key in _PHASE_KEYS.values()},
        },
        "flops_per_step": getattr(opt, "compiled_flops_per_iteration",
                                  None),
    }


# ---------------------------------------------------------------------------
# RoundArtifact: durable, versioned hardware evidence
# ---------------------------------------------------------------------------

ROUND_SCHEMA = "bigdl_tpu.round_artifact"
ROUND_ARTIFACT_VERSION = 1


def git_revision(repo_root: Optional[str] = None) -> Optional[str]:
    """Short git rev of the working tree, or None outside a checkout
    (provenance only — never load-bearing)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=repo_root or os.getcwd())
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else None
    except Exception:
        return None


def make_round_artifact(payload: Dict[str, Any], *,
                        kind: str,
                        timestamp: float,
                        device_kind: Optional[str] = None,
                        platform: Optional[str] = None,
                        confirmed_on_device: bool = False,
                        carried_forward: bool = False,
                        source: Optional[str] = None,
                        git_rev: Optional[str] = None) -> Dict[str, Any]:
    """Wrap a measurement dict in the versioned evidence envelope.

    ``timestamp`` is passed in by the caller, never sampled here: a
    promotion must carry the ORIGINAL measurement time (a chip-session
    number promoted hours later is evidence from when the chip was
    healthy, not from when the writer ran)."""
    if platform is None:
        platform = payload.get("platform")
    if device_kind is None:
        device_kind = payload.get("device_kind")
    return {
        "schema": ROUND_SCHEMA,
        "schema_version": ROUND_ARTIFACT_VERSION,
        "kind": kind,
        "timestamp": float(timestamp),
        "device_kind": device_kind,
        "platform": platform,
        "git_rev": git_rev,
        "confirmed_on_device": bool(confirmed_on_device),
        "carried_forward": bool(carried_forward),
        "source": source,
        "payload": payload,
    }


def write_round_artifact(path: str, artifact: Dict[str, Any]) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=1, default=str)
    return path


def load_round_artifact(path: str) -> Optional[Dict[str, Any]]:
    """Parse ``path`` as JSON, or None on any error (a corrupt file
    must not hide older evidence from :func:`latest_confirmed`)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except Exception:
        return None


def _is_envelope(doc: Dict[str, Any]) -> bool:
    return isinstance(doc, dict) and doc.get("schema") == ROUND_SCHEMA


def artifact_payload(doc: Dict[str, Any]) -> Dict[str, Any]:
    """The measurement dict inside an artifact — envelope-aware, so
    legacy flat ``BENCH_measured_*.json`` files read identically."""
    if _is_envelope(doc):
        payload = doc.get("payload")
        return payload if isinstance(payload, dict) else {}
    return doc if isinstance(doc, dict) else {}


def artifact_timestamp(doc: Dict[str, Any],
                       default: Optional[float] = None) -> Optional[float]:
    """The measurement's own timestamp: envelope field, else the
    payload's, else ``default`` (callers pass file mtime)."""
    for source in (doc, artifact_payload(doc)):
        ts = source.get("timestamp")
        if isinstance(ts, (int, float)):
            return float(ts)
    return default


def is_confirmed(doc: Dict[str, Any]) -> bool:
    """Does this document carry confirmed ON-DEVICE evidence?

    New schema: ``confirmed_on_device`` and not ``carried_forward``
    (a carried-forward copy must never become its own source — that
    would let stale evidence self-launder forward forever) and a
    nonzero headline value.  Legacy flat files: a complete real-chip
    run — ``platform == "tpu"``, no ``partial`` marker, nonzero
    ``value`` (the exact rule ``bench.py`` has always applied)."""
    if not isinstance(doc, dict):
        return False
    payload = artifact_payload(doc)
    if _is_envelope(doc):
        return (bool(doc.get("confirmed_on_device"))
                and not doc.get("carried_forward")
                and bool(payload.get("value")))
    return (payload.get("platform") == "tpu"
            and "partial" not in payload
            and not payload.get("carried_forward")
            and bool(payload.get("value")))


def latest_confirmed(directory: str, pattern: str = "BENCH_*.json") \
        -> Optional[Tuple[str, Dict[str, Any]]]:
    """The newest confirmed-on-device artifact under ``directory``
    matching ``pattern``, as ``(path, document)`` — newest by the
    measurement's own timestamp, falling back to file mtime for legacy
    files.  Driver round wrappers (``BENCH_rNN.json`` carrying only a
    command transcript) and corrupt files are skipped."""
    best: Optional[Tuple[float, str, Dict[str, Any]]] = None
    for path in _glob.glob(os.path.join(directory, pattern)):
        doc = load_round_artifact(path)
        if doc is None or not is_confirmed(doc):
            continue
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = 0.0
        ts = artifact_timestamp(doc, mtime) or mtime
        if best is None or ts > best[0]:
            best = (ts, path, doc)
    if best is None:
        return None
    return best[1], best[2]


def carried_forward_result(doc: Dict[str, Any], path: str,
                           note: Optional[str] = None) -> Dict[str, Any]:
    """A publishable round result built from prior confirmed evidence:
    the original measurements verbatim, plus ``carried_forward: true``,
    the source file, and the ORIGINAL timestamp — so a wedged bench
    window publishes real (clearly labeled) hardware numbers instead of
    0.0, and nothing downstream can mistake them for a fresh run."""
    out = dict(artifact_payload(doc))
    out["carried_forward"] = True
    out["carried_forward_from"] = os.path.basename(path)
    ts = artifact_timestamp(doc)
    if ts is None:
        try:
            ts = os.path.getmtime(path)
        except OSError:
            ts = None
    if ts is not None:
        out["original_timestamp"] = ts
    if note:
        out["carried_forward_note"] = note
    out["schema_version"] = ROUND_ARTIFACT_VERSION
    return out


# Session phases worth promoting into the BENCH round record next to
# the bench headline (VERDICT r05 item 4: real_jpeg_train has never
# landed in a round artifact).
_PROMOTED_SESSION_PHASES = (
    "real_jpeg_train", "int8_infer", "generate", "resnet50_fused",
    "resnet50_xla",
)


def promote_chip_session(session: Dict[str, Any], *,
                         timestamp: float,
                         out_dir: str,
                         date: Optional[str] = None,
                         git_rev: Optional[str] = None) -> Optional[str]:
    """Promote a ``scripts/chip_session.py`` output dict into a BENCH
    round record (``BENCH_measured_<date>.json`` in the RoundArtifact
    schema) — but only when the session's bench phase is a confirmed
    real-chip run; a CPU smoke or a partial must never shadow TPU
    evidence.  Non-error secondary phases (``real_jpeg_train``,
    ``int8_infer``, ...) ride along in the payload so device-fed
    real-data numbers finally live in the round record instead of a
    session-local file.  Returns the written path, or None when there
    is nothing confirmable to promote."""
    bench = session.get("bench")
    if not isinstance(bench, dict) or not is_confirmed(bench):
        return None
    payload = dict(bench)
    for tag in _PROMOTED_SESSION_PHASES:
        extra = session.get(tag)
        if isinstance(extra, dict) and "error" not in extra:
            payload[tag] = extra
    date = date or session.get("date") or "undated"
    artifact = make_round_artifact(
        payload, kind="bench", timestamp=timestamp,
        device_kind=bench.get("device_kind"),
        platform=bench.get("platform"),
        confirmed_on_device=True,
        source="scripts/chip_session.py",
        git_rev=git_rev)
    path = os.path.join(out_dir, f"BENCH_measured_{date}.json")
    return write_round_artifact(path, artifact)


def record_carried_forward_round() -> None:
    """Count a carried-forward round publication (cold path; the
    counter exists so a dashboard can see how often rounds run on
    stale evidence)."""
    try:
        from bigdl_tpu.telemetry import families as _tm
        _tm.bench_rounds_carried_forward_total().inc()
    except Exception:  # pragma: no cover - never break the publisher
        pass
