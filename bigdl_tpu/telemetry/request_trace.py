"""Request-scoped distributed tracing: one assembled timeline per
request, across every replica it touched.

The process-local span ring (:mod:`bigdl_tpu.telemetry.tracing`)
answers "where did THIS process's wall time go"; it cannot answer "why
did request X breach its TTFT SLO" once the serving fabric moves a
request between replicas — retries, hedged twins, mid-stream failover,
the disaggregated prefill→decode handoff.  This module adds the
request-scoped layer (Dapper, Sigelman et al. 2010):

* A :class:`TraceContext` (``trace_id`` + parent span id + origin pid)
  is minted at router admission and rides the request object through
  dispatch, the replica boundary, and the generation engine.  With
  telemetry disabled nothing is minted: the request carries ``None``
  and every instrumentation site pays the existing one-bool check.
* :func:`record_span` records a span BOTH into the process ring (with
  a ``trace_id`` arg, so ``/tracez`` and Chrome export cross-reference)
  and into a per-trace buffer here.
* **Tail-based retention** ("The Tail at Scale", Dean & Barroso 2013):
  completed traces land in a bounded bulk ring that drops healthy
  traffic by design, EXCEPT traces marked interesting — deadline
  expiry, shed, failover, hedge-won, TTFT / inter-token latency above
  a rolling percentile watermark — which move to the retained store.
  The p99 request is exactly the one a uniform sampler loses.
* **Cross-process stitching** rides the fleet file transport: a
  process drops its per-trace spans as an atomic JSON shard (the way
  replicas write health snapshots), wall-converted through its own
  ``wall_time_of`` anchor pair at write time, so
  :func:`assemble_trace` merges shards from any number of processes
  onto one wall-clock axis with no further rebasing.

Exemplars: the engine tags its TTFT / inter-token histogram
observations with the trace id (``Histogram.observe(v, exemplar=...)``)
so a metric breach on ``/statusz`` resolves in one step to the causing
trace via ``/tracez?trace=<id>``.
"""

from __future__ import annotations

import glob as _glob
import itertools
import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from bigdl_tpu.telemetry import tracing

__all__ = ["TraceContext", "mint", "record_span", "mark", "finish",
           "observe_ttft", "observe_inter_token", "assemble_trace",
           "write_trace_shard", "trace_ids", "retained_ids",
           "retained_reasons", "set_bulk_capacity",
           "set_retained_capacity", "reset_traces",
           "RETENTION_REASONS", "SHARD_PREFIX"]

# The retention vocabulary (docs/observability.md "Request tracing"):
# every mark() reason must come from here so the
# request_traces_retained_total{reason} label set stays bounded.
RETENTION_REASONS = ("deadline", "shed", "failover", "hedge_won",
                     "slow_ttft", "slow_inter_token")

SHARD_PREFIX = "trace_spans_"

_DEFAULT_BULK = 256          # completed healthy traces kept (ring)
_DEFAULT_RETAINED = 256      # completed marked traces kept (FIFO)
_WATERMARK_WINDOW = 512      # latency samples backing the watermark
_WATERMARK_MIN_SAMPLES = 30  # no watermark verdicts before this many
_WATERMARK_QUANTILE = 0.95   # "above the percentile watermark"
_WATERMARK_REFRESH = 32      # recompute cadence (samples)

# process tag: pid alone recycles; two random bytes make a trace id
# minted after a pid reuse distinguishable in a shared shard directory
_PROC_TAG = f"{os.getpid():x}-{os.urandom(2).hex()}"
_ids = itertools.count(1)

_lock = threading.Lock()
_active: Dict[str, "_Trace"] = {}
_bulk: "OrderedDict[str, _Trace]" = OrderedDict()
_retained: "OrderedDict[str, _Trace]" = OrderedDict()
_bulk_capacity = _DEFAULT_BULK
_retained_capacity = _DEFAULT_RETAINED


class TraceContext:
    """What rides the request object.  Allocation-light on purpose —
    minted once per admitted request, only when telemetry is on."""

    __slots__ = ("trace_id", "parent_span_id", "origin_pid")

    def __init__(self, trace_id: str,
                 parent_span_id: Optional[int] = None,
                 origin_pid: Optional[int] = None):
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.origin_pid = (os.getpid() if origin_pid is None
                           else int(origin_pid))

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id!r}, "
                f"parent={self.parent_span_id}, "
                f"pid={self.origin_pid})")


class _Trace:
    __slots__ = ("trace_id", "origin_pid", "t_start_wall", "spans",
                 "marks", "outcome")

    def __init__(self, trace_id: str, origin_pid: int):
        self.trace_id = trace_id
        self.origin_pid = origin_pid
        self.t_start_wall = time.time()
        self.spans: List[Dict[str, Any]] = []
        self.marks: List[str] = []
        self.outcome: Optional[str] = None


class _Reservoir:
    """Rolling latency window with a cached percentile watermark.
    ``over(v)`` is O(1) between refreshes — it runs per emitted token
    on the inter-token side, so no per-call sort."""

    __slots__ = ("values", "watermark", "_since_refresh")

    def __init__(self):
        self.values: deque = deque(maxlen=_WATERMARK_WINDOW)
        self.watermark: Optional[float] = None
        self._since_refresh = 0

    def over(self, v: float) -> bool:
        self.values.append(float(v))
        self._since_refresh += 1
        if (self.watermark is None
                or self._since_refresh >= _WATERMARK_REFRESH):
            self._since_refresh = 0
            if len(self.values) >= _WATERMARK_MIN_SAMPLES:
                s = sorted(self.values)
                self.watermark = s[min(
                    int(_WATERMARK_QUANTILE * len(s)), len(s) - 1)]
        return self.watermark is not None and v > self.watermark

    def reset(self) -> None:
        self.values.clear()
        self.watermark = None
        self._since_refresh = 0


_ttft_res = _Reservoir()
_itl_res = _Reservoir()


def _enabled() -> bool:
    from bigdl_tpu import telemetry
    return telemetry.enabled()


def _counters():
    from bigdl_tpu.telemetry import families
    return families


# ---- the write side --------------------------------------------------------

def mint(parent_span_id: Optional[int] = None) -> Optional[TraceContext]:
    """A fresh context for one admitted request, or None with
    telemetry disabled (the request object then carries None and the
    fabric's instrumentation sites all no-op on the existing bool)."""
    if not _enabled():
        return None
    tid = f"{_PROC_TAG}-{next(_ids):x}"
    ctx = TraceContext(tid, parent_span_id=parent_span_id)
    with _lock:
        _active[tid] = _Trace(tid, ctx.origin_pid)
    return ctx


def record_span(name: str, t_start: float, t_end: float,
                ctx: Optional[TraceContext] = None,
                parent_id: Optional[int] = None,
                **args) -> Optional[int]:
    """Record one span of ``ctx``'s trace (no-op when ``ctx`` is None
    or telemetry is off).  Endpoints on ``time.perf_counter`` like
    every span; the trace id lands in the ring span's args so the
    process-local ``/tracez`` view and the Chrome export carry the
    cross-reference.  Returns the ring span id."""
    if ctx is None or not _enabled():
        return None
    sid = tracing.record_span(name, t_start, t_end,
                              parent_id=(parent_id if parent_id
                                         is not None
                                         else ctx.parent_span_id),
                              trace_id=ctx.trace_id, **args)
    rec = {"name": name,
           # graftlint: disable=clock-discipline -- wall conversion at
           # record time IS the sanctioned bridge (wall_time_of): trace
           # spans are merged across processes, where perf_counter
           # values are not comparable
           "t_start_wall": tracing.wall_time_of(t_start),
           "t_end_wall": tracing.wall_time_of(t_end),
           "duration_s": max(float(t_end) - float(t_start), 0.0),
           "span_id": sid, "pid": os.getpid(),
           "args": args or None}
    with _lock:
        tr = _active.get(ctx.trace_id)
        if tr is None:
            # late span for an already-finished trace (an engine
            # callback racing terminal accounting): attach if the
            # trace is still held anywhere, else drop silently
            tr = _retained.get(ctx.trace_id) or _bulk.get(ctx.trace_id)
        if tr is not None:
            tr.spans.append(rec)
    if tr is not None:
        _counters().request_trace_spans_total().inc()
    return sid


def mark(ctx: Optional[TraceContext], reason: str) -> None:
    """Flag ``ctx``'s trace for tail retention.  ``reason`` must come
    from :data:`RETENTION_REASONS` (the metric label vocabulary).  A
    mark landing AFTER terminal filing (a hedge verdict resolving just
    behind the future) promotes the trace out of the droppable bulk
    ring — interesting-late is still interesting."""
    if ctx is None or not _enabled():
        return
    if reason not in RETENTION_REASONS:
        raise ValueError(f"unknown retention reason {reason!r}; "
                         f"expected one of {RETENTION_REASONS}")
    promoted = False
    with _lock:
        tr = (_active.get(ctx.trace_id)
              or _retained.get(ctx.trace_id))
        if tr is None:
            tr = _bulk.pop(ctx.trace_id, None)
            if tr is not None:
                _retained[ctx.trace_id] = tr
                while len(_retained) > _retained_capacity:
                    _retained.popitem(last=False)
                promoted = True
        if tr is not None and reason not in tr.marks:
            tr.marks.append(reason)
        else:
            promoted = False    # duplicate reason: nothing new to count
    if promoted:
        # finish() already ran and counted nothing for this trace (it
        # was unmarked then) — the retained tick happens here instead
        _counters().request_traces_retained_total().labels(reason).inc()


def finish(ctx: Optional[TraceContext],
           outcome: Optional[str] = None) -> None:
    """Terminal accounting for one request's trace: marked traces move
    to the retained store (FIFO-bounded), unmarked ones to the bulk
    ring whose evictions are the sampled-out healthy traffic."""
    if ctx is None:
        return
    reasons: List[str] = []
    dropped = 0
    with _lock:
        tr = _active.pop(ctx.trace_id, None)
        if tr is None:
            return
        tr.outcome = outcome
        if tr.marks:
            reasons = list(tr.marks)
            _retained[ctx.trace_id] = tr
            while len(_retained) > _retained_capacity:
                _retained.popitem(last=False)
        else:
            _bulk[ctx.trace_id] = tr
            while len(_bulk) > _bulk_capacity:
                _bulk.popitem(last=False)
                dropped += 1
    if not _enabled():
        return
    fam = _counters()
    for r in reasons:
        fam.request_traces_retained_total().labels(r).inc()
    if dropped:
        fam.request_traces_dropped_total().inc(dropped)


def observe_ttft(ctx: Optional[TraceContext], ttft_s: float) -> None:
    """Feed the TTFT watermark; marks ``slow_ttft`` when this request
    sits above the rolling p95 of recent traffic."""
    if ctx is None or not _enabled():
        return
    with _lock:
        slow = _ttft_res.over(ttft_s)
    if slow:
        mark(ctx, "slow_ttft")


def observe_inter_token(ctx: Optional[TraceContext],
                        gap_s: float) -> None:
    """Feed the inter-token watermark; marks ``slow_inter_token`` when
    one streaming gap sits above the rolling p95."""
    if ctx is None or not _enabled():
        return
    with _lock:
        slow = _itl_res.over(gap_s)
    if slow:
        mark(ctx, "slow_inter_token")


# ---- cross-process stitching (fleet file transport) ------------------------

def write_trace_shard(directory: str) -> Optional[str]:
    """Atomically drop this process's per-trace spans as
    ``trace_spans_<pid>.json`` under ``directory`` — the fleet
    snapshot idiom (unique tmp per pid+thread, then ``os.replace``; a
    merger must never read a torn write).  Spans are already
    wall-converted, so the reader needs no anchor math.  Returns the
    path, or None when there is nothing to write."""
    with _lock:
        traces: Dict[str, Dict[str, Any]] = {}
        for store in (_active, _retained, _bulk):
            for tid, tr in store.items():
                if tr.spans:
                    traces[tid] = {"origin_pid": tr.origin_pid,
                                   "marks": list(tr.marks),
                                   "outcome": tr.outcome,
                                   "spans": list(tr.spans)}
    if not traces:
        return None
    os.makedirs(directory, exist_ok=True)
    payload = {"pid": os.getpid(), "time": time.time(),
               "traces": traces}
    path = os.path.join(directory, f"{SHARD_PREFIX}{os.getpid()}.json")
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def _read_shards(directory: str,
                 trace_id: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for path in sorted(_glob.glob(
            os.path.join(directory, SHARD_PREFIX + "*.json"))):
        try:
            with open(path, "r", encoding="utf-8") as f:
                payload = json.load(f)
            entry = payload["traces"].get(trace_id)
        except Exception:
            continue        # torn/corrupt shard: skip, like the fleet
        if entry:
            out.append(entry)
    return out


def assemble_trace(trace_id: str,
                   directory: Optional[str] = None) \
        -> Optional[Dict[str, Any]]:
    """ONE timeline for ``trace_id``: local spans (live, retained, or
    bulk) merged with any per-process shards under ``directory``, all
    on the wall clock, sorted by start time.  Every replica the
    request touched appears by pid and span args; None when the trace
    is unknown everywhere."""
    spans: List[Dict[str, Any]] = []
    marks: List[str] = []
    outcome = None
    origin_pid = None
    found = False
    with _lock:
        tr = (_active.get(trace_id) or _retained.get(trace_id)
              or _bulk.get(trace_id))
        if tr is not None:
            found = True
            spans.extend(dict(s) for s in tr.spans)
            marks.extend(tr.marks)
            outcome = tr.outcome
            origin_pid = tr.origin_pid
    if directory is not None:
        local = {(s["pid"], s["span_id"]) for s in spans}
        for entry in _read_shards(directory, trace_id):
            found = True
            if origin_pid is None:
                origin_pid = entry.get("origin_pid")
            for r in entry.get("marks", []):
                if r not in marks:
                    marks.append(r)
            if outcome is None:
                outcome = entry.get("outcome")
            for s in entry.get("spans", []):
                key = (s.get("pid"), s.get("span_id"))
                if key in local:    # our own shard re-read: dedup
                    continue
                spans.append(dict(s))
    if not found:
        return None
    spans.sort(key=lambda s: (s.get("t_start_wall", 0.0),
                              s.get("t_end_wall", 0.0)))
    pids = sorted({s.get("pid") for s in spans if s.get("pid")})
    return {"trace_id": trace_id, "origin_pid": origin_pid,
            "retained_reasons": marks, "outcome": outcome,
            "pids": pids, "spans": spans,
            "names": [s["name"] for s in spans]}


# ---- reading / lifecycle ---------------------------------------------------

def trace_ids() -> List[str]:
    """Every trace id currently held (open, retained, or bulk)."""
    with _lock:
        return list(_active) + list(_retained) + list(_bulk)


def retained_ids() -> List[str]:
    with _lock:
        return list(_retained)


def retained_reasons() -> Dict[str, List[str]]:
    """trace_id -> retention reasons, for the retained store only."""
    with _lock:
        return {tid: list(tr.marks) for tid, tr in _retained.items()}


def set_bulk_capacity(n: int) -> None:
    global _bulk_capacity
    if n < 1:
        raise ValueError("bulk capacity must be >= 1")
    with _lock:
        _bulk_capacity = int(n)
        while len(_bulk) > _bulk_capacity:
            _bulk.popitem(last=False)


def set_retained_capacity(n: int) -> None:
    global _retained_capacity
    if n < 1:
        raise ValueError("retained capacity must be >= 1")
    with _lock:
        _retained_capacity = int(n)
        while len(_retained) > _retained_capacity:
            _retained.popitem(last=False)


def reset_traces() -> None:
    """Drop every held trace and both watermark reservoirs (wired into
    ``telemetry.reset()`` so tests start clean); capacities persist."""
    with _lock:
        _active.clear()
        _bulk.clear()
        _retained.clear()
        _ttft_res.reset()
        _itl_res.reset()
