"""Rule/pass registry: every graftlint pass declares itself here.

A pass is a function ``(tree: SourceTree) -> List[Finding]`` (kind
``"ast"``) or ``() -> List[Finding]`` (kind ``"hlo"`` — compiles real
programs, needs a jax backend with enough devices).  Registration is a
decorator so adding a rule is one file in ``analysis/passes/`` and
nothing else; the CLI and tests enumerate whatever is registered.
"""

from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

__all__ = ["register_pass", "get_passes", "pass_names", "PassInfo"]


class PassInfo(NamedTuple):
    name: str        # the rule id findings carry and pragmas name
    kind: str        # "ast" | "hlo"
    doc: str         # one-line "what it catches" for --list / docs
    fn: Callable
    rules: tuple     # every rule id this pass may emit (>= (name,))


_PASSES: Dict[str, PassInfo] = {}


def register_pass(name: str, kind: str = "ast", doc: str = "",
                  rules: tuple = ()) -> Callable:
    """Decorator: ``@register_pass("trace-safety", doc="...")``.
    ``rules`` lists extra rule ids the pass emits beyond its own name
    (baseline staleness is judged only against rules that RAN)."""
    if kind not in ("ast", "hlo"):
        raise ValueError(f"unknown pass kind {kind!r}")

    def deco(fn: Callable) -> Callable:
        if name in _PASSES:
            raise ValueError(f"pass {name!r} registered twice")
        _PASSES[name] = PassInfo(name, kind, doc or (fn.__doc__ or "")
                                 .strip().splitlines()[0], fn,
                                 (name,) + tuple(rules))
        return fn

    return deco


def _ensure_loaded() -> None:
    # importing the subpackage registers every pass (side effect)
    from bigdl_tpu.analysis import passes  # noqa: F401


def get_passes(kind: Optional[str] = None,
               select: Optional[Sequence[str]] = None) -> List[PassInfo]:
    _ensure_loaded()
    out = []
    for name in sorted(_PASSES):
        p = _PASSES[name]
        if kind is not None and p.kind != kind:
            continue
        if select is not None and name not in select:
            continue
        out.append(p)
    if select:
        unknown = set(select) - set(_PASSES)
        if unknown:
            raise ValueError(
                f"unknown pass(es) {sorted(unknown)}; "
                f"known: {sorted(_PASSES)}")
    return out


def pass_names() -> List[str]:
    _ensure_loaded()
    return sorted(_PASSES)
