"""``python -m bigdl_tpu.analysis`` — the graftlint CLI.

Usage::

    python -m bigdl_tpu.analysis                 # AST passes, fatal
    python -m bigdl_tpu.analysis --warn-only     # CI ride-along
    python -m bigdl_tpu.analysis --hlo           # + compiled-HLO passes
    python -m bigdl_tpu.analysis --budget        # + parallelism budgets
    python -m bigdl_tpu.analysis --json out.json # machine report
    python -m bigdl_tpu.analysis --select clock-discipline,trace-safety
    python -m bigdl_tpu.analysis --list          # rule catalog
    python -m bigdl_tpu.analysis --update-baseline  # excuse current
                                                    # errors (then EDIT
                                                    # the justifications)
    python -m bigdl_tpu.analysis --update-budget    # re-measure the
                                                    # probe matrix (then
                                                    # JUSTIFY the entries)

Exit status: 1 when any unsuppressed ``error`` finding remains (and
not ``--warn-only``), else 0.  ``scripts/lint.sh`` is the fatal
wrapper CI and ship habits use; see docs/static_analysis.md.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.analysis",
        description="graftlint: rule-based static analysis for "
                    "bigdl_tpu (AST + compiled-HLO passes)")
    p.add_argument("root", nargs="?", default=None,
                   help="package root to lint (default: the installed "
                        "bigdl_tpu package)")
    p.add_argument("--warn-only", action="store_true",
                   help="always exit 0 (CI ride-along mode)")
    p.add_argument("--json", metavar="FILE",
                   help="write the machine report (all findings incl. "
                        "suppressed) to FILE")
    p.add_argument("--select", metavar="RULES",
                   help="comma-separated rule ids to run (default all)")
    p.add_argument("--hlo", action="store_true",
                   help="also run the compiled-HLO passes (compiles "
                        "probe programs; needs >= 8 devices — forces "
                        "the virtual-CPU fallback)")
    p.add_argument("--hlo-only", action="store_true",
                   help="run ONLY the compiled-HLO passes")
    p.add_argument("--budget", action="store_true",
                   help="also run the parallelism-conformance budget "
                        "passes (lowers the probe matrix — model zoo x "
                        "strategy compositions — against "
                        "scripts/parallel_budget.json)")
    p.add_argument("--budget-only", action="store_true",
                   help="run ONLY the budget passes")
    p.add_argument("--budget-file", metavar="FILE", default=None,
                   help="budget file (default "
                        "scripts/parallel_budget.json)")
    p.add_argument("--update-budget", action="store_true",
                   help="re-measure the probe matrix and merge it into "
                        "the budget file; new/drifted entries get EMPTY "
                        "justifications so the gate stays red until "
                        "each is hand-reviewed")
    p.add_argument("--no-cache", action="store_true",
                   help="ignore the /tmp probe-compile cache and "
                        "re-lower the full matrix")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="baseline file (default "
                        "scripts/graftlint_baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (show the full debt)")
    p.add_argument("--update-baseline", action="store_true",
                   help="append every active error to the baseline "
                        "with an empty justification — the lint stays "
                        "red until each entry is justified by hand")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print pragma/baseline-suppressed findings")
    p.add_argument("--list", action="store_true",
                   help="list registered passes and exit")
    args = p.parse_args(argv)

    budget_mode = (args.budget or args.budget_only
                   or args.update_budget)

    from bigdl_tpu.analysis import (
        apply_suppressions, counts_of, default_baseline_path,
        get_passes, load_baseline, load_tree, render_human, render_json,
        run_ast_passes, write_baseline,
    )
    from bigdl_tpu.analysis.hlo_budget import BUDGET_RULES
    from bigdl_tpu.analysis.hlo_lint import HLO_RULES

    if args.list:
        for info in get_passes(kind="ast"):
            print(f"{info.name:24s} [ast] {info.doc}")
        for rule in HLO_RULES:
            print(f"{rule:24s} [hlo] see "
                  f"bigdl_tpu/analysis/hlo_lint.py")
        for rule in BUDGET_RULES:
            print(f"{rule:24s} [budget] see "
                  f"bigdl_tpu/analysis/hlo_budget.py")
        return 0

    select = (set(t.strip() for t in args.select.split(",") if t.strip())
              if args.select else None)
    ast_select = (None if select is None
                  else [r for r in select if not r.startswith("hlo-")
                        and r not in BUDGET_RULES])
    if select is not None:
        unknown_hlo = ({r for r in select if r.startswith("hlo-")}
                       - set(HLO_RULES) - set(BUDGET_RULES))
        if unknown_hlo:
            p.error(f"unknown HLO rule(s) {sorted(unknown_hlo)}; "
                    f"known: {list(HLO_RULES) + list(BUDGET_RULES)}")
        if (select & set(HLO_RULES)) and not (args.hlo or args.hlo_only):
            # selecting an hlo rule IS asking for the HLO passes — a
            # run that silently checks nothing and prints OK would be
            # worse than an error
            args.hlo = True
        if (select & set(BUDGET_RULES)) and not budget_mode:
            budget_mode = args.budget = True

    if args.hlo or args.hlo_only or budget_mode:
        # AFTER select implication (a bare `--select hlo-reshard` must
        # get the backend too), BEFORE the first backend touch: the
        # probe compiles need the 8-virtual-device CPU fallback
        from bigdl_tpu.analysis.hlo_lint import ensure_backend
        ensure_backend()

    findings = []
    tree = None
    ran_rules = {"parse-error"}
    if not (args.hlo_only or args.budget_only):
        tree = load_tree(args.root)
        if ast_select is None or ast_select:
            sel = ast_select if ast_select else None
            tree, findings = run_ast_passes(tree, select=sel)
            for info in get_passes(kind="ast", select=sel):
                ran_rules.update(info.rules)
    if (args.hlo or args.hlo_only) and not args.budget_only:
        from bigdl_tpu.analysis.hlo_lint import run_hlo_passes
        # an explicit --hlo with a --select naming no hlo rule still
        # runs EVERY hlo pass (the flag asked for the family; a run
        # that silently checks nothing and prints OK would be worse)
        hlo_select = (None if select is None
                      else ({r for r in select if r in HLO_RULES}
                            or None))
        findings.extend(run_hlo_passes(select=hlo_select))
        ran_rules.update(hlo_select if hlo_select else HLO_RULES)
    if budget_mode:
        from bigdl_tpu.analysis.hlo_budget import (
            PROBES, probe_matrix, run_budget_passes, update_budget,
        )
        specs = PROBES()
        matrix = None
        if args.update_budget:
            # lower the matrix ONCE and share it with the verdict run
            # below (a --no-cache update would otherwise pay the full
            # re-lower twice for identical results)
            matrix = probe_matrix(specs, no_cache=args.no_cache)
            path, added, refreshed = update_budget(
                budget_path=args.budget_file, specs=specs,
                matrix=matrix)
            print(f"graftlint: budget: added {added}, refreshed "
                  f"{refreshed} entr(ies) in {path} — justify every "
                  f"empty justification before shipping")
        # same family semantics as --hlo above: an explicit --budget
        # with a foreign --select runs every budget rule
        budget_select = (None if select is None
                         else ({r for r in select if r in BUDGET_RULES}
                               or None))
        findings.extend(run_budget_passes(
            select=budget_select, budget_path=args.budget_file,
            no_cache=args.no_cache, specs=specs, matrix=matrix))
        ran_rules.update(budget_select if budget_select
                         else BUDGET_RULES)
    if tree is None:
        tree = load_tree(args.root)

    baseline_path = args.baseline or default_baseline_path()
    baseline = [] if args.no_baseline else load_baseline(baseline_path)
    apply_suppressions(findings, tree, baseline,
                       baseline_path=baseline_path,
                       ran_rules=ran_rules)

    if args.update_baseline:
        # merge with what the FILE holds, not the in-memory view —
        # --no-baseline + --update-baseline must never rewrite the
        # baseline from empty and destroy the justified entries
        entries = list(load_baseline(baseline_path))
        known = {(e["rule"], e["file"], e["scope"], e["code"])
                 for e in entries}
        added = 0
        for f in findings:
            if f.suppressed or f.severity != "error":
                continue
            key = (f.rule, f.file, f.scope, f.code)
            if key in known:
                continue
            known.add(key)
            entries.append({**f.key(), "justification": ""})
            added += 1
        path = write_baseline(entries, baseline_path)
        print(f"graftlint: baseline: added {added} entr(ies) to {path} "
              f"— fill in every empty justification before shipping")

    for line in render_human(findings,
                             show_suppressed=args.show_suppressed):
        print(line)
    counts = counts_of(findings)
    if args.json:
        meta = {"root": os.path.relpath(tree.root, tree.repo),
                "hlo": bool(args.hlo or args.hlo_only),
                "budget": bool(budget_mode),
                "warn_only": bool(args.warn_only)}
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(render_json(findings, meta))
            f.write("\n")
    status = (f"{counts['error']} error(s), {counts['warning']} "
              f"warning(s), {counts['info']} info, "
              f"{counts['suppressed']} suppressed")
    if counts["error"] and not args.warn_only:
        print(f"graftlint: FAILED ({status})")
        return 1
    print(f"graftlint: OK ({status})" if not counts["error"]
          else f"graftlint: {status} (warn-only)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
