"""Parallelism-conformance budgets: the composition × collective-byte
matrix gate.

GSPMD-style compilers (GSPMD, Alpa) silently insert resharding
collectives when a partition spec is wrong — the failure mode is not a
crash but a 4× collective-byte bill, invisible until someone reads the
HLO.  Before the Optimizer façade starts composing dp×fsdp×tp×sp×ep×pp
(ROADMAP item 2), every supported composition's communication contract
is pinned here the way ``hlo-dcn-ratio`` pins the PR-8 sync envelope:
a **probe catalog** lowers a small model zoo (cnn, transformer_lm,
moe, plus the PR-8/PR-9 mlp probe) under every supported strategy
composition on the 8-fake-device mesh, extracts per-{op, axis}
collective bytes, FLOPs, donation coverage and temp-HBM watermarks
from each compiled program, and checks them against the committed,
per-entry-justified budget file ``scripts/parallel_budget.json`` (same
baseline/identity/staleness semantics as ``graftlint_baseline.json``).

Rules:

* ``hlo-budget-bytes`` — each composition's {op, axis} collective-byte
  matrix stays within its entry's declared tolerance; any drift is a
  red gate naming the offending {op, axis}.  The PR-8 dcn envelope
  (cross-slice 25.1 % fp32 / 13.1 % int8 of the flat baseline at S=2)
  lives here as the ``mlp/dcn_hier_*`` entries' bytes, not as
  hard-coded test constants.
* ``hlo-reshard`` — collectives in the compiled step that the
  composition's declared axes + the analytic plan
  (``parallel/sharding.grad_allreduce_bytes``) do NOT predict: the
  accidental full-parameter all-gather detector.  The deliberate
  failure-mode seam ``BIGDL_TPU_BUDGET_MISSPEC=1`` injects a probe
  whose rule shards parameters over the batch axis while declaring
  pure dp — GSPMD inserts the classic per-step param all-gather and
  this rule MUST flag it (asserted in tests; runnable by hand via
  ``BIGDL_TPU_BUDGET_MISSPEC=1 python -m bigdl_tpu.analysis
  --budget-only --select hlo-reshard`` — must FAIL).
* ``hlo-flops-parity`` — per-device FLOPs vs the same model's
  dp-baseline probe stays under the entry's declared parity bound
  (perfectly sharded compute is ≈1.0×; silently replicated compute
  shows up as the shard factor).
* ``hlo-budget-memory`` — argument+temp HBM watermark per composition
  vs budget, and donation coverage must not shrink.
* ``budget-justification`` / ``budget-stale`` — every entry carries a
  hand-written justification (empty = error, gate stays red after
  ``--update-budget`` until reviewed); an entry matching no probe is a
  staleness warning.

Probe compiles are cached under ``$BIGDL_TPU_BUDGET_CACHE`` (default
``/tmp/bigdl_tpu_hlo_budget``) keyed by (probe, jax version, hash of
every ``bigdl_tpu`` source file), so ``scripts/lint.sh --budget``
re-lowers the matrix only when the tree changed; ``--no-cache`` is the
escape hatch.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from bigdl_tpu.analysis.findings import Finding

__all__ = ["BUDGET_RULES", "PROBES", "default_budget_path",
           "load_budget", "write_budget", "probe_matrix",
           "run_budget_passes", "update_budget", "tree_fingerprint"]

BUDGET_RULES = ("hlo-budget-bytes", "hlo-reshard", "hlo-flops-parity",
                "hlo-budget-memory", "budget-justification",
                "budget-stale")

_BUDGET_VERSION = 1
_N_DEVICES = 8

# check defaults, overridable per budget entry
_BYTE_TOLERANCE = 0.05       # relative drift allowed on a byte bucket
_BYTE_FLOOR = 512.0          # buckets under this never gate (scalars)
_RESHARD_FLOOR = 2048.0      # unpredicted-collective size threshold
_PLAN_SLACK = 2.0            # measured grad sync <= slack × analytic
_MEMORY_TOLERANCE = 0.25     # watermark drift allowed
_PARITY_BOUND = 1.3          # default per-device flops vs dp baseline

# gradient-sync opcodes the analytic plan speaks for (the plan check
# compares these, per batch axis, against grad_allreduce_bytes)
_SYNC_OPS = ("all-reduce",)


def default_budget_path() -> str:
    from bigdl_tpu.analysis.astutil import repo_root
    return os.path.join(repo_root(), "scripts", "parallel_budget.json")


# ---------------------------------------------------------------------------
# budget file (same shape discipline as scripts/graftlint_baseline.json)
# ---------------------------------------------------------------------------

def load_budget(path: Optional[str] = None) -> List[Dict]:
    """The budget entries ([] when the file doesn't exist yet).
    Raises ValueError on a malformed file — a broken budget must not
    silently gate nothing."""
    path = path or default_budget_path()
    if not os.path.isfile(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != _BUDGET_VERSION \
            or not isinstance(doc.get("entries"), list):
        raise ValueError(
            f"{path}: not a parallel-budget file "
            f"(need {{version: {_BUDGET_VERSION}, entries: [...]}})")
    for e in doc["entries"]:
        missing = {"probe", "collective_bytes"} - set(e)
        if missing:
            raise ValueError(
                f"{path}: budget entry {e.get('probe', e)!r} missing "
                f"{sorted(missing)}")
    return doc["entries"]


def write_budget(entries: List[Dict], path: Optional[str] = None) -> str:
    path = path or default_budget_path()
    doc = {"version": _BUDGET_VERSION,
           "entries": sorted(entries, key=lambda e: e["probe"])}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


# ---------------------------------------------------------------------------
# probe catalog
# ---------------------------------------------------------------------------

class ProbeSpec(NamedTuple):
    """One (model, composition) probe: how to lower it and what its
    declared axes predict."""
    name: str                 # "<model>/<composition>"
    model: str
    composition: str
    build: Callable[[], Dict]  # -> {"compiled", "mesh", "plan_bytes",
    #                               "param_bytes"}
    # axis -> opcodes the composition's plan predicts on that axis;
    # anything else above the reshard floor is a reshard finding
    expected: Dict[str, Tuple[str, ...]]
    flops_baseline: Optional[str] = None   # probe name of dp baseline
    plan_check: bool = False  # compare sync bytes vs grad_allreduce_bytes
    negative: bool = False    # failure-mode seam: reshard check only


def _sum_param_nbytes(model) -> int:
    import numpy as np

    from bigdl_tpu.core.module import Module, ModuleList
    total = 0

    def rec(obj):
        nonlocal total
        if isinstance(obj, Module):
            for p in obj._params.values():
                total += int(np.prod(p.shape)) * np.dtype(p.dtype).itemsize
            for m in obj._modules.values():
                rec(m)
        elif isinstance(obj, ModuleList):
            for m in obj._items:
                rec(m)

    rec(model)
    return total


def _optimizer_probe(make_model, sample_shape, make_batch, axes=None,
                     rules=None, criterion=None, sample_dtype="float32",
                     hierarchical=False, wire=None, plan=None,
                     target_dtype="int64") -> Dict:
    """Lower the training step the Optimizer would dispatch for this
    (model, mesh, rules) triple — the same ``compile_step`` hook the
    comm tooling reads.  ``plan`` routes through
    ``Optimizer.set_partition_plan`` instead of raw ``set_mesh``: the
    ONE lowering path every composition shares, sp/ep/pp included —
    there is no direct-jit side door left in this catalog."""
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer, SGD
    from bigdl_tpu.parallel.mesh import MeshConfig
    from bigdl_tpu.parallel.sharding import grad_allreduce_bytes

    model = make_model()
    nested = (isinstance(sample_shape, tuple)
              and isinstance(sample_shape[0], tuple))
    target = np.zeros(sample_shape[1], target_dtype) if nested else 1
    feat_shape = sample_shape[0] if nested else sample_shape
    opt = (Optimizer(model,
                     [Sample(np.zeros(feat_shape, sample_dtype), target)],
                     criterion or nn.ClassNLLCriterion(), batch_size=16)
           .set_optim_method(SGD(0.1)))
    if plan is not None:
        opt.set_partition_plan(plan)
    else:
        opt.set_mesh(MeshConfig(**axes), rules)
    if hierarchical:
        opt.set_gradient_sync(hierarchical=True, wire_dtype=wire)
    compiled = opt.compile_step(make_batch())
    mesh = opt.mesh_config.build()
    plan_bytes = None
    if not hierarchical:
        try:
            plan_bytes = grad_allreduce_bytes(
                model, mesh,
                rules if rules is not None else opt.sharding_rules,
            )["bytes_per_step"]
        except Exception:
            plan_bytes = None
    return {"compiled": compiled, "mesh": mesh, "plan_bytes": plan_bytes,
            "param_bytes": _sum_param_nbytes(model)}


def _partition_plan(**kw):
    from bigdl_tpu.parallel.plan import PartitionPlan
    return PartitionPlan(**kw)


# -- model builders ---------------------------------------------------------

def _cnn():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils import set_seed
    set_seed(7)
    return nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, pad_w=1, pad_h=1), nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2), nn.Reshape((4 * 4 * 8,)),
        nn.Linear(4 * 4 * 8, 64), nn.ReLU(), nn.Linear(64, 10),
        nn.LogSoftMax())


def _cnn_batch():
    import numpy as np

    from bigdl_tpu.dataset.dataset import MiniBatch
    rng = np.random.default_rng(5)
    return MiniBatch(rng.normal(size=(16, 8, 8, 3)).astype(np.float32),
                     rng.integers(1, 11, size=(16,)).astype(np.int64))


def _mlp():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils import set_seed
    set_seed(99)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10),
                         nn.LogSoftMax())


def _mlp_batch():
    import numpy as np

    from bigdl_tpu.dataset.dataset import MiniBatch
    rng = np.random.default_rng(5)
    return MiniBatch(rng.normal(size=(16, 16)).astype(np.float32),
                     rng.integers(1, 11, size=(16,)).astype(np.int64))


def _lm():
    from bigdl_tpu.models import transformer_lm
    from bigdl_tpu.utils import set_seed
    set_seed(31)
    return transformer_lm(vocab_size=30, hidden_size=16, num_layers=2,
                          num_heads=2, filter_size=32, max_len=32)


def _lm_batch():
    import numpy as np

    from bigdl_tpu.dataset.dataset import MiniBatch
    rng = np.random.default_rng(9)
    return MiniBatch(rng.integers(1, 31, size=(16, 32)).astype(np.int32),
                     rng.integers(1, 31, size=(16, 32)).astype(np.int64))


def _lm_criterion():
    import bigdl_tpu.nn as nn
    return nn.TimeDistributedCriterion(nn.CrossEntropyCriterion(),
                                       dimension=2)


def _lm_tp_rules(fsdp=False):
    from bigdl_tpu.parallel.sharding import tensor_parallel_rules
    return tensor_parallel_rules(
        column=[r"q_layer", r"k_layer", r"v_layer", r"filter_layer"],
        row=[r"output_layer", r"out_layer"], fsdp=fsdp)


def _lm_probe(axes=None, rules=None, plan=None) -> Dict:
    return _optimizer_probe(
        _lm, ((32,), (32,)), _lm_batch, axes, rules,
        criterion=_lm_criterion(), sample_dtype="int32", plan=plan)


def _misspec_probe() -> Dict:
    """THE negative leg: every parameter sharded over the batch axis by
    rule while the composition declares pure dp (replicated params) —
    GSPMD must insert a full-parameter all-gather every step, exactly
    the silent reshard this gate exists to catch."""
    from bigdl_tpu.parallel.sharding import ShardingRules, fsdp_spec
    bad = ShardingRules(
        [(r".*", lambda shape, mesh: fsdp_spec(tuple(shape), mesh,
                                               axis="data"))])
    return _optimizer_probe(_cnn, (8, 8, 3), _cnn_batch, {"data": 8}, bad)


def _pipe():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.parallel import Pipeline
    from bigdl_tpu.utils import set_seed
    set_seed(13)
    return Pipeline([nn.TransformerEncoderLayer(16, 2, 32)
                     for _ in range(4)])


def _pipe_batch():
    import numpy as np

    from bigdl_tpu.dataset.dataset import MiniBatch
    rng = np.random.default_rng(0)
    return MiniBatch(rng.normal(size=(8, 6, 16)).astype(np.float32),
                     rng.normal(size=(8, 6, 16)).astype(np.float32))


def _pipe_probe(plan) -> Dict:
    import bigdl_tpu.nn as nn
    return _optimizer_probe(
        _pipe, ((6, 16), (6, 16)), _pipe_batch, plan=plan,
        criterion=nn.MSECriterion(), target_dtype="float32")


def _moe():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.moe import MoE
    from bigdl_tpu.utils import set_seed
    set_seed(12)
    return MoE(16, [nn.FeedForwardNetwork(16, 32) for _ in range(8)],
               top_k=2)


def _moe_batch():
    import numpy as np

    from bigdl_tpu.dataset.dataset import MiniBatch
    rng = np.random.default_rng(0)
    return MiniBatch(rng.normal(size=(16, 8, 16)).astype(np.float32),
                     rng.normal(size=(16, 8, 16)).astype(np.float32))


def _moe_probe(plan) -> Dict:
    import bigdl_tpu.nn as nn
    return _optimizer_probe(
        _moe, ((8, 16), (8, 16)), _moe_batch, plan=plan,
        criterion=nn.MSECriterion(), target_dtype="float32")


def _wd():
    from bigdl_tpu.models import WideAndDeep
    from bigdl_tpu.utils import set_seed
    set_seed(17)
    return WideAndDeep(64, 32, embed_dim=8, mlp_dims=(16,))


def _wd_batch():
    import numpy as np

    from bigdl_tpu.dataset.dataset import MiniBatch
    rng = np.random.default_rng(3)
    pairs = np.stack([rng.integers(1, 65, size=16),
                      rng.integers(1, 33, size=16)],
                     axis=1).astype(np.int32)
    return MiniBatch(pairs,
                     rng.integers(0, 2, size=(16, 1)).astype(np.float32))


def _wd_probe(sharded: bool) -> Dict:
    """Lower the wide-and-deep training step: pure dp (tables
    replicated, dense-gradient all-reduce — the FLOPs baseline), or
    the hybrid composition ``configure_hybrid`` wires (tables
    row-sharded over data, lookups as a2a, table gradients staying
    per-shard — the budget entry pins that the a2a ids+vectors bytes
    are ALL the tables put on the wire)."""
    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset.dataset import Sample
    from bigdl_tpu.optim import Optimizer, SGD
    from bigdl_tpu.parallel.mesh import MeshConfig
    from bigdl_tpu.parallel.sharding import ShardingRules

    model = _wd()
    opt = (Optimizer(model,
                     [Sample(np.ones((2,), np.int32),
                             np.zeros((1,), np.float32))],
                     nn.BCECriterion(), batch_size=16)
           .set_optim_method(SGD(0.1)))
    if sharded:
        from bigdl_tpu.embedding import configure_hybrid
        configure_hybrid(opt, axes={"data": _N_DEVICES})
    else:
        opt.set_mesh(MeshConfig(data=_N_DEVICES), ShardingRules())
    compiled = opt.compile_step(_wd_batch())
    return {"compiled": compiled, "mesh": opt.mesh_config.build(),
            "plan_bytes": None, "param_bytes": _sum_param_nbytes(model)}


def _gen_probe(program: str) -> Dict:
    """Lower a serving slot-pool program (single device): the chunked
    KV-carry-in prefill or the prefix-cache KV copy.  No collectives
    are legitimate in either — expected={} makes any collective above
    the floor a reshard finding — and the budget entry pins their
    donation coverage (the pool must update in place, never copy
    S x layers x max_len of K/V per call)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from bigdl_tpu.models import transformer_lm
    from bigdl_tpu.serving.generation import SlotPool
    from bigdl_tpu.utils import set_seed

    set_seed(21)
    lm = transformer_lm(vocab_size=30, hidden_size=16, num_layers=2,
                        num_heads=2, filter_size=32,
                        max_len=32).eval_mode()
    pool = SlotPool(lm, slots=2)
    compiled = (pool.chunk_prefill_compiled(8)
                if program == "chunk_prefill"
                else pool.kv_copy_compiled(8))
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
    return {"compiled": compiled, "mesh": mesh, "plan_bytes": None,
            "param_bytes": None}


def _build_probes() -> Dict[str, ProbeSpec]:
    from bigdl_tpu.parallel.sharding import ShardingRules
    # what each composition legitimately puts on each axis.  Tight for
    # dp/tp (any extra op above the floor = reshard); broad for the
    # fsdp families, whose param gathers — and, on the conv net, the
    # involuntary-remat reshuffles XLA warns about at compile time —
    # are part of the (budget-pinned) contract.
    DP = ("all-reduce",)
    FSDP = ("all-reduce", "all-gather", "reduce-scatter",
            "collective-permute", "all-to-all")
    specs = [
        # -- cnn (conv+MLP; the MULTICHIP dryrun model) ---------------------
        ProbeSpec(
            "cnn/dp", "cnn", "dp",
            lambda: _optimizer_probe(_cnn, (8, 8, 3), _cnn_batch,
                                     {"data": _N_DEVICES},
                                     ShardingRules()),
            expected={"data": DP}, plan_check=True),
        ProbeSpec(
            "cnn/fsdp", "cnn", "fsdp",
            lambda: _optimizer_probe(_cnn, (8, 8, 3), _cnn_batch,
                                     {"fsdp": _N_DEVICES},
                                     ShardingRules(fsdp=True)),
            expected={"fsdp": FSDP}, flops_baseline="cnn/dp"),
        ProbeSpec(
            "cnn/dp_fsdp", "cnn", "dp_fsdp",
            lambda: _optimizer_probe(_cnn, (8, 8, 3), _cnn_batch,
                                     {"data": 4, "fsdp": 2},
                                     ShardingRules(fsdp=True)),
            expected={"data": FSDP, "fsdp": FSDP},
            flops_baseline="cnn/dp"),
        ProbeSpec(
            "cnn/dcn_dp", "cnn", "dcn_dp",
            lambda: _optimizer_probe(_cnn, (8, 8, 3), _cnn_batch,
                                     {"dcn": 2, "data": -1},
                                     ShardingRules()),
            expected={"dcn": DP, "data": DP},
            flops_baseline="cnn/dp", plan_check=True),
        # -- mlp (the PR-8/PR-9 probe model: the dcn sync envelope) ---------
        ProbeSpec(
            "mlp/dp", "mlp", "dp",
            lambda: _optimizer_probe(_mlp, (16,), _mlp_batch,
                                     {"data": _N_DEVICES},
                                     ShardingRules()),
            expected={"data": DP}, plan_check=True),
        ProbeSpec(
            "mlp/dcn_dp", "mlp", "dcn_dp",
            lambda: _optimizer_probe(_mlp, (16,), _mlp_batch,
                                     {"dcn": 2, "data": -1},
                                     ShardingRules()),
            expected={"dcn": DP, "data": DP},
            flops_baseline="mlp/dp", plan_check=True),
        ProbeSpec(
            "mlp/dcn_hier_fp32", "mlp", "dcn_hier_fp32",
            lambda: _optimizer_probe(_mlp, (16,), _mlp_batch,
                                     {"dcn": 2, "data": -1},
                                     ShardingRules(), hierarchical=True),
            expected={"dcn": ("all-reduce",),
                      "data": ("reduce-scatter", "all-gather",
                               "all-reduce")},
            flops_baseline="mlp/dp"),
        ProbeSpec(
            "mlp/dcn_hier_bf16", "mlp", "dcn_hier_bf16",
            lambda: _optimizer_probe(_mlp, (16,), _mlp_batch,
                                     {"dcn": 2, "data": -1},
                                     ShardingRules(), hierarchical=True,
                                     wire="bf16"),
            expected={"dcn": ("all-to-all", "all-gather", "all-reduce"),
                      "data": ("reduce-scatter", "all-gather",
                               "all-reduce")},
            flops_baseline="mlp/dp"),
        ProbeSpec(
            "mlp/dcn_hier_int8", "mlp", "dcn_hier_int8",
            lambda: _optimizer_probe(_mlp, (16,), _mlp_batch,
                                     {"dcn": 2, "data": -1},
                                     ShardingRules(), hierarchical=True,
                                     wire="int8"),
            expected={"dcn": ("all-to-all", "all-gather", "all-reduce"),
                      "data": ("reduce-scatter", "all-gather",
                               "all-reduce")},
            flops_baseline="mlp/dp"),
        # -- transformer_lm -------------------------------------------------
        ProbeSpec(
            "transformer_lm/dp", "transformer_lm", "dp",
            lambda: _lm_probe({"data": _N_DEVICES}, ShardingRules()),
            expected={"data": DP}, plan_check=True),
        ProbeSpec(
            "transformer_lm/fsdp", "transformer_lm", "fsdp",
            lambda: _lm_probe({"fsdp": _N_DEVICES},
                              ShardingRules(fsdp=True)),
            expected={"fsdp": FSDP},
            flops_baseline="transformer_lm/dp"),
        ProbeSpec(
            "transformer_lm/dp_tp", "transformer_lm", "dp_tp",
            lambda: _lm_probe({"data": 4, "model": 2}, _lm_tp_rules()),
            expected={"data": DP, "model": DP},
            flops_baseline="transformer_lm/dp"),
        ProbeSpec(
            # 3-way: model axis gets the FSDP op set too — with
            # fsdp=True rules in play XLA legitimately stages the
            # unmatched leaves' gathers across the model axis as well
            # (pinned byte-for-byte by the budget entry)
            "transformer_lm/dp_fsdp_tp", "transformer_lm", "dp_fsdp_tp",
            lambda: _lm_probe({"data": 2, "fsdp": 2, "model": 2},
                              _lm_tp_rules(fsdp=True)),
            expected={"data": FSDP, "fsdp": FSDP, "model": FSDP},
            flops_baseline="transformer_lm/dp"),
        ProbeSpec(
            "transformer_lm/sp", "transformer_lm", "sp",
            lambda: _lm_probe(plan=_partition_plan(sp=_N_DEVICES)),
            expected={"seq": ("collective-permute", "all-gather",
                              "all-reduce")}),
        ProbeSpec(
            # the 1F1B schedule: fwd+loss+bwd run inside the pipeline
            # shard_map, gradients come back stacked per stage
            "transformer_lm/pp", "transformer_lm", "pp",
            lambda: _pipe_probe(_partition_plan(pp=4,
                                                pp_schedule="1f1b")),
            expected={"pipe": ("collective-permute", "all-reduce",
                               "all-gather")}),
        ProbeSpec(
            # 3-way through ONE plan: dp shards the batch, tp shards
            # parameter storage (stage compute inside the gpipe
            # shard_map is replicated over 'model' — the all-gathers
            # that re-assemble the stacked stage params are the pinned
            # contract), pp rings the microbatches
            "transformer_lm/dp_tp_pp", "transformer_lm", "dp_tp_pp",
            lambda: _lm_probe(plan=_partition_plan(dp=2, tp=2, pp=2)),
            expected={"data": ("all-reduce", "all-gather",
                               "collective-permute"),
                      "model": ("all-reduce", "all-gather",
                                "collective-permute"),
                      "pipe": ("collective-permute", "all-reduce",
                               "all-gather")},
            flops_baseline="transformer_lm/dp"),
        ProbeSpec(
            # fsdp×sp: ZeRO-3 param gathers on 'fsdp', ring attention
            # on 'seq' — the long-context + sharded-state composition
            "transformer_lm/fsdp_sp", "transformer_lm", "fsdp_sp",
            lambda: _lm_probe(plan=_partition_plan(fsdp=2, sp=4)),
            expected={"fsdp": FSDP,
                      "seq": ("collective-permute", "all-gather",
                              "all-reduce")},
            flops_baseline="transformer_lm/dp"),
        # -- moe ------------------------------------------------------------
        ProbeSpec(
            "moe/ep", "moe", "ep",
            lambda: _moe_probe(_partition_plan(
                ep=_N_DEVICES, ep_capacity_factor=2.0)),
            expected={"expert": ("all-to-all", "all-reduce",
                                 "collective-permute", "all-gather")}),
        ProbeSpec(
            "moe/ep_psum", "moe", "ep_psum",
            lambda: _moe_probe(_partition_plan(ep=4)),
            expected={"expert": ("all-reduce", "collective-permute",
                                 "all-gather")}),
        # -- wide_deep (sharded-embedding hybrid, embedding/) ---------------
        ProbeSpec(
            "wide_deep/dp", "wide_deep", "dp",
            lambda: _wd_probe(False),
            expected={"data": DP}),
        ProbeSpec(
            # hybrid: a2a carries ids out and vectors back per lookup;
            # all-reduce carries ONLY the dense tower + loss — a dense
            # (rows x dim) table all-reduce appearing here would blow
            # the pinned byte envelope (the sparsity regression gate)
            "wide_deep/dp_emb8", "wide_deep", "dp_emb8",
            lambda: _wd_probe(True),
            expected={"data": ("all-reduce", "all-to-all")},
            flops_baseline="wide_deep/dp"),
        # -- generation serving (single-device slot-pool programs) ----------
        ProbeSpec(
            "generation/chunk_prefill", "generation", "chunk_prefill",
            lambda: _gen_probe("chunk_prefill"), expected={}),
        ProbeSpec(
            "generation/kv_copy", "generation", "kv_copy",
            lambda: _gen_probe("kv_copy"), expected={}),
    ]
    if os.environ.get("BIGDL_TPU_BUDGET_MISSPEC"):
        specs.append(ProbeSpec(
            "cnn/misspec_dp", "cnn", "misspec_dp", _misspec_probe,
            expected={"data": DP}, plan_check=True, negative=True))
    return {s.name: s for s in specs}


def PROBES() -> Dict[str, ProbeSpec]:
    """The probe catalog (built lazily: probe builders import jax)."""
    return _build_probes()


# ---------------------------------------------------------------------------
# metric extraction + the /tmp compile cache
# ---------------------------------------------------------------------------

def tree_fingerprint() -> str:
    """sha256 over (jax version, every bigdl_tpu source file) — the
    cache key that makes 'unchanged tree' precise.  Any source edit
    invalidates every probe: over-invalidation costs one re-lower,
    under-invalidation would let a stale matrix green-light a
    regression."""
    import jax

    from bigdl_tpu.analysis.astutil import repo_root
    h = hashlib.sha256(jax.__version__.encode())
    root = os.path.join(repo_root(), "bigdl_tpu")
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames) if d != "__pycache__"]
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            h.update(os.path.relpath(path, root).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()[:24]


def _cache_dir() -> str:
    override = os.environ.get("BIGDL_TPU_BUDGET_CACHE")
    if override:
        return override
    # uid-scoped: /tmp is shared, and a fatal ship gate must not trust
    # metrics another local user could pre-seed under a fixed path
    import tempfile
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(),
                        f"bigdl_tpu_hlo_budget-uid{uid}")


def _cache_trusted(path: str) -> bool:
    """Only read cache entries we own (same shared-/tmp concern)."""
    if not hasattr(os, "getuid"):
        return True
    try:
        return os.stat(path).st_uid == os.getuid()
    except OSError:
        return False


def _extract_metrics(spec: ProbeSpec, build: Dict) -> Dict:
    from bigdl_tpu.parallel.mesh import axis_coord_maps
    from bigdl_tpu.utils.xla_cost import (
        collective_hlo_bytes, compiled_flops, per_axis_hlo_bytes,
    )
    compiled, mesh = build["compiled"], build["mesh"]
    matrix = per_axis_hlo_bytes(compiled, axis_coord_maps(mesh))
    total = collective_hlo_bytes(compiled)
    out = {
        "probe": spec.name,
        "model": spec.model,
        "composition": spec.composition,
        "mesh_axes": {a: int(mesh.shape[a]) for a in mesh.axis_names},
        "collective_bytes": matrix if matrix is not None else None,
        "collective_total": None if total is None else total["total"],
        "flops": compiled_flops(compiled),
        "plan_bytes": build.get("plan_bytes"),
        "param_bytes": build.get("param_bytes"),
    }
    try:
        text = compiled.as_text()
    except Exception:
        text = ""
    from bigdl_tpu.analysis.hlo_lint import donated_alias_bytes
    don, n_don = donated_alias_bytes(text) if text else (0.0, 0)
    out["donated_bytes"] = don
    out["donated_params"] = n_don
    try:
        ma = compiled.memory_analysis()
        out["argument_bytes"] = int(ma.argument_size_in_bytes)
        out["temp_bytes"] = int(ma.temp_size_in_bytes)
        out["output_bytes"] = int(ma.output_size_in_bytes)
    except Exception:
        out["argument_bytes"] = out["temp_bytes"] = None
        out["output_bytes"] = None
    return out


def probe_matrix(specs: Optional[Dict[str, ProbeSpec]] = None,
                 no_cache: bool = False,
                 fingerprint: Optional[str] = None) -> Dict[str, Dict]:
    """Compile (or cache-load) every probe and return
    ``{probe_name: metrics}``.  A probe whose build raises contributes
    a ``{"error": ...}`` record — the budget pass turns it into a
    finding instead of killing the whole gate."""
    specs = specs or PROBES()
    fp = fingerprint or tree_fingerprint()
    cdir = os.path.join(_cache_dir(), fp)
    out: Dict[str, Dict] = {}
    backend_ready = False
    for name in sorted(specs):
        spec = specs[name]
        cpath = os.path.join(cdir, name.replace("/", "__") + ".json")
        if not no_cache and os.path.isfile(cpath) \
                and _cache_trusted(cpath):
            try:
                with open(cpath, "r", encoding="utf-8") as f:
                    out[name] = json.load(f)
                continue
            except Exception:
                pass  # corrupt cache entry: recompute
        if not backend_ready:
            # first cache miss: the probes need the 8-virtual-device
            # backend regardless of how the caller reached here
            from bigdl_tpu.analysis.hlo_lint import ensure_backend
            ensure_backend()
            backend_ready = True
        try:
            metrics = _extract_metrics(spec, spec.build())
        except Exception as e:  # surfaced as a finding, never a crash
            out[name] = {"probe": name, "error": f"{type(e).__name__}: {e}"}
            continue
        out[name] = metrics
        try:
            os.makedirs(cdir, mode=0o700, exist_ok=True)
            tmp = cpath + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(metrics, f, indent=2, sort_keys=True)
            os.replace(tmp, cpath)
        except OSError:
            pass  # cache is an optimization, not a requirement
    return out


# ---------------------------------------------------------------------------
# the checks
# ---------------------------------------------------------------------------

def _finding(rule: str, severity: str, probe: str, message: str,
             code: str = "") -> Finding:
    """Budget findings anchor on the budget file; identity rides
    (rule, probe, code) so entries survive value edits."""
    return Finding(rule, severity, "scripts/parallel_budget.json", 0,
                   message, scope=probe, code=code or rule)


def _check_bytes(spec: ProbeSpec, metrics: Dict, entry: Optional[Dict],
                 out: List[Finding]) -> None:
    if entry is None:
        out.append(_finding(
            "hlo-budget-bytes", "error", spec.name,
            f"no budget entry for probe {spec.name} — every supported "
            f"composition must carry a justified budget (run "
            f"--update-budget, then justify the new entry)"))
        return
    measured = metrics.get("collective_bytes") or {}
    budgeted = entry.get("collective_bytes") or {}
    tol = float(entry.get("tolerance", _BYTE_TOLERANCE))
    floor = float(entry.get("byte_floor", _BYTE_FLOOR))
    for key in sorted(set(measured) | set(budgeted)):
        m = float(measured.get(key, 0.0))
        b = float(budgeted.get(key, 0.0))
        if max(m, b) <= floor:
            continue
        drift = abs(m - b) / max(b, 1.0)
        if drift > tol:
            direction = "grew" if m > b else "shrank"
            out.append(_finding(
                "hlo-budget-bytes", "error", spec.name,
                f"{spec.name}: {{{key}}} {direction} to {m:.0f} B vs budget "
                f"{b:.0f} B ({drift:+.1%} vs tolerance {tol:.0%}) — "
                f"the {spec.composition} composition's communication "
                f"contract moved; re-measure, THEN re-justify the "
                f"entry if the change is intended", code=key))
    out.append(_finding(
        "hlo-budget-bytes", "info", spec.name,
        f"{spec.name}: matrix {json.dumps(measured, sort_keys=True)} "
        f"(budget tolerance {tol:.0%})", code="matrix"))


def _check_reshard(spec: ProbeSpec, metrics: Dict, entry: Optional[Dict],
                   out: List[Finding]) -> None:
    measured = metrics.get("collective_bytes") or {}
    floor = float((entry or {}).get("reshard_floor_bytes",
                                    _RESHARD_FLOOR))
    for key in sorted(measured):
        nbytes = float(measured[key])
        if nbytes <= floor:
            continue  # scalar losses / counters span every axis
        op, _, axis = key.partition("|")
        allowed = spec.expected.get(axis)
        if allowed is None or op not in allowed:
            out.append(_finding(
                "hlo-reshard", "error", spec.name,
                f"{spec.name}: {nbytes:.0f} B of {op} over axis '{axis}' that the "
                f"{spec.composition} composition's declared plan does "
                f"not predict (expected on '{axis}': "
                f"{sorted(allowed) if allowed else 'nothing'}) — a "
                f"GSPMD-inserted reshard (mis-specified partition "
                f"spec: the classic silent full-parameter all-gather)",
                code=key))
    # the analytic tie-in: measured gradient sync vs the plan's floor
    plan = metrics.get("plan_bytes")
    if spec.plan_check and plan:
        from bigdl_tpu.parallel.mesh import BATCH_AXES
        slack = float((entry or {}).get("plan_slack", _PLAN_SLACK))
        sync = sum(float(v) for k, v in measured.items()
                   if k.partition("|")[0] in _SYNC_OPS
                   and k.partition("|")[2] in BATCH_AXES)
        # a flat all-reduce on a multi-axis batch mesh charges every
        # axis it spans; compare against the plan scaled the same way
        n_axes = max(1, sum(1 for a in BATCH_AXES
                            if metrics["mesh_axes"].get(a, 1) > 1))
        if sync > slack * plan * n_axes + floor:
            out.append(_finding(
                "hlo-reshard", "error", spec.name,
                f"{spec.name}: gradient-sync bytes {sync:.0f} exceed "
                f"{slack:.1f}x the analytic plan "
                f"({plan:.0f} B/axis x {n_axes} axes, "
                f"grad_allreduce_bytes) — the step syncs more than the "
                f"parameters it owns", code="plan"))


def _check_flops(spec: ProbeSpec, metrics: Dict, entry: Optional[Dict],
                 matrix: Dict[str, Dict], out: List[Finding]) -> None:
    if spec.flops_baseline is None:
        return
    base = matrix.get(spec.flops_baseline, {})
    flops, base_flops = metrics.get("flops"), base.get("flops")
    if not flops or not base_flops:
        out.append(_finding(
            "hlo-flops-parity", "warning", spec.name,
            f"{spec.name}: flops unavailable (probe {flops!r}, baseline "
            f"{spec.flops_baseline} {base_flops!r}) — parity not "
            f"provable"))
        return
    ratio = flops / base_flops
    bound = float((entry or {}).get("flops_parity_bound", _PARITY_BOUND))
    if ratio > bound:
        out.append(_finding(
            "hlo-flops-parity", "error", spec.name,
            f"{spec.name}: per-device FLOPs are {ratio:.2f}x the "
            f"{spec.flops_baseline} baseline (entry bound "
            f"{bound:.2f}x) — compute is being replicated instead of "
            f"sharded (a partition spec matched nothing, or an axis "
            f"stopped dividing)", code="parity"))
    else:
        out.append(_finding(
            "hlo-flops-parity", "info", spec.name,
            f"{spec.name}: per-device FLOPs {ratio:.2f}x vs "
            f"{spec.flops_baseline} "
            f"(bound {bound:.2f}x)", code="parity"))


def _check_memory(spec: ProbeSpec, metrics: Dict, entry: Optional[Dict],
                  out: List[Finding]) -> None:
    if entry is None:
        return  # hlo-budget-bytes already demands the entry
    arg, temp = metrics.get("argument_bytes"), metrics.get("temp_bytes")
    if arg is None or temp is None:
        out.append(_finding(
            "hlo-budget-memory", "warning", spec.name,
            f"{spec.name}: memory analysis unavailable on this backend — the HBM "
            "watermark cannot be checked"))
        return
    watermark = arg + temp
    b_arg = entry.get("argument_bytes")
    b_temp = entry.get("temp_bytes")
    tol = float(entry.get("memory_tolerance", _MEMORY_TOLERANCE))
    if b_arg is not None and b_temp is not None:
        budget_mark = float(b_arg) + float(b_temp)
        drift = abs(watermark - budget_mark) / max(budget_mark, 1.0)
        if drift > tol:
            out.append(_finding(
                "hlo-budget-memory", "error", spec.name,
                f"{spec.name}: param+temp HBM watermark {watermark} B vs budget "
                f"{budget_mark:.0f} B ({drift:+.1%} vs tolerance "
                f"{tol:.0%}) — the composition's memory footprint "
                f"moved", code="watermark"))
    don, b_don = metrics.get("donated_bytes"), entry.get("donated_bytes")
    if b_don is not None and float(don or 0.0) < float(b_don) * (1 - tol):
        out.append(_finding(
            "hlo-budget-memory", "error", spec.name,
            f"{spec.name}: donation coverage shrank to {don:.0f} B vs budget "
            f"{float(b_don):.0f} B — donated buffers no longer elide "
            f"the full-size copy", code="donation"))


def run_budget_passes(select=None, budget_path: Optional[str] = None,
                      no_cache: bool = False,
                      specs: Optional[Dict[str, ProbeSpec]] = None,
                      budget: Optional[List[Dict]] = None,
                      matrix: Optional[Dict[str, Dict]] = None) \
        -> List[Finding]:
    """Compile/cache-load the probe matrix and run every budget check
    (or the subset ``select`` names by rule id).  ``budget`` and
    ``matrix`` override the file/compiles for tests."""
    specs = specs or PROBES()
    if budget is None:
        budget = load_budget(budget_path)
    entries = {e["probe"]: e for e in budget}

    def on(rule):
        return select is None or rule in select

    # the four probe-level rules need compiled programs; the file-level
    # rules (justification/staleness) are pure JSON checks — a
    # `--select budget-stale` run must not pay the matrix lowering
    probe_rules = ("hlo-budget-bytes", "hlo-reshard",
                   "hlo-flops-parity", "hlo-budget-memory")
    need_matrix = any(on(r) for r in probe_rules)
    if matrix is None:
        matrix = (probe_matrix(specs, no_cache=no_cache)
                  if need_matrix else {})

    # probe failures must surface under a rule the caller SELECTED, or
    # a `--select hlo-reshard` negative leg whose probe failed to build
    # would pass vacuously while the report claims the rule ran
    fail_rule = ("hlo-budget-bytes" if on("hlo-budget-bytes")
                 else next((r for r in probe_rules if on(r)),
                           "hlo-budget-bytes"))

    findings: List[Finding] = []
    for name in (sorted(specs) if need_matrix else ()):
        spec, metrics = specs[name], matrix.get(name, {})
        if metrics.get("error"):
            findings.append(_finding(
                fail_rule, "error", name,
                f"{name}: probe failed to lower: {metrics['error']}"))
            continue
        if metrics.get("collective_bytes") is None:
            findings.append(_finding(
                fail_rule, "error", name,
                f"{name}: compiled module text unavailable — the byte matrix "
                "cannot be measured"))
            continue
        entry = entries.get(name)
        if spec.negative:
            # failure-mode seam: only the reshard detector applies (a
            # deliberately broken probe has no budget to conform to)
            if on("hlo-reshard"):
                _check_reshard(spec, metrics, entry, findings)
            continue
        if on("hlo-budget-bytes"):
            _check_bytes(spec, metrics, entry, findings)
        if on("hlo-reshard"):
            _check_reshard(spec, metrics, entry, findings)
        if on("hlo-flops-parity"):
            _check_flops(spec, metrics, entry, matrix, findings)
        if on("hlo-budget-memory"):
            _check_memory(spec, metrics, entry, findings)

    base_rel = "scripts/parallel_budget.json"
    for name in sorted(entries):
        e = entries[name]
        if name in specs and not specs[name].negative:
            if not str(e.get("justification", "")).strip() \
                    and on("budget-justification"):
                findings.append(Finding(
                    "budget-justification", "error", base_rel, 0,
                    f"budget entry {name} has no justification — every "
                    f"pinned number must say why it is what it is",
                    scope=name, code="justification"))
        elif on("budget-stale"):
            findings.append(Finding(
                "budget-stale", "warning", base_rel, 0,
                f"budget entry {name} matches no probe in the catalog "
                f"— the composition was removed or renamed; delete the "
                f"entry", scope=name, code="stale"))
    return findings


# ---------------------------------------------------------------------------
# --update-budget
# ---------------------------------------------------------------------------

_ENTRY_FIELDS = ("collective_bytes", "flops", "argument_bytes",
                 "temp_bytes", "donated_bytes")


def update_budget(budget_path: Optional[str] = None,
                  no_cache: bool = False,
                  specs: Optional[Dict[str, ProbeSpec]] = None,
                  matrix: Optional[Dict[str, Dict]] = None) \
        -> Tuple[str, int, int]:
    """Measure the matrix and merge it into the budget file: new
    probes append with EMPTY justifications (the gate stays red until
    each is hand-reviewed); drifted entries get their measured fields
    refreshed and their justification CLEARED — a number that moved
    needs its reviewed reason re-earned.  Pass ``matrix`` to reuse an
    already-measured matrix (the CLI shares one between the update and
    the verdict run).  Returns (path, n_added, n_refreshed)."""
    specs = specs or PROBES()
    entries = list(load_budget(budget_path))
    by_name = {e["probe"]: e for e in entries}
    if matrix is None:
        matrix = probe_matrix(specs, no_cache=no_cache)
    added = refreshed = 0
    for name in sorted(specs):
        spec, metrics = specs[name], matrix.get(name, {})
        if spec.negative or metrics.get("error") \
                or metrics.get("collective_bytes") is None:
            continue
        fresh = {f: metrics.get(f) for f in _ENTRY_FIELDS}
        e = by_name.get(name)
        if e is None:
            entry = dict(probe=name, tolerance=_BYTE_TOLERANCE,
                         justification="", **fresh)
            if spec.flops_baseline is not None:
                entry["flops_parity_bound"] = _PARITY_BOUND
            entries.append(entry)
            by_name[name] = entry
            added += 1
            continue
        probe_findings = run_budget_passes(
            select={"hlo-budget-bytes", "hlo-budget-memory",
                    "hlo-flops-parity"},
            specs={name: spec, **({spec.flops_baseline:
                                   specs[spec.flops_baseline]}
                                  if spec.flops_baseline in specs
                                  else {})},
            budget=entries, matrix=matrix)
        drifted = any(f.severity == "error" and f.scope == name
                      for f in probe_findings)
        if drifted:
            e.update(fresh)
            e["justification"] = ""
            refreshed += 1
    path = write_budget(entries, budget_path)
    return path, added, refreshed
