"""``bigdl_tpu.analysis`` — graftlint, the rule-based static-analysis
suite.

Eight PRs of review rounds kept re-finding the same machine-checkable
bug classes: spans stranded off the trace clock, unguarded shared-state
writes in the threaded tiers, raw collectives bypassing the accounting
wrappers, XLA silently widening the compressed dcn wire.  graftlint
turns each into a registered pass over whole-program invariants no
single test exercises:

* **AST passes** (no jax needed): ``trace-safety``,
  ``lock-discipline``, ``collective-discipline`` /
  ``collective-axis``, ``clock-discipline``, ``metrics-catalog``.
* **Compiled-HLO passes** (:mod:`bigdl_tpu.analysis.hlo_lint`, need a
  backend with >= 8 devices): cross-slice byte invariants, the
  narrow-dtype wire pin, donation elision, recompile determinism,
  host-callback census.

Run ``python -m bigdl_tpu.analysis`` (or ``scripts/lint.sh``); see
``docs/static_analysis.md`` for the rule catalog, suppression pragmas,
and the baseline policy.
"""

from bigdl_tpu.analysis.astutil import SourceTree, load_tree  # noqa: F401
from bigdl_tpu.analysis.findings import (  # noqa: F401
    Finding, counts_of, render_human, render_json,
)
from bigdl_tpu.analysis.registry import (  # noqa: F401
    get_passes, pass_names, register_pass,
)
from bigdl_tpu.analysis.suppress import (  # noqa: F401
    apply_suppressions, default_baseline_path, load_baseline,
    write_baseline,
)

__all__ = [
    "Finding", "SourceTree", "load_tree", "counts_of", "render_human",
    "render_json", "get_passes", "pass_names", "register_pass",
    "apply_suppressions", "default_baseline_path", "load_baseline",
    "write_baseline", "run_ast_passes",
]


def run_ast_passes(tree=None, select=None):
    """Run every registered AST pass over ``tree`` (default: the
    ``bigdl_tpu`` package) and return the raw findings, parse errors
    included — suppression is the caller's next step."""
    tree = tree or load_tree()
    findings = list(tree.parse_findings)
    for p in get_passes(kind="ast", select=select):
        findings.extend(p.fn(tree))
    return tree, findings
