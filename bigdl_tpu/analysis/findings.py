"""The :class:`Finding` record every graftlint pass emits.

A finding is one diagnosed site: rule id, severity, ``file:line``, a
message, and two identity fields — the enclosing ``scope`` (module /
``Class.method`` qualname) and the stripped source ``code`` line.  The
identity triple ``(rule, file, scope, code)`` is what the baseline file
matches on: line numbers shift whenever anything above them is edited,
so a baseline keyed on them would go stale on every unrelated diff,
while the scope+code pair survives reflows and stays reviewable (the
baseline entry quotes the exact code it excuses).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional

__all__ = ["Finding", "SEVERITIES", "render_human", "render_json",
           "counts_of"]

# ordered most → least severe; "error" fails the fatal lint, "warning"
# is advisory, "info" is reporting (per-program stats, counts)
SEVERITIES = ("error", "warning", "info")


class Finding:
    """One diagnosed site.  Plain object: thousands may be created on a
    whole-tree run."""

    __slots__ = ("rule", "severity", "file", "line", "message",
                 "scope", "code", "suppressed")

    def __init__(self, rule: str, severity: str, file: str, line: int,
                 message: str, scope: str = "", code: str = ""):
        if severity not in SEVERITIES:
            raise ValueError(f"severity {severity!r} not in {SEVERITIES}")
        self.rule = rule
        self.severity = severity
        self.file = file
        self.line = int(line)
        self.message = message
        self.scope = scope
        self.code = code
        # None = active; "pragma" / "baseline" once suppressed
        self.suppressed: Optional[str] = None

    def key(self) -> Dict[str, str]:
        """The baseline-matching identity (no line number — see module
        docstring)."""
        return {"rule": self.rule, "file": self.file,
                "scope": self.scope, "code": self.code}

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "severity": self.severity,
                "file": self.file, "line": self.line,
                "message": self.message, "scope": self.scope,
                "code": self.code, "suppressed": self.suppressed}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Finding({self.rule}, {self.severity}, "
                f"{self.file}:{self.line}, {self.message[:40]!r})")


def counts_of(findings: Iterable[Finding]) -> Dict[str, int]:
    out = {s: 0 for s in SEVERITIES}
    out["suppressed"] = 0
    for f in findings:
        if f.suppressed:
            out["suppressed"] += 1
        else:
            out[f.severity] += 1
    return out


def render_human(findings: List[Finding],
                 show_suppressed: bool = False) -> List[str]:
    """One ``graftlint: <sev>: file:line: [rule] message`` line per
    finding, errors first, then file order."""
    order = {s: i for i, s in enumerate(SEVERITIES)}
    lines = []
    for f in sorted(findings, key=lambda f: (order[f.severity], f.file,
                                             f.line, f.rule)):
        if f.suppressed and not show_suppressed:
            continue
        tag = (f" (suppressed: {f.suppressed})" if f.suppressed else "")
        lines.append(f"graftlint: {f.severity}: {f.file}:{f.line}: "
                     f"[{f.rule}] {f.message}{tag}")
    return lines


def render_json(findings: List[Finding],
                meta: Optional[Dict[str, Any]] = None) -> str:
    """The machine report (``ANALYSIS_r<N>.json``): counts + every
    finding including suppressed ones, so lint debt is a tracked
    trajectory, not just a pass/fail bit."""
    doc = {
        "schema": "graftlint_report",
        "version": 1,
        "counts": counts_of(findings),
        "findings": [f.to_dict() for f in findings],
    }
    if meta:
        doc.update(meta)
    return json.dumps(doc, indent=2, sort_keys=True)
