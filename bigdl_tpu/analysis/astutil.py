"""Shared AST infrastructure for the graftlint passes.

One parse of the package per run: :func:`load_tree` walks a source
root, parses every ``.py`` into a :class:`FileSource` (text, split
lines, AST, inline-pragma map, import tables), and the passes consume
the resulting :class:`SourceTree`.  Parsing failures become findings
(rule ``parse-error``) instead of crashing the run — a lint that dies
on the broken file it should be reporting is useless mid-incident.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from bigdl_tpu.analysis.findings import Finding

__all__ = ["FileSource", "SourceTree", "load_tree", "repo_root",
           "call_name", "call_attr_chain", "mesh_axes"]

# `# graftlint: disable=rule-a,rule-b -- optional reason`
_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable=([a-z0-9\-_,\s]+?)(?:\s*--.*)?$")


def repo_root() -> str:
    """The repository root (parent of the ``bigdl_tpu`` package)."""
    import bigdl_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(bigdl_tpu.__file__)))


class FileSource:
    """One parsed source file."""

    __slots__ = ("path", "rel", "module", "text", "lines", "tree",
                 "pragmas")

    def __init__(self, path: str, rel: str, module: str, text: str,
                 tree: Optional[ast.AST]):
        self.path = path
        self.rel = rel            # repo-relative, posix separators
        self.module = module      # dotted module name
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        # 1-based line -> rules disabled on that line
        self.pragmas: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = _PRAGMA_RE.search(line)
            if m:
                self.pragmas[i] = {
                    t.strip() for t in m.group(1).split(",") if t.strip()}

    def code_at(self, line: int) -> str:
        """The stripped source of a 1-based line ("" out of range)."""
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def pragma_disables(self, line: int, rule: str) -> bool:
        """True when ``rule`` is pragma-disabled for ``line`` — by a
        trailing comment on the line itself, or by a pragma anywhere in
        the contiguous block of comment-only lines directly above it
        (so a pragma's ``-- reason`` may wrap over several comment
        lines)."""
        check = [line]
        ln = line - 1
        while ln >= 1 and self.lines[ln - 1].lstrip().startswith("#"):
            check.append(ln)
            ln -= 1
        for ln in check:
            rules = self.pragmas.get(ln)
            if rules and (rule in rules or "all" in rules):
                return True
        return False


class SourceTree:
    """Every parsed file of one lint run, keyed by repo-relative
    path."""

    def __init__(self, root: str, repo: str):
        self.root = root          # the directory that was walked
        self.repo = repo          # repo root (paths are relative to it)
        self.files: Dict[str, FileSource] = {}
        self.parse_findings: List[Finding] = []

    def __iter__(self) -> Iterator[FileSource]:
        for rel in sorted(self.files):
            yield self.files[rel]

    def get(self, rel: str) -> Optional[FileSource]:
        return self.files.get(rel)

    def finding(self, rule: str, severity: str, src: FileSource,
                line: int, message: str, scope: str = "") -> Finding:
        """A finding anchored in ``src`` with the code line filled in
        (the baseline identity needs it)."""
        return Finding(rule, severity, src.rel, line, message,
                       scope=scope, code=src.code_at(line))


def _module_name(rel: str) -> str:
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("/", ".").replace("\\", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def load_tree(root: Optional[str] = None,
              repo: Optional[str] = None) -> SourceTree:
    """Parse every ``.py`` under ``root`` (default: the ``bigdl_tpu``
    package) into a :class:`SourceTree`."""
    repo = repo or repo_root()
    root = root or os.path.join(repo, "bigdl_tpu")
    tree = SourceTree(root, repo)
    for dirpath, dirnames, files in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__"]
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, repo).replace(os.sep, "/")
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
            try:
                parsed = ast.parse(text, filename=rel)
            except SyntaxError as e:
                src = FileSource(path, rel, _module_name(rel), text, None)
                tree.files[rel] = src
                tree.parse_findings.append(Finding(
                    "parse-error", "error", rel, e.lineno or 0,
                    f"cannot parse: {e.msg}"))
                continue
            tree.files[rel] = FileSource(path, rel, _module_name(rel),
                                         text, parsed)
    return tree


# ---------------------------------------------------------------------------
# call-site helpers
# ---------------------------------------------------------------------------

def call_name(node: ast.Call) -> str:
    """The last name segment of a call's callee (``f`` for both
    ``f(...)`` and ``a.b.f(...)``), "" when dynamic."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def call_attr_chain(node: ast.Call) -> Tuple[str, ...]:
    """The dotted callee as name segments: ``jax.lax.psum(...)`` ->
    ("jax", "lax", "psum").  Empty when the base is not a plain name
    chain (subscripts, calls)."""
    parts: List[str] = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
        return tuple(reversed(parts))
    return ()


def imports_of(mod_ast: ast.AST) -> Tuple[Dict[str, str],
                                          Dict[str, Tuple[str, str]]]:
    """(module-alias table, from-import table) for a module, walking
    EVERY import statement including function-local ones (a resolution
    over-approximation a lint is allowed)."""
    mod_alias: Dict[str, str] = {}
    from_import: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(mod_ast):
        if isinstance(node, ast.Import):
            for a in node.names:
                mod_alias[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                from_import[a.asname or a.name] = (node.module, a.name)
    return mod_alias, from_import


def mesh_axes(tree: SourceTree) -> Set[str]:
    """The canonical mesh axis names, read from the ``AXES`` tuple
    literal in ``parallel/mesh.py`` — by AST, so the AST passes never
    need a live jax import.  Falls back to the known set when the file
    moved (the collective-discipline pass then still works)."""
    src = tree.get("bigdl_tpu/parallel/mesh.py")
    if src is not None and src.tree is not None:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == "AXES"
                            for t in node.targets) \
                    and isinstance(node.value, ast.Tuple):
                names = {e.value for e in node.value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)}
                if names:
                    return names
    return {"dcn", "data", "fsdp", "model", "pipe", "seq", "expert"}
