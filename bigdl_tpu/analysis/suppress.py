"""Suppression machinery: inline pragmas + the reviewed baseline file.

Two ways to silence a finding, both leaving an audit trail:

* ``# graftlint: disable=<rule>[,rule...] -- reason`` on the flagged
  line (or the line directly above it) — for sites where the
  explanation belongs next to the code;
* a baseline entry in ``scripts/graftlint_baseline.json`` — for
  findings reviewed once and excused with a **mandatory** one-line
  justification.  An entry without a non-empty ``justification`` is
  itself an error (the whole point is that every exception carries its
  reviewed reason), and an entry matching nothing is a ``warning``
  (stale baseline — the debt it excused was paid; delete the entry).

Baseline identity is ``(rule, file, scope, code)`` — see
:mod:`bigdl_tpu.analysis.findings` for why line numbers are excluded.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from bigdl_tpu.analysis.astutil import SourceTree, repo_root
from bigdl_tpu.analysis.findings import Finding

__all__ = ["default_baseline_path", "load_baseline", "write_baseline",
           "apply_suppressions"]

_BASELINE_VERSION = 1


def default_baseline_path() -> str:
    return os.path.join(repo_root(), "scripts",
                        "graftlint_baseline.json")


def load_baseline(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """The baseline entries ([] when the file doesn't exist yet).
    Raises ValueError on a malformed file — a broken baseline must not
    silently suppress nothing (or everything)."""
    path = path or default_baseline_path()
    if not os.path.isfile(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != _BASELINE_VERSION \
            or not isinstance(doc.get("entries"), list):
        raise ValueError(
            f"{path}: not a graftlint baseline "
            f"(need {{version: {_BASELINE_VERSION}, entries: [...]}})")
    for e in doc["entries"]:
        missing = {"rule", "file", "scope", "code"} - set(e)
        if missing:
            raise ValueError(
                f"{path}: baseline entry {e!r} missing {sorted(missing)}")
    return doc["entries"]


def write_baseline(entries: List[Dict[str, Any]],
                   path: Optional[str] = None) -> str:
    path = path or default_baseline_path()
    doc = {"version": _BASELINE_VERSION,
           "entries": sorted(entries, key=lambda e: (
               e["rule"], e["file"], e["scope"], e["code"]))}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def _entry_key(e: Dict[str, Any]) -> tuple:
    return (e["rule"], e["file"], e["scope"], e["code"])


def apply_suppressions(findings: List[Finding], tree: SourceTree,
                       baseline: List[Dict[str, Any]],
                       baseline_path: str = "",
                       ran_rules: Optional[set] = None) -> List[Finding]:
    """Mark pragma- and baseline-suppressed findings in place, and
    append the baseline's own findings (missing justification = error,
    stale entry = warning).  ``ran_rules`` names the rule ids that
    actually executed this run (None = all): staleness is only judged
    for entries whose rule ran — a ``--select``ed subset must not
    declare every other pass's baseline debt paid.  Returns the same
    list for chaining."""
    by_key: Dict[tuple, Dict[str, Any]] = {}
    matched: Dict[tuple, bool] = {}
    base_rel = (os.path.relpath(baseline_path, tree.repo)
                .replace(os.sep, "/") if baseline_path else
                "scripts/graftlint_baseline.json")
    for e in baseline:
        by_key[_entry_key(e)] = e
        matched[_entry_key(e)] = False

    for f in findings:
        src = tree.get(f.file)
        if src is not None and src.pragma_disables(f.line, f.rule):
            f.suppressed = "pragma"
            continue
        key = (f.rule, f.file, f.scope, f.code)
        e = by_key.get(key)
        if e is not None:
            matched[key] = True
            if str(e.get("justification", "")).strip():
                f.suppressed = "baseline"
            # else: stays active — and the missing justification is
            # reported below, so the fix is visible in one run

    for key, e in by_key.items():
        if not str(e.get("justification", "")).strip():
            findings.append(Finding(
                "baseline-justification", "error", base_rel, 0,
                f"baseline entry for [{e['rule']}] {e['file']} "
                f"({e['scope'] or 'module'}) has no justification — "
                f"every excused finding must say why",
                scope=e["scope"], code=e["code"]))
        elif not matched[key] and (ran_rules is None
                                   or e["rule"] in ran_rules):
            findings.append(Finding(
                "baseline-stale", "warning", base_rel, 0,
                f"baseline entry for [{e['rule']}] {e['file']} "
                f"({e['scope'] or 'module'}: {e['code'][:60]!r}) matches "
                f"no finding — the debt was paid, delete the entry",
                scope=e["scope"], code=e["code"]))
    return findings
