"""graftlint AST passes.  Importing this package registers every pass
with :mod:`bigdl_tpu.analysis.registry` (one module per rule family —
adding a rule is adding a file here)."""

from bigdl_tpu.analysis.passes import (  # noqa: F401
    clock_discipline,
    collective_discipline,
    lock_discipline,
    metrics_catalog,
    thread_lifecycle,
    trace_safety,
)
