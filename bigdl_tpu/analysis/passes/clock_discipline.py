"""clock-discipline: one trace clock, wall time for timestamps only.

The telemetry contract (``telemetry/tracing.py``): span intervals and
every in-process DURATION are measured on ``time.perf_counter()`` —
the monotonic clock spans are exported on — while ``time.time()`` is
for TIMESTAMPS (manifest stamps, event times, cross-process staleness
comparisons) where epoch meaning is required.  PR 3's review round
found optimizer spans stranded ~an epoch off-timeline because the two
were mixed; wall-clock durations are also simply wrong across an NTP
step.  This pass flags:

* a ``time.time()`` DIFFERENCE — any subtraction with a wall-tainted
  operand (a direct call, a local assigned from one, a ``self`` attr
  assigned from one anywhere in the class, or a module global) — used
  where a duration on the monotonic clock belongs;
* a wall-tainted value passed to ``record_span`` — a span stamped off
  the trace clock's timeline.

Legal wall-clock uses (pure timestamps: storing ``time.time()`` in a
record, comparing against another process's epoch stamp) either don't
subtract in-process or carry a pragma naming why wall time is required
(see ``telemetry/fleet.py`` staleness checks).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from bigdl_tpu.analysis.astutil import SourceTree, call_attr_chain
from bigdl_tpu.analysis.findings import Finding
from bigdl_tpu.analysis.registry import register_pass

RULE = "clock-discipline"


def _is_wall_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = call_attr_chain(node)
    return chain[-2:] == ("time", "time") or chain == ("time",)


class _FuncTaint(ast.NodeVisitor):
    """Per-function taint walk.  ``class_attrs`` carries the enclosing
    class's wall-tainted ``self.X`` names; ``module_names`` the
    module-global ones."""

    def __init__(self, tree: SourceTree, src, scope: str,
                 class_attrs: Set[str], module_names: Set[str],
                 findings: List[Finding]):
        self.tree = tree
        self.src = src
        self.scope = scope
        self.class_attrs = class_attrs
        self.module_names = module_names
        self.locals: Set[str] = set()
        self.findings = findings

    # -- taint sources -----------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        if _is_wall_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.locals.add(t.id)
                elif isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    self.class_attrs.add(t.attr)
        self.generic_visit(node)

    def _tainted(self, node: ast.AST) -> bool:
        if _is_wall_call(node):
            return True
        if isinstance(node, ast.Name):
            return node.id in self.locals or node.id in self.module_names
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return node.attr in self.class_attrs
        return False

    # -- taint sinks -------------------------------------------------------

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, ast.Sub) and (
                self._tainted(node.left) or self._tainted(node.right)):
            self.findings.append(self.tree.finding(
                RULE, "error", self.src, node.lineno,
                "wall-clock (time.time) difference used as a duration "
                "— use time.perf_counter(), the trace clock; wall "
                "clock is for timestamps only "
                "(telemetry/tracing.py clock contract)",
                scope=self.scope))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        chain = call_attr_chain(node)
        if chain and chain[-1] == "record_span":
            stamps = list(node.args[1:3]) + [
                kw.value for kw in node.keywords
                if kw.arg in ("t_start", "t_end")]
            if any(self._tainted(a) for a in stamps):
                self.findings.append(self.tree.finding(
                    RULE, "error", self.src, node.lineno,
                    "record_span stamped with a time.time() value — "
                    "spans live on the perf_counter trace clock; a "
                    "wall stamp strands the span off-timeline",
                    scope=self.scope))
        self.generic_visit(node)

    # nested defs get their own walker (fresh locals, shared attrs)
    def visit_FunctionDef(self, node) -> None:
        if getattr(self, "_entered", False):
            sub = _FuncTaint(self.tree, self.src,
                             f"{self.scope}.{node.name}",
                             self.class_attrs, self.module_names,
                             self.findings)
            sub._entered = True
            for child in node.body:
                sub.visit(child)
        else:
            self._entered = True
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _wall_attrs_of_class(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_wall_call(node.value):
            for t in node.targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    out.add(t.attr)
    return out


@register_pass(RULE, doc="time.time() differences used as durations / "
                         "span stamps off the perf_counter trace clock")
def run(tree: SourceTree) -> List[Finding]:
    findings: List[Finding] = []
    for src in tree:
        if src.tree is None:
            continue
        module_names: Set[str] = {
            t.id for node in src.tree.body
            if isinstance(node, ast.Assign) and _is_wall_call(node.value)
            for t in node.targets if isinstance(t, ast.Name)}

        def walk(body, scope: str, class_attrs: Optional[Set[str]]):
            for node in body:
                if isinstance(node, ast.ClassDef):
                    qual = f"{scope}.{node.name}" if scope else node.name
                    walk(node.body, qual, _wall_attrs_of_class(node))
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    qual = f"{scope}.{node.name}" if scope else node.name
                    v = _FuncTaint(tree, src, qual,
                                   class_attrs if class_attrs is not None
                                   else set(), module_names, findings)
                    v._entered = True
                    for child in node.body:
                        v.visit(child)
                elif isinstance(node, (ast.If, ast.Try, ast.With)):
                    walk(ast.iter_child_nodes(node), scope, class_attrs)

        walk(src.tree.body, "", None)
    return findings
